"""Per-algorithm benchmark table: every BASELINE.json config, one JSON line each.

Sweeps the algorithm catalog over the same ResNet-50 synthetic protocol as
bench.py (shared measurement core) and reports, per config: training
imgs/sec, ratio vs the uncompressed-allreduce baseline, and bytes-on-wire
per step per rank (grace_tpu.utils.wire_report — a first-class metric the
reference never measured). Covers BASELINE.json configs 2-5: Top-K 1%,
QSGD/TernGrad, PowerSGD rank-4, 1-bit/signSGD; plus a fusion ablation for
the headline pair (flat vs unfused — Horovod's 64MiB-fusion-buffer analog,
SURVEY.md §2.4).

Usage:
    python bench_all.py             # probe TPU, fall back to CPU mesh
    python bench_all.py --_worker cpu   # force the simulated-CPU mesh

Output: one JSON line per config on stdout, e.g.
  {"config": "qsgd", "imgs_per_sec": ..., "vs_baseline": ...,
   "wire_bytes_per_step": ..., "wire_ratio": ..., "platform": "tpu"}
"""

from __future__ import annotations

import json
import os
import sys

import bench

# Ordered by evidence value: rows persist one by one (progressive_emit), so
# if the flaky tunnel dies mid-sweep the completed prefix survives — put
# the rows the analysis needs most right after the headline pair.
CONFIGS = [
    # The headline pair (dense baseline first) comes verbatim from bench.py
    # so the two benchmarks can never drift apart.
    *bench.HEADLINE,
    # ---- Round-5 priority block (VERDICT r4 items 1+3): the rows the
    # analysis needs most, placed right after the headline pair because
    # the tunnel historically dies mid-sweep and only the prefix lands. --
    #
    # THE beat-dense candidates (VERDICT r4 item 1): two-shot keeps recv
    # ~O(k) flat in W (vs allgather's O(W·k) and dense's 2·n), so at the
    # amortizing batch its multi-chip projection is the one config with a
    # shot at speedup_vs_dense > 1 on DCN. Round 4 only measured twoshot
    # at bs=32 (0.53x, fixed-overhead-dominated).
    {"name": "topk1pct_twoshot_bs256", "per_device_bs": 256,
     "params": {"compressor": "topk", "compress_ratio": 0.01,
                "topk_algorithm": "chunk", "memory": "residual",
                "communicator": "twoshot", "fusion": "flat"}},
    # + bf16 residual state: the cheapest HBM lever that doesn't touch
    # model numerics (rounding rides the error-feedback loop).
    {"name": "topk1pct_twoshot_bs256_rbf16", "per_device_bs": 256,
     "params": {"compressor": "topk", "compress_ratio": 0.01,
                "topk_algorithm": "chunk", "memory": "residual",
                "memory_dtype": "bfloat16",
                "communicator": "twoshot", "fusion": "flat"}},
    # Both levers: bf16 params AND bf16 residual on the twoshot wire.
    {"name": "topk1pct_twoshot_bs256_pbf16_rbf16", "per_device_bs": 256,
     "param_dtype": "bfloat16",
     "note": "bf16 grads take the staged chunk Top-K "
             "(the Pallas kernel is f32-only)",
     "params": {"compressor": "topk", "compress_ratio": 0.01,
                "topk_algorithm": "chunk", "memory": "residual",
                "memory_dtype": "bfloat16",
                "communicator": "twoshot", "fusion": "flat"}},
    # Re-capture of the round-4 best measured row (0.9246x, spread 0.05%)
    # plus its bf16 variants — never measured in round 4 (dead rows).
    {"name": "topk1pct_bs256", "per_device_bs": 256,
     "params": {"compressor": "topk", "compress_ratio": 0.01,
                "topk_algorithm": "chunk", "memory": "residual",
                "communicator": "allgather", "fusion": "flat"}},
    # Both amortization levers together: the headline batch AND bf16
    # params — round-4 candidates for the best measured ratio.
    {"name": "topk1pct_bs256_pbf16", "per_device_bs": 256,
     "param_dtype": "bfloat16",
     "note": "bf16 grads take the staged chunk Top-K "
             "(the Pallas kernel is f32-only)",
     "params": {"compressor": "topk", "compress_ratio": 0.01,
                "topk_algorithm": "chunk", "memory": "residual",
                "communicator": "allgather", "fusion": "flat"}},
    # bf16 RESIDUAL with f32 params (ResidualMemory state_dtype): halves
    # the largest state tensor's HBM traffic without touching the model's
    # numerics; the rounding rides the same feedback loop as the
    # compression error.
    {"name": "topk1pct_bs256_rbf16", "per_device_bs": 256,
     "params": {"compressor": "topk", "compress_ratio": 0.01,
                "topk_algorithm": "chunk", "memory": "residual",
                "memory_dtype": "bfloat16",
                "communicator": "allgather", "fusion": "flat"}},
    # Fused dense at the headline batch: with the round-5 headline moving
    # to per-leaf (see bench.HEADLINE), this row keeps the strict
    # fused-vs-fused pairing measurable against topk1pct_bs256 above
    # (dense fused-vs-unfused measured 2285.9 vs 2289.8 — ~0.2%).
    {"name": "none_flat_bs256", "per_device_bs": 256,
     "params": {"compressor": "none", "memory": "none",
                "communicator": "allreduce", "fusion": "flat"}},
    # Ring all-reduce (ISSUE 4): hop-pipelined reduce-scatter/all-gather
    # that keeps the payload compressed on every hop — recv ~2·k·(W-1)/W,
    # flat in W like two-shot, but aggregation is spread around the ring
    # and phase 2 ships the reduced shards still in wire format. The bs=256
    # row pairs with topk1pct_bs256/topk1pct_twoshot_bs256 above for the
    # three-way allgather/twoshot/ring comparison at the amortizing batch.
    {"name": "topk1pct_ring_bs256", "per_device_bs": 256,
     "params": {"compressor": "topk", "compress_ratio": 0.01,
                "topk_algorithm": "chunk", "memory": "residual",
                "communicator": "ring", "fusion": "flat"}},
    # The FSDP exchange (ISSUE 14): one all_to_all + one all_gather,
    # payload-space sums for exact codecs and exactly ONE requant
    # boundary for topk — the schedule whose requant chain stays ≤1 at
    # any W (the flat ring pays W−2), so it is the flat schedule the
    # tuner can still rank at pod scale. Pairs with the ring/twoshot
    # rows above for the four-way comparison at the amortizing batch.
    {"name": "topk1pct_rscatter_bs256", "per_device_bs": 256,
     "params": {"compressor": "topk", "compress_ratio": 0.01,
                "topk_algorithm": "chunk", "memory": "residual",
                "communicator": "rscatter", "fusion": "flat"}},
    # QSGD on the ring exercises the per-hop requantization path proper
    # (decompress → accumulate → requantize each hop; topk re-selects).
    # use_pallas pinned False to match the staged qsgd row below —
    # communicator is the only variable between the pair.
    {"name": "qsgd_ring", "params": {"compressor": "qsgd",
                                     "quantum_num": 64,
                                     "use_pallas": False,
                                     "memory": "none",
                                     "communicator": "ring",
                                     "fusion": "flat"}},
    # Hierarchical ICI×DCN family (ISSUE 7): the two-level schedule whose
    # xslice projection is THE cross-slice headline — flat topk+allgather
    # LOSES to dense at W=256 over DCN (0.896×, see the projection blocks
    # of topk1pct_bs256); the hier rows keep ~2·k·(S−1)/S on ICI and ship
    # only (K−1)·k/S across DCN, so the same measured step time projects
    # >1× dense at W=256, slice_size=8. slice_size=8 matches the one real
    # v5e slice this repo measures on AND the xslice projection topology,
    # so recv_link_bytes prices a genuinely mixed split in every row.
    # (On the single 8-chip mesh the schedule collapses to the flat ring —
    # the measured step time is the ring's; the projection is the story.)
    {"name": "topk1pct_hier_bs256", "per_device_bs": 256,
     "params": {"compressor": "topk", "compress_ratio": 0.01,
                "topk_algorithm": "chunk", "memory": "residual",
                "communicator": "hier", "slice_size": 8,
                "fusion": "flat"}},
    {"name": "qsgd_hier", "params": {"compressor": "qsgd",
                                     "quantum_num": 64,
                                     "use_pallas": False,
                                     "memory": "none",
                                     "communicator": "hier",
                                     "slice_size": 8,
                                     "fusion": "flat"}},
    {"name": "none_hier", "params": {"compressor": "none",
                                     "memory": "none",
                                     "communicator": "hier",
                                     "slice_size": 8,
                                     "fusion": "flat"}},
    # Aggregation-homomorphic row family (ISSUE 13): shared-scale qsgd4
    # whose integer payloads SUM on every hop and at the slice boundary —
    # zero requant regardless of W, one decode at the schedule's end, one
    # scalar pmax negotiation before stage 1. Pairs with qsgd_ring (the
    # per-hop requant path this family retires: W−1 re-encodes, the
    # PR-12 MAX_REQUANT_CHAIN degradation) and with the hier rows (same
    # two-level schedule, boundary requant → boundary integer add). Wire
    # is int16 (fp16-width) — the story is the quality-at-ring-cost, not
    # the bytes: hop-count-independent compression error at ring/hier's
    # O(k), where the tuner's funnel now prices requant-chain 0.
    {"name": "homoqsgd4_ring_bs256", "per_device_bs": 256,
     "params": {"compressor": "homoqsgd", "quantum_num": 7,
                "memory": "residual", "communicator": "ring",
                "fusion": "flat"}},
    {"name": "homoqsgd4_hier_slice8", "per_device_bs": 256,
     "params": {"compressor": "homoqsgd", "quantum_num": 7,
                "memory": "residual", "communicator": "hier",
                "slice_size": 8, "fusion": "flat"}},
    # graft-adapt row (ISSUE 15): the self-tuning homoqsgd ladder (dense
    # escape → 8-bit → 4-bit) over the zero-requant ring, measured at its
    # quiet steady state — the top rung IS homoqsgd4_ring_bs256's codec,
    # so this row's delta against that one is the controller's whole
    # overhead bill (the per-step scalar pmean/pmax signal + the ladder
    # switch + the telemetry ring). The acceptance claim is ~parity:
    # a self-tuning config matching the best static config's steady-state
    # throughput (the convergence-floor half lives in tests/test_adapt).
    {"name": "adapt_homoqsgd4_ring_bs256", "per_device_bs": 256,
     "note": "self-tuning ladder (dense->homoqsgd8->homoqsgd4) at its "
             "steady state; compare against homoqsgd4_ring_bs256 for "
             "the controller overhead",
     "params": {"compressor": "homoqsgd", "quantum_num": 7,
                "memory": "residual", "communicator": "ring",
                "fusion": "flat", "escape": "fp16", "telemetry": 16,
                "adapt": {"window": 25,
                          "ladder": [{"quantum_num": 127}]}}},
    # The overdue graft-tune chip-window row (ISSUE 12 / ROADMAP item 1):
    # everything PRs 7-10 built, on in one config — fused Pallas
    # quantize-and-pack (4-bit nibbles, 2 codes/byte) feeding the bucketed
    # overlap executor over the hop-requant ring, at the amortizing batch.
    # The committed TPU captures predate all of it (the sweep's qsgd rows
    # are staged, unbucketed, quantum_num=64); this row plus the hier rows
    # above are the `--tuned` family, so refreshing the evidence at the
    # next tunnel window is one command: `python bench_all.py --tuned`.
    # tpu_only for the same reason as qsgd_pallas: interpret-mode Pallas
    # off-chip is a per-element emulation.
    {"name": "qsgd4_packed_bucketed_pallas_bs256", "per_device_bs": 256,
     "tpu_only": True,
     "note": "graft-tune row family: fused quantize-pack kernel + "
             "bucketed executor + hop-requant ring",
     "params": {"compressor": "qsgd", "quantum_num": 7,
                "use_pallas": True, "memory": "none",
                "communicator": "ring", "fusion": 1024}},
    # Its staged twin keeps the kernel ablation measurable (and gives the
    # CPU smoke a runnable row of the same wire format + executor).
    {"name": "qsgd4_packed_bucketed_bs256", "per_device_bs": 256,
     "params": {"compressor": "qsgd", "quantum_num": 7,
                "use_pallas": False, "memory": "none",
                "communicator": "ring", "fusion": 1024}},
    # Sub-nibble wire widths (ISSUE 19): quantum_num=1 ships 2-bit fields
    # (4 codes/byte — 16x under int8, 2x under the 4-bit nibble) and
    # quantum_num=3 the 3-bit LSB-first bitstream (8 codes / 3 bytes),
    # both through the hop-requant ring. Rows stamp pack_width so the
    # 2/3/4-bit family is distinguishable in the evidence; the quality
    # cost of the coarser lattice is the convergence suite's question,
    # the wire win is this sweep's.
    {"name": "qsgd2_packed_ring_bs256", "per_device_bs": 256,
     "params": {"compressor": "qsgd", "quantum_num": 1,
                "use_pallas": False, "memory": "none",
                "communicator": "ring", "fusion": "flat"}},
    {"name": "qsgd3_packed_ring_bs256", "per_device_bs": 256,
     "params": {"compressor": "qsgd", "quantum_num": 3,
                "use_pallas": False, "memory": "none",
                "communicator": "ring", "fusion": "flat"}},
    # Double-buffered ring twins (ISSUE 19): pipeline=2 splits the flat
    # buffer into two segments whose ring schedules overlap on real links
    # — the delta against the serial siblings above is the measured side
    # of the wire_pipeline story (rows stamp pipelined=2, projections
    # discount the wire leg by wire_overlap_fraction, and flow pass 5
    # referees the >= 2 independent chains statically).
    {"name": "qsgd2_packed_ring_pipelined_bs256", "per_device_bs": 256,
     "params": {"compressor": "qsgd", "quantum_num": 1,
                "use_pallas": False, "memory": "none",
                "communicator": "ring", "fusion": "flat", "pipeline": 2}},
    {"name": "qsgd4_packed_ring_pipelined_bs256", "per_device_bs": 256,
     "params": {"compressor": "qsgd", "quantum_num": 7,
                "use_pallas": False, "memory": "none",
                "communicator": "ring", "fusion": "flat", "pipeline": 2}},
    # qsgd vs qsgd_pallas: THE evidence gate for flipping QSGD's
    # use_pallas default (VERDICT r3 item 5, two rounds dark).
    # use_pallas pinned False: this row is the STAGED side of the
    # qsgd-vs-qsgd_pallas A/B. (The round-5 A/B measured the kernel 42%
    # faster, so 'auto' — the factory default — now resolves kernel-on
    # for TPU; leaving this unpinned would make both rows measure the
    # kernel and erase the ablation.)
    {"name": "qsgd",       "params": {"compressor": "qsgd",
                                      "quantum_num": 64,
                                      "use_pallas": False,
                                      "memory": "none",
                                      "communicator": "allgather",
                                      "fusion": "flat"}},
    # tpu_only: off-TPU this forces the quant kernel into interpret mode
    # over the full 25.5M-param model — observed >45 min for ONE config on
    # the CPU smoke (interpret Pallas is a per-element emulation); the
    # kernel's off-TPU correctness is covered at small sizes by
    # tests/test_pallas_quant.py, and the row only means anything on-chip.
    {"name": "qsgd_pallas", "tpu_only": True,
     "params": {"compressor": "qsgd",
                "quantum_num": 64,
                "use_pallas": True,
                "memory": "none",
                "communicator": "allgather",
                "fusion": "flat"}},
    {"name": "powersgd_r4", "params": {"compressor": "powersgd",
                                       "compress_rank": 4,
                                       "memory": "powersgd",
                                       "communicator": "allreduce",
                                       "fusion": "none"}},
    # Fixed-cost psum majority vote (~4n bf16 on the wire, W-independent):
    # the pod-scale route for sign methods, next to the packed allgather
    # row below (also VERDICT round-2 item 5). Errored mid-remote-compile
    # in round 4 when the tunnel dropped — verify the retry lands.
    # The vote at the amortizing batch, per-leaf: 0.9775x dense single-chip
    # (round-5 capture) with recv flat in W (bf16 psum = half dense's
    # bytes), so it projects above dense on DCN at every W — the third
    # winning family after PowerSGD and small-mesh per-leaf Top-K.
    {"name": "signsgd_vote_bs256", "per_device_bs": 256,
     "params": {"compressor": "signsgd", "memory": "residual",
                "communicator": "sign_allreduce", "fusion": "none"}},
    {"name": "signsgd_vote", "params": {"compressor": "signsgd",
                                        "memory": "none",
                                        "communicator": "sign_allreduce",
                                        "fusion": "flat"}},
    {"name": "onebit",     "params": {"compressor": "onebit",
                                      "memory": "residual",
                                      "communicator": "allgather",
                                      "fusion": "flat"}},
    {"name": "terngrad",   "params": {"compressor": "terngrad",
                                      "memory": "none",
                                      "communicator": "allgather",
                                      "fusion": "flat"}},
    # ---- end priority block ----
    # Two-shot scatter-reduce-recompress all-reduce at the small batch:
    # isolates the stage-2 recompress overhead (VERDICT round-2 item 5).
    {"name": "topk1pct_twoshot", "params": {"compressor": "topk",
                                            "compress_ratio": 0.01,
                                            "topk_algorithm": "chunk",
                                            "memory": "residual",
                                            "communicator": "twoshot",
                                            "fusion": "flat"}},
    {"name": "signsgd",    "params": {"compressor": "signsgd",
                                      "memory": "none",
                                      "communicator": "allgather",
                                      "fusion": "flat"}},
    {"name": "topk1pct_bf16", "params": {"compressor": "topk",
                                         "compress_ratio": 0.01,
                                         "topk_algorithm": "chunk",
                                         "wire_dtype": "bfloat16",
                                         "memory": "residual",
                                         "communicator": "allgather",
                                         "fusion": "flat"}},
    # Top-K selection variants (the headline uses 'chunk'; exact top-k
    # lowers to a full sort — the most expensive op in the pipeline; see
    # compressors/topk.py):
    {"name": "topk1pct_approx", "params": {"compressor": "topk",
                                           "compress_ratio": 0.01,
                                           "topk_algorithm": "approx",
                                           "memory": "residual",
                                           "communicator": "allgather",
                                           "fusion": "flat"}},
    {"name": "topk1pct_exact", "params": {"compressor": "topk",
                                          "compress_ratio": 0.01,
                                          "topk_algorithm": "exact",
                                          "memory": "residual",
                                          "communicator": "allgather",
                                          "fusion": "flat"}},
    # Batch-size sweep tail (VERDICT round-3 item 4): bs64/bs128 show where
    # the fixed compression cost amortizes; measured in round 4, kept for
    # re-capture freshness. bench_configs re-measures the dense baseline at
    # each row's own shapes so vs_baseline stays like-for-like.
    *[{"name": f"topk1pct_bs{bs}", "per_device_bs": bs,
       "params": {"compressor": "topk", "compress_ratio": 0.01,
                  "topk_algorithm": "chunk", "memory": "residual",
                  "communicator": "allgather", "fusion": "flat"}}
      for bs in (64, 128)],
    # bf16 master params at the amortizing batch. NOTE the fused Pallas
    # Top-K kernel is f32-only (compressors/topk.py fused gate) so bf16
    # grads take the STAGED chunk path — the note rides the emitted row.
    {"name": "topk1pct_bs128_pbf16", "per_device_bs": 128,
     "param_dtype": "bfloat16",
     "note": "bf16 grads take the staged chunk Top-K "
             "(the Pallas kernel is f32-only; staged is the default "
             "everywhere since round 4 anyway)",
     "params": {"compressor": "topk", "compress_ratio": 0.01,
                "topk_algorithm": "chunk", "memory": "residual",
                "communicator": "allgather", "fusion": "flat"}},
    # Ablation: chunk selection WITH the fused Pallas kernels forced on
    # (ops/pallas_topk.py). The round-4 on-chip A/B measured the staged
    # XLA path FASTER end-to-end (1602 vs 1441 imgs/sec at bs=32, same
    # session), so 'auto' now resolves to staged and this row keeps the
    # kernel measurable should a later change flip the verdict back.
    {"name": "topk1pct_pallas", "params": {"compressor": "topk",
                                           "compress_ratio": 0.01,
                                           "topk_algorithm": "chunk",
                                           "use_pallas": True,
                                           "memory": "residual",
                                           "communicator": "allgather",
                                           "fusion": "flat"}},
    # Fusion ablation (headline pair unfused, and Horovod's default 64 MiB
    # bucketing — SURVEY.md §2.4):
    {"name": "none_unfused", "params": {"compressor": "none",
                                        "memory": "none",
                                        "communicator": "allreduce",
                                        "fusion": "none"}},
    {"name": "topk1pct_unfused", "params": {"compressor": "topk",
                                            "compress_ratio": 0.01,
                                            "topk_algorithm": "chunk",
                                            "memory": "residual",
                                            "communicator": "allgather",
                                            "fusion": "none"}},
    {"name": "topk1pct_64mib", "params": {"compressor": "topk",
                                          "compress_ratio": 0.01,
                                          "topk_algorithm": "chunk",
                                          "memory": "residual",
                                          "communicator": "allgather",
                                          "fusion": 64 * 2**20}},
]

# The graft-tune evidence family (ISSUE 12): the dense anchor + headline
# pair plus the rows the committed captures are missing — hier at the
# projection topology and the packed+bucketed+pallas qsgd4 row. One
# command refreshes them all: `python bench_all.py --tuned`.
TUNED_ROW_NAMES = ("none", "topk1pct", "topk1pct_hier_bs256", "qsgd_hier",
                   "none_hier", "qsgd4_packed_bucketed_pallas_bs256",
                   "qsgd4_packed_bucketed_bs256",
                   # the homomorphic family (ISSUE 13): the zero-requant
                   # ring/hier rows the tuner's requant-chain-0 pricing
                   # needs measured evidence for
                   "homoqsgd4_ring_bs256", "homoqsgd4_hier_slice8",
                   # graft-shard (ISSUE 14): the rscatter schedule now
                   # tops the W256/slice8 static ranking — its measured
                   # step time is the next capture's most-wanted row
                   "topk1pct_rscatter_bs256",
                   # graft-adapt (ISSUE 15): the self-tuning ladder at
                   # its steady state next to its static twin — the
                   # controller-overhead ablation the acceptance
                   # criterion ("matches the best static config's
                   # steady-state throughput") needs on-chip
                   "adapt_homoqsgd4_ring_bs256",
                   # graft-wire (ISSUE 19): the 2/3-bit pack widths and
                   # the double-buffered ring twins — the serial vs
                   # pipelined deltas are the measured side of the
                   # wire_pipeline discount
                   "qsgd2_packed_ring_bs256", "qsgd3_packed_ring_bs256",
                   "qsgd2_packed_ring_pipelined_bs256",
                   "qsgd4_packed_ring_pipelined_bs256")


def active_configs():
    """The sweep's config list, honoring the --tuned selection (carried
    to the worker subprocess via GRACE_BENCH_TUNED — orchestrate() spawns
    workers with an inherited environment). configs[0] must stay the
    dense-recipe anchor in both modes (bench_configs' baseline contract)."""
    if os.environ.get("GRACE_BENCH_TUNED"):
        return [c for c in CONFIGS if c["name"] in TUNED_ROW_NAMES]
    return list(CONFIGS)


# Per-config budget: first compile dominates (~20-40s TPU, minutes on the
# CPU fallback mesh), so size the worker timeout by sweep length.
WORKER_TIMEOUT_S = 600 * len(CONFIGS)


# Sweep-specific TPU evidence file (same incremental-persistence contract as
# bench.py's BENCH_TPU_LAST.json): every measured row lands on disk
# immediately, so a mid-sweep tunnel death keeps the completed prefix.
SWEEP_EVIDENCE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_ALL_TPU_LAST.json")


def _resume_configs():
    """Attach previously measured rows (persisted in SWEEP_EVIDENCE_PATH)
    as cached_row so bench_configs re-emits them instead of re-measuring —
    a retry after a mid-sweep tunnel death then only pays for the missing
    configs. Two gates (a stale last-week file must never replay as fresh):

    * GRACE_BENCH_RESUME — explicit operator override, any file accepted;
    * GRACE_BENCH_RESUME_SINCE=<unix epoch> — set by tools/tpu_watch.sh at
      watcher start: the file is only reused if its captured_at stamp is
      at/after that moment, i.e. it was written by this watcher run.

    Rows must match the config's current shapes (bs/hw/dtype), carry a real
    measurement (no error rows), and get "resumed": true stamped on."""
    configs = [dict(c) for c in active_configs()]
    explicit = os.environ.get("GRACE_BENCH_RESUME")
    since = os.environ.get("GRACE_BENCH_RESUME_SINCE")
    if not (explicit or since):
        return configs
    try:
        with open(SWEEP_EVIDENCE_PATH) as f:
            doc = json.load(f)
        if not explicit:
            from datetime import datetime
            captured = datetime.fromisoformat(doc["captured_at"]).timestamp()
            if captured < float(since):
                return configs
        prev = {r["config"]: r for r in doc.get("rows", [])
                if r.get("config") and r.get("imgs_per_sec") is not None}
    except Exception:
        return configs
    for cfg in configs:
        row = prev.get(cfg["name"])
        if not row:
            continue
        # Shape defaults come from bench.py's exported constants — the
        # literals here once duplicated bench_configs' and could drift
        # (ADVICE r4): a collision with old rows could replay a
        # wrong-shape row.
        want = (cfg.get("per_device_bs", bench.TPU_DEFAULT_BS),
                cfg.get("image_hw", bench.TPU_DEFAULT_HW),
                cfg.get("param_dtype", bench.TPU_DEFAULT_PDTYPE))
        got = (row.get("per_device_bs"), row.get("image_hw"),
               row.get("param_dtype"))
        if want != got:
            continue
        # Same name + shapes is not enough: a config whose *params* were
        # edited since the row was measured must re-measure. Rows stamp
        # grace_params (bench_configs); a row without the stamp predates
        # it and is only trusted under the explicit operator override.
        if "grace_params" in row:
            if row["grace_params"] != cfg["params"]:
                continue
        elif not explicit:
            continue
        cfg["cached_row"] = {**row, "resumed": True}
        if explicit:
            # The operator's assertion that this file is trustworthy also
            # covers rows predating the pallas_enabled stamp — the
            # bench-side gate (_cached_row_valid) fails closed on those
            # otherwise.
            cfg["cached_row"]["resume_trusted"] = True
    return configs


def _worker(platform: str) -> None:
    # The watcher's GRACE_BENCH_RESUME_SINCE env is TPU-only (ADVICE r4): a
    # CPU-fallback worker inheriting it would re-emit cached platform-'tpu'
    # rows and rewrite the TPU evidence file with a fresh captured_at over
    # a rows list mixing CPU-measured rows. The operator's EXPLICIT
    # GRACE_BENCH_RESUME override still works off-TPU (a CPU-fallback
    # resume re-emits real on-chip rows instead of skip rows), but with
    # evidence persistence disabled — re-emission must never masquerade as
    # a fresh TPU capture.
    if platform == "tpu":
        configs, evidence_path = _resume_configs(), SWEEP_EVIDENCE_PATH
    elif os.environ.get("GRACE_BENCH_RESUME"):
        configs, evidence_path = _resume_configs(), None
    else:
        configs, evidence_path = [dict(c) for c in active_configs()], None
    emit = bench.progressive_emit(
        lambda r: print(json.dumps(r), flush=True),
        n_expected=len(configs),
        evidence_path=evidence_path,
        metric="resnet50_all_configs_imgs_per_sec")
    bench.bench_configs(platform, configs, emit)


def main() -> None:
    here = os.path.abspath(__file__)
    best_partial: list = []

    def salvage(out):
        # Keep the longest prefix of per-config rows any failed attempt
        # produced — a mid-sweep timeout should not discard measured configs.
        rows = bench._json_lines(out, "config")
        if len(rows) > len(best_partial):
            best_partial[:] = rows

    def parse(out, stages):
        rows = bench._json_lines(out, "config")
        if len(rows) != len(active_configs()):
            return None
        for r in rows:
            if stages:
                r["stages"] = stages
            print(json.dumps(r), flush=True)
        return rows

    def emit_failure(stages):
        for r in best_partial:
            r["partial"] = True
            print(json.dumps(r), flush=True)
        print(json.dumps({"config": None, "error": "all attempts failed",
                          "partial_rows": len(best_partial),
                          "stages": stages}), flush=True)

    if not bench.orchestrate(here, parse, emit_failure,
                             worker_timeout=WORKER_TIMEOUT_S,
                             salvage=salvage):
        sys.exit(1)


if __name__ == "__main__":
    if "--tuned" in sys.argv:
        # One-command graft-tune evidence refresh: restrict the sweep to
        # the tuned row family. Carried via env so the orchestrator's
        # worker subprocesses (and their retries) inherit the selection.
        os.environ["GRACE_BENCH_TUNED"] = "1"
        sys.argv = [a for a in sys.argv if a != "--tuned"]
    if len(sys.argv) > 2 and sys.argv[1] == "--_worker":
        _worker(sys.argv[2])
    else:
        main()
