"""Real-MNIST convergence evidence: LeNet on the bundled 10k-image set.

The flagship real-data curve (VERDICT round-2 item 3): the repo bundles the
public-domain MNIST test set (10,000 real handwritten digits, the same
fixture files the reference commits under examples/torch/data-0/MNIST/raw
so its 2-rank examples run without downloads) at examples/data/MNIST/raw.
`grace_tpu.data.mnist_split_dataset` makes a deterministic 8,000/2,000
train/test split; training runs the full GRACE pipeline (compensate →
compress → update → exchange) over the device mesh, so a healthy accuracy
curve here is end-to-end evidence that compressed training converges on
real MNIST — superseding the 8×8 UCI digits curve (digits_lenet.py) as the
primary committed evidence.

Run (simulated 8-device mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/mnist10k_lenet.py --compressor topk \\
        --compress-ratio 0.01 --memory residual \\
        --tsv logs/mnist10k_topk1pct.tsv
"""

from __future__ import annotations

import argparse
import os

import jax
import jax.numpy as jnp
import optax

import common  # noqa: E402 — sys.path bootstrap so grace_tpu imports resolve
from grace_tpu import grace_from_params
from grace_tpu.models import lenet
from grace_tpu.data import prefetch_to_device
from grace_tpu.parallel import data_parallel_mesh
from grace_tpu.train import (init_stateful_train_state,
                             make_stateful_train_step)
from grace_tpu.utils import (TableLogger, Timer, rank_zero_print,
                             run_provenance, wire_report)

def run(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    common.add_grace_args(parser)
    parser.add_argument("--epochs", type=int, default=40)
    parser.add_argument("--batch-size", type=int, default=256,
                        help="global batch (split across the mesh)")
    parser.add_argument("--lr", type=float, default=0.02)
    parser.add_argument("--cosine-lr", action="store_true",
                        help="cosine-decay the LR to 0 over the run (sign "
                             "methods need decay — fixed-step signSGD "
                             "wanders once near the optimum)")
    parser.add_argument("--sgd-momentum", type=float, default=0.9,
                        help="heavy-ball momentum of the outer SGD (use 0 "
                             "for signsgd: the vote output is ±1 per "
                             "coordinate, and momentum multiplies that "
                             "fixed-magnitude step ~10x into divergence)")
    parser.add_argument("--data-dir", default=common.BUNDLED_MNIST_DIR,
                        help="directory with the MNIST t10k idx(.gz) files")
    parser.add_argument("--tsv", default=None,
                        help="write per-epoch log (epoch\\tloss\\tacc) here")
    args = parser.parse_args(argv)

    mesh = data_parallel_mesh()
    x_train, y_train, x_test, y_test = common.load_mnist_auto(args.data_dir)
    rank_zero_print(f"real MNIST: {len(x_train)} train / {len(x_test)} test")

    grace = grace_from_params(common.grace_params_from_args(args))
    steps_per_epoch = max(1, len(x_train) // args.batch_size)
    lr = optax.cosine_decay_schedule(args.lr, args.epochs * steps_per_epoch) \
        if args.cosine_lr else args.lr
    optimizer = optax.chain(
        grace.transform(seed=args.seed),
        optax.sgd(lr, momentum=args.sgd_momentum or None))
    params, mstate = lenet.init(jax.random.key(args.seed))
    rank_zero_print("wire cost:", wire_report(grace.compressor, params))

    def loss_fn(params, mstate, batch):
        xb, yb = batch
        logits, new_mstate = lenet.apply(params, mstate, xb)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
        return loss.mean(), new_mstate

    step = make_stateful_train_step(loss_fn, optimizer, mesh)
    ts = init_stateful_train_state(params, mstate, optimizer, mesh)

    # The 2,000-image test split evaluates in one replicated jit call on
    # device 0 — exactness matters more than speed here.
    eval_fn = jax.jit(lambda p, s, x: lenet.apply(p, s, x, train=False))

    def accuracy(params, mstate):
        logits, _ = eval_fn(params, mstate, jnp.asarray(x_test))
        return float(jnp.mean(jnp.argmax(logits, -1) == jnp.asarray(y_test)))

    log = TableLogger()
    timer = Timer()
    rows = ["epoch\ttrain_loss\ttest_acc"]
    test_acc = 0.0
    for epoch in range(1, args.epochs + 1):
        losses = []
        host_batches = common.batches(x_train, y_train, args.batch_size,
                                      shuffle=True, seed=args.seed + epoch)
        # Device-side double buffering: batch t+1's host->HBM transfer is
        # in flight while step t computes (grace_tpu.data.prefetch_to_device).
        for batch in prefetch_to_device(host_batches, mesh=mesh, size=2):
            ts, loss = step(ts, batch)
            # Per-step host sync: this epoch enqueues ~60 steps, and on a
            # host with fewer cores than mesh devices an unbounded queue of
            # multi-device programs can starve the collective rendezvous
            # (all device threads futex-parked). On a real TPU mesh drop
            # this and let XLA pipeline.
            losses.append(float(loss))
        train_loss = sum(losses) / len(losses)
        test_acc = accuracy(ts.params, ts.model_state)
        log.append({"epoch": epoch, "train loss": train_loss,
                    "epoch time": timer(), "test acc": test_acc})
        rows.append(f"{epoch}\t{train_loss:.4f}\t{test_acc:.4f}")

    if args.tsv:
        os.makedirs(os.path.dirname(args.tsv) or ".", exist_ok=True)
        # Self-describing evidence: data source + platform in the file.
        prov = run_provenance(data=f"real:mnist({args.data_dir})",
                              **common.grace_provenance(args))
        with open(args.tsv, "w") as f:
            f.write("\n".join([f"# {k}: {v}" for k, v in prov.items()]
                              + rows) + "\n")
        rank_zero_print(f"log -> {args.tsv}")
    return test_acc


if __name__ == "__main__":
    acc = run()
    rank_zero_print(f"final test accuracy: {acc:.4f}")
