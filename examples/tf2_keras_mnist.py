"""Keras MNIST with DistributedOptimizer — the reference's Keras path.

TPU-native port of examples/tensorflow/tensorflow2_keras_mnist.py (:60-89):
`model.fit` with a grace-wrapped Keras optimizer (BASELINE.json config 5 —
the TF 1-bit/signSGD path — is `--compressor onebit --memory residual` or
`--compressor signsgd`) plus the reference's callback set: initial-state
broadcast, cross-rank metric averaging, and LR warmup.

Run (simulated 8-device mesh; TF stays on CPU):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/tf2_keras_mnist.py --epochs 3 --compressor onebit \\
        --memory residual --communicator allgather
"""

from __future__ import annotations

import argparse

import numpy as np

import common


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    common.add_grace_args(parser)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--lr", type=float, default=0.001)
    parser.add_argument("--warmup-epochs", type=int, default=3)
    parser.add_argument("--train-size", type=int, default=8192)
    parser.add_argument("--data-dir", default=None,
                        help="MNIST idx directory (default: synthetic)")
    parser.add_argument("--ckpt", default=None,
                        help="save the trained model here (.keras); reload "
                             "with grace_tpu.interop.keras.load_model")
    args = parser.parse_args()

    import jax
    import keras

    from grace_tpu import grace_from_params
    from grace_tpu.interop.keras import (BroadcastGlobalVariablesCallback,
                                         DistributedOptimizer,
                                         LearningRateWarmupCallback,
                                         MetricAverageCallback)
    from grace_tpu.parallel import data_parallel_mesh, initialize_distributed
    from grace_tpu.utils import rank_zero_print

    initialize_distributed()
    mesh = data_parallel_mesh()
    world = mesh.devices.size
    grc = grace_from_params(common.grace_params_from_args(args))

    if args.data_dir:
        x, y = common.load_mnist_idx(args.data_dir, train=True)
    else:
        x, y = common.synthetic_mnist(args.train_size, seed=args.seed)

    keras.utils.set_random_seed(args.seed)
    model = keras.Sequential([
        keras.layers.Conv2D(32, 3, activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Conv2D(64, 3, activation="relu"),
        keras.layers.MaxPooling2D(),
        keras.layers.Flatten(),
        keras.layers.Dense(128, activation="relu"),
        keras.layers.Dense(10),
    ])
    opt = DistributedOptimizer(keras.optimizers.SGD(args.lr), grc,
                               mesh=mesh, seed=args.seed)
    model.compile(
        optimizer=opt,
        loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"])

    callbacks = [
        BroadcastGlobalVariablesCallback(root_rank=0),
        MetricAverageCallback(),
        LearningRateWarmupCallback(world_size=world,
                                   warmup_epochs=args.warmup_epochs,
                                   verbose=jax.process_index() == 0),
    ]
    model.fit(x.astype(np.float32), y.astype(np.int32),
              batch_size=args.batch_size, epochs=args.epochs,
              callbacks=callbacks,
              verbose=2 if jax.process_index() == 0 else 0)

    if args.ckpt and jax.process_index() == 0:
        model.save(args.ckpt)
        rank_zero_print(f"model saved to {args.ckpt}")


if __name__ == "__main__":
    main()
