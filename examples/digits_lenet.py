"""Real-data convergence evidence: LeNet on the UCI digits dataset.

The image has no network access and no MNIST/CIFAR files on disk, so the
committed convergence run (VERDICT round-1 item 5) uses the one real image
dataset that ships inside the environment: scikit-learn's bundled UCI
handwritten digits (1,797 scanned 8x8 digits, upscaled to LeNet's 28x28).
Same training harness as examples/mnist_lenet.py — full GRACE pipeline
(compensate → compress → update → exchange) over the device mesh — so a
healthy accuracy curve here is end-to-end evidence that compressed training
converges on real data.

Run (simulated 8-device mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/digits_lenet.py --compressor topk \\
        --compress-ratio 0.01 --memory residual --tsv logs/digits_topk1pct.tsv
"""

from __future__ import annotations

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

import common  # noqa: E402 — sys.path bootstrap so grace_tpu imports resolve
from grace_tpu import grace_from_params
from grace_tpu.data import digits_dataset
from grace_tpu.models import lenet
from grace_tpu.parallel import batch_sharded, data_parallel_mesh
from grace_tpu.train import (init_stateful_train_state, make_eval_step,
                             make_stateful_train_step)
from grace_tpu.utils import (TableLogger, Timer, rank_zero_print,
                             run_provenance, wire_report)



def run(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    common.add_grace_args(parser)
    parser.add_argument("--epochs", type=int, default=60)
    parser.add_argument("--batch-size", type=int, default=128,
                        help="global batch (split across the mesh)")
    parser.add_argument("--lr", type=float, default=0.02)
    parser.add_argument("--tsv", default=None,
                        help="write per-epoch log (epoch\\tloss\\tacc) here")
    args = parser.parse_args(argv)

    mesh = data_parallel_mesh()
    train = digits_dataset(train=True)
    test = digits_dataset(train=False)
    x_train = train.normalize(train.images)
    y_train = train.labels
    # Eval uses the train stats (the torchvision convention), full test split.
    x_test = train.normalize(test.images)
    y_test = test.labels

    grace = grace_from_params(common.grace_params_from_args(args))
    optimizer = optax.chain(grace.transform(seed=args.seed),
                            optax.sgd(args.lr, momentum=0.9))
    params, mstate = lenet.init(jax.random.key(args.seed))
    rank_zero_print("wire cost:", wire_report(grace.compressor, params))

    def loss_fn(params, mstate, batch):
        xb, yb = batch
        logits, new_mstate = lenet.apply(params, mstate, xb)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
        return loss.mean(), new_mstate

    step = make_stateful_train_step(loss_fn, optimizer, mesh)
    ts = init_stateful_train_state(params, mstate, optimizer, mesh)

    # Test split (360) is smaller than a sharded batch budget; evaluate
    # replicated on host-fed device 0 — exactness matters more than speed.
    eval_fn = jax.jit(lambda p, s, x: lenet.apply(p, s, x, train=False))

    def accuracy(params, mstate):
        logits, _ = eval_fn(params, mstate, jnp.asarray(x_test))
        return float(jnp.mean(jnp.argmax(logits, -1) == jnp.asarray(y_test)))

    log = TableLogger()
    timer = Timer()
    rows = ["epoch\ttrain_loss\ttest_acc"]
    test_acc = 0.0
    for epoch in range(1, args.epochs + 1):
        losses = []
        for xb, yb in common.batches(x_train, y_train, args.batch_size,
                                     shuffle=True, seed=args.seed + epoch):
            batch = jax.device_put((jnp.asarray(xb), jnp.asarray(yb)),
                                   batch_sharded(mesh))
            ts, loss = step(ts, batch)
            losses.append(loss)
        train_loss = float(jnp.mean(jnp.stack(losses)))
        test_acc = accuracy(ts.params, ts.model_state)
        log.append({"epoch": epoch, "train loss": train_loss,
                    "epoch time": timer(), "test acc": test_acc})
        rows.append(f"{epoch}\t{train_loss:.4f}\t{test_acc:.4f}")

    if args.tsv:
        os.makedirs(os.path.dirname(args.tsv) or ".", exist_ok=True)
        # Self-describing evidence: data source + platform in the file.
        prov = run_provenance(data="real:sklearn-uci-digits",
                              **common.grace_provenance(args))
        with open(args.tsv, "w") as f:
            f.write("\n".join([f"# {k}: {v}" for k, v in prov.items()]
                              + rows) + "\n")
        rank_zero_print(f"log -> {args.tsv}")
    return test_acc


if __name__ == "__main__":
    acc = run()
    rank_zero_print(f"final test accuracy: {acc:.4f}")
