"""Shared example plumbing: CLI flags, synthetic datasets, idx/CIFAR readers.

The reference's examples each re-declare argparse flags and dataset loading
(SURVEY.md §2.8); this module factors the common part. Data policy: synthetic
datasets by default (runs anywhere, zero downloads), with loaders for the
standard on-disk formats (MNIST idx, CIFAR-10 binary batches) when a
--data-dir is supplied.
"""

from __future__ import annotations

import argparse
import gzip
import os
import struct
import sys

# Examples run as scripts (`python examples/foo.py`), where sys.path[0] is
# examples/ — put the repo root first so `import grace_tpu` resolves without
# an install step. Examples import this module before grace_tpu.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# Honor JAX_PLATFORMS=cpu even where a sitecustomize pre-imports jax and pins
# an accelerator platform (ignoring the env var set at launch). Re-asserting
# via jax.config is legal until the first backend initializes, so it must
# happen here — before any grace_tpu/jax device touch.
from grace_tpu.parallel import relax_cpu_collective_timeouts

relax_cpu_collective_timeouts()  # N device threads on a few-core host

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    import re as _re

    import jax as _jax
    _jax.config.update("jax_platforms", "cpu")
    _m = _re.search(r"--xla_force_host_platform_device_count=(\d+)",
                    os.environ.get("XLA_FLAGS", ""))
    if _m:
        from grace_tpu.parallel import set_cpu_device_count
        set_cpu_device_count(int(_m.group(1)))

import numpy as np

GRACE_FLAG_DOC = """GRACE compression flags (reference params-dict schema,
grace_dl/dist/helper.py): --compressor/--memory/--communicator select the
triad; per-algorithm hyperparameters have the reference defaults."""


def add_grace_args(parser: argparse.ArgumentParser) -> None:
    g = parser.add_argument_group("grace", GRACE_FLAG_DOC)
    g.add_argument("--compressor", default="none",
                   help="none|fp16|topk|randomk|threshold|qsgd|homoqsgd|"
                        "countsketch|terngrad|signsgd|signum|efsignsgd|"
                        "onebit|natural|dgc|powersgd|u8bit|sketch|adaq|"
                        "inceptionn")
    g.add_argument("--memory", default="none",
                   help="none|residual|efsignsgd|dgc|powersgd")
    g.add_argument("--communicator", default="allgather",
                   help="allreduce|allgather|broadcast|sign_allreduce|"
                        "twoshot|ring|hier|identity")
    g.add_argument("--slice-size", type=int, default=None,
                   help="with --communicator hier: ranks per ICI slice "
                        "(the two-level schedule needs whole slices)")
    g.add_argument("--compress-ratio", type=float, default=0.01)
    g.add_argument("--quantum-num", type=int, default=64)
    g.add_argument("--threshold", type=float, default=0.01)
    g.add_argument("--momentum", type=float, default=0.9)
    g.add_argument("--compress-rank", type=int, default=4,
                   help="PowerSGD rank")
    g.add_argument("--fusion", default="flat",
                   help="flat|grouped|none|<bytes> — gradient fusion buffer")
    g.add_argument("--topk-algorithm", default="exact",
                   help="exact|approx|chunk — top-k selection strategy")
    g.add_argument("--recall-target", type=float, default=0.95,
                   help="recall for --topk-algorithm approx")
    g.add_argument("--use-pallas", default="auto",
                   choices=["auto", "on", "off"],
                   help="fused Pallas kernels (qsgd quantize, chunk top-k "
                        "local pipeline): auto = each compressor's default "
                        "(staged since round 4's on-chip A/B); on = force")
    g.add_argument("--memory-dtype", default=None,
                   help="storage dtype for the residual memory state "
                        "(e.g. bfloat16 halves its HBM traffic; round-4 "
                        "grace-tpu extension, ResidualMemory.state_dtype)")
    g.add_argument("--seed", type=int, default=42)


def grace_params_from_args(args) -> dict:
    fusion = args.fusion
    if fusion in ("none", "None", ""):
        fusion = None
    elif fusion not in ("flat", "grouped"):
        fusion = int(fusion)
    params = {
        "compressor": args.compressor,
        "memory": args.memory,
        "communicator": args.communicator,
        "compress_ratio": args.compress_ratio,
        "quantum_num": args.quantum_num,
        "threshold": args.threshold,
        "momentum": args.momentum,
        "compress_rank": args.compress_rank,
        "fusion": fusion,
        "topk_algorithm": args.topk_algorithm,
        "recall_target": args.recall_target,
    }
    if getattr(args, "slice_size", None):
        params["slice_size"] = args.slice_size
    # Only force use_pallas when the operator explicitly asked: the flag's
    # resting default must leave each compressor's own default in charge —
    # 'auto' resolves per the measured on-chip A/Bs (TopK: staged; QSGD:
    # kernel on TPU since the round-5 measurement, see TRAINING.md).
    if args.use_pallas != "auto":
        params["use_pallas"] = args.use_pallas == "on"
    if getattr(args, "memory_dtype", None):
        if args.memory != "residual":
            # Fail fast like the library does for a bad dtype string: the
            # knob only exists on ResidualMemory, and a silently ignored
            # flag would leave the operator believing the state is narrow.
            raise SystemExit(
                f"--memory-dtype applies only to --memory residual "
                f"(got --memory {args.memory})")
        params["memory_dtype"] = args.memory_dtype
    return params


def grace_provenance(args) -> dict:
    """The grace-config fields every curve evidence file must carry —
    one place, so a new curve-affecting knob (round-4 case:
    --memory-dtype) cannot be added without its provenance stamp."""
    prov = {"compressor": args.compressor, "memory": args.memory,
            "communicator": args.communicator,
            # fusion changes selection semantics (flat = global-k,
            # none = per-tensor-k, the round-5 headline mode) — a curve
            # without it is ambiguous evidence.
            "fusion": args.fusion}
    if getattr(args, "memory_dtype", None):
        prov["memory_dtype"] = args.memory_dtype
    if args.compressor == "topk":
        prov["topk_algorithm"] = args.topk_algorithm
    return prov


# ---------------------------------------------------------------------------
# Datasets
# ---------------------------------------------------------------------------

def _synthetic_classification(n, seed, shape, noise, proto_seed):
    """Class-conditional data: 10 fixed prototype images + per-sample noise.
    The prototypes come from ``proto_seed`` so train/test splits built with
    different ``seed`` values share the same underlying task."""
    protos = np.random.default_rng(proto_seed).standard_normal(
        (10, *shape)).astype(np.float32)
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, n).astype(np.int32)
    x = protos[y] + noise * rng.standard_normal((n, *shape)).astype(np.float32)
    return x, y


def synthetic_mnist(n: int, seed: int = 0, proto_seed: int = 1234):
    """Synthetic digits, separable enough that LeNet exceeds 95% quickly."""
    return _synthetic_classification(n, seed, (28, 28, 1), 0.3, proto_seed)


def synthetic_cifar10(n: int, seed: int = 0, proto_seed: int = 1234):
    return _synthetic_classification(n, seed, (32, 32, 3), 0.5, proto_seed)


def load_mnist_idx(data_dir: str, train: bool = True):
    """Read the standard MNIST idx(.gz) files from ``data_dir``."""
    prefix = "train" if train else "t10k"

    def _open(name):
        for cand in (os.path.join(data_dir, name),
                     os.path.join(data_dir, name + ".gz")):
            if os.path.exists(cand):
                return gzip.open(cand, "rb") if cand.endswith(".gz") \
                    else open(cand, "rb")
        raise FileNotFoundError(f"{name}[.gz] not found under {data_dir}")

    with _open(f"{prefix}-images-idx3-ubyte") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad idx magic {magic}"
        x = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols, 1)
    with _open(f"{prefix}-labels-idx1-ubyte") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad idx magic {magic}"
        y = np.frombuffer(f.read(), np.uint8).astype(np.int32)
    x = (x.astype(np.float32) / 255.0 - 0.1307) / 0.3081
    return x, y


BUNDLED_MNIST_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "data", "MNIST", "raw")


def load_mnist_auto(data_dir: str, split_seed: int = 0):
    """(x_train, y_train, x_test, y_test), normalized, from whatever MNIST
    files ``data_dir`` holds: the full train/t10k pair when present, else a
    deterministic 8,000/2,000 split of the t10k set alone (the bundled
    fixture case — see grace_tpu.data.mnist_split_dataset)."""
    has_full = any(
        os.path.exists(os.path.join(data_dir, "train-images-idx3-ubyte" + s))
        for s in ("", ".gz"))
    if has_full:
        return (*load_mnist_idx(data_dir, train=True),
                *load_mnist_idx(data_dir, train=False))
    from grace_tpu.data import mnist_split_dataset
    tr = mnist_split_dataset(data_dir, train=True, split_seed=split_seed)
    te = mnist_split_dataset(data_dir, train=False, split_seed=split_seed)
    # Eval uses the train stats (the torchvision convention).
    return (tr.normalize(tr.images), tr.labels,
            tr.normalize(te.images), te.labels)


def load_cifar10_binary(data_dir: str, train: bool = True):
    """Read CIFAR-10 binary batches (data_batch_*.bin / test_batch.bin)."""
    names = [f"data_batch_{i}.bin" for i in range(1, 6)] if train \
        else ["test_batch.bin"]
    xs, ys = [], []
    for name in names:
        path = os.path.join(data_dir, name)
        raw = np.fromfile(path, np.uint8).reshape(-1, 3073)
        ys.append(raw[:, 0].astype(np.int32))
        xs.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
    x = np.concatenate(xs).astype(np.float32) / 255.0
    y = np.concatenate(ys)
    mean = np.array([0.4914, 0.4822, 0.4465], np.float32)
    std = np.array([0.2471, 0.2435, 0.2616], np.float32)
    return (x - mean) / std, y


def batches(x, y, batch_size: int, *, shuffle: bool, seed: int,
            drop_last: bool = True):
    """Shuffled minibatch iterator over host arrays."""
    n = x.shape[0]
    idx = np.arange(n)
    if shuffle:
        np.random.default_rng(seed).shuffle(idx)
    stop = n - (n % batch_size) if drop_last else n
    for i in range(0, stop, batch_size):
        sel = idx[i:i + batch_size]
        yield x[sel], y[sel]


def compute_dtype():
    """bf16 on TPU (MXU-native), f32 elsewhere (bf16 is emulated-slow on CPU)."""
    import jax
    import jax.numpy as jnp
    return jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
