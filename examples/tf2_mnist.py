"""TF2 MNIST with DistributedGradientTape — the reference's TF2 tape path.

TPU-native port of the reference's examples/tensorflow/tensorflow2_mnist.py
(:64-99): a small CNN trained in eager/`tf.function` mode where
`tape.gradient` returns globally aggregated, compressed-exchanged gradients.
The exchange itself runs as one jitted JAX/XLA program on the device mesh;
TF only supplies/consumes gradients (grace_tpu/interop/tensorflow.py).

Run (simulated 8-device mesh; TF stays on CPU):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/tf2_mnist.py --steps 200 \\
        --compressor topk --compress-ratio 0.1 --memory residual
"""

from __future__ import annotations

import argparse

import numpy as np

import common


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    common.add_grace_args(parser)
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--lr", type=float, default=0.001)
    parser.add_argument("--train-size", type=int, default=8192)
    parser.add_argument("--data-dir", default=None,
                        help="MNIST idx directory (default: synthetic)")
    parser.add_argument("--ckpt-dir", default=None,
                        help="rank-0 tf.train.Checkpoint directory")
    args = parser.parse_args()

    import jax
    import tensorflow as tf

    from grace_tpu import grace_from_params
    from grace_tpu.interop.tensorflow import (DistributedGradientTape,
                                              broadcast_variables)
    from grace_tpu.parallel import data_parallel_mesh, initialize_distributed
    from grace_tpu.utils import rank_zero_print

    initialize_distributed()
    mesh = data_parallel_mesh()
    grc = grace_from_params(common.grace_params_from_args(args))

    if args.data_dir:
        x, y = common.load_mnist_idx(args.data_dir, train=True)
    else:
        x, y = common.synthetic_mnist(args.train_size, seed=args.seed)
    ds = (tf.data.Dataset.from_tensor_slices(
            (x.astype(np.float32), y.astype(np.int64)))
          .shuffle(8192, seed=args.seed).repeat()
          .batch(args.batch_size, drop_remainder=True))

    # Reference model shape (tensorflow2_mnist.py:38-47): conv-pool x2 + MLP.
    tf.random.set_seed(args.seed)
    model = tf.keras.Sequential([
        tf.keras.layers.Conv2D(32, 3, activation="relu"),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Conv2D(64, 3, activation="relu"),
        tf.keras.layers.MaxPooling2D(),
        tf.keras.layers.Flatten(),
        tf.keras.layers.Dense(128, activation="relu"),
        tf.keras.layers.Dense(10),
    ])
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)
    opt = tf.keras.optimizers.Adam(args.lr)

    def training_step(images, labels, first_batch):
        with tf.GradientTape() as tape:
            logits = model(images, training=True)
            loss = loss_fn(labels, logits)
        tape = DistributedGradientTape(tape, grc, mesh=mesh, seed=args.seed)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        # Broadcast initial state after the first step so lazily created
        # variables (conv kernels, Adam slots) exist — same protocol as the
        # reference (tensorflow2_mnist.py:82-84).
        if first_batch:
            broadcast_variables(model.variables)
            broadcast_variables(opt.variables)
        return loss

    for step, (images, labels) in enumerate(ds.take(args.steps)):
        loss = training_step(images, labels, step == 0)
        if step % 10 == 0:
            rank_zero_print(f"step {step:5d}  loss {float(loss):.4f}")

    if args.ckpt_dir and jax.process_index() == 0:
        tf.train.Checkpoint(model=model).save(args.ckpt_dir + "/ckpt")
        rank_zero_print(f"checkpoint saved to {args.ckpt_dir}")


if __name__ == "__main__":
    main()
