"""TF2 synthetic throughput benchmark through the compressed tape path.

TPU-native port of the reference's
examples/tensorflow/tensorflow2_synthetic_benchmark.py (:46-49, :97): a
Keras-applications model on random data, timed img/sec over warm iterations,
with gradients exchanged through DistributedGradientTape — i.e. the same
fused JAX/XLA compression pipeline as every other frontend, fed by TF.

Run (simulated 8-device mesh; TF stays on CPU):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/tf2_synthetic_benchmark.py --model small \\
        --compressor signsgd --num-iters 3
"""

from __future__ import annotations

import argparse
import time

import numpy as np

import common


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    common.add_grace_args(parser)
    parser.set_defaults(compressor="signsgd", memory="none",
                        communicator="allgather")
    parser.add_argument("--model", default="small",
                        help="small (3-conv CNN) | resnet50 (keras "
                             "applications, ImageNet shapes)")
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--num-iters", type=int, default=5,
                        help="timed iterations")
    parser.add_argument("--num-batches-per-iter", type=int, default=5)
    parser.add_argument("--num-warmup-batches", type=int, default=2)
    parser.add_argument("--lr", type=float, default=0.01)
    args = parser.parse_args()

    import tensorflow as tf

    from grace_tpu import grace_from_params
    from grace_tpu.interop.tensorflow import DistributedGradientTape
    from grace_tpu.parallel import data_parallel_mesh, initialize_distributed
    from grace_tpu.utils import rank_zero_print

    initialize_distributed()
    mesh = data_parallel_mesh()
    grc = grace_from_params(common.grace_params_from_args(args))

    tf.random.set_seed(args.seed)
    if args.model == "resnet50":
        model = tf.keras.applications.ResNet50(weights=None)
        hw, classes = 224, 1000
    else:
        model = tf.keras.Sequential([
            tf.keras.layers.Conv2D(32, 3, activation="relu"),
            tf.keras.layers.MaxPooling2D(),
            tf.keras.layers.Conv2D(64, 3, activation="relu"),
            tf.keras.layers.MaxPooling2D(),
            tf.keras.layers.Conv2D(64, 3, activation="relu"),
            tf.keras.layers.Flatten(),
            tf.keras.layers.Dense(10),
        ])
        hw, classes = 32, 10

    rng = np.random.default_rng(args.seed)
    images = tf.constant(
        rng.standard_normal((args.batch_size, hw, hw, 3)), tf.float32)
    labels = tf.constant(rng.integers(0, classes, args.batch_size), tf.int64)
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)
    opt = tf.keras.optimizers.SGD(args.lr)

    def step():
        with tf.GradientTape() as tape:
            loss = loss_fn(labels, model(images, training=True))
        tape = DistributedGradientTape(tape, grc, mesh=mesh, seed=args.seed)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        return loss

    for _ in range(args.num_warmup_batches):
        step()

    # Reference protocol: mean +/- 1.96 sigma over num_iters iterations
    # (tensorflow2_synthetic_benchmark.py:46-49).
    rates = []
    for it in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            step()
        dt = time.perf_counter() - t0
        rates.append(args.batch_size * args.num_batches_per_iter / dt)
        rank_zero_print(f"iter {it}: {rates[-1]:.1f} imgs/sec")
    rank_zero_print(f"imgs/sec per worker: {np.mean(rates):.1f} "
                    f"+- {1.96 * np.std(rates):.1f}")


if __name__ == "__main__":
    main()
