"""CIFAR-10 DAWNBench-style training: cifar10-fast ResNet, 24 epochs, TSV log.

TPU-native port of the reference's examples/dist/CIFAR10-dawndist (dawn.py +
core.py): same model family (cifar10-fast ResNet with whitening-free conv
blocks), same piecewise-linear LR schedule shape, same DAWNBench TSV output
(epoch / cumulative hours / top-1). The reference's per-parameter
`grc.step(grad, name)` loop (core.py:203-206) is one jitted fused exchange.

Target from the reference README (examples/dist/CIFAR10-dawndist/README.md:17):
94% test accuracy in 24 epochs on real CIFAR-10 (pass --data-dir with the
binary batches); the synthetic default checks the plumbing anywhere.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import common  # noqa: E402 — sys.path bootstrap so grace_tpu imports resolve
from grace_tpu import grace_from_params
from grace_tpu.models import resnet_cifar
from grace_tpu.parallel import (batch_sharded, data_parallel_mesh,
                                initialize_distributed)
from grace_tpu.train import (init_stateful_train_state, make_eval_step,
                             make_stateful_train_step)
from grace_tpu.utils import (TableLogger, Timer, TSVLogger, rank_zero_print,
                             run_provenance)



def piecewise_linear_lr(step, steps_per_epoch, peak_epoch=5, total_epochs=24,
                        peak_lr=0.4):
    """cifar10-fast schedule: 0→peak at epoch 5, then linear to 0 at 24.

    Short runs (total_epochs <= peak_epoch) pull the peak forward to the
    midpoint so the schedule stays a valid ramp instead of dividing by zero.
    """
    if total_epochs <= peak_epoch:
        peak_epoch = max(1, total_epochs // 2)
    e = step / steps_per_epoch
    return jnp.where(
        e < peak_epoch, peak_lr * e / peak_epoch,
        peak_lr * jnp.maximum(0.0, (total_epochs - e)
                              / max(total_epochs - peak_epoch, 1e-9)))


def augment(x, rng):
    """Standard cifar10-fast augmentation: pad-reflect 4, random crop, flip.
    Fully vectorized — runs in the training wall-clock the DAWNBench metric
    counts, so no per-image Python loop."""
    n = x.shape[0]
    padded = np.pad(x, [(0, 0), (4, 4), (4, 4), (0, 0)], mode="reflect")
    dx = rng.integers(0, 9, n)
    dy = rng.integers(0, 9, n)
    rows = dy[:, None, None] + np.arange(32)[None, :, None]   # (n, 32, 1)
    cols = dx[:, None, None] + np.arange(32)[None, None, :]   # (n, 1, 32)
    out = padded[np.arange(n)[:, None, None], rows, cols]
    flip = rng.random(n) < 0.5
    out[flip] = out[flip, :, ::-1]
    return out


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    common.add_grace_args(parser)
    parser.add_argument("--epochs", type=int, default=24)
    parser.add_argument("--batch-size", type=int, default=512)
    parser.add_argument("--peak-lr", type=float, default=0.4)
    parser.add_argument("--weight-decay", type=float, default=5e-4)
    parser.add_argument("--data-dir", default=None,
                        help="CIFAR-10 binary batches dir (default synthetic)")
    parser.add_argument("--train-size", type=int, default=8192,
                        help="synthetic dataset size")
    parser.add_argument("--no-augment", action="store_true")
    parser.add_argument("--tsv", default="logs.tsv")
    args = parser.parse_args()

    initialize_distributed()
    mesh = data_parallel_mesh()

    if args.data_dir:
        x_train, y_train = common.load_cifar10_binary(args.data_dir, True)
        x_test, y_test = common.load_cifar10_binary(args.data_dir, False)
    else:
        x_train, y_train = common.synthetic_cifar10(args.train_size, args.seed)
        x_test, y_test = common.synthetic_cifar10(2048, args.seed + 1)

    if len(x_train) < args.batch_size or len(x_test) < args.batch_size:
        raise SystemExit(f"--batch-size {args.batch_size} exceeds dataset "
                         f"split sizes ({len(x_train)} train / {len(x_test)} "
                         "test)")
    steps_per_epoch = len(x_train) // args.batch_size
    grace = grace_from_params(common.grace_params_from_args(args))
    schedule = lambda step: piecewise_linear_lr(  # noqa: E731
        step, steps_per_epoch, total_epochs=args.epochs,
        peak_lr=args.peak_lr)
    optimizer = optax.chain(
        grace.transform(seed=args.seed),
        optax.add_decayed_weights(args.weight_decay),
        optax.sgd(schedule, momentum=0.9, nesterov=True))

    params, mstate = resnet_cifar.init(jax.random.key(args.seed))

    def loss_fn(params, mstate, batch):
        xb, yb = batch
        logits, new_mstate = resnet_cifar.apply(
            params, mstate, xb.astype(common.compute_dtype()), train=True)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
        return loss.mean(), new_mstate

    def metric_fn(ps, batch):
        p, ms = ps
        xb, yb = batch
        logits, _ = resnet_cifar.apply(p, ms, xb.astype(common.compute_dtype()),
                                       train=False)
        return {"acc": jnp.mean(jnp.argmax(logits, -1) == yb)}

    step = make_stateful_train_step(loss_fn, optimizer, mesh)
    eval_step = make_eval_step(metric_fn, mesh)
    ts = init_stateful_train_state(params, mstate, optimizer, mesh)

    aug_rng = np.random.default_rng(args.seed)
    # The TSV is an evidence file: it must say on its face whether it
    # trained on real CIFAR-10 (the 94%/24-epoch DAWNBench claim) or the
    # synthetic plumbing-check default, and on what platform.
    prov = run_provenance(
        data=f"real:{args.data_dir}" if args.data_dir else "synthetic",
        recipe="cifar10_dawn 24-epoch DAWNBench",
        epochs=args.epochs, batch_size=args.batch_size,
        **common.grace_provenance(args))
    table, tsv = TableLogger(), TSVLogger(provenance=prov)
    timer = Timer()
    for epoch in range(1, args.epochs + 1):
        xs = x_train if args.no_augment else augment(x_train, aug_rng)
        losses = []
        for xb, yb in common.batches(xs, y_train, args.batch_size,
                                     shuffle=True, seed=args.seed + epoch):
            batch = jax.device_put((jnp.asarray(xb), jnp.asarray(yb)),
                                   batch_sharded(mesh))
            ts, loss = step(ts, batch)
            losses.append(loss)
        # Materialize before reading the clock: steps dispatch asynchronously.
        train_loss = float(jnp.mean(jnp.stack(losses)))
        train_time = timer()

        n_eval = len(x_test) - (len(x_test) % args.batch_size)
        accs = []
        for xb, yb in common.batches(x_test[:n_eval], y_test[:n_eval],
                                     args.batch_size, shuffle=False, seed=0):
            batch = jax.device_put((jnp.asarray(xb), jnp.asarray(yb)),
                                   batch_sharded(mesh))
            accs.append(eval_step((ts.params, ts.model_state), batch)["acc"])
        test_acc = float(jnp.mean(jnp.stack(accs)))
        timer(include_in_total=False)   # DAWNBench: eval time excluded
        row = {"epoch": epoch, "lr": float(schedule(epoch * steps_per_epoch)),
               "train loss": train_loss,
               "train time": train_time, "test acc": test_acc,
               "total time": timer.total_time}
        table.append(row)
        tsv.append(row)
        if jax.process_index() == 0:
            # Rewrite after every epoch: a 24-epoch run on the CPU mesh is
            # hours long, and a killed run must still leave its curve.
            tsv.write(args.tsv)

    if jax.process_index() == 0:
        rank_zero_print(f"TSV log -> {args.tsv}")


if __name__ == "__main__":
    main()
