"""BERT-style encoder with PowerSGD rank-r compressed training.

BASELINE.json config 4 ("BERT-base SQuAD + PowerSGD rank-4, error-feedback").
The reference defers BERT workloads to its external benchmarks repo
(README.md:34); grace-tpu runs the pairing natively: the transformer's 2-D
projection matrices are exactly PowerSGD's target shape, and PowerSGD's
in-compress allreduces (reference grace_dl/dist/compressor/powersgd.py:45-52)
ride ICI inside the same jitted step.

Synthetic sequence-classification task by default (cluster-separable token
sequences); swap in real tokenized data via the obvious hooks.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from grace_tpu import grace_from_params
from grace_tpu.models import transformer
from grace_tpu.parallel import (batch_sharded, data_parallel_mesh,
                                initialize_distributed)
from grace_tpu.train import (init_stateful_train_state,
                             make_stateful_train_step)
from grace_tpu.utils import TableLogger, Timer, rank_zero_print, wire_report

import common


def synthetic_sequences(n, cfg, seed=0):
    """Two-class synthetic text: each class draws tokens from a different
    half of the vocabulary (plus shared noise tokens)."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, cfg.num_classes, n).astype(np.int32)
    half = cfg.vocab_size // cfg.num_classes
    base = rng.integers(0, half, (n, 32)) + y[:, None] * half
    noise = rng.integers(0, cfg.vocab_size, (n, 32))
    use_noise = rng.random((n, 32)) < 0.3
    ids = np.where(use_noise, noise, base).astype(np.int32)
    return ids, y


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    common.add_grace_args(parser)
    parser.set_defaults(compressor="powersgd", memory="powersgd",
                        communicator="allreduce", fusion="none")
    parser.add_argument("--size", default="tiny", help="tiny|base")
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--train-size", type=int, default=8192)
    parser.add_argument("--lr", type=float, default=1e-3)
    args = parser.parse_args()

    initialize_distributed()
    mesh = data_parallel_mesh()

    cfg = transformer.tiny() if args.size == "tiny" else transformer.base()
    params, mstate = transformer.init(jax.random.key(args.seed), cfg)
    ids, y = synthetic_sequences(args.train_size, cfg, args.seed)

    grace = grace_from_params(common.grace_params_from_args(args))
    rank_zero_print(f"PowerSGD rank {args.compress_rank}; wire cost:",
                    wire_report(grace.compressor, params)
                    if args.compressor != "powersgd" else
                    "(PowerSGD communicates P/Q factors inside compress)")
    optimizer = optax.chain(grace.transform(seed=args.seed),
                            optax.adamw(args.lr))

    def loss_fn(params, mstate, batch):
        idb, yb = batch
        logits, new_mstate = transformer.apply(params, mstate, idb, cfg=cfg,
                                               dtype=common.compute_dtype())
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
        return loss.mean(), new_mstate

    step = make_stateful_train_step(loss_fn, optimizer, mesh)
    ts = init_stateful_train_state(params, mstate, optimizer, mesh)

    log, timer = TableLogger(), Timer()
    for epoch in range(1, args.epochs + 1):
        losses = []
        for idb, yb in common.batches(ids, y, args.batch_size, shuffle=True,
                                      seed=args.seed + epoch):
            batch = jax.device_put((jnp.asarray(idb), jnp.asarray(yb)),
                                   batch_sharded(mesh))
            ts, loss = step(ts, batch)
            losses.append(loss)
        log.append({"epoch": epoch,
                    "train loss": float(jnp.mean(jnp.stack(losses))),
                    "epoch time": timer()})


if __name__ == "__main__":
    main()
