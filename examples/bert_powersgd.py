"""BERT-base + PowerSGD rank-4: the BASELINE.json config-4 pairing.

Shape-faithful to "BERT-base SQuAD": a 12-layer/768-hidden/12-head encoder
(`transformer.base()`), sequence length 384 (the standard SQuAD fine-tuning
length), and a span-prediction head — per-token start/end logits, trained
with the sum of start- and end-position cross-entropies. The reference
defers BERT workloads to its external benchmarks repo (README.md:34);
grace-tpu runs the pairing natively: the transformer's 2-D projection
matrices are exactly PowerSGD's target shape, and PowerSGD's in-compress
allreduces (reference grace_dl/dist/compressor/powersgd.py:45-52) ride ICI
inside the same jitted step.

Data is synthetic SQuAD-like QA (no network in this environment): each
context hides one contiguous "answer" span drawn from a reserved vocabulary
range, and the labels are the span's start/end positions — so span accuracy
is learnable and a falling loss demonstrates end-to-end convergence through
the compressed pipeline.

Run on a TPU slice (full size):
    python examples/bert_powersgd.py
Smoke-run on a simulated CPU mesh:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/bert_powersgd.py --size tiny --seq-len 64 \\
        --batch-size 32 --train-size 256 --epochs 2
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import common  # noqa: E402 — sys.path bootstrap so grace_tpu imports resolve
from grace_tpu import grace_from_params
from grace_tpu.models import layers as L
from grace_tpu.models import transformer
from grace_tpu.parallel import (batch_sharded, data_parallel_mesh,
                                initialize_distributed)
from grace_tpu.train import (init_stateful_train_state,
                             make_stateful_train_step)
from grace_tpu.utils import TableLogger, Timer, rank_zero_print, wire_report


def synthetic_squad(n, cfg, seq_len, seed=0):
    """Contexts with one hidden answer span; labels = (start, end).

    Context tokens come from the lower 90% of the vocabulary; the answer
    span (length 1-8) is drawn from the reserved top-10% range, so "where
    is the answer" is inferable from token identity alone — a learnable
    stand-in for extractive QA.
    """
    if seq_len < 16:
        raise ValueError(f"--seq-len must be >=16 (got {seq_len}): contexts "
                         "need room for a 1-8 token answer span")
    rng = np.random.default_rng(seed)
    answer_lo = int(cfg.vocab_size * 0.9)
    ids = rng.integers(0, answer_lo, (n, seq_len)).astype(np.int32)
    span_len = rng.integers(1, 9, n)
    start = rng.integers(0, seq_len - 8, n)
    end = start + span_len - 1
    for i in range(n):
        ids[i, start[i]:end[i] + 1] = rng.integers(
            answer_lo, cfg.vocab_size, span_len[i])
    return ids, np.stack([start, end], 1).astype(np.int32)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    common.add_grace_args(parser)
    parser.set_defaults(compressor="powersgd", memory="powersgd",
                        communicator="allreduce", fusion="none")
    parser.add_argument("--size", default="base", help="base|tiny")
    parser.add_argument("--seq-len", type=int, default=384,
                        help="384 = standard SQuAD fine-tuning length")
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--train-size", type=int, default=1024)
    parser.add_argument("--lr", type=float, default=5e-5)
    args = parser.parse_args()

    initialize_distributed()
    mesh = data_parallel_mesh()

    if args.size == "tiny":
        cfg = transformer.tiny(num_classes=2, max_len=max(64, args.seq_len))
    else:
        cfg = transformer.base(num_classes=2, max_len=args.seq_len)
    params, mstate = transformer.init(jax.random.key(args.seed), cfg)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    rank_zero_print(f"BERT-{args.size}: {n_params/1e6:.1f}M params, "
                    f"seq_len {args.seq_len}")
    ids, spans = synthetic_squad(args.train_size, cfg, args.seq_len, args.seed)

    grace = grace_from_params(common.grace_params_from_args(args))
    rank_zero_print(f"PowerSGD rank {args.compress_rank}; wire cost:",
                    wire_report(grace.compressor, params)
                    if args.compressor != "powersgd" else
                    "(PowerSGD communicates P/Q factors inside compress)")
    optimizer = optax.chain(grace.transform(seed=args.seed),
                            optax.adamw(args.lr))

    def loss_fn(params, mstate, batch):
        idb, spanb = batch
        # Span head: per-token dense → (N, T, 2) → start/end logits (N, T).
        x = transformer.encode(params, idb, cfg, dtype=common.compute_dtype())
        logits = L.dense_apply(params["cls"], x.astype(jnp.float32))
        start_logits, end_logits = logits[..., 0], logits[..., 1]
        loss = (optax.softmax_cross_entropy_with_integer_labels(
                    start_logits, spanb[:, 0])
                + optax.softmax_cross_entropy_with_integer_labels(
                    end_logits, spanb[:, 1]))
        return loss.mean(), mstate

    step = make_stateful_train_step(loss_fn, optimizer, mesh)
    ts = init_stateful_train_state(params, mstate, optimizer, mesh)

    log, timer = TableLogger(), Timer()
    for epoch in range(1, args.epochs + 1):
        losses, n_seq, t0 = [], 0, time.perf_counter()
        for idb, spanb in common.batches(ids, spans, args.batch_size,
                                         shuffle=True, seed=args.seed + epoch):
            batch = jax.device_put((jnp.asarray(idb), jnp.asarray(spanb)),
                                   batch_sharded(mesh))
            ts, loss = step(ts, batch)
            losses.append(loss)
            n_seq += idb.shape[0]
        jax.block_until_ready(losses[-1])
        dt = time.perf_counter() - t0
        log.append({"epoch": epoch,
                    "train loss": float(jnp.mean(jnp.stack(losses))),
                    "epoch time": timer(),
                    "seq/sec": n_seq / dt})


if __name__ == "__main__":
    main()
