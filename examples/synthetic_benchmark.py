"""Synthetic throughput benchmark: ResNet / BERT on random data.

TPU-native port of the reference's examples/torch/pytorch_synthetic_benchmark.py
(and the TF2 twin): fixed random batch, timed iterations, img/sec mean
±1.96σ. Covers BASELINE.json configs 2/3/5 via the grace flags, e.g.:

    python examples/synthetic_benchmark.py --model resnet50 \\
        --compressor topk --compress-ratio 0.01 --memory residual
    python examples/synthetic_benchmark.py --model resnet50 \\
        --compressor qsgd --quantum-num 128
    python examples/synthetic_benchmark.py --model resnet50 \\
        --compressor signsgd --memory residual
    python examples/synthetic_benchmark.py --model bert \\
        --compressor powersgd --memory powersgd --communicator allreduce
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import common  # noqa: E402 — sys.path bootstrap so grace_tpu imports resolve
from grace_tpu import grace_from_params
from grace_tpu.models import resnet, transformer, vgg
from grace_tpu.parallel import (batch_sharded, data_parallel_mesh,
                                initialize_distributed)
from grace_tpu.train import (init_stateful_train_state,
                             make_stateful_train_step)
from grace_tpu.utils import rank_zero_print, wire_report



def build(args, mesh):
    if args.model.startswith("resnet") or args.model.startswith("vgg"):
        prefix = "resnet" if args.model.startswith("resnet") else "vgg"
        net = resnet if prefix == "resnet" else vgg
        spec = args.model[len(prefix):]
        kwargs = {}
        if prefix == "vgg":
            # torchvision naming: vgg16 is plain, vgg16_bn has BatchNorm
            kwargs["batch_norm"] = spec.endswith("_bn")
            spec = spec.removesuffix("_bn")
        if not spec.isdigit() or int(spec) not in net.SUPPORTED_DEPTHS:
            raise SystemExit(f"unknown --model {args.model}")
        params, mstate = net.init(jax.random.key(args.seed), depth=int(spec),
                                  num_classes=args.num_classes, **kwargs)

        def loss_fn(params, mstate, batch):
            x, y = batch
            logits, new_mstate = net.apply(
                params, mstate, x.astype(common.compute_dtype()), train=True)
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            return loss.mean(), new_mstate

        rng = np.random.default_rng(args.seed)
        n = args.batch_size * mesh.devices.size
        data = (jnp.asarray(rng.standard_normal(
                    (n, args.image_size, args.image_size, 3)), jnp.float32),
                jnp.asarray(rng.integers(0, args.num_classes, (n,)),
                            jnp.int32))
    elif args.model == "benchnet":
        # The exact architecture of torch_synthetic_benchmark.py's BenchNet
        # (conv 3→32 s2, conv 32→64 s2, global mean pool, fc 64→512→512→C,
        # biased convs like torch.nn.Conv2d) so `--model benchnet` here vs
        # the torch script is a same-model frontend-overhead comparison
        # (TRAINING.md "Interop overhead").
        from grace_tpu.models import layers as L
        keys = L.split_keys(jax.random.key(args.seed), 5)
        params = {"conv1": L.conv_init(keys[0], 3, 3, 3, 32, use_bias=True),
                  "conv2": L.conv_init(keys[1], 3, 3, 32, 64, use_bias=True),
                  "fc1": L.dense_init(keys[2], 64, 512),
                  "fc2": L.dense_init(keys[3], 512, 512),
                  "fc3": L.dense_init(keys[4], 512, args.num_classes)}
        mstate = {}

        def loss_fn(params, mstate, batch):
            x, y = batch
            x = x.astype(common.compute_dtype())
            x = jax.nn.relu(L.conv_apply(params["conv1"], x, stride=2))
            x = jax.nn.relu(L.conv_apply(params["conv2"], x, stride=2))
            x = x.mean(axis=(1, 2))
            x = jax.nn.relu(L.dense_apply(params["fc1"], x))
            x = jax.nn.relu(L.dense_apply(params["fc2"], x))
            logits = L.dense_apply(params["fc3"], x).astype(jnp.float32)
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            return loss.mean(), mstate

        rng = np.random.default_rng(args.seed)
        n = args.batch_size * mesh.devices.size
        data = (jnp.asarray(rng.standard_normal(
                    (n, args.image_size, args.image_size, 3)), jnp.float32),
                jnp.asarray(rng.integers(0, args.num_classes, (n,)),
                            jnp.int32))
    elif args.model == "bert":
        cfg = transformer.base(num_classes=args.num_classes)
        params, mstate = transformer.init(jax.random.key(args.seed), cfg)

        def loss_fn(params, mstate, batch):
            ids, y = batch
            logits, new_mstate = transformer.apply(
                params, mstate, ids, cfg=cfg, dtype=common.compute_dtype())
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            return loss.mean(), new_mstate

        rng = np.random.default_rng(args.seed)
        n = args.batch_size * mesh.devices.size
        data = (jnp.asarray(rng.integers(0, cfg.vocab_size,
                                         (n, args.seq_len)), jnp.int32),
                jnp.asarray(rng.integers(0, args.num_classes, (n,)),
                            jnp.int32))
    else:
        raise SystemExit(f"unknown --model {args.model}")
    return params, mstate, loss_fn, data


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    common.add_grace_args(parser)
    parser.add_argument("--model", default="resnet50",
                        help="resnet50|resnet101|resnet152|vgg{11,13,16,19}"
                             "[_bn]|bert|benchnet (the torch interop "
                             "benchmark's model, for frontend comparisons)")
    parser.add_argument("--batch-size", type=int, default=32,
                        help="per-device batch (reference default 32)")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--num-iters", type=int, default=10,
                        help="timed iterations (reference protocol: 10)")
    parser.add_argument("--num-batches-per-iter", type=int, default=10)
    parser.add_argument("--num-warmup-batches", type=int, default=10)
    parser.add_argument("--lr", type=float, default=0.01)
    args = parser.parse_args()

    initialize_distributed()
    mesh = data_parallel_mesh()
    params, mstate, loss_fn, data = build(args, mesh)

    grace = grace_from_params(common.grace_params_from_args(args))
    optimizer = optax.chain(grace.transform(seed=args.seed),
                            optax.sgd(args.lr))
    step = make_stateful_train_step(loss_fn, optimizer, mesh)
    ts = init_stateful_train_state(params, mstate, optimizer, mesh)
    batch = jax.device_put(data, batch_sharded(mesh))

    rank_zero_print(f"Model: {args.model}, global batch "
                    f"{batch[1].shape[0]} over {mesh.devices.size} devices")
    rank_zero_print("wire cost:", wire_report(grace.compressor, params))

    loss = None
    for _ in range(args.num_warmup_batches):
        ts, loss = step(ts, batch)
    if loss is not None:
        float(loss)   # true sync: on tunneled platforms only a value fetch
                      # waits for execution (block_until_ready returns early)

    items = batch[1].shape[0] * args.num_batches_per_iter
    unit = "seq" if args.model == "bert" else "img"
    per_iter = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            ts, loss = step(ts, batch)
        float(loss)   # fetch bounds the window (steps are dependent)
        per_iter.append(items / (time.perf_counter() - t0))
        rank_zero_print(f"Iter #{i}: {per_iter[-1]:.1f} {unit}/sec")

    mean = float(np.mean(per_iter))
    rank_zero_print(f"{unit}/sec: {mean:.1f} "
                    f"+-{1.96 * float(np.std(per_iter)):.1f}")
    rank_zero_print(f"{unit}/sec/device: {mean / mesh.devices.size:.1f}")


if __name__ == "__main__":
    main()
