"""PyTorch MNIST through the TPU interop path.

Line-for-line workflow parity with the reference's
examples/torch/pytorch_mnist.py — build a torch CNN, wrap the optimizer with
``DistributedOptimizer(opt, grace, named_parameters=...)``, broadcast initial
state — but the gradient exchange runs as one jitted XLA program on the TPU
mesh instead of per-parameter Horovod NCCL ops.

Each process drives its own model copy on its local batch shard (the
Horovod SPMD model); under `jax.distributed` the mesh spans all processes.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np
import torch
import torch.nn.functional as F

import common  # noqa: E402 — sys.path bootstrap so grace_tpu imports resolve
from grace_tpu import grace_from_params
from grace_tpu.interop.torch import (DistributedOptimizer,
                                     broadcast_optimizer_state,
                                     broadcast_parameters)
from grace_tpu.parallel import data_parallel_mesh, initialize_distributed
from grace_tpu.utils import TableLogger, Timer, rank_zero_print



class Net(torch.nn.Module):
    """The reference example's LeNet-ish CNN (pytorch_mnist.py:73-90)."""

    def __init__(self):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(1, 10, kernel_size=5)
        self.conv2 = torch.nn.Conv2d(10, 20, kernel_size=5)
        self.fc1 = torch.nn.Linear(320, 50)
        self.fc2 = torch.nn.Linear(50, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2(x), 2))
        x = x.flatten(1)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    common.add_grace_args(parser)
    parser.set_defaults(compressor="topk", compress_ratio=0.3,
                        memory="residual")
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--train-size", type=int, default=8192)
    parser.add_argument("--data-dir", default=None)
    args = parser.parse_args()

    initialize_distributed()
    mesh = data_parallel_mesh()
    torch.manual_seed(args.seed)

    if args.data_dir:
        x_train, y_train = common.load_mnist_idx(args.data_dir, train=True)
    else:
        x_train, y_train = common.synthetic_mnist(args.train_size, args.seed)
    # Per-process shard of the dataset (the DistributedSampler analog,
    # reference pytorch_mnist.py:69-70): rank r takes every P-th sample.
    rank, nproc = jax.process_index(), jax.process_count()
    x_train, y_train = x_train[rank::nproc], y_train[rank::nproc]
    # NHWC -> NCHW for torch
    x_train = np.transpose(x_train, (0, 3, 1, 2)).copy()

    model = Net()
    # Initial state sync across processes (reference pytorch_mnist.py:116-117)
    broadcast_parameters(model.state_dict(), root_rank=0)
    optimizer = torch.optim.SGD(model.parameters(), lr=args.lr, momentum=0.5)
    broadcast_optimizer_state(optimizer, root_rank=0)

    grace = grace_from_params(common.grace_params_from_args(args))
    optimizer = DistributedOptimizer(
        optimizer, grace, named_parameters=model.named_parameters(),
        mesh=mesh, seed=args.seed)

    log, timer = TableLogger(), Timer()
    for epoch in range(1, args.epochs + 1):
        model.train()
        losses = []
        for xb, yb in common.batches(x_train, y_train, args.batch_size,
                                     shuffle=True, seed=args.seed + epoch):
            optimizer.zero_grad()
            out = model(torch.from_numpy(xb))
            loss = F.nll_loss(out, torch.from_numpy(yb).long())
            loss.backward()
            optimizer.step()
            losses.append(loss.item())
        log.append({"epoch": epoch, "train loss": float(np.mean(losses)),
                    "epoch time": timer()})


if __name__ == "__main__":
    main()
