"""PyTorch synthetic throughput benchmark through the TPU interop path.

Workflow parity with the reference's flagship benchmark
(examples/torch/pytorch_synthetic_benchmark.py: torchvision model, fixed
random batch, img/sec mean ±1.96σ over timed iterations), driven through
``DistributedOptimizer`` so the compressed exchange runs as one jitted XLA
program. torchvision is not a dependency here, so the model is a first-party
torch ResNet-ish CNN whose parameter count is dominated by a wide classifier
— communication-bound like the reference's default, at a CPU-torch-friendly
scale (the reference assumes a GPU for the backward pass; this image's torch
is CPU-only, SURVEY.md §2.9).

Run (simulated 8-device mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/torch_synthetic_benchmark.py \\
        --compressor signum --memory residual   # the reference's active grc
"""

from __future__ import annotations

import argparse
import time

import numpy as np
import torch
import torch.nn.functional as F

import common  # noqa: E402 — sys.path bootstrap so grace_tpu imports resolve
from grace_tpu import grace_from_params
from grace_tpu.interop.torch import DistributedOptimizer, broadcast_parameters
from grace_tpu.parallel import data_parallel_mesh, initialize_distributed
from grace_tpu.utils import rank_zero_print


class BenchNet(torch.nn.Module):
    """Small conv trunk + wide head: most parameters sit in the exchange."""

    def __init__(self, width: int = 512, num_classes: int = 1000):
        super().__init__()
        self.conv1 = torch.nn.Conv2d(3, 32, 3, stride=2, padding=1)
        self.conv2 = torch.nn.Conv2d(32, 64, 3, stride=2, padding=1)
        self.fc1 = torch.nn.Linear(64, width)
        self.fc2 = torch.nn.Linear(width, width)
        self.fc3 = torch.nn.Linear(width, num_classes)

    def forward(self, x):
        x = F.relu(self.conv1(x))
        x = F.relu(self.conv2(x))
        x = x.mean(dim=(2, 3))
        x = F.relu(self.fc1(x))
        x = F.relu(self.fc2(x))
        return self.fc3(x)


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    common.add_grace_args(parser)
    parser.set_defaults(compressor="signum", memory="residual")
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--image-size", type=int, default=64)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--num-batches-per-iter", type=int, default=10)
    parser.add_argument("--num-warmup-batches", type=int, default=10)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--bucket-cap-mb", type=float, default=32.0,
                        help="exchange bucket size for backward overlap; "
                             "0 = one fused launch at the last grad hook")
    args = parser.parse_args()

    initialize_distributed()
    mesh = data_parallel_mesh()
    torch.manual_seed(args.seed)

    model = BenchNet(num_classes=args.num_classes)
    n_params = sum(p.numel() for p in model.parameters())
    rank_zero_print(f"Model: BenchNet, {n_params / 1e6:.1f}M params, "
                    f"batch {args.batch_size}/process")

    grace = grace_from_params(common.grace_params_from_args(args))
    broadcast_parameters(model.state_dict(), root_rank=0)
    opt = torch.optim.SGD(model.parameters(), lr=args.lr)
    opt = DistributedOptimizer(opt, grace,
                               named_parameters=model.named_parameters(),
                               mesh=mesh, seed=args.seed,
                               bucket_cap_mb=args.bucket_cap_mb or None)

    rng = np.random.default_rng(args.seed)
    data = torch.from_numpy(rng.standard_normal(
        (args.batch_size, 3, args.image_size, args.image_size)
    ).astype(np.float32))
    target = torch.from_numpy(rng.integers(
        0, args.num_classes, (args.batch_size,)).astype(np.int64))

    def run_batch():
        opt.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        opt.step()

    for _ in range(args.num_warmup_batches):
        run_batch()

    per_iter = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            run_batch()
        dt = time.perf_counter() - t0
        ips = args.batch_size * args.num_batches_per_iter / dt
        per_iter.append(ips)
        rank_zero_print(f"Iter #{i}: {ips:.1f} img/sec per process")

    mean = float(np.mean(per_iter))
    rank_zero_print(f"Img/sec per process: {mean:.1f} "
                    f"+-{1.96 * float(np.std(per_iter)):.1f}")


if __name__ == "__main__":
    main()
