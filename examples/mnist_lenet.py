"""MNIST LeNet with compressed data-parallel training — the flagship example.

TPU-native port of the reference's examples/torch/pytorch_mnist.py and
examples/tensorflow/tensorflow2_mnist.py (BASELINE.json config 1): LeNet on
MNIST, GRACE triad configurable from the CLI, per-epoch eval with cross-rank
metric averaging, rank-0 checkpointing.

Run (simulated 8-device mesh):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/mnist_lenet.py --epochs 2 \\
        --compressor topk --compress-ratio 0.1 --memory residual

On a TPU slice just run it plainly; the mesh spans all visible chips.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import common  # noqa: E402 — sys.path bootstrap so grace_tpu imports resolve
from grace_tpu import grace_from_params
from grace_tpu.models import lenet
from grace_tpu.parallel import (batch_sharded, data_parallel_mesh,
                                initialize_distributed)
from grace_tpu.train import (init_stateful_train_state, make_eval_step,
                             make_stateful_train_step)
from grace_tpu.utils import TableLogger, Timer, rank_zero_print, wire_report



def main():
    parser = argparse.ArgumentParser(description=__doc__)
    common.add_grace_args(parser)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=512,
                        help="global batch (split across the mesh)")
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--train-size", type=int, default=16384,
                        help="synthetic dataset size (with --synthetic)")
    parser.add_argument("--data-dir", default=common.BUNDLED_MNIST_DIR,
                        help="directory with MNIST idx files (default: the "
                             "bundled 10k-image fixture set, split 80/20)")
    parser.add_argument("--synthetic", action="store_true",
                        help="train on synthetic digits instead of the "
                             "bundled real MNIST images")
    parser.add_argument("--ckpt-dir", default=None,
                        help="save a checkpoint here after training")
    args = parser.parse_args()

    initialize_distributed()
    mesh = data_parallel_mesh()
    world = mesh.devices.size
    if args.batch_size % world:
        raise SystemExit(f"--batch-size {args.batch_size} must divide by "
                         f"the {world}-device mesh")

    if args.synthetic or not args.data_dir:
        x_train, y_train = common.synthetic_mnist(args.train_size, args.seed)
        x_test, y_test = common.synthetic_mnist(4096, args.seed + 1)
    else:
        x_train, y_train, x_test, y_test = common.load_mnist_auto(
            args.data_dir)
        rank_zero_print(f"real MNIST from {args.data_dir}: "
                        f"{len(x_train)} train / {len(x_test)} test")

    if len(x_train) < args.batch_size or len(x_test) < args.batch_size:
        raise SystemExit(f"--batch-size {args.batch_size} exceeds dataset "
                         f"split sizes ({len(x_train)} train / {len(x_test)} "
                         "test)")
    grace_params = common.grace_params_from_args(args)
    grace = grace_from_params(grace_params)
    optimizer = optax.chain(grace.transform(seed=args.seed),
                            optax.sgd(args.lr, momentum=0.9))

    params, mstate = lenet.init(jax.random.key(args.seed))
    rank_zero_print("wire cost:", wire_report(grace.compressor, params))

    def loss_fn(params, mstate, batch):
        xb, yb = batch
        logits, new_mstate = lenet.apply(params, mstate, xb)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
        return loss.mean(), new_mstate

    def metric_fn(params_and_state, batch):
        p, ms = params_and_state
        xb, yb = batch
        logits, _ = lenet.apply(p, ms, xb)
        return {"acc": jnp.mean(jnp.argmax(logits, -1) == yb),
                "loss": optax.softmax_cross_entropy_with_integer_labels(
                    logits, yb).mean()}

    step = make_stateful_train_step(loss_fn, optimizer, mesh)
    eval_step = make_eval_step(metric_fn, mesh)
    ts = init_stateful_train_state(params, mstate, optimizer, mesh)

    log = TableLogger()
    timer = Timer()
    for epoch in range(1, args.epochs + 1):
        losses = []
        for xb, yb in common.batches(x_train, y_train, args.batch_size,
                                     shuffle=True, seed=args.seed + epoch):
            batch = jax.device_put((jnp.asarray(xb), jnp.asarray(yb)),
                                   batch_sharded(mesh))
            ts, loss = step(ts, batch)
            losses.append(loss)
        train_loss = float(jnp.mean(jnp.stack(losses)))
        train_time = timer()

        n_eval = len(x_test) - (len(x_test) % args.batch_size)
        accs = []
        for xb, yb in common.batches(x_test[:n_eval], y_test[:n_eval],
                                     args.batch_size, shuffle=False,
                                     seed=0):
            batch = jax.device_put((jnp.asarray(xb), jnp.asarray(yb)),
                                   batch_sharded(mesh))
            accs.append(eval_step((ts.params, ts.model_state), batch)["acc"])
        test_acc = float(jnp.mean(jnp.stack(accs)))
        log.append({"epoch": epoch, "train loss": train_loss,
                    "epoch time": train_time, "test acc": test_acc})

    if args.ckpt_dir:
        # Collective save: EVERY process calls it (orbax coordinates the
        # shard writes internally) — no rank-0 guard, see grace_tpu/checkpoint.
        from grace_tpu.checkpoint import save_checkpoint
        save_checkpoint(args.ckpt_dir, ts, step=args.epochs)
        rank_zero_print(f"checkpoint (incl. compression state) -> "
                        f"{args.ckpt_dir}")


if __name__ == "__main__":
    main()
