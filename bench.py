"""Headline benchmark: compressed vs uncompressed ResNet-50 training throughput.

Mirrors the reference's synthetic benchmark protocol
(examples/torch/pytorch_synthetic_benchmark.py:180-198: ResNet-50, random
data, img/sec over timed iterations) and the BASELINE.json north star: Top-K
k=1% + residual memory should reach >=90% of the uncompressed-allreduce
throughput. Runs the full GRACE pipeline (compensate -> compress -> update ->
exchange) on the available device mesh.

Always prints ONE JSON line as the last stdout line:
  {"metric": "resnet50_topk1pct_imgs_per_sec", "value": ..., "unit":
   "imgs/sec", "vs_baseline": <compressed/uncompressed ratio>, "platform": ...}

Failure engineering (round-1 postmortem: the TPU tunnel backend hung >9 min
in init and the bench emitted nothing): the measurement runs in a worker
subprocess under a hard timeout; the orchestrator first probes backend init
separately, retries once, and on TPU failure falls back to an 8-device
simulated-CPU mesh so a real number is captured either way. Stage
diagnostics go to stderr; stdout carries only the final JSON line.

The measurement core (`bench_configs`) is shared with bench_all.py, which
sweeps the whole BASELINE.json config list instead of the headline pair.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

PROBE_TIMEOUTS_S = (180, 420)  # healthy tunnel inits in seconds; second
                               # probe gets a long leash for slow cold init
WORKER_TIMEOUT_S = 1200        # full bench incl. first compile (~20-40s/fn)

# On-TPU default measurement shapes (the reference protocol's bs=32 at
# ImageNet 224²). Single source for bench_configs AND bench_all's resume
# shape-match gate (_resume_configs) — duplicated literals once drifted
# risk: a silent mismatch would re-measure (safe) but a collision with old
# rows could replay a wrong-shape row (ADVICE r4).
TPU_DEFAULT_BS = 32
TPU_DEFAULT_HW = 224
TPU_DEFAULT_PDTYPE = "float32"

HEADLINE = [
    # Per-leaf (fusion "none") on BOTH sides — the reference's own dist
    # backend issues one collective per tensor (SURVEY.md §3.3), so the
    # per-tensor pair is protocol-faithful AND measured fastest: the
    # round-5 on-chip A/B at bs=256 (2026-08-01, same session) put
    # per-leaf Top-K at 0.9895x dense (HEADLINE figure = the stamped
    # evidence-table ratio, BENCH_r05/README; per-row ratios use
    # interleaved dense brackets, so the raw row quotient differs in the
    # 4th digit) vs
    # 0.9346x for the fused-flat pair — the whole-model fusion buffer
    # (concat + one monolithic pipeline), not the selection, carries most
    # of the fused overhead. The fused rows stay in bench_all (fusion is
    # the right call on real multi-host meshes where 161 small collectives
    # pay per-launch latency; single-chip the step has no such cost).
    #
    # per_device_bs=256: chosen from the measured on-chip bs sweep
    # (BENCH_ALL_TPU_LAST.json): the fixed compression cost is ~45% of a
    # bs=32 step but amortizes at bs=256 — the batch a throughput-tuned
    # ResNet-50 run would use anyway. The dense baseline is measured at
    # the SAME bs in the same session, so the ratio stays like-for-like;
    # bs=32..256 rows stay in the bench_all sweep for the full curve.
    # (BASELINE.md north star pins no batch size; the reference's
    # synthetic harness default is bs=32, kept as the sweep's first point.)
    {"name": "none", "per_device_bs": 256,
     "params": {"compressor": "none", "memory": "none",
                "communicator": "allreduce",
                "fusion": "none"}},
    # Top-K selection uses the chunked argmax (top-1 per strided chunk, a
    # pure VPU reduction) with the scatter-free one-hot decompress
    # (ops/sparse.py chunkwise_dense). Measured on the chip in one
    # interleaved session (BENCH_ALL_TPU_LAST.json, 2026-07-31): chunk
    # 0.56x dense at bs=32 rising to 0.92x at bs=256 (fused), vs
    # approx_max_k 0.69x (bs=32) and exact-sort far below — both the
    # full-buffer top-k select AND the scatter in decompress were the
    # bottleneck; chunk mode removes both. Selection is DGC-style relaxed
    # (top-1 per chunk, not global top-k); residual error feedback
    # compensates — chunk tracks exact step-for-step on a toy convex
    # problem (2.303->0.534 vs 0.533 at 1% over 120 steps, 8-device mesh)
    # and the real-MNIST curve is committed at
    # examples/logs/mnist10k_topk1pct_chunk.tsv. bench_all.py measures
    # exact/approx/chunk side by side.
    {"name": "topk1pct", "per_device_bs": 256,
     "params": {"compressor": "topk",
                "compress_ratio": 0.01,
                "topk_algorithm": "chunk",
                "memory": "residual",
                "communicator": "allgather",
                "fusion": "none"}},
]


# --------------------------------------------------------------------------
# Measurement core (runs inside a worker subprocess; also used by bench_all)
# --------------------------------------------------------------------------

# Peak dense bf16 FLOP/s per *jax device*, keyed by device_kind substring
# (first match wins; most specific first). v2/v3 expose one device per core,
# v4+ one per chip, hence per-core numbers for the older generations.
# Sources: cloud.google.com/tpu/docs/system-architecture-tpu-vm (public
# per-chip peaks: v2 45T, v3 123T, v4 275T, v5e 197T, v5p 459T, v6e 918T).
PEAK_BF16_FLOPS = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 61.5e12),
    ("v2", 22.5e12),
)


def device_peak_flops(device) -> float | None:
    """Peak bf16 FLOP/s for one jax device, or None if unknown (CPU)."""
    kind = getattr(device, "device_kind", "").lower()
    if device.platform != "tpu":
        return None
    for key, peak in PEAK_BF16_FLOPS:
        if key in kind:
            return peak
    return None


def step_flops(step, ts, batch) -> float | None:
    """Per-device FLOPs of one compiled train step, via XLA cost analysis
    on the lowered (SPMD, per-device) module. Host-side only — no device
    round-trip, so it is safe on a flaky tunnel. None if unavailable."""
    try:
        fn = next(iter(step.jit_cache.values()))
        cost = fn.lower(ts, batch).cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if cost is None:   # AOT/tunnel backends return no analysis; the
            return None    # analytic FLOP model takes over silently
        f = float(cost.get("flops", 0.0))
        return f if f > 0 else None
    except Exception as e:
        print(f"[bench] cost_analysis unavailable: {e}",
              file=sys.stderr, flush=True)
        return None


def setup_platform(platform: str):
    """Pin jax to the requested platform BEFORE any backend init."""
    import jax

    # Persistent compilation cache — TPU only: the two ResNet-50 train-step
    # compiles dominate worker wall-clock on the tunnel (minutes each) and
    # put the run uncomfortably close to WORKER_TIMEOUT_S; any earlier bench
    # run on this host makes later ones compile-free. NOT enabled for the
    # CPU fallback: XLA:CPU caches AOT machine code keyed loosely enough
    # that an entry compiled under different detected CPU features loads
    # with a "could lead to SIGILL" warning — a crash there would cost the
    # fallback number entirely, for a compile that is cheap anyway.
    if platform == "tpu":
        try:
            import tempfile
            cache_dir = os.path.join(tempfile.gettempdir(),
                                     f"grace_tpu_jax_cache_{os.getuid()}")
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        except Exception as e:  # cache is an optimization, not a requirement
            print(f"[bench] compilation cache unavailable: {e}",
                  file=sys.stderr, flush=True)

    if platform == "cpu":
        # Same dance as tests/conftest.py: the image's sitecustomize latches
        # jax onto the TPU tunnel, so env vars alone are not enough.
        jax.config.update("jax_platforms", "cpu")
        from grace_tpu.parallel import (relax_cpu_collective_timeouts,
                                        set_cpu_device_count)
        set_cpu_device_count(8)
        relax_cpu_collective_timeouts()  # 8 device threads, few-core host
    devices = jax.devices()
    if platform == "tpu" and devices[0].platform != "tpu":
        raise RuntimeError(f"wanted tpu, got {devices[0].platform}")
    return devices


# ---------------------------------------------------------------------------
# Multi-chip wire projection (VERDICT round-3 item 6): real multi-chip
# hardware is not reachable from this box, so the bench turns the measured
# single-chip step time plus the analytic per-rank received-bytes model into
# a projected step time and speedup-vs-dense at pod scales. Bandwidth
# constants are the public per-chip numbers (model assumptions, clearly
# labeled in the output): TPU v5e has 4 ICI links per chip in a 2D torus at
# ~45 GB/s per direction per link (scaling-book / TPU system-architecture
# docs); a 1-D ring collective rides 2 links (both torus directions), hence
# ~90 GB/s of per-chip collective bandwidth. DCN (between slices/hosts) is
# ~25 GB/s per host. The projection is a NO-OVERLAP upper bound on wire
# cost: projected_step = measured_single_chip_step + recv_bytes/bandwidth.
ICI_RING_BYTES_PER_S = 9.0e10
DCN_BYTES_PER_S = 2.5e10
# Cross-region (WAN) bandwidth: a documented model assumption, not a
# measured number — inter-metro links budget ~2 Gb/s of sustained
# per-host collective bandwidth (~100x below DCN), the regime where
# compression decides feasibility rather than step time.
WAN_BYTES_PER_S = 2.5e8
PROJECTION_WORLDS = (8, 16, 64, 256)
# Cross-slice scenario topology: slices of 8 chips (the one real v5e slice
# this repo has measured), DCN between them. Drives the per-link
# (ici_bytes, dcn_bytes) split in each projection row via the shared
# Communicator.recv_link_bytes model.
XSLICE_CHIPS = 8
# Three-tier scenario: W=1024 ranks as 4 regions x 256 ranks, slices of
# XSLICE_CHIPS — the cross-region projection row (project_three_tier).
REGION_WORLD = 1024
REGION_CHIPS = 256

# Stamped ONCE per evidence document (_write_evidence) and once in the
# headline JSON line so the numbers carry their own assumptions (VERDICT r4
# item 5: "projections are quoted in every row — they must survive
# scrutiny") without duplicating ~1.2 KB of prose into all 26 sweep rows.
PROJECTION_MODEL = {
    "ici_bytes_per_s": ICI_RING_BYTES_PER_S,
    "dcn_bytes_per_s": DCN_BYTES_PER_S,
    "wan_bytes_per_s": WAN_BYTES_PER_S,
    "constants_source": (
        "TPU v5e: 4 ICI links/chip in a 2D torus, ~45 GB/s per direction "
        "per link (cloud.google.com/tpu/docs/system-architecture-tpu-vm; "
        "jax-ml.github.io/scaling-book/ 'TPU networking'); a 1-D ring "
        "collective rides 2 links -> ~90 GB/s per chip. DCN ~25 GB/s/host "
        "(scaling-book cross-slice figure). WAN ~0.25 GB/s/host of "
        "sustained cross-region collective bandwidth — a MODEL ASSUMPTION "
        "(~100x below DCN), not a measurement."),
    "assumption": (
        "NO-OVERLAP upper bound on wire cost: projected_step = "
        "measured_single_chip_step + recv_bytes/bandwidth. Real XLA "
        "overlaps collectives with compute, so absolute step times are "
        "pessimistic for BOTH sides of the speedup ratio; dense (whose "
        "allreduce overlaps the backward pass) benefits from overlap more "
        "than compressed (whose gather waits on compress), so "
        "speedup_vs_dense is an OPTIMISTIC bound for compression wherever "
        "wire dominates and both get pessimistic step times. Measure the "
        "realized overlap fraction from a device trace with "
        "tools/perf_report.py (grace_tpu.profiling) to close the gap. ONE "
        "declared exception: a double-buffered communicator (pipeline=P "
        "on ring/hier) discounts its own wire leg by its "
        "wire_overlap_fraction() — a claim flow pass 5 referees "
        "statically (the traced graph must expose >= P independent "
        "chains) and the row stamps as wire_pipeline_overlap."),
    "per_link": (
        f"each row's xslice block splits received bytes by link class via "
        f"Communicator.recv_link_bytes under a Topology(slice_size="
        f"{XSLICE_CHIPS}) and prices ici/dcn separately. Flat communicators "
        "degenerate to all-DCN the moment the axis crosses slices (the "
        "critical rank's incoming ring link is the slice boundary); "
        "HierarchicalAllreduce (communicator='hier') overrides "
        "recv_link_bytes with the genuinely mixed split of its two-level "
        "schedule — ~2·k·(S-1)/S on ICI, (K-1)·k/S on DCN — which is what "
        "flips the W=256 xslice speedup above 1x dense for topk1pct; "
        "graft-lint's wire_reconciliation pass audits the split "
        "leg-by-leg against the traced collectives."),
    "three_tier": (
        f"the region block projects W={REGION_WORLD} as "
        f"{REGION_WORLD // REGION_CHIPS} regions x {REGION_CHIPS} ranks "
        f"(slices of {XSLICE_CHIPS}) under Topology(slice_size="
        f"{XSLICE_CHIPS}, region_size={REGION_CHIPS}), pricing each leg "
        "at its own bandwidth. A flat two-tier hier comm's whole "
        "cross-slice leg crosses regions (its groups mix regions), so it "
        "prices at WAN; the three-level schedule keeps (K/R-1) partials "
        "on DCN and ships only (R-1) shards across WAN — the gap that "
        "makes cross-region training feasible at all under the WAN "
        "constant."),
}


def recv_bytes_model(comm, vote: bool, payload_b: int, n_elems: int,
                     w: int) -> int:
    """Received bytes per rank per step at world size ``w`` — the
    communicator-aware wire number (payload bytes alone are communicator-
    blind and cannot show e.g. twoshot's O(k) vs allgather's O(W·k)).
    Delegates to ``Communicator.recv_wire_bytes`` — ONE model shared by the
    live-mesh measurement, the multi-chip projection, and the in-graph
    telemetry ring's wire_bytes field, so the three can never disagree.
    (Formulas: allgather (W-1)·payload; allreduce/twoshot/ring ride ring
    schedules at ~2·payload·(W-1)/W; vote psums move dense bf16 ±1s.)"""
    return comm.recv_wire_bytes(payload_b, n_elems, w, vote=vote)


def project_multichip(step_s: float, dense_step_s: float, grace,
                      wire_b: int, dense_b: int, n_elems: int) -> list:
    """Projected per-step wire cost and speedup-vs-dense at pod scales.
    Dense rides a ring allreduce — priced through the same shared
    ``Communicator.recv_link_bytes`` model as the compressed config, so
    the two sides of every ratio can never use different wire math.

    Three scenarios per world: all-ICI (one giant slice), all-DCN (the
    legacy flat pessimum), and ``xslice`` — slices of ``XSLICE_CHIPS``
    chips with the per-link (ici_bytes, dcn_bytes) split priced at each
    link's own bandwidth. For today's flat communicators xslice collapses
    to the DCN leg beyond one slice (see recv_link_bytes); it exists so a
    hierarchical communicator's mixed split is projected honestly."""
    from grace_tpu.comm import Allreduce
    from grace_tpu.core import Topology

    vote = getattr(grace.compressor, "vote_aggregate", False)
    dense_comm = Allreduce()
    xtopo = Topology(slice_size=XSLICE_CHIPS)
    # wire_pipeline discount (ISSUE 19): the ONE exception to the
    # NO-OVERLAP assumption — a double-buffered communicator (pipeline=P
    # on ring/hier) declares its own overlap fraction
    # (WIRE_PIPELINE_EFFICIENCY · (P−1)/P), statically refereed by flow
    # pass 5's >= P independent-chain requirement, so only its wire leg is
    # scaled by (1 − overlap). Dense always keeps the undiscounted bound.
    keep = 1.0 - float(getattr(grace.communicator, "wire_overlap_fraction",
                               lambda: 0.0)())
    out = []
    for w in PROJECTION_WORLDS:
        cfg_recv = recv_bytes_model(grace.communicator, vote, wire_b,
                                    n_elems, w)
        dense_recv = dense_comm.recv_wire_bytes(dense_b, n_elems, w)
        row = {"world": w, "recv_bytes_per_rank": cfg_recv}
        if keep < 1.0:
            row["wire_pipeline_overlap"] = round(1.0 - keep, 6)
        for net, bw in (("ici", ICI_RING_BYTES_PER_S),
                        ("dcn", DCN_BYTES_PER_S)):
            t_cfg = step_s + cfg_recv / bw * keep
            t_dense = dense_step_s + dense_recv / bw
            row[f"step_ms_{net}"] = round(t_cfg * 1e3, 3)
            row[f"speedup_vs_dense_{net}"] = round(t_dense / t_cfg, 3)
        cfg_link = grace.communicator.recv_link_bytes(
            wire_b, n_elems, w, topology=xtopo, vote=vote)
        dense_link = dense_comm.recv_link_bytes(
            dense_b, n_elems, w, topology=xtopo)

        def t_split(base_s, link, keep=1.0):
            return (base_s + (link.ici / ICI_RING_BYTES_PER_S
                              + link.dcn / DCN_BYTES_PER_S) * keep)

        t_cfg = t_split(step_s, cfg_link, keep)
        row["xslice"] = {
            "slice_size": XSLICE_CHIPS,
            "ici_bytes": cfg_link.ici,
            "dcn_bytes": cfg_link.dcn,
            "step_ms": round(t_cfg * 1e3, 3),
            "speedup_vs_dense": round(
                t_split(dense_step_s, dense_link) / t_cfg, 3),
        }
        out.append(row)
    return out


def project_three_tier(step_s: float, dense_step_s: float, grace,
                       wire_b: int, dense_b: int, n_elems: int) -> dict:
    """The W=1024 cross-region projection row: this config's codec at 4
    regions × 256 ranks (slices of ``XSLICE_CHIPS``), with each leg of the
    per-link split priced at its own bandwidth — ICI / DCN / WAN.

    Three schedules over the SAME codec payload, all through the one
    shared ``recv_link_bytes`` model: ``dense`` (flat ring, whole bill at
    WAN — the critical rank's incoming link crosses regions),
    ``flat_two_tier_hier`` (slices only: its cross-slice groups mix
    regions, so the (K−1)·k/S partial-exchange leg ALSO prices at WAN),
    and ``three_tier_hier`` (the three-level schedule: cross-slice
    partials stay on DCN inside each region; only (R−1) shards cross
    WAN). Under the ~100×-below-DCN WAN constant the three-level schedule
    is what keeps the projected step bounded at all — the row exists to
    make that gap a quoted number rather than prose."""
    from grace_tpu.comm import Allreduce, HierarchicalAllreduce
    from grace_tpu.core import Topology

    w = REGION_WORLD
    vote = getattr(grace.compressor, "vote_aggregate", False)
    topo3 = Topology(slice_size=XSLICE_CHIPS, region_size=REGION_CHIPS)

    def t_split(base_s, link):
        return (base_s + link.ici / ICI_RING_BYTES_PER_S
                + link.dcn / DCN_BYTES_PER_S + link.wan / WAN_BYTES_PER_S)

    def leg(link):
        return {"ici_bytes": int(link.ici), "dcn_bytes": int(link.dcn),
                "wan_bytes": int(link.wan)}

    dense_link = Allreduce().recv_link_bytes(
        dense_b, n_elems, w, topology=topo3)
    hier2_link = HierarchicalAllreduce(
        slice_size=XSLICE_CHIPS).recv_link_bytes(
            wire_b, n_elems, w, topology=topo3, vote=vote)
    hier3_link = HierarchicalAllreduce(
        slice_size=XSLICE_CHIPS, region_size=REGION_CHIPS).recv_link_bytes(
            wire_b, n_elems, w, topology=topo3, vote=vote)

    t_dense = t_split(dense_step_s, dense_link)
    t_hier2 = t_split(step_s, hier2_link)
    t_hier3 = t_split(step_s, hier3_link)
    return {
        "world": w,
        "slice_size": XSLICE_CHIPS,
        "region_size": REGION_CHIPS,
        "regions": w // REGION_CHIPS,
        "dense": {**leg(dense_link),
                  "step_ms": round(t_dense * 1e3, 3)},
        "flat_two_tier_hier": {**leg(hier2_link),
                               "step_ms": round(t_hier2 * 1e3, 3)},
        "three_tier_hier": {**leg(hier3_link),
                            "step_ms": round(t_hier3 * 1e3, 3),
                            "speedup_vs_dense": round(t_dense / t_hier3, 3),
                            "speedup_vs_flat_hier": round(
                                t_hier2 / t_hier3, 3)},
    }


def throughput(step, ts, batch, n_batches, warmup=2):
    """Fetch-bounded step timing; returns (items/sec, new_state).

    On the axon tunnel block_until_ready does not wait for device execution
    — only a value fetch synchronizes. Drain with a fetch, time n dependent
    steps bounded by a final fetch, and subtract the measured fetch RTT
    (~65 ms) so the window covers device execution, not tunnel latency.
    Module-level so model-specific benches (tools/tpu_bert_bench.py) share
    the exact timing discipline."""
    for _ in range(warmup):
        ts, loss = step(ts, batch)
    float(loss)
    # The probe program (scalar add + fetch) must be compiled BEFORE the
    # timed RTT measurement — its first dispatch pays a multi-second
    # compile on the tunnel, which once inflated rtt past the whole
    # measurement window and collapsed dt to the 1e-9 clamp. Median of 3
    # samples: a single jittery RTT (tunnel hiccups of 100+ ms happen)
    # once moved the dense headline by 2x when the window was short.
    float(loss + 1.0)
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(loss + 1.0)        # cache-hit dispatch: pure fetch RTT
        samples.append(time.perf_counter() - t0)
    rtt = sorted(samples)[1]

    t0 = time.perf_counter()
    for _ in range(n_batches):
        ts, loss = step(ts, batch)
    float(loss)
    elapsed = time.perf_counter() - t0
    # Never subtract more than half the window: a jittery RTT sample must
    # degrade precision, not fabricate a throughput number.
    dt = elapsed - min(rtt, 0.5 * elapsed)
    return batch[1].shape[0] * n_batches / dt, ts


def _resolved_pallas(compressor):
    """RESOLVED kernel engagement for a built compressor: True/False for
    kernel-capable compressors, None for the rest. The single source both
    the row stamp and the resume gate use — they must never drift."""
    mode = getattr(compressor, "_pallas_mode", None)
    return bool(mode()[0]) if mode is not None else None


def _cached_row_valid(cfg) -> bool:
    """Last resume gate, evaluated where the platform is already pinned:
    a raw params dict cannot express a *semantic default* change (round-4
    case: use_pallas='auto' flipped from kernel-on to staged with no
    params edit), so rows stamp the RESOLVED pallas mode and a cached row
    is only replayed if the config still resolves the same way today.
    A kernel-capable config whose row predates the stamp fails CLOSED
    (re-measures) unless the row carries resume_trusted — the explicit
    operator override's assertion (the round-4 bs-sweep rows were
    measured while 'auto' still meant kernel-on; nothing in them says
    so)."""
    row = cfg["cached_row"]
    from grace_tpu import grace_from_params
    now = _resolved_pallas(grace_from_params(cfg["params"]).compressor)
    if "pallas_enabled" not in row:
        if now is None:   # never was kernel-capable: nothing to compare
            return True
        if row.get("resume_trusted"):
            return True
        print(f"[bench] {cfg['name']}: cached row predates the "
              "pallas_enabled stamp; re-measuring",
              file=sys.stderr, flush=True)
        return False
    # Stamped row: a now-missing capability (now is None) is itself a
    # semantic change — fail closed rather than replay a kernel-measured
    # number for a compressor that can no longer engage the kernel.
    if now == row["pallas_enabled"]:
        return True
    print(f"[bench] {cfg['name']}: cached row invalid "
          f"(pallas_enabled {row['pallas_enabled']} -> {now}); re-measuring",
          file=sys.stderr, flush=True)
    return False


def bench_configs(platform: str, configs, emit) -> None:
    """Measure each config's ResNet-50 training throughput; call
    ``emit(result_dict)`` once per config (first config = the dense
    baseline *recipe*).

    Self-consistency hardening (VERDICT round-3 item 2): every compressed
    row's ``vs_baseline`` comes from dense-baseline samples measured in the
    SAME session, interleaved sample-for-sample with that row's own samples
    — never from a dense number captured in another session (the round-3
    contradiction: 0.555x vs 1.024x, two numbers two sessions apart). Each
    row reports its raw samples, the median, and ``spread_pct``
    (100·(max−min)/median), and carries ``same_session: true`` as the
    auditable marker. A config may override ``per_device_bs`` /
    ``image_hw`` / ``param_dtype`` (the batch-size sweep); its baseline is
    the dense recipe re-measured at the SAME shapes, so the ratio stays
    like-for-like. A config that fails (e.g. OOM at a large batch) emits an
    ``error`` row and the sweep continues."""
    devices = setup_platform(platform)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from grace_tpu.parallel import batch_sharded, data_parallel_mesh

    on_tpu = devices[0].platform == "tpu"
    mesh = data_parallel_mesh(devices)

    def build_step(grace_params, num_classes, param_dtype="float32"):
        from grace_tpu import grace_from_params
        from grace_tpu.models import resnet
        from grace_tpu.train import (init_stateful_train_state,
                                     make_stateful_train_step)

        grace = grace_from_params(grace_params)
        optimizer = optax.chain(grace.transform(seed=0), optax.sgd(1e-3))

        def loss_fn(params, mstate, batch):
            x, y = batch
            logits, new_mstate = resnet.apply(
                params, mstate, x.astype(jnp.bfloat16), train=True)
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            return loss.mean(), new_mstate

        step = make_stateful_train_step(loss_fn, optimizer, mesh)
        params, mstate = resnet.init(jax.random.key(0), depth=50,
                                     num_classes=num_classes)
        if param_dtype != "float32":
            dt = jnp.dtype(param_dtype)
            params = jax.tree.map(
                lambda a: a.astype(dt)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, params)
        ts = init_stateful_train_state(params, mstate, optimizer, mesh)
        return step, ts, grace, params

    # Reference protocol: bs=32 per worker, ImageNet shapes on accelerators;
    # the CPU fallback shrinks shapes so a number lands anywhere. Configs
    # may override per_device_bs / image_hw / param_dtype (bs sweep).
    default_bs = TPU_DEFAULT_BS if on_tpu else 4
    default_hw = TPU_DEFAULT_HW if on_tpu else 64
    repeats = 3 if on_tpu else 1
    num_classes = 1000

    rng = np.random.default_rng(0)
    batch_cache: dict = {}

    def batch_for(bs, hw):
        key = (bs, hw)
        if key not in batch_cache:
            n = bs * len(devices)
            x = jnp.asarray(rng.standard_normal((n, hw, hw, 3)), jnp.float32)
            y = jnp.asarray(rng.integers(0, num_classes, (n,)), jnp.int32)
            batch_cache[key] = jax.device_put((x, y), batch_sharded(mesh))
        return batch_cache[key]

    def n_batches_for(bs):
        # The timed window must dwarf the tunnel fetch RTT (~65 ms, jitter
        # to 100+ ms): at 30 batches the dense window was ~340 ms and one
        # bad RTT sample swung the measured dense throughput 2x between
        # sessions (1446 vs 2849 imgs/sec, 2026-07-31). 120 batches at
        # bs=32 puts every window >=1.3 s, bounding RTT-induced error at
        # ~5%; larger batches take proportionally longer per step, so the
        # count scales down without shrinking the window.
        return max(24, (120 * 32) // bs) if on_tpu else 3

    class _Entry:
        """A built config: compiled step + live (donated) train state."""

        def __init__(self, grace_params, bs, hw, pdtype):
            self.step, self.ts, self.grace, self.params = build_step(
                grace_params, num_classes, pdtype)
            self.batch = batch_for(bs, hw)
            self.n_batches = n_batches_for(bs)
            self.warmed = False

        def measure(self):
            warm = 2 if self.warmed else 4
            tput, self.ts = throughput(self.step, self.ts, self.batch,
                                       self.n_batches, warmup=warm)
            self.warmed = True
            return tput

    # Dense-baseline entries stay alive for the whole sweep, one per shape
    # key, so every compressed sample can be bracketed by a fresh dense
    # sample from the same session/thermal/tunnel conditions.
    baselines: dict = {}

    def baseline_for(bs, hw, pdtype):
        key = (bs, hw, pdtype)
        if key not in baselines:
            baselines[key] = _Entry(configs[0]["params"], bs, hw, pdtype)
        return baselines[key]

    def wire_bytes(grace, params):
        """Bytes-on-wire per step per rank. PowerSGD is covered by its
        analytic Compressor.wire_nbytes (its compress psums inside
        shard_map, out of shape-tracing's reach); a compressor that fails
        here is a real bug — re-raise rather than emit plausible-looking
        wrong numbers."""
        from grace_tpu.utils import wire_report
        rep = wire_report(grace.compressor, params)
        return rep.dense_bytes, rep.wire_bytes

    chip = getattr(devices[0], "device_kind", devices[0].platform)
    peak = device_peak_flops(devices[0])

    print(f"[bench] mesh: {len(devices)}x {devices[0].platform} "
          f"({chip}, peak={peak})", file=sys.stderr, flush=True)
    med = statistics.median
    for cfg in configs:
        name = cfg["name"]
        cached_ok = "cached_row" in cfg and _cached_row_valid(cfg)
        if not cached_ok and cfg.get("tpu_only") and not on_tpu:
            # e.g. forced-Pallas rows: interpret mode off-TPU runs a
            # per-element emulation (>45 min/config observed) and the
            # number would mean nothing anyway. A valid cached row wins:
            # a CPU-fallback resume must re-emit a real on-chip
            # measurement, not replace it with a skip row.
            emit({"config": name, "skipped": "tpu_only",
                  "platform": devices[0].platform})
            continue
        if cached_ok:
            # Resume support (bench_all GRACE_BENCH_RESUME): a row measured
            # earlier in this tunnel session is re-emitted instead of
            # re-burning the chip; it carries "resumed": true. configs[0]
            # stays the dense-recipe anchor either way.
            print(f"[bench] {name}: cached row (resume)",
                  file=sys.stderr, flush=True)
            # Strip gate-only metadata: resume_trusted is the operator's
            # one-run assertion — persisting it would turn it into a
            # durable trust token future resumes silently honor.
            emit({k: v for k, v in cfg["cached_row"].items()
                  if k != "resume_trusted"})
            continue
        # Shape overrides are TPU-tuning knobs (the bs=256 headline would
        # be a 2048-image step on the one-core CPU fallback and time the
        # whole worker out); the CPU smoke keeps its tiny shapes and rows
        # always stamp the bs/hw they actually ran.
        bs = cfg.get("per_device_bs", default_bs) if on_tpu else default_bs
        hw = cfg.get("image_hw", default_hw) if on_tpu else default_hw
        pdtype = cfg.get("param_dtype", TPU_DEFAULT_PDTYPE)
        try:
            base = baseline_for(bs, hw, pdtype)
            if cfg["params"] == configs[0]["params"]:
                # This row IS the dense recipe at these shapes: its samples
                # are the baseline samples.
                samples = [base.measure() for _ in range(repeats)]
                bsamples = list(samples)
                ent = base
            else:
                ent = _Entry(cfg["params"], bs, hw, pdtype)
                samples, bsamples = [], []
                for _ in range(repeats):
                    bsamples.append(base.measure())
                    samples.append(ent.measure())
        except Exception as e:
            # One config must not kill the sweep (e.g. OOM at bs=256): emit
            # an error row so the evidence shows the config was attempted.
            print(f"[bench] {name} FAILED: {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)
            emit({"config": name,
                  "error": f"{type(e).__name__}: {str(e)[:300]}",
                  "platform": devices[0].platform,
                  "n_devices": len(devices), "per_device_bs": bs,
                  "image_hw": hw, "param_dtype": pdtype})
            continue
        imgs = med(samples)
        base_med = med(bsamples)
        spread = 100.0 * (max(samples) - min(samples)) / imgs if imgs else 0.0
        dense_b, wire_b = wire_bytes(ent.grace, ent.params)
        n_elems = sum(l.size
                      for l in jax.tree_util.tree_leaves(ent.params))
        vote = getattr(ent.grace.compressor, "vote_aggregate", False)
        flops = step_flops(ent.step, ent.ts, ent.batch)
        flops_src = "xla_cost_analysis" if flops else "analytic_resnet50"
        # Analytic fallback: ResNet-50 fwd ≈ 4.1 GFLOP/img at 224², scaled
        # by (hw/224)², train step ≈ 3× fwd — the convention the
        # reference's synthetic benchmark discussion uses; per device.
        flops = flops or 3 * 4.1e9 * (hw / 224.0) ** 2 * bs
        # MFU: delivered FLOP/s ÷ peak. imgs/sec is mesh-global; per-device
        # steps/sec = imgs/sec ÷ global batch; flops is the per-device SPMD
        # module, so the n_devices factors cancel.
        global_bs = bs * len(devices)
        mfu = (flops * (imgs / global_bs) / peak) if peak else None
        print(f"[bench] {name}: {imgs:.2f} imgs/sec "
              f"(x{imgs / base_med:.3f} vs dense, spread {spread:.1f}%)"
              + (f", mfu={mfu:.4f}" if mfu is not None else ""),
              file=sys.stderr, flush=True)
        row_extra = {"grace_params": cfg["params"]}
        resolved = _resolved_pallas(ent.grace.compressor)
        if resolved is not None:
            # Resolved (not configured) kernel engagement — the resume
            # gate compares this across semantic default changes.
            row_extra["pallas_enabled"] = resolved
        # The RESOLVED fusion mode as a first-class row key (None | 'flat'
        # | 'grouped' | int bucket bytes), not just a field buried in
        # grace_params: a bucketed-executor capture and the flat-fusion
        # headline must be distinguishable row-by-row, the same honesty
        # contract as pallas_enabled.
        row_extra["fusion"] = ent.grace.fusion
        # Wire-path provenance (ISSUE 19), same honesty contract as
        # fusion/pallas_enabled: the packed field width the payload
        # actually ships (absent for byte-wide formats) and the
        # communicator's pipeline depth — a pipelined capture and its
        # serial twin, or a 2-bit and a 4-bit row, must be
        # distinguishable row-by-row.
        _comp = ent.grace.compressor
        if getattr(_comp, "packed_wire", False):
            row_extra["pack_width"] = int(_comp.pack_width)
        elif getattr(_comp, "accum_bits", None):
            row_extra["pack_width"] = int(_comp.accum_bits)
        _pipe = int(getattr(ent.grace.communicator, "pipeline", 1) or 1)
        if _pipe > 1:
            row_extra["pipelined"] = _pipe
        if cfg.get("note"):
            # Config-level caveat (e.g. "bf16 grads use the staged Top-K
            # path") — evidence rows must carry their own context.
            row_extra["note"] = cfg["note"]
        from grace_tpu.ops import _env_true
        if _env_true("GRACE_DISABLE_PALLAS"):
            # The escape hatch means this row measured the staged XLA path
            # even for configs whose default is the Pallas kernel — the
            # evidence must say so, not attribute the number to the kernel.
            # _env_true matches pallas_disabled()'s false-spelling semantics
            # so an explicit "=0" enable is not stamped as staged.
            row_extra["env_pallas_disabled"] = True
        if _env_true("GRACE_DISABLE_PALLAS_QUANT"):
            row_extra["env_pallas_quant_disabled"] = True
        if _env_true("GRACE_DISABLE_PALLAS_TOPK"):
            row_extra["env_pallas_topk_disabled"] = True
        emit({
            **row_extra,
            "config": name,
            "imgs_per_sec": round(imgs, 2),
            "samples": [round(s, 2) for s in samples],
            "spread_pct": round(spread, 2),
            "baseline_imgs_per_sec": round(base_med, 2),
            "baseline_samples": [round(s, 2) for s in bsamples],
            "vs_baseline": round(imgs / base_med, 4),
            "same_session": True,
            "wire_bytes_per_step": wire_b,
            "wire_ratio": round(wire_b / max(1, dense_b), 6),
            "wire_recv_bytes_per_step": recv_bytes_model(
                ent.grace.communicator, vote, wire_b, n_elems,
                len(devices)),
            "projection": project_multichip(
                global_bs / imgs, global_bs / base_med, ent.grace,
                wire_b, dense_b, n_elems),
            "projection_three_tier": project_three_tier(
                global_bs / imgs, global_bs / base_med, ent.grace,
                wire_b, dense_b, n_elems),
            "platform": devices[0].platform,
            "n_devices": len(devices),
            "per_device_bs": bs,
            "image_hw": hw,
            "param_dtype": pdtype,
            "n_batches_timed": ent.n_batches,
            "chip": chip,
            "peak_flops": peak,
            "model_flops_per_step": round(flops),
            "flops_source": flops_src,
            "mfu": round(mfu, 4) if mfu is not None else None,
        })


def _worker(platform: str) -> None:
    results = []
    # Persist every TPU row the moment it is measured (round-2 postmortem:
    # the tunnel died between the dense and compressed runs and the whole
    # pair was lost — now the dense number lands on disk immediately).
    emit = progressive_emit(results.append, n_expected=len(HEADLINE))
    bench_configs(platform, HEADLINE, emit)
    compressed = results[1]
    if any("imgs_per_sec" not in r for r in results[:2]):
        # A headline config emitted an error row (OOM/compile failure):
        # surface the structured failure instead of a KeyError traceback,
        # and fail the worker so the orchestrator retries/falls back.
        print(json.dumps({
            "metric": "resnet50_topk1pct_imgs_per_sec", "value": None,
            "unit": "imgs/sec", "vs_baseline": None,
            "error": "; ".join(r.get("error", "") for r in results[:2]
                               if r.get("error")),
        }), flush=True)
        sys.exit(3)
    print(json.dumps({
        "metric": "resnet50_topk1pct_imgs_per_sec",
        "value": compressed["imgs_per_sec"],
        "unit": "imgs/sec",
        "vs_baseline": compressed["vs_baseline"],
        "same_session": compressed.get("same_session"),
        "spread_pct": compressed.get("spread_pct"),
        "baseline_imgs_per_sec": compressed.get("baseline_imgs_per_sec"),
        "platform": compressed["platform"],
        "chip": compressed.get("chip"),
        "peak_flops": compressed.get("peak_flops"),
        "model_flops_per_step": compressed.get("model_flops_per_step"),
        "mfu": compressed.get("mfu"),
        "mfu_dense": results[0].get("mfu"),
        "projection": compressed.get("projection"),
        "projection_three_tier": compressed.get("projection_three_tier"),
        "projection_model": PROJECTION_MODEL,
    }), flush=True)


# --------------------------------------------------------------------------
# Orchestrator: probe -> run -> retry -> CPU fallback; always emit JSON
# --------------------------------------------------------------------------

def _run_sub(args, timeout, extra_env=None):
    """Run a python subprocess; return (rc, stdout, stderr|'timeout')."""
    env = dict(os.environ, **(extra_env or {}))
    try:
        p = subprocess.run([sys.executable, *args], capture_output=True,
                           text=True, timeout=timeout, env=env)
        return p.returncode, p.stdout, p.stderr
    except subprocess.TimeoutExpired as e:
        out = e.stdout or ""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        return 124, out, f"timeout after {timeout}s"


def _json_lines(stdout: str, key: str):
    found = []
    for line in stdout.strip().splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
                if key in obj:
                    found.append(obj)
            except json.JSONDecodeError:
                continue
    return found


def _last_json_line(stdout: str):
    lines = _json_lines(stdout, "metric")
    return lines[-1] if lines else None


def _probe_tpu(timeout: float) -> bool:
    rc, out, err = _run_sub(
        ["-c", "import jax; d = jax.devices(); "
               "print(d[0].platform, len(d))"],
        timeout)
    ok = rc == 0 and out.strip().startswith("tpu")
    print(f"[bench] tpu probe: rc={rc} out={out.strip()!r} "
          f"err_tail={err[-200:]!r}", file=sys.stderr, flush=True)
    return ok


def orchestrate(script_path: str, parse, emit_failure,
                worker_timeout: float = WORKER_TIMEOUT_S,
                salvage=None) -> bool:
    """probe TPU -> run worker (retry once) -> CPU fallback.

    ``parse(stdout, stages) -> result|None`` extracts and emits the worker's
    output (``stages`` records earlier probe/attempt failures so a
    degraded CPU-fallback run stays diagnosable); ``emit_failure(stages)``
    prints the failure JSON. ``salvage(stdout)``, if given, sees every
    *failed* attempt's captured stdout so partial per-line results survive a
    mid-sweep timeout. Returns success.
    """
    stages = []

    def attempt_failed(out):
        if salvage is not None:
            salvage(out)

    for attempt, probe_timeout in enumerate(PROBE_TIMEOUTS_S, start=1):
        if not _probe_tpu(probe_timeout):
            stages.append({"stage": "backend_init", "attempt": attempt,
                           "error": "tpu probe failed/timed out"})
            continue
        rc, out, err = _run_sub([script_path, "--_worker", "tpu"],
                                worker_timeout)
        if rc == 0 and parse(out, stages):
            return True
        attempt_failed(out)
        stages.append({"stage": "tpu_bench", "attempt": attempt, "rc": rc,
                       "error": err[-500:]})
        print(f"[bench] tpu attempt {attempt} failed rc={rc}: {err[-500:]}",
              file=sys.stderr, flush=True)

    print("[bench] falling back to 8-device simulated-CPU mesh",
          file=sys.stderr, flush=True)
    rc, out, err = _run_sub([script_path, "--_worker", "cpu"], worker_timeout)
    if rc == 0 and parse(out, stages):
        return True
    attempt_failed(out)
    stages.append({"stage": "cpu_bench", "rc": rc, "error": err[-500:]})
    emit_failure(stages)
    return False


# Last successful on-TPU headline result, committed as evidence: the tunnel
# to the single real chip has been observed to stay unreachable for hours at
# a stretch, so a CPU-fallback (or failed) run carries the most recent real
# number along, clearly labeled with its capture time.
TPU_EVIDENCE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BENCH_TPU_LAST.json")


def _write_evidence(rows: list, path: str, metric: str, n_expected: int,
                    headline_config: str = "topk1pct",
                    value_key: str = "imgs_per_sec") -> None:
    """Write the TPU evidence file from the rows measured so far. Called
    after EVERY row on TPU so a mid-run tunnel death still leaves the dense
    baseline (and any completed configs) on disk, clearly marked partial."""
    import datetime
    comp = next((r for r in rows if r.get("config") == headline_config
                 and value_key in r), None)
    try:
        # Same provenance block the telemetry JSONL artifacts carry
        # (platform/devices/UTC/git commit) so every evidence file is
        # attributable to a revision. Best-effort: evidence persistence
        # must survive a broken git checkout.
        from grace_tpu.utils.logging import run_provenance
        # The headline row's resolved kernel/fusion modes ride the
        # document-level provenance too: an evidence file whose headline
        # was measured with pallas off or a different executor is
        # distinguishable from one capture-level field, without digging
        # through rows.
        provenance = run_provenance(
            data="synthetic", tool="bench", argv=" ".join(sys.argv[1:]),
            pallas_enabled=(comp.get("pallas_enabled") if comp else None),
            fusion=(comp.get("fusion") if comp else None))
    except Exception as e:
        print(f"[bench] provenance unavailable: {e}",
              file=sys.stderr, flush=True)
        provenance = None
    rec = {
        "metric": metric,
        "provenance": provenance,
        "value": comp[value_key] if comp else None,
        "unit": value_key.replace("_per_sec", "/sec").replace("_", " "),
        "vs_baseline": comp["vs_baseline"] if comp else None,
        "same_session": comp.get("same_session") if comp else None,
        "spread_pct": comp.get("spread_pct") if comp else None,
        "platform": "tpu",
        "n_devices": rows[0].get("n_devices"),
        "chip": rows[0].get("chip"),
        "peak_flops": rows[0].get("peak_flops"),
        "mfu": comp.get("mfu") if comp else None,
        "partial": len(rows) < n_expected,
        "rows_measured": len(rows),
        "rows_expected": n_expected,
        "rows": rows,
        # Document-level stamp (not per-row: 26 identical copies of ~1.2 KB
        # of prose would bloat every sweep file and the trimmed summary
        # drops per-row fields anyway).
        "projection_model": PROJECTION_MODEL,
        "captured_at": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    # Atomic replace: a kill mid-write must not truncate the evidence the
    # row-by-row persistence exists to protect — fsync before the rename or
    # a power cut can land the rename with un-flushed content. And never
    # let a lesser record clobber a better one (a fresh attempt starts with
    # rows=[]; its 1-row partial must not erase an earlier complete run or
    # a longer partial prefix) — demoted records go to a '.partial' sibling
    # instead. Transient OSErrors (the flaky tunnel's NFS blips) retry with
    # the same bounded backoff the checkpointer uses.
    tmp = path + ".tmp"
    final = {"dest": path}

    def write():
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        old = load_tpu_evidence(path)
        final["dest"] = (path + ".partial" if _regresses(rec, old)
                         else path)
        os.replace(tmp, final["dest"])

    try:
        _evidence_retry_io(write, "TPU evidence")
    except OSError as e:
        print(f"[bench] could not save TPU evidence: {e}",
              file=sys.stderr, flush=True)
        return
    # Ledger emission (ISSUE 17): once the sweep is complete and landed at
    # its real destination (not a demoted .partial), append the provenance
    # record graft_gate audits claims against. Raise-free inside
    # record_artifact — ledger trouble must never cost the capture.
    in_repo = (os.path.dirname(os.path.abspath(path)) ==
               os.path.dirname(os.path.abspath(TPU_EVIDENCE_PATH)))
    if final["dest"] == path and not rec.get("partial") and in_repo:
        try:
            from grace_tpu.evidence.ledger import record_artifact
            n_dev = rec.get("n_devices")
            record_artifact(
                path, id=_ledger_id(metric), metric=metric,
                value=rec.get("vs_baseline"), claim_class="measured",
                tool="bench", platform=rec.get("platform"),
                chip=rec.get("chip"), n_devices=n_dev,
                topology={"world": n_dev, "tiers": ["ici"],
                          "slice": None, "region": None},
                config=headline_config, lint_clean=None,
                unit="vs_dense", abs_value=rec.get("value"))
        except Exception as e:              # noqa: BLE001
            print(f"[bench] ledger emission failed: {e}",
                  file=sys.stderr, flush=True)


# Stable ledger ids per bench metric family: re-runs append fresh records
# under the same id (last-writer-wins in the ledger), so README markers
# never need editing when evidence refreshes.
_LEDGER_IDS = {
    "resnet50_topk1pct_imgs_per_sec": "bench-headline-tpu",
    "resnet50_all_configs_imgs_per_sec": "bench-sweep-tpu",
    "bert_powersgd_r4_tokens_per_sec": "bench-bert-tpu",
}


def _ledger_id(metric: str) -> str:
    return _LEDGER_IDS.get(
        metric, "bench-" + metric.replace("_", "-").replace("/", "-"))


def _evidence_retry_io(fn, what: str):
    """checkpoint._retry_io when available (orbax pulls in heavy deps a
    bench-only box may lack); single attempt otherwise."""
    try:
        from grace_tpu.checkpoint import _retry_io
    except Exception:
        return fn()
    return _retry_io(fn, what)


def _regresses(new: dict, old) -> bool:
    """True iff writing ``new`` over ``old`` would lose evidence."""
    if not isinstance(old, dict):
        return False
    # Round-2-format records have no rows/partial fields; a non-null value
    # means they carry a real measured headline.
    old_partial = old.get("partial", old.get("value") is None)
    old_rows = old.get("rows_measured",
                       1 if old.get("value") is not None else 0)
    if not old_partial and new.get("partial"):
        return True
    return new.get("rows_measured", 0) < old_rows


def progressive_emit(emit, n_expected: int,
                     evidence_path: str = TPU_EVIDENCE_PATH,
                     metric: str = "resnet50_topk1pct_imgs_per_sec",
                     headline_config: str = "topk1pct",
                     value_key: str = "imgs_per_sec"):
    """Wrap a per-row emit callback with immediate TPU evidence persistence.
    ``n_expected`` is the sweep length — fewer persisted rows means the run
    died mid-sweep and the record is marked ``partial``."""
    rows: list = []

    def wrapped(r):
        rows.append(r)
        emit(r)
        # evidence_path=None disables persistence entirely: a CPU worker
        # re-emitting cached platform-'tpu' rows (explicit operator resume)
        # must never rewrite the TPU evidence file with a fresh captured_at
        # over a rows list mixing CPU-measured rows (ADVICE r4).
        if r.get("platform") == "tpu" and evidence_path:
            _write_evidence(rows, evidence_path, metric, n_expected,
                            headline_config, value_key)

    return wrapped


def load_tpu_evidence(path: str = TPU_EVIDENCE_PATH):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


# Mirrored in grace_tpu.evidence.staleness.STALE_BANNER (tests pin the
# two equal): bench keeps a literal so `bench.py --help` on a stripped
# box never imports the package just for the banner string.
STALE_BANNER = "STALE — predates PRs 7–10"


def evidence_staleness(doc) -> list:
    """Why a persisted TPU evidence document predates the current feature
    set — the honesty check every reader of these files applies before
    quoting a headline (ISSUE 12). Empty list = current.

    Since ISSUE 17 this is a thin delegate to the ONE unified detector,
    :func:`grace_tpu.evidence.staleness.evidence_staleness` — feature
    stamps (PR 7 hier rows, PR 10 pallas/fusion provenance) plus the
    git-ancestry check — so this function, ``evidence_summary.py``, the
    tuner's carry-along banner, and ``graft_gate`` cannot disagree about
    what counts as stale.
    """
    from grace_tpu.evidence.staleness import evidence_staleness as unified
    return unified(doc)


def _mark_stale(doc):
    """A copy of ``doc`` carrying the stale banner when it earned one."""
    reasons = evidence_staleness(doc)
    if not reasons:
        return doc
    return {**doc, "stale": STALE_BANNER, "stale_reasons": reasons}


SWEEP_SUMMARY_PATH = os.path.join(os.path.dirname(TPU_EVIDENCE_PATH),
                                  "BENCH_ALL_TPU_LAST.json")


def load_tpu_sweep_summary(path: str = SWEEP_SUMMARY_PATH):
    """Trimmed view of the last on-TPU per-algorithm sweep, carried along
    by fallback runs next to ``last_tpu``: the headline file alone can
    understate the round (round-4 case: the bs=32 headline pair reads
    0.56x while the same-session sweep holds the deliberately-chosen
    bs=256 record at 0.92x). Row payloads are cut to the fields a reader
    ranks configs by."""
    doc = load_tpu_evidence(path)
    if not doc or not doc.get("rows"):
        return None
    keep = ("config", "imgs_per_sec", "vs_baseline", "spread_pct",
            "same_session", "per_device_bs", "param_dtype", "wire_ratio",
            "mfu", "note", "resumed", "error")
    return {"captured_at": doc.get("captured_at"),
            "partial": doc.get("partial"),
            "rows": [{k: r[k] for k in keep if k in r}
                     for r in doc["rows"]]}


def _attach_tpu_evidence(d: dict) -> None:
    """Attach the latest persisted on-TPU records to a non-TPU result —
    one helper for both the parse() and emit_failure() sites so the two
    outputs can never drift. Stale records (evidence_staleness) carry the
    banner so a carried-along number is never mistaken for a capture of
    the current feature set."""
    last = load_tpu_evidence()
    if last:
        d["last_tpu"] = _mark_stale(last)
    sweep = load_tpu_sweep_summary()
    if sweep:
        # The summary is trimmed; staleness is judged on the full document.
        reasons = evidence_staleness(load_tpu_evidence(SWEEP_SUMMARY_PATH))
        if reasons:
            sweep = {**sweep, "stale": STALE_BANNER,
                     "stale_reasons": reasons}
        d["last_tpu_sweep"] = sweep


def main() -> None:
    here = os.path.abspath(__file__)

    def parse(out, stages):
        result = _last_json_line(out)
        if result:
            result["stages"] = stages
            if result.get("platform") != "tpu":
                # TPU evidence is written by the worker itself, row by row;
                # a fallback run just carries the latest real numbers along.
                _attach_tpu_evidence(result)
            print(json.dumps(result), flush=True)
        return result

    def emit_failure(stages):
        out = {
            "metric": "resnet50_topk1pct_imgs_per_sec",
            "value": None, "unit": "imgs/sec", "vs_baseline": None,
            "stages": stages,
        }
        _attach_tpu_evidence(out)
        print(json.dumps(out), flush=True)

    if not orchestrate(here, parse, emit_failure):
        sys.exit(1)


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--_worker":
        _worker(sys.argv[2])
    else:
        main()
