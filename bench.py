"""Headline benchmark: compressed vs uncompressed ResNet-50 training throughput.

Mirrors the reference's synthetic benchmark protocol
(examples/torch/pytorch_synthetic_benchmark.py:180-198: ResNet-50, random
data, img/sec over timed iterations) and the BASELINE.json north star: Top-K
k=1% + residual memory should reach >=90% of the uncompressed-allreduce
throughput. Runs the full GRACE pipeline (compensate -> compress -> update ->
exchange) on the available device mesh.

Always prints ONE JSON line as the last stdout line:
  {"metric": "resnet50_topk1pct_imgs_per_sec", "value": ..., "unit":
   "imgs/sec", "vs_baseline": <compressed/uncompressed ratio>, "platform": ...}

Failure engineering (round-1 postmortem: the TPU tunnel backend hung >9 min
in init and the bench emitted nothing): the measurement runs in a worker
subprocess under a hard timeout; the orchestrator first probes backend init
separately, retries once, and on TPU failure falls back to an 8-device
simulated-CPU mesh so a real number is captured either way. Stage
diagnostics go to stderr; stdout carries only the final JSON line.

The measurement core (`bench_configs`) is shared with bench_all.py, which
sweeps the whole BASELINE.json config list instead of the headline pair.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

PROBE_TIMEOUTS_S = (180, 420)  # healthy tunnel inits in seconds; second
                               # probe gets a long leash for slow cold init
WORKER_TIMEOUT_S = 1200        # full bench incl. first compile (~20-40s/fn)

HEADLINE = [
    # Both sides get the fusion buffer — Horovod fuses the uncompressed
    # baseline too, so a like-for-like ratio must as well.
    {"name": "none", "params": {"compressor": "none", "memory": "none",
                                "communicator": "allreduce",
                                "fusion": "flat"}},
    # Top-K selection uses the chunked argmax (top-1 per strided chunk, a
    # pure VPU reduction) with the scatter-free one-hot decompress
    # (ops/sparse.py chunkwise_dense). Measured on the chip
    # (TPU_VARIANTS.jsonl, 2026-07-31): chunk 1.02x dense vs approx_max_k
    # 0.69x and exact-sort far below — both the full-buffer top-k select
    # AND the scatter in decompress were the bottleneck; chunk mode removes
    # both. Selection is DGC-style relaxed (top-1 per chunk, not global
    # top-k); residual error feedback compensates — chunk tracks exact
    # step-for-step on a toy convex problem (2.303->0.534 vs 0.533 at 1%
    # over 120 steps, 8-device mesh) and the real-MNIST curve is committed
    # at examples/logs/mnist10k_topk1pct_chunk.tsv. bench_all.py measures
    # exact/approx/chunk side by side.
    {"name": "topk1pct", "params": {"compressor": "topk",
                                    "compress_ratio": 0.01,
                                    "topk_algorithm": "chunk",
                                    "memory": "residual",
                                    "communicator": "allgather",
                                    "fusion": "flat"}},
]


# --------------------------------------------------------------------------
# Measurement core (runs inside a worker subprocess; also used by bench_all)
# --------------------------------------------------------------------------

# Peak dense bf16 FLOP/s per *jax device*, keyed by device_kind substring
# (first match wins; most specific first). v2/v3 expose one device per core,
# v4+ one per chip, hence per-core numbers for the older generations.
# Sources: cloud.google.com/tpu/docs/system-architecture-tpu-vm (public
# per-chip peaks: v2 45T, v3 123T, v4 275T, v5e 197T, v5p 459T, v6e 918T).
PEAK_BF16_FLOPS = (
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 61.5e12),
    ("v2", 22.5e12),
)


def device_peak_flops(device) -> float | None:
    """Peak bf16 FLOP/s for one jax device, or None if unknown (CPU)."""
    kind = getattr(device, "device_kind", "").lower()
    if device.platform != "tpu":
        return None
    for key, peak in PEAK_BF16_FLOPS:
        if key in kind:
            return peak
    return None


def step_flops(step, ts, batch) -> float | None:
    """Per-device FLOPs of one compiled train step, via XLA cost analysis
    on the lowered (SPMD, per-device) module. Host-side only — no device
    round-trip, so it is safe on a flaky tunnel. None if unavailable."""
    try:
        fn = next(iter(step.jit_cache.values()))
        cost = fn.lower(ts, batch).cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        f = float(cost.get("flops", 0.0))
        return f if f > 0 else None
    except Exception as e:
        print(f"[bench] cost_analysis unavailable: {e}",
              file=sys.stderr, flush=True)
        return None


def setup_platform(platform: str):
    """Pin jax to the requested platform BEFORE any backend init."""
    import jax

    # Persistent compilation cache — TPU only: the two ResNet-50 train-step
    # compiles dominate worker wall-clock on the tunnel (minutes each) and
    # put the run uncomfortably close to WORKER_TIMEOUT_S; any earlier bench
    # run on this host makes later ones compile-free. NOT enabled for the
    # CPU fallback: XLA:CPU caches AOT machine code keyed loosely enough
    # that an entry compiled under different detected CPU features loads
    # with a "could lead to SIGILL" warning — a crash there would cost the
    # fallback number entirely, for a compile that is cheap anyway.
    if platform == "tpu":
        try:
            import tempfile
            cache_dir = os.path.join(tempfile.gettempdir(),
                                     f"grace_tpu_jax_cache_{os.getuid()}")
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        except Exception as e:  # cache is an optimization, not a requirement
            print(f"[bench] compilation cache unavailable: {e}",
                  file=sys.stderr, flush=True)

    if platform == "cpu":
        # Same dance as tests/conftest.py: the image's sitecustomize latches
        # jax onto the TPU tunnel, so env vars alone are not enough.
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
        from grace_tpu.parallel import relax_cpu_collective_timeouts
        relax_cpu_collective_timeouts()  # 8 device threads, few-core host
    devices = jax.devices()
    if platform == "tpu" and devices[0].platform != "tpu":
        raise RuntimeError(f"wanted tpu, got {devices[0].platform}")
    return devices


def bench_configs(platform: str, configs, emit) -> None:
    """Measure each config's ResNet-50 training throughput; call
    ``emit(result_dict)`` per config (first config = the dense baseline)."""
    devices = setup_platform(platform)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from grace_tpu.parallel import batch_sharded, data_parallel_mesh

    on_tpu = devices[0].platform == "tpu"
    mesh = data_parallel_mesh(devices)

    def build_step(grace_params, num_classes):
        from grace_tpu import grace_from_params
        from grace_tpu.models import resnet
        from grace_tpu.train import (init_stateful_train_state,
                                     make_stateful_train_step)

        grace = grace_from_params(grace_params)
        optimizer = optax.chain(grace.transform(seed=0), optax.sgd(1e-3))

        def loss_fn(params, mstate, batch):
            x, y = batch
            logits, new_mstate = resnet.apply(
                params, mstate, x.astype(jnp.bfloat16), train=True)
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            return loss.mean(), new_mstate

        step = make_stateful_train_step(loss_fn, optimizer, mesh)
        params, mstate = resnet.init(jax.random.key(0), depth=50,
                                     num_classes=num_classes)
        ts = init_stateful_train_state(params, mstate, optimizer, mesh)
        return step, ts, grace, params

    def throughput(step, ts, batch, n_batches, warmup=2):
        # Fetch-bounded timing: on the axon tunnel block_until_ready does not
        # wait for device execution — only a value fetch synchronizes. Drain
        # with a fetch, time n dependent steps bounded by a final fetch, and
        # subtract the measured fetch RTT (~65 ms) so the window covers
        # device execution, not tunnel latency.
        for _ in range(warmup):
            ts, loss = step(ts, batch)
        float(loss)
        # The probe program (scalar add + fetch) must be compiled BEFORE the
        # timed RTT measurement — its first dispatch pays a multi-second
        # compile on the tunnel, which once inflated rtt past the whole
        # measurement window and collapsed dt to the 1e-9 clamp. Median of 3
        # samples: a single jittery RTT (tunnel hiccups of 100+ ms happen)
        # once moved the dense headline by 2x when the window was short.
        float(loss + 1.0)
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(loss + 1.0)        # cache-hit dispatch: pure fetch RTT
            samples.append(time.perf_counter() - t0)
        rtt = sorted(samples)[1]

        t0 = time.perf_counter()
        for _ in range(n_batches):
            ts, loss = step(ts, batch)
        float(loss)
        elapsed = time.perf_counter() - t0
        # Never subtract more than half the window: a jittery RTT sample must
        # degrade precision, not fabricate a throughput number.
        dt = elapsed - min(rtt, 0.5 * elapsed)
        return batch[1].shape[0] * n_batches / dt, ts

    # Reference protocol: bs=32 per worker, ImageNet shapes on accelerators;
    # the CPU fallback shrinks shapes so a number lands anywhere.
    per_device_bs = 32 if on_tpu else 4
    image_hw = 224 if on_tpu else 64
    # The timed window must dwarf the tunnel fetch RTT (~65 ms, jitter to
    # 100+ ms): at 30 batches the dense window was ~340 ms and one bad RTT
    # sample swung the measured dense throughput 2x between sessions
    # (1446 vs 2849 imgs/sec, 2026-07-31). 120 batches puts every window
    # >=1.3 s, bounding RTT-induced error at ~5%.
    n_batches = 120 if on_tpu else 3
    repeats = 3 if on_tpu else 1
    num_classes = 1000

    n = per_device_bs * len(devices)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((n, image_hw, image_hw, 3)),
                    jnp.float32)
    y = jnp.asarray(rng.integers(0, num_classes, (n,)), jnp.int32)
    batch = jax.device_put((x, y), batch_sharded(mesh))

    def wire_bytes(grace, params):
        """Bytes-on-wire per step per rank. PowerSGD is covered by its
        analytic Compressor.wire_nbytes (its compress psums inside
        shard_map, out of shape-tracing's reach); a compressor that fails
        here is a real bug — re-raise rather than emit plausible-looking
        wrong numbers."""
        from grace_tpu.utils import wire_report
        rep = wire_report(grace.compressor, params)
        return rep.dense_bytes, rep.wire_bytes

    def recv_bytes(grace, payload_b, n_elems, w):
        """Received bytes per rank per step for this mesh — the
        communicator-aware number (payload_b alone is communicator-blind
        and cannot show e.g. twoshot's O(k) vs allgather's O(W·k)).
        Ring model for the reduce-style collectives."""
        from grace_tpu.comm import (Allgather, Allreduce, SignAllreduce,
                                    TwoShotAllreduce)
        c = grace.communicator
        if isinstance(c, TwoShotAllreduce):
            # stage-1 all_to_all + stage-2 all_gather, each ~payload_b·(W-1)/W
            return 2 * payload_b * (w - 1) // max(1, w)
        vote = getattr(grace.compressor, "vote_aggregate", False)
        if isinstance(c, SignAllreduce) or (isinstance(c, Allreduce) and vote):
            # psum of dense ±1 votes in bf16 (2 bytes), ring: 2·(W-1)/W·n·2
            return 2 * 2 * n_elems * (w - 1) // max(1, w)
        if isinstance(c, Allreduce):
            return 2 * payload_b * (w - 1) // max(1, w)
        if isinstance(c, Allgather):   # Broadcast subclasses Allgather
            return payload_b * (w - 1)
        return 0                       # Identity

    chip = getattr(devices[0], "device_kind", devices[0].platform)
    peak = device_peak_flops(devices[0])
    # Analytic fallback for model FLOPs if XLA cost analysis is unavailable:
    # ResNet-50 fwd ≈ 4.1 GFLOP/img at 224², scaled by (hw/224)², train step
    # ≈ 3× fwd (bwd ≈ 2× fwd) — the convention the reference's synthetic
    # benchmark discussion uses; per *device* = × local batch.
    analytic_flops = 3 * 4.1e9 * (image_hw / 224.0) ** 2 * per_device_bs

    print(f"[bench] mesh: {len(devices)}x {devices[0].platform} "
          f"({chip}, peak={peak})", file=sys.stderr, flush=True)
    baseline = None
    for cfg in configs:
        step, ts, grace, params = build_step(cfg["params"], num_classes)
        best = 0.0
        # best-of-N to damp chip/host jitter (~8% run-to-run on the tunnel)
        for _ in range(repeats):
            tput, ts = throughput(step, ts, batch, n_batches, warmup=4)
            best = max(best, tput)
        dense_b, wire_b = wire_bytes(grace, params)
        if baseline is None:
            baseline = best
        flops = step_flops(step, ts, batch)
        flops_src = "xla_cost_analysis" if flops else "analytic_resnet50"
        flops = flops or analytic_flops
        # MFU: delivered FLOP/s ÷ peak. imgs/sec is mesh-global; per-device
        # steps/sec = imgs/sec ÷ global batch; flops is the per-device SPMD
        # module, so the n_devices factors cancel.
        steps_per_sec = best / batch[1].shape[0]
        mfu = (flops * steps_per_sec / peak) if peak else None
        print(f"[bench] {cfg['name']}: {best:.2f} imgs/sec"
              + (f", mfu={mfu:.4f}" if mfu is not None else ""),
              file=sys.stderr, flush=True)
        row_extra = {}
        if os.environ.get("GRACE_DISABLE_PALLAS"):
            # The escape hatch means this row measured the staged XLA path
            # even for configs whose default is the Pallas kernel — the
            # evidence must say so, not attribute the number to the kernel.
            row_extra["env_pallas_disabled"] = True
        emit({
            **row_extra,
            "config": cfg["name"],
            "imgs_per_sec": round(best, 2),
            "vs_baseline": round(best / baseline, 4),
            "wire_bytes_per_step": wire_b,
            "wire_ratio": round(wire_b / max(1, dense_b), 6),
            "wire_recv_bytes_per_step": recv_bytes(
                grace, wire_b,
                sum(l.size for l in jax.tree_util.tree_leaves(params)),
                len(devices)),
            "platform": devices[0].platform,
            "n_devices": len(devices),
            "chip": chip,
            "peak_flops": peak,
            "model_flops_per_step": round(flops),
            "flops_source": flops_src,
            "mfu": round(mfu, 4) if mfu is not None else None,
        })


def _worker(platform: str) -> None:
    results = []
    # Persist every TPU row the moment it is measured (round-2 postmortem:
    # the tunnel died between the dense and compressed runs and the whole
    # pair was lost — now the dense number lands on disk immediately).
    emit = progressive_emit(results.append, n_expected=len(HEADLINE))
    bench_configs(platform, HEADLINE, emit)
    compressed = results[1]
    print(json.dumps({
        "metric": "resnet50_topk1pct_imgs_per_sec",
        "value": compressed["imgs_per_sec"],
        "unit": "imgs/sec",
        "vs_baseline": compressed["vs_baseline"],
        "platform": compressed["platform"],
        "chip": compressed.get("chip"),
        "peak_flops": compressed.get("peak_flops"),
        "model_flops_per_step": compressed.get("model_flops_per_step"),
        "mfu": compressed.get("mfu"),
        "mfu_dense": results[0].get("mfu"),
    }), flush=True)


# --------------------------------------------------------------------------
# Orchestrator: probe -> run -> retry -> CPU fallback; always emit JSON
# --------------------------------------------------------------------------

def _run_sub(args, timeout, extra_env=None):
    """Run a python subprocess; return (rc, stdout, stderr|'timeout')."""
    env = dict(os.environ, **(extra_env or {}))
    try:
        p = subprocess.run([sys.executable, *args], capture_output=True,
                           text=True, timeout=timeout, env=env)
        return p.returncode, p.stdout, p.stderr
    except subprocess.TimeoutExpired as e:
        out = e.stdout or ""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        return 124, out, f"timeout after {timeout}s"


def _json_lines(stdout: str, key: str):
    found = []
    for line in stdout.strip().splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
                if key in obj:
                    found.append(obj)
            except json.JSONDecodeError:
                continue
    return found


def _last_json_line(stdout: str):
    lines = _json_lines(stdout, "metric")
    return lines[-1] if lines else None


def _probe_tpu(timeout: float) -> bool:
    rc, out, err = _run_sub(
        ["-c", "import jax; d = jax.devices(); "
               "print(d[0].platform, len(d))"],
        timeout)
    ok = rc == 0 and out.strip().startswith("tpu")
    print(f"[bench] tpu probe: rc={rc} out={out.strip()!r} "
          f"err_tail={err[-200:]!r}", file=sys.stderr, flush=True)
    return ok


def orchestrate(script_path: str, parse, emit_failure,
                worker_timeout: float = WORKER_TIMEOUT_S,
                salvage=None) -> bool:
    """probe TPU -> run worker (retry once) -> CPU fallback.

    ``parse(stdout, stages) -> result|None`` extracts and emits the worker's
    output (``stages`` records earlier probe/attempt failures so a
    degraded CPU-fallback run stays diagnosable); ``emit_failure(stages)``
    prints the failure JSON. ``salvage(stdout)``, if given, sees every
    *failed* attempt's captured stdout so partial per-line results survive a
    mid-sweep timeout. Returns success.
    """
    stages = []

    def attempt_failed(out):
        if salvage is not None:
            salvage(out)

    for attempt, probe_timeout in enumerate(PROBE_TIMEOUTS_S, start=1):
        if not _probe_tpu(probe_timeout):
            stages.append({"stage": "backend_init", "attempt": attempt,
                           "error": "tpu probe failed/timed out"})
            continue
        rc, out, err = _run_sub([script_path, "--_worker", "tpu"],
                                worker_timeout)
        if rc == 0 and parse(out, stages):
            return True
        attempt_failed(out)
        stages.append({"stage": "tpu_bench", "attempt": attempt, "rc": rc,
                       "error": err[-500:]})
        print(f"[bench] tpu attempt {attempt} failed rc={rc}: {err[-500:]}",
              file=sys.stderr, flush=True)

    print("[bench] falling back to 8-device simulated-CPU mesh",
          file=sys.stderr, flush=True)
    rc, out, err = _run_sub([script_path, "--_worker", "cpu"], worker_timeout)
    if rc == 0 and parse(out, stages):
        return True
    attempt_failed(out)
    stages.append({"stage": "cpu_bench", "rc": rc, "error": err[-500:]})
    emit_failure(stages)
    return False


# Last successful on-TPU headline result, committed as evidence: the tunnel
# to the single real chip has been observed to stay unreachable for hours at
# a stretch, so a CPU-fallback (or failed) run carries the most recent real
# number along, clearly labeled with its capture time.
TPU_EVIDENCE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "BENCH_TPU_LAST.json")


def _write_evidence(rows: list, path: str, metric: str, n_expected: int,
                    headline_config: str = "topk1pct") -> None:
    """Write the TPU evidence file from the rows measured so far. Called
    after EVERY row on TPU so a mid-run tunnel death still leaves the dense
    baseline (and any completed configs) on disk, clearly marked partial."""
    import datetime
    comp = next((r for r in rows if r.get("config") == headline_config), None)
    rec = {
        "metric": metric,
        "value": comp["imgs_per_sec"] if comp else None,
        "unit": "imgs/sec",
        "vs_baseline": comp["vs_baseline"] if comp else None,
        "platform": "tpu",
        "n_devices": rows[0].get("n_devices"),
        "chip": rows[0].get("chip"),
        "peak_flops": rows[0].get("peak_flops"),
        "mfu": comp.get("mfu") if comp else None,
        "partial": len(rows) < n_expected,
        "rows_measured": len(rows),
        "rows_expected": n_expected,
        "rows": rows,
        "captured_at": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
    }
    # Atomic replace: a kill mid-write must not truncate the evidence the
    # row-by-row persistence exists to protect. And never let a lesser
    # record clobber a better one (a fresh attempt starts with rows=[];
    # its 1-row partial must not erase an earlier complete run or a longer
    # partial prefix) — demoted records go to a '.partial' sibling instead.
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
        old = load_tpu_evidence(path)
        os.replace(tmp, path + ".partial" if _regresses(rec, old) else path)
    except OSError as e:
        print(f"[bench] could not save TPU evidence: {e}",
              file=sys.stderr, flush=True)


def _regresses(new: dict, old) -> bool:
    """True iff writing ``new`` over ``old`` would lose evidence."""
    if not isinstance(old, dict):
        return False
    # Round-2-format records have no rows/partial fields; a non-null value
    # means they carry a real measured headline.
    old_partial = old.get("partial", old.get("value") is None)
    old_rows = old.get("rows_measured",
                       1 if old.get("value") is not None else 0)
    if not old_partial and new.get("partial"):
        return True
    return new.get("rows_measured", 0) < old_rows


def progressive_emit(emit, n_expected: int,
                     evidence_path: str = TPU_EVIDENCE_PATH,
                     metric: str = "resnet50_topk1pct_imgs_per_sec"):
    """Wrap a per-row emit callback with immediate TPU evidence persistence.
    ``n_expected`` is the sweep length — fewer persisted rows means the run
    died mid-sweep and the record is marked ``partial``."""
    rows: list = []

    def wrapped(r):
        rows.append(r)
        emit(r)
        if r.get("platform") == "tpu":
            _write_evidence(rows, evidence_path, metric, n_expected)

    return wrapped


def load_tpu_evidence(path: str = TPU_EVIDENCE_PATH):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def main() -> None:
    here = os.path.abspath(__file__)

    def parse(out, stages):
        result = _last_json_line(out)
        if result:
            result["stages"] = stages
            if result.get("platform") != "tpu":
                # TPU evidence is written by the worker itself, row by row;
                # a fallback run just carries the latest real number along.
                last = load_tpu_evidence()
                if last:
                    result["last_tpu"] = last
            print(json.dumps(result), flush=True)
        return result

    def emit_failure(stages):
        out = {
            "metric": "resnet50_topk1pct_imgs_per_sec",
            "value": None, "unit": "imgs/sec", "vs_baseline": None,
            "stages": stages,
        }
        last = load_tpu_evidence()
        if last:
            out["last_tpu"] = last
        print(json.dumps(out), flush=True)

    if not orchestrate(here, parse, emit_failure):
        sys.exit(1)


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--_worker":
        _worker(sys.argv[2])
    else:
        main()
