"""Headline benchmark: compressed vs uncompressed ResNet-50 training throughput.

Mirrors the reference's synthetic benchmark protocol
(examples/torch/pytorch_synthetic_benchmark.py:180-198: ResNet-50, random
data, img/sec over timed iterations) and the BASELINE.json north star: Top-K
k=1% + residual memory should reach >=90% of the uncompressed-allreduce
throughput. Runs the full GRACE pipeline (compensate -> compress -> update ->
exchange) on the available device mesh.

Prints ONE JSON line:
  {"metric": "resnet50_topk1pct_imgs_per_sec", "value": ..., "unit":
   "imgs/sec", "vs_baseline": <compressed/uncompressed throughput ratio>}
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax


def _build_step(grace_params, mesh, num_classes, sgd_lr=1e-3):
    from grace_tpu import grace_from_params
    from grace_tpu.models import resnet
    from grace_tpu.train import (init_stateful_train_state,
                                 make_stateful_train_step)

    grace = grace_from_params(grace_params)
    optimizer = optax.chain(grace.transform(seed=0), optax.sgd(sgd_lr))

    def loss_fn(params, mstate, batch):
        x, y = batch
        logits, new_mstate = resnet.apply(params, mstate, x.astype(jnp.bfloat16),
                                          train=True)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        return loss.mean(), new_mstate

    step = make_stateful_train_step(loss_fn, optimizer, mesh)
    params, mstate = resnet.init(jax.random.key(0), depth=50,
                                 num_classes=num_classes)
    ts = init_stateful_train_state(params, mstate, optimizer, mesh)
    return step, ts


def _throughput(step, ts, batch, n_batches, warmup=2):
    """Fetch-bounded timing window.

    On remote-tunneled platforms (axon) `jax.block_until_ready` does NOT
    wait for device execution — only a value fetch truly synchronizes. So:
    drain the queue with a fetch, time n dependent steps bounded by a final
    fetch, and subtract the measured fetch round-trip so the window covers
    device execution, not tunnel latency. Returns (imgs/sec, final state) —
    the step donates its inputs, so callers must thread the live state.
    """
    import time

    for _ in range(warmup):
        ts, loss = step(ts, batch)
    float(loss)                      # drain: all queued work done
    # RTT on a fresh trivial computation — re-fetching `loss` would hit
    # jax's cached host copy and measure nothing.
    t0 = time.perf_counter()
    float(loss + 1.0)
    rtt = time.perf_counter() - t0   # tiny-dispatch + fetch round-trip

    t0 = time.perf_counter()
    for _ in range(n_batches):
        ts, loss = step(ts, batch)
    float(loss)                      # bounds the window: steps are dependent
    dt = max(1e-9, time.perf_counter() - t0 - rtt)
    return batch[1].shape[0] * n_batches / dt, ts


def main():
    from grace_tpu.parallel import batch_sharded, data_parallel_mesh

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    mesh = data_parallel_mesh(devices)

    # Reference protocol: bs=32 per worker, ImageNet shapes on accelerators;
    # CPU fallback shrinks shapes so the bench stays runnable anywhere.
    per_device_bs = 32 if on_tpu else 4
    image_hw = 224 if on_tpu else 64
    n_batches = 30 if on_tpu else 3
    repeats = 2 if on_tpu else 1
    num_classes = 1000

    n = per_device_bs * len(devices)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((n, image_hw, image_hw, 3)),
                    jnp.float32)
    y = jnp.asarray(rng.integers(0, num_classes, (n,)), jnp.int32)
    batch = jax.device_put((x, y), batch_sharded(mesh))

    def run(grace_params):
        # best-of-N to damp chip/host jitter (~8% run-to-run on the tunnel)
        step, ts = _build_step(grace_params, mesh, num_classes)
        best = 0.0
        for _ in range(repeats):
            tput, ts = _throughput(step, ts, batch, n_batches, warmup=4)
            best = max(best, tput)
        return best

    # Both sides get the fusion buffer — Horovod fuses the uncompressed
    # baseline too, so a like-for-like ratio must as well.
    baseline = run({"compressor": "none", "memory": "none",
                    "communicator": "allreduce", "fusion": "flat"})
    compressed = run({"compressor": "topk", "compress_ratio": 0.01,
                      "memory": "residual", "communicator": "allgather",
                      "fusion": "flat"})

    print(json.dumps({
        "metric": "resnet50_topk1pct_imgs_per_sec",
        "value": round(compressed, 2),
        "unit": "imgs/sec",
        "vs_baseline": round(compressed / baseline, 4),
    }))


if __name__ == "__main__":
    main()
