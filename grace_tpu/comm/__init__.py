"""Communicators: XLA collectives over a named mesh axis.

TPU-native replacements for the reference's three communicators
(grace_dl/dist/communicator/{allreduce,allgather,broadcast}.py), which issue
eager c10d/Horovod NCCL calls per tensor. Here each communicator is a pure
function of the payload built from `jax.lax` collectives, traced inside
`shard_map`/`pjit` over a device mesh so XLA schedules them on ICI and
overlaps them with compute — no handle tables, no background thread
(cf. patch_files/horovod/torch/mpi_ops.py:68-75,423-439).

Compatibility matrix (reference IMPLEMENTING.md:43-45): ``Allreduce`` only
suits compressors whose payload is dense, same-shaped and summable (none,
fp16, randomk, powersgd); ``Allgather`` is general-purpose; ``Broadcast``
exists for parity and is realised with the same all-gather collective — a
loop of per-root broadcasts (grace_dl/dist/communicator/broadcast.py:18-33)
would serialise W collectives for an identical result.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import Communicator, Compressor, Ctx, Payload

__all__ = ["Allreduce", "Allgather", "Broadcast", "Identity",
           "SignAllreduce"]


def _psum_majority_vote(payload: Payload, ctx: Ctx, compressor: Compressor,
                        axis_name: str, vote_dtype: str) -> jax.Array:
    """Decompress this rank's ±1 signs, psum, re-sign: exact majority vote
    at fixed (world-size-independent) collective cost — SURVEY.md §7 hard
    part 4. Shared by SignAllreduce and the Allreduce vote routing."""
    if vote_dtype == "bfloat16":
        w = lax.axis_size(axis_name)       # static at trace time
        if w > 256:
            raise ValueError(
                f"vote_dtype='bfloat16' is integer-exact only up to world "
                f"size 256; this axis has {w} — use vote_dtype='float32'.")
    dec = compressor.decompress(payload, ctx)
    summed = lax.psum(dec.astype(vote_dtype), axis_name)
    out = (summed >= 0).astype(vote_dtype) * 2 - 1
    return out.astype(dec.dtype)


@dataclasses.dataclass(frozen=True)
class Allreduce(Communicator):
    """Sum payloads across ranks, then decompress once.

    Mirrors grace_dl/dist/communicator/allreduce.py:6-13: all-reduce each
    payload tensor, divide by world size if ``compressor.average``, then
    decompress the summed payload. Valid only for linear codecs — and unlike
    the reference, which merely documents that (IMPLEMENTING.md:43-45) and
    psums e.g. Top-K values belonging to different indices without complaint,
    this enforces ``compressor.summable_payload``. Majority-vote compressors
    (``vote_aggregate=True``: signsgd, signum) are legal here too and are
    routed through the fixed-cost psum vote (:class:`SignAllreduce`
    semantics) — psumming their packed sign *bytes* would be garbage.
    """

    vote_dtype: str = "bfloat16"

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        if getattr(compressor, "vote_aggregate", False):
            return _psum_majority_vote(payload, ctx, compressor,
                                       self.axis_name, self.vote_dtype)
        if not getattr(compressor, "summable_payload", False):
            raise TypeError(
                f"Allreduce requires a payload that sums meaningfully across "
                f"ranks; {type(compressor).__name__} does not declare "
                "summable_payload=True (its per-rank payloads decode "
                "differently, e.g. per-rank indices or norms). Use "
                "Allgather/Broadcast instead — reference compatibility "
                "matrix, IMPLEMENTING.md:43-45.")
        summed = tuple(lax.psum(t, self.axis_name) for t in payload)
        if compressor.average and payload:
            if not all(jnp.issubdtype(t.dtype, jnp.inexact) for t in summed):
                raise TypeError(
                    "Allreduce with average=True requires float payloads; "
                    f"got {[t.dtype for t in summed]}. Use Allgather for "
                    "integer-coded compressors (see IMPLEMENTING.md:43-45 "
                    "compatibility matrix in the reference).")
            w = self.world_size()
            summed = tuple(t / w for t in summed)
        return compressor.decompress(summed, ctx)


@dataclasses.dataclass(frozen=True)
class Allgather(Communicator):
    """Gather every rank's payload, decompress per rank, aggregate.

    Mirrors grace_dl/dist/communicator/allgather.py:7-45. The reference's
    variable-size path (gather sizes → pad → split, lines 16-38) is
    unnecessary: payloads are statically shaped under XLA, with invalid lanes
    zero-valued (see compressors with static-capacity payloads). Per-rank
    decompression is vmapped over the gathered world axis and runs as one
    fused XLA computation instead of the reference's Python loop
    (SURVEY.md §3.1 hot spot).
    """

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        if not payload:
            # e.g. PowerSGD: communication already happened inside compress.
            return compressor.decompress(payload, ctx)
        gathered = tuple(
            lax.all_gather(t, self.axis_name, axis=0, tiled=False)
            for t in payload)
        stacked = jax.vmap(lambda p: compressor.decompress(p, ctx))(gathered)
        out = compressor.aggregate(stacked)
        if compressor.average:
            out = out / self.world_size()
        return out


@dataclasses.dataclass(frozen=True)
class Broadcast(Allgather):
    """Parity alias for the reference's broadcast communicator.

    The reference loops over root ranks broadcasting each payload and
    decompressing it (grace_dl/dist/communicator/broadcast.py:18-33) — W
    sequential collectives computing exactly what one all-gather computes.
    On TPU we keep the all-gather realisation; semantics (per-rank decompress
    → aggregate → optional average) are identical.
    """


@dataclasses.dataclass(frozen=True)
class SignAllreduce(Communicator):
    """Majority vote via psum instead of allgather (SURVEY.md §7 hard part 4).

    Decompress this rank's payload to ±1, ``psum`` over the axis, re-sign —
    mathematically identical to Allgather + the sign compressors' majority-
    vote ``aggregate`` (sum of ±1 then sign), but the collective is a fixed-
    cost all-reduce instead of a world-size-proportional gather. Wire math
    per rank: allgather of packed signs receives (W-1)·n/8 bytes; an XLA
    ring all-reduce of ±1 in bf16 moves ~2·(2n) bytes regardless of W — so
    allgather wins on small meshes (W ≲ 32) and SignAllreduce wins on pod
    slices beyond that. Same decision the reference could not express: its
    allgather was the only variable-size-safe collective (IMPLEMENTING.md:
    43-45); here both sides are static-shaped, so the choice is free.

    Only valid for compressors whose decompressed tensors are exactly the
    vote inputs and whose aggregate is the majority vote (signsgd, signum).
    ``vote_dtype='bfloat16'`` is integer-exact for vote sums up to |W|=256;
    pick ``'float32'`` on larger meshes.
    """

    vote_dtype: str = "bfloat16"

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        if not getattr(compressor, "vote_aggregate", False):
            raise TypeError(
                "SignAllreduce implements majority-vote aggregation; "
                f"{type(compressor).__name__} does not declare "
                "vote_aggregate=True (its aggregate carries scaling the "
                "re-sign would drop) — use Allreduce/Allgather instead.")
        return _psum_majority_vote(payload, ctx, compressor,
                                   self.axis_name, self.vote_dtype)


@dataclasses.dataclass(frozen=True)
class Identity(Communicator):
    """No-op communicator: decompress this rank's own payload.

    No reference analog; used for single-device debugging and as the
    injectable no-comm fake the reference never wrote (SURVEY.md §4).
    """

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        return compressor.decompress(payload, ctx)
