"""Communicators: XLA collectives over a named mesh axis.

TPU-native replacements for the reference's three communicators
(grace_dl/dist/communicator/{allreduce,allgather,broadcast}.py), which issue
eager c10d/Horovod NCCL calls per tensor. Here each communicator is a pure
function of the payload built from `jax.lax` collectives, traced inside
`shard_map`/`pjit` over a device mesh so XLA schedules them on ICI and
overlaps them with compute — no handle tables, no background thread
(cf. patch_files/horovod/torch/mpi_ops.py:68-75,423-439).

Compatibility matrix (reference IMPLEMENTING.md:43-45): ``Allreduce`` only
suits compressors whose payload is dense, same-shaped and summable (none,
fp16, randomk, powersgd); ``Allgather`` is general-purpose; ``Broadcast``
exists for parity and is realised with the same all-gather collective — a
loop of per-root broadcasts (grace_dl/dist/communicator/broadcast.py:18-33)
would serialise W collectives for an identical result.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import (Communicator, Compressor, Ctx, LinkBytes,
                            Payload, SINGLE_SLICE, Topology, axis_size)
from grace_tpu.telemetry.scopes import (STAGE_DECOMPRESS, STAGE_EXCHANGE,
                                        STAGE_PIPELINE, STAGE_RING_HOP,
                                        trace_stage)

__all__ = ["Allreduce", "Allgather", "Broadcast", "Identity",
           "SignAllreduce", "TwoShotAllreduce", "RingAllreduce",
           "ReduceScatterAllreduce", "HierarchicalAllreduce",
           "vote_exact_max_world", "masked_broadcast",
           "masked_broadcast_tree"]


def vote_exact_max_world(vote_dtype) -> int:
    """Largest world size whose ±1 majority-vote sums stay integer-exact
    in ``vote_dtype`` — the declared numeric contract of the psum-vote
    routing, derived from first principles rather than hardcoded: a float
    with p explicit mantissa bits represents every integer up to
    ``2^(p+1)`` exactly (p stored bits plus the implicit leading one), and
    a W-rank vote tally lives in ``[-W, W]``, so the sum is exact iff
    ``W <= 2^(p+1)``. bfloat16 (p=7) gives the famous 256; float16 (p=10)
    gives 2048; float32 (p=23) gives 16,777,216.

    ONE constant, two enforcement points: the runtime check in
    ``_psum_majority_vote`` raises past the bound on a live mesh, and the
    static auditor's ``numeric_safety`` pass
    (:mod:`grace_tpu.analysis.flow`) re-verifies every traced vote psum
    against the same function — the bound can never drift between the
    docstring, the runtime guard, and the lint gate.
    """
    dt = jnp.dtype(vote_dtype)
    if not jnp.issubdtype(dt, jnp.floating):
        raise TypeError(f"vote_dtype must be a float dtype; got {dt.name}")
    return int(2 ** (jnp.finfo(dt).nmant + 1))


# XLA-TPU layout pathology guard (observed on BERT-base, 2026-08-01): a
# materialized 1-D f32[108793346] that feeds an all-reduce and is then
# consumed by a ~200-way slice/reshape fan-out gets assigned layout
# f32[54396673,2]{1,0:T(8,128)} — the minor-dim pad 2->128 inflates 435 MB
# to 27.8 GB and OOMs 16 GB HBM at compile time. Psumming such buffers in
# fixed-size chunks keeps every materialized piece small enough that XLA
# picks a sane layout (verified: same program compiles at 2.2 GB temp).
# ResNet-50's 25.5 M-element fused gradient does NOT trigger it (measured
# 4.7 MB temp), so chunking only engages above _PSUM_CHUNK_ELEMS to leave
# proven-clean programs byte-identical.
_PSUM_CHUNK_ELEMS = 8_388_608          # 32 MiB of f32 per collective chunk
_PSUM_CHUNK_THRESHOLD = 33_554_432     # chunk only oversized 1-D payloads

# Fraction of a pipelined segment's wire time the tuner may credit as
# hidden behind the neighbouring segment's compute (stage-1 encode /
# hop decode-accumulate-requant). Deliberately conservative: a 2-segment
# double buffer can at best hide min(compute, wire) of every inner
# boundary, and the hop kernels are far cheaper than the ppermute they
# overlap, so crediting half of the steady-state (P-1)/P overlap keeps
# the projection honest until a measured trace replaces it. ONE constant:
# ``wire_overlap_fraction`` here, the tuner's ``wire_pipeline`` discount
# (tuning/cost.py), and the bench projections all read it.
WIRE_PIPELINE_EFFICIENCY = 0.5


def _pipeline_segments(n: int, pipeline: int) -> list[tuple[int, int]]:
    """Static ``[lo, hi)`` bounds of the ``pipeline`` contiguous segments a
    flat ``n``-element buffer is split into by the double-buffered ring
    schedule. Equal ``ceil(n/P)`` segments (the last may be shorter);
    clamped so no segment is empty — tiny buffers simply pipeline less."""
    p = max(1, min(int(pipeline), n if n else 1))
    per = -(-n // p)
    return [(lo, min(lo + per, n)) for lo in range(0, max(n, 1), per)]


@dataclasses.dataclass(frozen=True)
class _PipelinedView:
    """Decompress-only adapter over P per-segment :class:`_ChunkedView`
    ctxs: each segment's stacked shard payloads decode and reassemble
    independently, then concatenate back into the full leaf — so every
    Memory's ``update`` sees one reconstruction of the whole buffer and
    the error-feedback contract is unchanged by pipelining."""

    inner: Compressor

    def decompress(self, payload: Payload, ctx) -> jax.Array:
        seg_ctxs, n, shape, dtype = ctx
        view = _ChunkedView(self.inner)
        parts = [view.decompress(p, c).reshape(-1)
                 for p, c in zip(payload, seg_ctxs)]
        flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        return flat[:n].reshape(shape).astype(dtype)


def _psum(t: jax.Array, axis_name: str) -> jax.Array:
    """``lax.psum`` with oversized 1-D operands split into chunked psums
    (numerically identical: psum is elementwise)."""
    if t.ndim != 1 or t.shape[0] <= _PSUM_CHUNK_THRESHOLD:
        return lax.psum(t, axis_name)
    n = t.shape[0]
    return jnp.concatenate([
        lax.psum(t[o:min(o + _PSUM_CHUNK_ELEMS, n)], axis_name)
        for o in range(0, n, _PSUM_CHUNK_ELEMS)])


def _psum_majority_vote(payload: Payload, ctx: Ctx, compressor: Compressor,
                        axis_name: str, vote_dtype: str) -> jax.Array:
    """Decompress this rank's ±1 signs, psum, re-sign: exact majority vote
    at fixed (world-size-independent) collective cost — SURVEY.md §7 hard
    part 4. Shared by SignAllreduce and the Allreduce vote routing."""
    w = axis_size(axis_name)           # static at trace time
    bound = vote_exact_max_world(vote_dtype)
    if w > bound:
        raise ValueError(
            f"vote_dtype={vote_dtype!r} is integer-exact only up to world "
            f"size {bound} (comm.vote_exact_max_world: 2^(mantissa+1)); "
            f"this axis has {w} — use vote_dtype='float32'.")
    with trace_stage(STAGE_DECOMPRESS):
        dec = compressor.decompress(payload, ctx)
    with trace_stage(f"{STAGE_EXCHANGE}/psum_vote"):
        summed = _psum(dec.astype(vote_dtype), axis_name)
    out = (summed >= 0).astype(vote_dtype) * 2 - 1
    return out.astype(dec.dtype)


def _algebra(compressor) -> str | None:
    """The codec's declared payload algebra (core.PAYLOAD_ALGEBRAS)."""
    return getattr(compressor, "payload_algebra", None)


def _check_payload_sum_world(compressor: Compressor, world: int,
                             schedule: str) -> None:
    """Runtime twin of the static shared-scale overflow gate: the payload-
    space sum of ``world`` ranks must stay exact in the payload dtype —
    the bound is the codec's OWN ``payload_sum_max_world`` constant (e.g.
    ``iinfo(accum_dtype).max // quantum_num`` for homomorphic QSGD), the
    same function flow pass 6 and the tuner's numeric gate evaluate, so
    the three enforcement points can never disagree (the
    ``vote_exact_max_world`` pattern)."""
    bound = compressor.payload_sum_max_world()
    if bound is not None and world > bound:
        raise ValueError(
            f"{schedule} sums {type(compressor).__name__} payloads across "
            f"{world} ranks but the payload dtype carries exact sums only "
            f"up to world {bound} (payload_sum_max_world: accumulator "
            "iinfo.max // max level) — widen accum_dtype or lower "
            "quantum_num; the numeric_safety pass rejects this statically "
            "from the same constant.")


_MB_UINT = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


def masked_broadcast(x: jax.Array, root, axis_name: str) -> jax.Array:
    """Bit-exact broadcast of rank ``root``'s value over ``axis_name``.

    Realised as an ``lax.axis_index``-masked psum in *integer bit space*:
    the value is reinterpreted as unsigned words, every rank except ``root``
    contributes zeros, and the integer sum reconstructs root's words exactly.
    A float-space masked psum would NOT be bit-exact (``-0.0 + 0.0 == +0.0``
    flips the sign bit, and NaN payloads are not preserved through float
    adds), which matters because the consensus repair path
    (:mod:`grace_tpu.resilience.consensus`) must leave replicas
    *bit-identical* — fingerprints are bit-pattern checksums.

    ``root`` may be a static int or a traced (replicated) scalar. Must be
    called where ``axis_name`` is bound (inside ``shard_map``/``pjit``).

    This integer-bit-space idiom is now *enforced repo-wide*: the static
    auditor's bit-exactness pass (:mod:`grace_tpu.analysis`,
    ``tools/graft_lint.py``) taint-tracks bitcast products through every
    registered config's jaxpr and fails CI on any float-space
    cross-replica reduction over them — re-introducing the PR-3 bug class
    is a lint error, not a code-review catch.
    """
    x = jnp.asarray(x)
    i = lax.axis_index(axis_name)
    is_root = (i == root)
    if x.dtype == jnp.bool_:
        v = x.astype(jnp.uint8)
        out = lax.psum(jnp.where(is_root, v, jnp.zeros_like(v)), axis_name)
        return out != 0
    if jnp.issubdtype(x.dtype, jnp.integer):
        masked = jnp.where(is_root, x, jnp.zeros_like(x))
        return lax.psum(masked, axis_name)
    uint = _MB_UINT[x.dtype.itemsize]
    bits = lax.bitcast_convert_type(x, uint)
    summed = lax.psum(jnp.where(is_root, bits, jnp.zeros_like(bits)),
                      axis_name)
    return lax.bitcast_convert_type(summed, x.dtype)


def masked_broadcast_tree(tree, root, axis_name: str):
    """:func:`masked_broadcast` over every array leaf of a pytree."""
    return jax.tree_util.tree_map(
        lambda l: masked_broadcast(l, root, axis_name), tree)


@dataclasses.dataclass(frozen=True)
class Allreduce(Communicator):
    """Sum payloads across ranks, then decompress once.

    Mirrors grace_dl/dist/communicator/allreduce.py:6-13: all-reduce each
    payload tensor, divide by world size if ``compressor.average``, then
    decompress the summed payload. Valid only for linear codecs — and unlike
    the reference, which merely documents that (IMPLEMENTING.md:43-45) and
    psums e.g. Top-K values belonging to different indices without complaint,
    this enforces ``compressor.summable_payload``. Majority-vote compressors
    (``vote_aggregate=True``: signsgd, signum) are legal here too and are
    routed through the fixed-cost psum vote (:class:`SignAllreduce`
    semantics) — psumming their packed sign *bytes* would be garbage.
    """

    vote_dtype: str = "bfloat16"

    def _recv_total_bytes(self, payload_nbytes: int, n_elems: int,
                          world: int, vote: bool = False) -> int:
        # max(0, W-1): the tuner enumerates degenerate meshes (W=0/1 single
        # rank, no exchange) and a negative byte price would rank them best.
        if vote:
            # psum of dense ±1 votes in bf16 (2 bytes), ring: 2·(W-1)/W·n·2
            return 2 * 2 * n_elems * max(0, world - 1) // max(1, world)
        return 2 * payload_nbytes * max(0, world - 1) // max(1, world)

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        if getattr(compressor, "vote_aggregate", False):
            return _psum_majority_vote(payload, ctx, compressor,
                                       self.axis_name, self.vote_dtype)
        if not getattr(compressor, "summable_payload", False):
            raise TypeError(
                f"Allreduce requires a payload that sums meaningfully across "
                f"ranks; {type(compressor).__name__} does not declare "
                "summable_payload=True (its per-rank payloads decode "
                "differently, e.g. per-rank indices or norms). Use "
                "Allgather/Broadcast instead — reference compatibility "
                "matrix, IMPLEMENTING.md:43-45.")
        homo = _algebra(compressor) in ("shared_scale", "sketch")
        if homo:
            _check_payload_sum_world(compressor, axis_size(self.axis_name),
                                     "Allreduce")
        with trace_stage(f"{STAGE_EXCHANGE}/psum"):
            summed = tuple(_psum(t, self.axis_name) for t in payload)
        if homo:
            # Homomorphic decode: integer level sums / merged sketch
            # tables decode ONCE, and the mean divides the decoded dense
            # tensor (an int payload cannot carry the /W; a sketch's
            # median estimate commutes with positive scaling either way).
            with trace_stage(STAGE_DECOMPRESS):
                out = compressor.decompress(summed, ctx)
            if compressor.average:
                out = out / self.world_size()
            return out
        if compressor.average and payload:
            if not all(jnp.issubdtype(t.dtype, jnp.inexact) for t in summed):
                raise TypeError(
                    "Allreduce with average=True requires float payloads; "
                    f"got {[t.dtype for t in summed]}. Use Allgather for "
                    "integer-coded compressors (see IMPLEMENTING.md:43-45 "
                    "compatibility matrix in the reference).")
            w = self.world_size()
            summed = tuple(t / w for t in summed)
        with trace_stage(STAGE_DECOMPRESS):
            return compressor.decompress(summed, ctx)


@dataclasses.dataclass(frozen=True)
class Allgather(Communicator):
    """Gather every rank's payload, decompress per rank, aggregate.

    Mirrors grace_dl/dist/communicator/allgather.py:7-45. The reference's
    variable-size path (gather sizes → pad → split, lines 16-38) is
    unnecessary: payloads are statically shaped under XLA, with invalid lanes
    zero-valued (see compressors with static-capacity payloads). Per-rank
    decompression is vmapped over the gathered world axis and runs as one
    fused XLA computation instead of the reference's Python loop
    (SURVEY.md §3.1 hot spot).
    """

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        if not payload:
            # e.g. PowerSGD: communication already happened inside compress.
            with trace_stage(STAGE_DECOMPRESS):
                return compressor.decompress(payload, ctx)
        with trace_stage(f"{STAGE_EXCHANGE}/all_gather"):
            gathered = tuple(
                lax.all_gather(t, self.axis_name, axis=0, tiled=False)
                for t in payload)
        with trace_stage(STAGE_DECOMPRESS):
            fused = getattr(compressor, "fused_aggregate_decompress", None)
            if fused is not None:
                out = fused(gathered, ctx, axis_size(self.axis_name))
                if out is not None:      # handles aggregate + average itself
                    return out
            stacked = jax.vmap(
                lambda p: compressor.decompress(p, ctx))(gathered)
            out = compressor.aggregate(stacked)
            if compressor.average:
                out = out / self.world_size()
            return out


@dataclasses.dataclass(frozen=True)
class Broadcast(Allgather):
    """Parity alias for the reference's broadcast communicator.

    The reference loops over root ranks broadcasting each payload and
    decompressing it (grace_dl/dist/communicator/broadcast.py:18-33) — W
    sequential collectives computing exactly what one all-gather computes.
    On TPU we keep the all-gather realisation; semantics (per-rank decompress
    → aggregate → optional average) are identical.
    """


@dataclasses.dataclass(frozen=True)
class SignAllreduce(Communicator):
    """Majority vote via psum instead of allgather (SURVEY.md §7 hard part 4).

    Decompress this rank's payload to ±1, ``psum`` over the axis, re-sign —
    mathematically identical to Allgather + the sign compressors' majority-
    vote ``aggregate`` (sum of ±1 then sign), but the collective is a fixed-
    cost all-reduce instead of a world-size-proportional gather. Wire math
    per rank: allgather of packed signs receives (W-1)·n/8 bytes; an XLA
    ring all-reduce of ±1 in bf16 moves ~2·(2n) bytes regardless of W — so
    allgather wins on small meshes (W ≲ 32) and SignAllreduce wins on pod
    slices beyond that. Same decision the reference could not express: its
    allgather was the only variable-size-safe collective (IMPLEMENTING.md:
    43-45); here both sides are static-shaped, so the choice is free.

    Only valid for compressors whose decompressed tensors are exactly the
    vote inputs and whose aggregate is the majority vote (signsgd, signum).
    ``vote_dtype='bfloat16'`` is integer-exact for vote sums up to |W|=256;
    pick ``'float32'`` on larger meshes.
    """

    vote_dtype: str = "bfloat16"

    def _recv_total_bytes(self, payload_nbytes: int, n_elems: int,
                          world: int, vote: bool = False) -> int:
        return 2 * 2 * n_elems * max(0, world - 1) // max(1, world)

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        if not getattr(compressor, "vote_aggregate", False):
            raise TypeError(
                "SignAllreduce implements majority-vote aggregation; "
                f"{type(compressor).__name__} does not declare "
                "vote_aggregate=True (its aggregate carries scaling the "
                "re-sign would drop) — use Allreduce/Allgather instead.")
        return _psum_majority_vote(payload, ctx, compressor,
                                   self.axis_name, self.vote_dtype)


def _split_ctx(ctx):
    """Partition a ctx pytree into (treedef, [leaf|None static], [arrays])."""
    leaves, treedef = jax.tree_util.tree_flatten(ctx)
    is_arr = [isinstance(l, (jax.Array, jnp.ndarray)) for l in leaves]
    static = [None if a else l for a, l in zip(is_arr, leaves)]
    arrays = [l for a, l in zip(is_arr, leaves) if a]
    return treedef, static, arrays


def _join_ctx(treedef, static, arrays):
    arrays = iter(arrays)
    leaves = [next(arrays) if s is None else s for s in static]
    return jax.tree_util.tree_unflatten(treedef, leaves)


@functools.lru_cache(maxsize=256)
def ctx_is_data_free(compressor: Compressor, n: int, dtype) -> bool:
    """True iff no ctx array leaf of ``compressor.compress`` depends on the
    *data* (rng-derived and constant leaves are fine). Cached per
    (compressor, n, dtype) — compressors are frozen dataclasses, so the
    answer is a pure config property and the extra compress trace is paid
    once, not per leaf per jit trace.

    TwoShotAllreduce decodes every rank's gathered stage-2 chunk with the
    rank-local ctx2 from compressing this rank's own (rank-divergent)
    aggregate. That is only sound when ctx array leaves are functions of
    shape and the shared rng alone — a codec that stashes e.g. its input's
    norm in ctx would silently corrupt every other rank's chunk. Checked
    structurally: trace ``compress`` to a jaxpr and taint-walk from the data
    input; conservative for opaque sub-calls (pjit/scan/cond propagate taint
    through all outputs), so a false *positive* is possible but a silent
    false negative is not.
    """
    def ctx_arrays(x, key):
        _, ctx, _ = compressor.compress(x, None, key)
        _, _, arrays = _split_ctx(ctx)
        return tuple(arrays)

    from jax.extend.core import Var

    closed = jax.make_jaxpr(ctx_arrays)(
        jax.ShapeDtypeStruct((n,), dtype),
        jax.eval_shape(lambda: jax.random.key(0)))
    jaxpr = closed.jaxpr
    tainted = {jaxpr.invars[0]}
    for eqn in jaxpr.eqns:
        if any(isinstance(v, Var) and v in tainted for v in eqn.invars):
            tainted.update(eqn.outvars)
    return not any(isinstance(v, Var) and v in tainted
                   for v in jaxpr.outvars)


@dataclasses.dataclass(frozen=True)
class _ChunkedView:
    """Decompress-only adapter: (w, …) stacked chunk payloads → full leaf.

    Lets every Memory's ``update`` (which only ever calls
    ``compressor.decompress``) compute the stage-1 residual/keep-mask of the
    two-shot pipeline without knowing about chunking. With stage-2 feedback,
    the owner's re-compression error is subtracted at the owned chunk so a
    residual-style memory (``compensated − decompress``) accumulates it."""

    inner: Compressor

    def decompress(self, payload: Payload, ctx) -> jax.Array:
        treedef, static, arr_stack, n, shape, dtype, stage2 = ctx

        def dec(p, arrs):
            return self.inner.decompress(p, _join_ctx(treedef, static, arrs))

        chunks = jax.vmap(dec)(payload, arr_stack)      # (w, m)
        flat = chunks.reshape(-1)
        if stage2 is not None:
            e2, start = stage2                          # own-chunk error (m,)
            flat = lax.dynamic_update_slice(
                flat, lax.dynamic_slice(flat, (start,), e2.shape)
                - e2.astype(flat.dtype), (start,))
        return flat[:n].reshape(shape).astype(dtype)


def _shard_compress(compressor: Compressor, chunks: jax.Array,
                    rng: jax.Array, comm_name: str, shared=None):
    """Stage-1 shard encode shared by the shard-parallel communicators
    (TwoShotAllreduce, RingAllreduce): probe one shard to pin the
    (shard-uniform) static ctx structure, then vmap ``compress`` over the
    ``(w, m)`` shard stack under shard-folded shared keys. Validates the
    shared soundness conditions — a wire payload must exist to shard, and
    ctx arrays must be data-free so every rank's locally derived ctx for
    shard ``c`` equals the one the sender compressed with (the condition
    that lets ranks decode each other's shard payloads without shipping
    ctx). ``shared`` is the hoisted shared-scale negotiation result
    (``payload_algebra == 'shared_scale'``): when present, every shard
    encodes against it and the data-free-ctx gate is replaced by the
    stronger collective-replication argument — the scale came out of a
    full-axis pmax, so the ctx it seeds is rank-identical by construction
    even though it is data-derived. Returns ``(payloads, ctx_arrays,
    treedef, static)`` with payloads and ctx arrays stacked along the
    shard axis."""
    w = chunks.shape[0]

    def enc(chunk, key):
        if shared is None:
            return compressor.compress(chunk, None, key)
        return compressor.compress(chunk, None, key, shared=shared)

    probe_payload, probe_ctx, _ = enc(chunks[0], jax.random.fold_in(rng, 0))
    if not probe_payload:
        raise TypeError(
            f"{comm_name} needs a wire payload to scatter; "
            f"{type(compressor).__name__} communicates inside compress "
            "— use Allreduce instead.")
    if shared is None and not ctx_is_data_free(compressor, chunks.shape[1],
                                               chunks.dtype):
        raise TypeError(
            f"{comm_name} requires a data-free ctx; "
            f"{type(compressor).__name__}.compress puts data-derived "
            "arrays in ctx, and ranks decode each other's shard payloads "
            "with locally derived ctx (identical across ranks only when "
            "ctx arrays are functions of shape and the shared rng alone) "
            "— other ranks' shards would decode against the wrong values. "
            "Keep data-derived arrays in the payload (they travel on the "
            "wire) or use Allgather/Allreduce.")
    treedef, static, _ = _split_ctx(probe_ctx)

    def comp_one(chunk, c):
        payload, ctx, _ = enc(chunk, jax.random.fold_in(rng, c))
        _, _, arrays = _split_ctx(ctx)
        return tuple(payload), tuple(arrays)

    payloads, ctx_arrays = jax.vmap(comp_one)(chunks, jnp.arange(w))
    return payloads, ctx_arrays, treedef, static


def _gathered_aggregate(base: Compressor, codec: Compressor, stacked,
                        ctx, k: int) -> jax.Array:
    """Aggregate ``k`` gathered wire payloads (leading axis ``k`` on every
    leaf) that share one data-free ``ctx`` — the requant boundaries'
    decode-and-reduce, shared by ReduceScatterAllreduce's owned chunk and
    HierarchicalAllreduce's slice/region boundaries. When ``codec``
    overrides :meth:`Compressor.decode_accumulate` (the wire-path codecs:
    qsgd/signsgd) the decode and the accumulate run as ONE fused pass —
    the payloads never materialise densely — and the singleton
    ``aggregate`` re-signs vote tallies exactly like the ring's final
    hop; otherwise the staged vmap-decompress + aggregate spelling runs
    unchanged. ``base`` supplies the aggregation semantics (sum or
    majority vote) even when a distinct WAN ``codec`` did the encode.

    The fused spelling engages only when the codec's wire kernels are
    LIVE (``codec.wire_fused()``): the K-way fused pass accumulates
    sequentially while the staged ``aggregate`` reduces with ``jnp.sum``,
    and float adds are not associative — with the kernel disabled the
    committed staged spelling must keep running bit-for-bit."""
    if (codec.wire_fused()
            and type(codec).decode_accumulate
            is not Compressor.decode_accumulate):
        parts = tuple(tuple(t[j] for t in stacked) for j in range(k))
        partial = codec.decode_accumulate(parts, (ctx,) * k)
        return base.aggregate(partial[None])
    decoded = jax.vmap(lambda p: codec.decompress(p, ctx))(stacked)
    return base.aggregate(decoded)


@dataclasses.dataclass(frozen=True)
class TwoShotAllreduce(Communicator):
    """Scatter–reduce–(re)compress all-reduce: O(k) wire per rank.

    The reference's only general communicator, allgather, costs every rank
    (W−1)·k received payload bytes — linear in world size
    (grace_dl/dist/communicator/allgather.py:7-45). The standard fix in the
    compression literature (ScaleCom's scatter-reduce, arXiv:2104.11125;
    DynamiQ's multi-hop compressed all-reduce, arXiv:2602.08923; EQuARX's
    quantized XLA all-reduce, arXiv:2506.17615) is a two-shot scheme, which
    XLA collectives express directly inside shard_map:

    1. split the compensated gradient into W equal chunks; compress each
       with a chunk-folded shared rng;
    2. ``all_to_all`` the stacked chunk payloads — rank i receives every
       rank's payload for chunk i (wire ≈ k);
    3. decompress + ``aggregate`` (sum / majority vote) the owned chunk,
       divide by W if ``compressor.average``;
    4. re-compress the aggregated chunk (shared stage-2 rng) and
       ``all_gather`` it (wire ≈ k); every rank decodes all W chunk
       aggregates and concatenates.

    Total ≈ 2k per rank vs allgather's (W−1)k: break-even at W=3, ~4× at
    W=8, ~100× on a 256-chip pod. Cost: the aggregate is compressed once
    more (stage-2 loss, not covered by error feedback — ScaleCom §III
    discusses why this is benign for mean-like aggregates), and selection
    codecs select per chunk rather than globally (same trade as
    ``topk_algorithm='chunk'``).

    Works with any *stateless* codec (stateful ones — signum momentum,
    powersgd Q — hold full-tensor state that has no per-chunk meaning and
    are rejected; powersgd's in-compress psum makes two-shot moot anyway).
    All memories compose: ``update`` sees a stage-1 reconstruction via
    :class:`_ChunkedView`.

    ``stage2_feedback=True`` (ScaleCom's chunk-owner error feedback,
    arXiv:2104.11125 §III) additionally folds each owner's stage-2
    re-compression error into its residual at the owned chunk, so a
    residual-style memory corrects it on later steps — each chunk has a
    fixed owner, so the whole stage-2 error is covered exactly once across
    ranks. Requires a memory whose update is ``compensated − decompress``
    (Residual/EFSignSGD/PowerSGD-style); DgcMemory interprets nonzero
    decompressed lanes as "transmitted" and would wrongly clear its
    accumulators over the whole owned chunk, so it is rejected.
    """

    stage2_feedback: bool = False
    shard_parallel = True

    def _recv_total_bytes(self, payload_nbytes: int, n_elems: int,
                          world: int, vote: bool = False) -> int:
        # stage-1 all_to_all + stage-2 all_gather, each ~payload_b·(W-1)/W
        return 2 * payload_nbytes * max(0, world - 1) // max(1, world)

    def step(self, x: jax.Array, mem_state, comp_state,
             memory, compressor: Compressor, rng: jax.Array):
        if comp_state is not None:
            raise TypeError(
                f"TwoShotAllreduce requires a stateless compressor; "
                f"{type(compressor).__name__} carries cross-step state "
                "(init_state != None) that has no per-chunk meaning — use "
                "Allgather/Allreduce instead.")
        shape, dtype = x.shape, x.dtype
        compensated, mem_state = memory.compensate(x, mem_state)
        flat = compensated.reshape(-1)
        n = flat.size
        w, _, pad = self.shard_spec(n)              # static at trace time
        chunks = jnp.pad(flat, (0, pad)).reshape(w, -1)

        # Stage 1: per-chunk compress under a chunk-folded shared key
        # (shared shard plumbing; the data-free-ctx gate is what makes
        # stage 3's decode of every rank's gathered chunk with the
        # rank-local ctx2 — built from this rank's own divergent
        # aggregate — sound).
        with trace_stage(f"{STAGE_EXCHANGE}/twoshot_stage1_compress"):
            payloads, ctx_arrays, treedef, static = _shard_compress(
                compressor, chunks, rng, "TwoShotAllreduce")

        if self.stage2_feedback:
            from grace_tpu.memories import DgcMemory
            if isinstance(memory, DgcMemory):
                raise TypeError(
                    "TwoShotAllreduce(stage2_feedback=True) is incompatible "
                    "with DgcMemory: its keep-mask reads decompress()==0 and "
                    "the injected stage-2 error would clear the accumulators "
                    "across the whole owned chunk. Use ResidualMemory or "
                    "disable stage2_feedback.")

        # Stage 2: swap chunk axis for world axis; aggregate the owned chunk.
        i = lax.axis_index(self.axis_name)
        with trace_stage(f"{STAGE_EXCHANGE}/twoshot_all_to_all"):
            mine = tuple(lax.all_to_all(p, self.axis_name, 0, 0)
                         for p in payloads)
        my_ctx = _join_ctx(treedef, static,
                           [jnp.take(a, i, axis=0) for a in ctx_arrays])
        stacked = jax.vmap(lambda p: compressor.decompress(p, my_ctx))(mine)
        agg = compressor.aggregate(stacked)
        if compressor.average:
            agg = agg / w

        # Stage 3: re-compress the aggregate (shared stage-2 key: ctx must
        # be chunk-index-independent so every rank can decode every chunk),
        # all-gather, decode, reassemble.
        agg = agg.astype(chunks.dtype)
        payload2, ctx2, _ = compressor.compress(
            agg, None, jax.random.fold_in(rng, w))

        stage2 = None
        if self.stage2_feedback:
            e2 = agg - compressor.decompress(payload2, ctx2)
            # A mean-aggregate dilutes a single owner's correction by 1/W;
            # pre-scale so the error is repaid exactly once across ranks.
            if compressor.average:
                e2 = e2 * w
            stage2 = (e2, i * chunks.shape[1])
        view_ctx = (treedef, static, ctx_arrays, n, shape, dtype, stage2)
        mem_state = memory.update(compensated, payloads, view_ctx,
                                  _ChunkedView(compressor), mem_state)

        with trace_stage(f"{STAGE_EXCHANGE}/twoshot_all_gather"):
            gathered = tuple(
                lax.all_gather(p, self.axis_name, axis=0, tiled=False)
                for p in payload2)
        with trace_stage(STAGE_DECOMPRESS):
            out = jax.vmap(
                lambda p: compressor.decompress(p, ctx2))(gathered)
        out = out.reshape(-1)[:n].reshape(shape).astype(dtype)
        return out, mem_state, comp_state

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        raise TypeError("TwoShotAllreduce re-chunks the gradient before "
                        "compression; it only supports the full step() "
                        "pipeline, not a bare exchange().")


@dataclasses.dataclass(frozen=True)
class RingAllreduce(Communicator):
    """Hop-pipelined compressed ring all-reduce: O(k) wire per rank.

    The classic ring decomposition (reduce-scatter around the ring, then
    all-gather the reduced shards) with the payload kept **compressed on
    every hop** — the regime EQuARX (quantized allreduce decomposed inside
    XLA, arXiv:2506.17615) and DynamiQ (compressed multi-hop all-reduce,
    arXiv:2602.08923) target. Expressed with ``lax.ppermute`` over the mesh
    axis so XLA schedules the W−1 neighbor exchanges on ICI:

    1. split the compensated gradient into W equal shards
       (``Communicator.shard_spec``); compress each with a shard-folded
       shared key (the stage-1 encode shared with ``TwoShotAllreduce`` —
       error-feedback memories see exactly this reconstruction);
    2. **reduce-scatter**, W−1 hops: at hop s rank i sends the running
       partial of shard (i−1−s) mod W to rank i+1 and receives shard
       (i−2−s) mod W from rank i−1; each hop decompresses the received
       payload, accumulates its own stage-1 contribution for that shard,
       and — on the requant path — re-compresses the partial for the next
       hop. After the last hop rank i holds the full reduction of shard i;
    3. **all-gather** the W reduced shards, still in wire format; every
       rank decodes all W and reassembles.

    Wire per rank ≈ 2·(W−1)/W·k received (like two-shot) vs allgather's
    (W−1)·k, and the aggregation work is spread around the ring instead of
    replicated on every rank (allgather) or concentrated on the shard owner
    (two-shot). Three accumulation paths, gated on the compressor's
    declared ``payload_algebra`` — the compatibility matrix is *enforced*,
    not documented:

    * **exact path** (``payload_algebra='exact'``: none, fp16/bf16,
      randomk) — the codec is linear, so hops add wire words directly
      (payload-space accumulation). No requant round-trip, no per-hop loss
      beyond the accumulation dtype; phase 2 gathers the summed payloads
      themselves.
    * **homomorphic path** (``payload_algebra='shared_scale'`` — homoqsgd,
      or ``'sketch'`` — countsketch): same zero-requant hop adds, but the
      scale negotiation is hoisted before stage 1 (one pmax; ctx becomes
      rank-identical by collective replication rather than data-freeness),
      the integer/sketch sums are bounded by the codec's
      ``payload_sum_max_world`` (runtime gate here, static twin in flow
      pass 6), and the mean divides AFTER the single final decode. ONE
      decode for the whole schedule, zero requant regardless of W — the
      THC regime that kills the tuner's ``MAX_REQUANT_CHAIN`` degradation.
    * **requant path** (``supports_hop_requant=True``: topk, qsgd, signsgd)
      — decompress → accumulate → requantize at each hop with a shared hop
      key (data-free ctx lets the receiver derive the sender's ctx
      locally). Each intermediate requant adds one codec error that is NOT
      covered by error feedback (the memory covers only the stage-1 encode,
      like two-shot's stage-2 loss) — W−2 intermediate hops + the final
      shard encode, so the requant error grows ~linearly in W. For
      vote codecs (signsgd) the hop requant re-signs the running partial —
      a *cascaded* vote whose result can differ from the one-shot majority
      on split coordinates (unanimous coordinates are preserved exactly).

    Works with any *stateless* codec (same gate as two-shot; powersgd
    communicates inside compress and is rejected at the wire-payload
    check). ``average`` divides the owned shard by W before the gather.
    Per-hop spans are named under ``STAGE_RING_HOP`` in device traces.
    The hop loop is unrolled at trace time (W−1 ppermutes of statically
    shaped payloads) — compile cost grows with W, the trade XLA's static
    ring collectives make themselves.

    **Double-buffered wire pipeline** (``pipeline=P > 1``): the flat
    buffer splits into P contiguous segments and each segment runs the
    WHOLE schedule above under its own ``grace/pipeline/<p>`` scope and
    rng fold — P independent collective chains, so XLA can overlap
    segment p's ppermute hops with segment p±1's encode/decode compute
    (the classic double buffer at P=2). Pure schedule restructuring:
    per-segment error feedback reassembles to the full buffer
    (:class:`_PipelinedView`), the static overlap auditor (flow pass 5)
    counts the chains, and the tuner credits
    ``wire_overlap_fraction`` = ``WIRE_PIPELINE_EFFICIENCY·(P−1)/P`` of
    the wire bill. ``pipeline=1`` is the committed single-chain schedule
    bit-for-bit. Segmentation DOES change the stochastic encodes (each
    segment folds its own keys), so a pipelined config is a different —
    equally valid — draw of the same estimator, not a bit-twin of its
    serial sibling.
    """

    pipeline: int = 1
    shard_parallel = True

    def __post_init__(self):
        if self.pipeline < 1:
            raise ValueError(
                f"RingAllreduce pipeline must be >= 1; got {self.pipeline} "
                "— it is the number of double-buffered buffer segments, "
                "each running the full hop schedule.")

    def wire_overlap_fraction(self) -> float:
        p = self.pipeline
        if p <= 1:
            return 0.0
        return WIRE_PIPELINE_EFFICIENCY * (p - 1) / p

    def _recv_total_bytes(self, payload_nbytes: int, n_elems: int,
                          world: int, vote: bool = False) -> int:
        # (W-1) reduce-scatter hop payloads + (W-1) gathered shard
        # payloads, each ~payload/W: ≈ 2·payload·(W-1)/W, flat in W.
        # Pipeline-invariant: P segments each move the same formula over
        # 1/P of the buffer; per-segment shard padding adds at most
        # P·(W-1) extra elements — inside the wire-reconciliation
        # tolerance, so the scalar model stays the serial one.
        return 2 * payload_nbytes * max(0, world - 1) // max(1, world)

    def step(self, x: jax.Array, mem_state, comp_state,
             memory, compressor: Compressor, rng: jax.Array):
        if comp_state is not None:
            raise TypeError(
                f"RingAllreduce requires a stateless compressor; "
                f"{type(compressor).__name__} carries cross-step state "
                "(init_state != None) that has no per-shard meaning — use "
                "Allgather/Allreduce instead.")
        algebra = _algebra(compressor)
        homo = algebra in ("shared_scale", "sketch")
        exact = bool(getattr(compressor, "summable_payload", False))
        requant = bool(getattr(compressor, "supports_hop_requant", False))
        if not (exact or requant):
            raise TypeError(
                f"RingAllreduce keeps the payload compressed on every hop, "
                "which needs a payload algebra (exact: none/fp16/randomk; "
                "shared_scale: homoqsgd; sketch: countsketch — all give "
                "exact payload-space accumulation) or an opt-in to per-hop "
                "requantization (supports_hop_requant=True: "
                "topk/qsgd/signsgd); "
                f"{type(compressor).__name__} declares neither — its "
                "payload carries structure a partial sum destroys. Use "
                "Allgather (general-purpose) or TwoShotAllreduce instead.")
        shape, dtype = x.shape, x.dtype
        compensated, mem_state = memory.compensate(x, mem_state)
        flat = compensated.reshape(-1)
        n = flat.size
        if homo:
            _check_payload_sum_world(compressor, axis_size(self.axis_name),
                                     "RingAllreduce")

        # Shared-scale negotiation, hoisted before stage 1 over the WHOLE
        # buffer (one per-bucket scale, not per shard or per pipeline
        # segment): every shard then encodes against the identical
        # replicated scale, so hop sums are exact and error feedback
        # covers this single encode.
        shared = None
        if algebra == "shared_scale":
            with trace_stage(f"{STAGE_EXCHANGE}/negotiate_scale"):
                shared = compressor.negotiate(flat, self.axis_name,
                                              rng=rng)

        segs = _pipeline_segments(n, self.pipeline)
        if len(segs) == 1:
            out, payloads, ctx_arrays, treedef, static = \
                self._segment_schedule(flat, compressor, rng, shared,
                                       homo, exact)
            # Error feedback covers the stage-1 encode exactly (the hop
            # requant losses are downstream of it, like two-shot's
            # stage-2 loss).
            view_ctx = (treedef, static, ctx_arrays, n, shape, dtype, None)
            mem_state = memory.update(compensated, payloads, view_ctx,
                                      _ChunkedView(compressor), mem_state)
        else:
            # Double-buffered schedule: every contiguous segment runs the
            # WHOLE ring under its own pipeline scope and rng fold — P
            # independent collective chains XLA can interleave, so
            # segment p's ppermutes hide behind segment p±1's
            # encode/decode compute. Error feedback still covers the
            # full-buffer stage-1 encode: the per-segment reconstructions
            # concatenate through _PipelinedView.
            outs, seg_pay, seg_ctx = [], [], []
            for p, (lo, hi) in enumerate(segs):
                with trace_stage(f"{STAGE_PIPELINE}/{p}"):
                    o, pay, arrs, treedef, static = \
                        self._segment_schedule(
                            flat[lo:hi], compressor,
                            jax.random.fold_in(rng, p), shared, homo,
                            exact)
                outs.append(o)
                seg_pay.append(pay)
                seg_ctx.append((treedef, static, arrs, hi - lo,
                                (hi - lo,), flat.dtype, None))
            out = jnp.concatenate(outs)
            view_ctx = (tuple(seg_ctx), n, shape, dtype)
            mem_state = memory.update(compensated, tuple(seg_pay),
                                      view_ctx, _PipelinedView(compressor),
                                      mem_state)
        out = out[:n].reshape(shape).astype(dtype)
        return out, mem_state, comp_state

    def _segment_schedule(self, flat, compressor: Compressor,
                          rng: jax.Array, shared, homo: bool, exact: bool):
        """One full ring schedule over one contiguous flat segment — the
        stage-1 shard encode, the W−1 hops, the gather and the decode,
        shared verbatim by the single-segment run (``pipeline=1``: the
        committed path bit-for-bit) and the pipelined segments. Returns
        ``(decoded flat segment, stage-1 payloads, ctx arrays, treedef,
        static)`` so the caller wires error feedback."""
        n = flat.shape[0]
        w, _, pad = self.shard_spec(n)              # static at trace time
        chunks = jnp.pad(flat, (0, pad)).reshape(w, -1)

        with trace_stage(f"{STAGE_EXCHANGE}/ring_stage1_compress"):
            payloads, ctx_arrays, treedef, static = _shard_compress(
                compressor, chunks, rng, "RingAllreduce", shared=shared)

        i = lax.axis_index(self.axis_name)
        perm = [(j, (j + 1) % w) for j in range(w)]

        def take_payload(stack, c):
            return tuple(jnp.take(t, c, axis=0) for t in stack)

        def shard_ctx(c):
            return _join_ctx(treedef, static,
                             [jnp.take(a, c, axis=0) for a in ctx_arrays])

        if exact:
            # Payload-space accumulation: decode-the-sum == sum-the-decodes
            # (the Allreduce linearity condition), so the wire format IS
            # the accumulator and phase 2 needs no re-encode. The same
            # hops serve all three algebras — homomorphic (shared_scale /
            # sketch) payloads add exactly as integers/merged tables, with
            # ZERO requant at any hop regardless of W.
            send = take_payload(payloads, (i - 1) % w)
            for s in range(w - 1):
                with trace_stage(f"{STAGE_RING_HOP}/{s}"):
                    recv = tuple(lax.ppermute(t, self.axis_name, perm)
                                 for t in send)
                    own = take_payload(payloads, (i - 2 - s) % w)
                    # payload_add is the codec's payload-space add —
                    # elementwise for plain wire words (the committed
                    # spelling bit-for-bit), a packed-field add (fused
                    # Pallas accumulate) for sub-byte homomorphic
                    # payloads that a byte-wise ``+`` would corrupt.
                    send = compressor.payload_add(recv, own)
            owned = send                 # wire-format reduction of shard i
            if compressor.average and not homo:
                if not all(jnp.issubdtype(t.dtype, jnp.inexact)
                           for t in owned):
                    raise TypeError(
                        "RingAllreduce with average=True requires float "
                        f"payloads; got {[t.dtype for t in owned]} — "
                        "integer-coded payloads cannot carry the mean "
                        "(reference compatibility matrix, "
                        "IMPLEMENTING.md:43-45; shared_scale/sketch "
                        "algebras divide after the final decode instead).")
                owned = tuple(t / w for t in owned)
            with trace_stage(f"{STAGE_EXCHANGE}/ring_all_gather"):
                gathered = tuple(
                    lax.all_gather(t, self.axis_name, axis=0, tiled=False)
                    for t in owned)
            with trace_stage(STAGE_DECOMPRESS):
                # gathered[j] is rank j's owned shard == shard j, so the
                # stacked stage-1 ctx arrays align by construction.
                def dec(p, arrs):
                    return compressor.decompress(
                        p, _join_ctx(treedef, static, list(arrs)))

                out = jax.vmap(dec)(gathered, ctx_arrays)
            if homo and compressor.average:
                # The ONE decode already happened; an int-level/sketch
                # payload cannot carry /W, so the mean lands on the dense
                # result — bit-equal placement to the escape psum's /W.
                out = out / w
        else:
            hop_ctx = None
            send = take_payload(payloads, (i - 1) % w)
            partial = None
            for s in range(w - 1):
                with trace_stage(f"{STAGE_RING_HOP}/{s}"):
                    recv = tuple(lax.ppermute(t, self.axis_name, perm)
                                 for t in send)
                    rc = (i - 2 - s) % w
                    # Hop 0 arrives in stage-1 format (per-shard keys);
                    # later hops in the previous hop's requant format. The
                    # receiver's own compress at the same shared key
                    # produced identical (data-free) ctx arrays, so the
                    # local hop_ctx decodes the neighbor's payload.
                    rctx = shard_ctx(rc) if s == 0 else hop_ctx
                    # decode_accumulate defaults to the committed
                    # sequential decompress-and-add spelling; wire-path
                    # codecs (qsgd/signsgd) override it with ONE fused
                    # Pallas decode→accumulate pass, bit-identical by the
                    # tests' contract.
                    partial = compressor.decode_accumulate(
                        (recv, take_payload(payloads, rc)),
                        (rctx, shard_ctx(rc)))
                    if s < w - 2:
                        pay, hop_ctx, _ = compressor.compress(
                            partial, None,
                            jax.random.fold_in(rng, w + 1 + s))
                        send = tuple(pay)
            if partial is None:                     # w == 1: nothing moved
                partial = compressor.decompress(take_payload(payloads, 0),
                                                shard_ctx(0))
            # Singleton stack: sum codecs pass through, vote codecs re-sign
            # the final tally — the one place the aggregate differs.
            owned = compressor.aggregate(partial[None])
            if compressor.average:
                owned = owned / w
            # Phase 2: one final shard encode under a shared key, gather
            # still in wire format, decode all W shards locally.
            payload2, ctx2, _ = compressor.compress(
                owned.astype(chunks.dtype), None, jax.random.fold_in(rng, w))
            with trace_stage(f"{STAGE_EXCHANGE}/ring_all_gather"):
                gathered = tuple(
                    lax.all_gather(t, self.axis_name, axis=0, tiled=False)
                    for t in payload2)
            with trace_stage(STAGE_DECOMPRESS):
                out = jax.vmap(
                    lambda p: compressor.decompress(p, ctx2))(gathered)
        return out.reshape(-1)[:n], payloads, ctx_arrays, treedef, static

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        raise TypeError("RingAllreduce re-shards the gradient before "
                        "compression; it only supports the full step() "
                        "pipeline, not a bare exchange().")


@dataclasses.dataclass(frozen=True)
class ReduceScatterAllreduce(Communicator):
    """One-shot compressed reduce-scatter + all-gather: the FSDP exchange.

    The sharded-model track's collective (``communicator: "rscatter"``):
    on a dp×fsdp mesh each device's gradient is already its fsdp shard's,
    and the reduce to compress is the **per-shard reduce-scatter over the
    dp axis**. This schedule expresses it as ONE ``all_to_all`` (the
    reduce-scatter's data movement) plus one ``all_gather``, instead of
    the ring's W−1 pipelined hops:

    1. split the compensated (per-shard) gradient into W equal chunks
       (``Communicator.shard_spec``); stage-1 encode shared with
       Ring/TwoShot via ``_shard_compress`` — error feedback covers it
       exactly, so residuals stay on the shard owner;
    2. ``all_to_all`` the stacked chunk payloads: rank i receives every
       dp peer's payload for chunk i (wire ≈ payload·(W−1)/W);
    3. reduce the owned chunk — this is where the PR-13 payload algebra
       pays off, with accumulation paths gated exactly like Ring's:

       * **exact / homomorphic path** (``summable_payload``: none, fp16,
         randomk; ``shared_scale``: homoqsgd — negotiation hoisted before
         stage 1, sum bounded by ``payload_sum_max_world``; ``sketch``:
         countsketch) — the W received payloads are summed **in payload
         space** and the summed wire words themselves are gathered in
         step 4. ZERO re-encode anywhere: unlike the ring (which also
         sums in payload space but pays W−1 hop latencies) and unlike
         TwoShot (which re-compresses the aggregate even for linear
         codecs), this path is bit-identical to the one-shot
         decode-of-the-sum at one collective's latency;
       * **single-requant path** (``supports_hop_requant=True``: topk,
         qsgd, signsgd) — decompress all W chunk payloads, ``aggregate``
         (sum, or a true one-shot majority vote for sign codecs — not
         the ring's cascaded vote), re-encode ONCE under a shared key.
         Exactly one requant boundary regardless of W — the flat ring
         pays W−2 intermediate requants, which is the ScaleCom
         degradation cliff the tuner's ``MAX_REQUANT_CHAIN`` gate
         rejects at pod scale; this schedule's requant chain is 1 at
         any W.

    4. ``all_gather`` the reduced shards, still in wire format; decode
       all W locally and reassemble.

    Wire per rank ≈ 2·payload·(W−1)/W received — same bytes as
    Ring/TwoShot, priced through the shared per-link model (a flat
    schedule: all-ICI within one slice, honestly all-DCN beyond it; pair
    with ``HierarchicalAllreduce`` when the dp axis crosses slices).
    Same enforced gates as Ring: stateless codec, wire payload, data-free
    ctx (or a hoisted negotiation), and ``summable_payload`` or
    ``supports_hop_requant``.
    """

    shard_parallel = True

    def _recv_total_bytes(self, payload_nbytes: int, n_elems: int,
                          world: int, vote: bool = False) -> int:
        # all_to_all receives (W-1)/W of the stacked stage-1 payloads +
        # all_gather receives (W-1) reduced shards of ~payload/W each.
        return 2 * payload_nbytes * max(0, world - 1) // max(1, world)

    def step(self, x: jax.Array, mem_state, comp_state,
             memory, compressor: Compressor, rng: jax.Array):
        if comp_state is not None:
            raise TypeError(
                f"ReduceScatterAllreduce requires a stateless compressor; "
                f"{type(compressor).__name__} carries cross-step state "
                "(init_state != None) that has no per-shard meaning — use "
                "Allgather/Allreduce instead.")
        algebra = _algebra(compressor)
        homo = algebra in ("shared_scale", "sketch")
        exact = bool(getattr(compressor, "summable_payload", False))
        requant = bool(getattr(compressor, "supports_hop_requant", False))
        if not (exact or requant):
            raise TypeError(
                f"ReduceScatterAllreduce sums or re-aggregates chunk "
                "payloads after the all_to_all, which needs a payload "
                "algebra (exact: none/fp16/randomk; shared_scale: "
                "homoqsgd; sketch: countsketch — exact payload-space "
                "summation at the owned chunk) or an opt-in to "
                "re-encoding the aggregate once "
                "(supports_hop_requant=True: topk/qsgd/signsgd); "
                f"{type(compressor).__name__} declares neither — its "
                "payload carries structure a partial sum destroys. Use "
                "Allgather (general-purpose) instead.")
        shape, dtype = x.shape, x.dtype
        compensated, mem_state = memory.compensate(x, mem_state)
        flat = compensated.reshape(-1)
        n = flat.size
        w, _, pad = self.shard_spec(n)              # static at trace time
        if homo:
            _check_payload_sum_world(compressor, w,
                                     "ReduceScatterAllreduce")
        chunks = jnp.pad(flat, (0, pad)).reshape(w, -1)

        # Shared-scale negotiation hoisted over the WHOLE buffer before
        # stage 1 (one pmax; every shard encodes against the identical
        # replicated scale), exactly as Ring/Hier do.
        shared = None
        if algebra == "shared_scale":
            with trace_stage(f"{STAGE_EXCHANGE}/negotiate_scale"):
                shared = compressor.negotiate(flat, self.axis_name,
                                              rng=rng)

        with trace_stage(f"{STAGE_EXCHANGE}/rscatter_stage1_compress"):
            payloads, ctx_arrays, treedef, static = _shard_compress(
                compressor, chunks, rng, "ReduceScatterAllreduce",
                shared=shared)

        # Error feedback covers the stage-1 shard encode exactly; the
        # single requant boundary (requant path only) is downstream of it
        # — the same contract as Ring/TwoShot.
        view_ctx = (treedef, static, ctx_arrays, n, shape, dtype, None)
        mem_state = memory.update(compensated, payloads, view_ctx,
                                  _ChunkedView(compressor), mem_state)

        i = lax.axis_index(self.axis_name)

        def shard_ctx(c):
            return _join_ctx(treedef, static,
                             [jnp.take(a, c, axis=0) for a in ctx_arrays])

        # The reduce-scatter's data movement: swap chunk axis for world
        # axis — rank i now holds every dp peer's payload for chunk i.
        with trace_stage(f"{STAGE_EXCHANGE}/rscatter_all_to_all"):
            mine = tuple(lax.all_to_all(p, self.axis_name, 0, 0)
                         for p in payloads)

        if exact:
            # Payload-space reduction of the owned chunk: the wire format
            # IS the accumulator, and phase 2 gathers the summed wire
            # words themselves — zero requant at any W. payload_sum is
            # the codec's stacked payload-space reduction: the committed
            # dtype-pinned jnp.sum for plain wire words (integer level
            # sums stay in the declared accumulator width), the fused
            # packed-field accumulate for sub-byte homomorphic payloads.
            owned = compressor.payload_sum(mine)
            if compressor.average and not homo:
                if not all(jnp.issubdtype(t.dtype, jnp.inexact)
                           for t in owned):
                    raise TypeError(
                        "ReduceScatterAllreduce with average=True requires "
                        f"float payloads; got {[t.dtype for t in owned]} — "
                        "integer-coded payloads cannot carry the mean "
                        "(shared_scale/sketch algebras divide after the "
                        "final decode instead).")
                owned = tuple(t / w for t in owned)
            with trace_stage(f"{STAGE_EXCHANGE}/rscatter_all_gather"):
                gathered = tuple(
                    lax.all_gather(t, self.axis_name, axis=0, tiled=False)
                    for t in owned)
            with trace_stage(STAGE_DECOMPRESS):
                # gathered[j] is rank j's owned shard == shard j, so the
                # stacked stage-1 ctx arrays align by construction.
                def dec(p, arrs):
                    return compressor.decompress(
                        p, _join_ctx(treedef, static, list(arrs)))

                out = jax.vmap(dec)(gathered, ctx_arrays)
            if homo and compressor.average:
                # The ONE decode already happened; int/sketch payloads
                # cannot carry /W, so the mean divides the dense result.
                out = out / w
        else:
            # Single-requant path: decode all W contributions for the
            # owned chunk with the locally derived (data-free) ctx,
            # aggregate — a true ONE-SHOT sum/majority vote, not the
            # ring's cascaded one — and re-encode exactly once under a
            # shared key every rank can decode. _gathered_aggregate fuses
            # the decode+reduce into one kernel pass for wire-path codecs.
            my_ctx = shard_ctx(i)
            agg = _gathered_aggregate(compressor, compressor, mine,
                                      my_ctx, w)
            if compressor.average:
                agg = agg / w
            payload2, ctx2, _ = compressor.compress(
                agg.astype(chunks.dtype), None, jax.random.fold_in(rng, w))
            with trace_stage(f"{STAGE_EXCHANGE}/rscatter_all_gather"):
                gathered = tuple(
                    lax.all_gather(t, self.axis_name, axis=0, tiled=False)
                    for t in payload2)
            with trace_stage(STAGE_DECOMPRESS):
                out = jax.vmap(
                    lambda p: compressor.decompress(p, ctx2))(gathered)
        out = out.reshape(-1)[:n].reshape(shape).astype(dtype)
        return out, mem_state, comp_state

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        raise TypeError("ReduceScatterAllreduce re-shards the gradient "
                        "before compression; it only supports the full "
                        "step() pipeline, not a bare exchange().")


@dataclasses.dataclass(frozen=True)
class HierarchicalAllreduce(Communicator):
    """Multi-level ICI×DCN[×WAN] compressed all-reduce: the cross-slice
    (and, with ``region_size``, cross-region) schedule.

    Every flat communicator above treats the mesh axis as one ring/gather —
    which goes all-DCN the moment the axis crosses an ICI slice (see
    ``Communicator.recv_link_bytes``), and is why topk+allgather *loses* to
    dense at W=256 over DCN in the bench projections. This is the
    DynamiQ-style fix (compressed multi-hop allreduce, arXiv:2602.08923;
    THC's aggregation-friendly encodings): exploit the bandwidth hierarchy
    with a two-level schedule that keeps the bulk of the traffic on the fast
    intra-slice links and ships only the S-times-smaller per-slice partials
    across DCN. With ``slice_size=S`` on a world of ``W = K·S`` ranks
    (ranks ``[k·S, (k+1)·S)`` form slice ``k`` — the
    :class:`~grace_tpu.core.Topology` layout):

    1. **intra-slice ring reduce-scatter** (S−1 ``ppermute`` hops over ICI):
       split the compensated gradient into S shards
       (stage-1 encode shared with Ring/TwoShot via ``_shard_compress``;
       error feedback covers it exactly), then run the PR-4 hop machinery
       over the *slice sub-axis* — the permutation rotates ranks within
       their slice only, so no hop touches DCN. After the last hop, local
       rank ℓ of every slice holds its slice's partial of shard ℓ.
    2. **cross-slice exchange** (one grouped ``all_gather`` over DCN):
       the K ranks sharing local index ℓ — one per slice — exchange their
       shard-ℓ partials. Linear codecs (``summable_payload``) ship the
       wire-format partial and sum in payload space (zero extra loss);
       requant codecs (``supports_hop_requant``) re-encode the partial
       ONCE at the slice boundary, gather, decompress all K and
       ``aggregate`` (sum / majority vote). Either way the DCN leg moves
       ≈(K−1)·k/S bytes per rank — ~S²/K× less than the flat allgather's
       (W−1)·k once the whole flat schedule is priced at DCN (the flat
       *ring* moves 2·k over DCN: less than this leg beyond K=2S slices,
       but it pays every hop's latency through the boundary link, which
       the critical-path byte model deliberately understates).
    3. **intra-slice all-gather** (grouped over ICI): every slice gathers
       its S reduced shards, still in wire format, and decodes locally.

    Wire per rank: ``2·k·(S−1)/S`` over ICI + ``(K−1)·k/S`` over DCN — the
    first genuinely *mixed* ``recv_link_bytes`` split in the repo; bench
    xslice projections, telemetry's per-link fields, and graft-lint's
    wire-reconciliation pass all price it through the override below.
    ``slice_size=None`` (or ``world <= slice_size``) collapses the schedule
    and the model to the flat ring bit-for-bit: one slice, no DCN leg.

    **Three-level (region) schedule**: ``region_size=Rz`` ranks (a whole
    number of slices, ``Kr = Rz/S`` per region) adds the WAN tier. The
    cross-slice exchange splits in two: the boundary partial is first
    summed/aggregated *within the region* over DCN (the ``Kr``-member
    groups), then the region partial crosses regions over WAN (the
    ``R``-member groups, ``R = W/Rz``). Exact/homomorphic payloads cross
    WAN exactly-summable (the zero-requant property one level up —
    ``wan_compressor`` is rejected for them); requant codecs re-encode the
    region partial ONCE at the region boundary, optionally through a more
    *aggressive per-level codec* (``wan_compressor``, itself a
    ``supports_hop_requant`` codec with a data-free ctx) so the
    ~100×-slower WAN leg ships ``(R−1)·k_wan/S`` bytes at whatever ratio
    the WAN budget demands. ``region_size=None`` (or ``world <=
    region_size``, or a single region after an elastic shrink) collapses
    the schedule and the model to the two-level one bit-for-bit.

    Same enforced gates as Ring: stateless codec, wire payload, data-free
    ctx, and ``summable_payload`` or ``supports_hop_requant``. Requant loss:
    S−2 intermediate intra-slice hops + 1 slice-boundary encode
    [+ 1 region-boundary encode when R > 1] + 1 final shard encode — each
    boundary encode is paid once regardless of Kr/R (a cross-slice or
    cross-region *ring* would pay a requant per hop), which is the point of
    aggregating the gathered partials locally instead of hopping them.
    ``world % S != 0`` / ``world % Rz != 0`` are trace-time ValueErrors (an
    uneven split would silently mis-shard).
    """

    slice_size: Optional[int] = None
    region_size: Optional[int] = None
    wan_compressor: Optional[Compressor] = None
    pipeline: int = 1
    shard_parallel = True

    def __post_init__(self):
        if self.pipeline < 1:
            raise ValueError(
                "HierarchicalAllreduce pipeline must be >= 1; got "
                f"{self.pipeline} — it is the number of double-buffered "
                "buffer segments, each running the full multi-level "
                "schedule (the RingAllreduce.pipeline semantics applied "
                "to the intra-slice ring and both boundary exchanges).")
        if self.slice_size is not None and self.slice_size < 1:
            raise ValueError(f"slice_size must be >= 1 or None; "
                             f"got {self.slice_size}")
        if self.region_size is not None:
            if self.slice_size is None:
                raise ValueError(
                    "HierarchicalAllreduce(region_size=...) requires "
                    "slice_size — the region tier groups whole ICI slices, "
                    "so a three-level schedule without a slice level is "
                    f"contradictory (got region_size={self.region_size}, "
                    "slice_size=None).")
            if (self.region_size < self.slice_size
                    or self.region_size % self.slice_size):
                raise ValueError(
                    f"region_size {self.region_size} must be a whole "
                    f"multiple of slice_size {self.slice_size} — regions "
                    "are made of whole slices (the Topology contract).")
        if self.wan_compressor is not None and self.region_size is None:
            raise ValueError(
                "HierarchicalAllreduce(wan_compressor=...) without "
                "region_size — there is no WAN level to re-encode for; "
                "set region_size or drop the WAN codec.")

    def shrunk(self, topology: Topology) -> "HierarchicalAllreduce":
        """The communicator for a post-resize world described by
        ``topology`` (typically :meth:`grace_tpu.core.Topology.shrink`'s
        result): same axis, the surviving tier widths. A whole-region loss
        keeps both tiers (R→R−1 never touches intra-region schedule); a
        whole-slice loss keeps ``slice_size`` (K→K−1); a partial-slice
        loss hands back the flat ring — matching the topology collapse.
        The WAN codec rides along only while a region tier survives (a
        two-level or flat schedule has no WAN leg to encode for)."""
        wan = self.wan_compressor if topology.region_size is not None \
            else None
        return dataclasses.replace(self, slice_size=topology.slice_size,
                                   region_size=topology.region_size,
                                   wan_compressor=wan)

    def wire_overlap_fraction(self) -> float:
        p = self.pipeline
        if p <= 1:
            return 0.0
        return WIRE_PIPELINE_EFFICIENCY * (p - 1) / p

    def _split(self, world: int) -> tuple[int, int]:
        """(intra-slice size S, slice count K) for this world. Static."""
        s = self.slice_size
        if s is None or world <= s:
            return max(1, world), 1
        if world % s:
            raise ValueError(
                f"HierarchicalAllreduce(slice_size={s}) does not divide "
                f"world size {world} — the two-level schedule needs whole "
                "slices (ranks [k*S, (k+1)*S) per slice); run on a "
                "world that is a multiple of slice_size or adjust "
                "slice_size to the physical slice width.")
        return s, world // s

    def _split3(self, world: int) -> tuple[int, int, int]:
        """(S intra-slice, Kr slices per region, R regions). Static.
        ``R == 1`` is the two-level schedule (and ``Kr`` its K); a world
        inside one region never pays a WAN leg."""
        s, k = self._split(world)
        rz = self.region_size
        if rz is None or k == 1 or world <= rz:
            return s, k, 1
        if world % rz:
            raise ValueError(
                f"HierarchicalAllreduce(region_size={rz}) does not divide "
                f"world size {world} — the three-level schedule needs "
                "whole regions (ranks [r*Rz, (r+1)*Rz) per region); run "
                "on a world that is a multiple of region_size or adjust "
                "region_size to the physical region width.")
        return s, rz // s, world // rz

    def _wan_leg_nbytes(self, payload_nbytes: int, n_elems: int,
                        s: int, r: int) -> int:
        """Per-rank WAN-leg bytes: (R−1) region partials of one shard.
        With a ``wan_compressor`` the shard crosses at the WAN codec's own
        payload width (sized on the padded float32 shard — the dtype every
        registered config's compensated gradient carries), else at the
        base payload's per-shard share."""
        if r <= 1:
            return 0
        per = payload_nbytes // max(1, s)
        if self.wan_compressor is not None:
            from grace_tpu.utils.metrics import payload_nbytes as _pnb
            n = int(n_elems)
            shard = (n + (-n) % max(1, s)) // max(1, s)
            per = int(_pnb(self.wan_compressor,
                           jax.ShapeDtypeStruct((shard,), jnp.float32)))
        return (r - 1) * per

    def _recv_total_bytes(self, payload_nbytes: int, n_elems: int,
                          world: int, vote: bool = False) -> int:
        s, kr, r = self._split3(world)
        # (S-1) intra hops + (S-1) gathered shards of ~payload/S each over
        # ICI; (Kr-1) cross-slice partials of ~payload/S over DCN; (R-1)
        # cross-region partials over WAN (at the WAN codec's width when one
        # is armed). R == 1 reduces to the committed two-level formula
        # bit-for-bit (Kr is then the full slice count K).
        intra = 2 * payload_nbytes * (s - 1) // max(1, s)
        dcn = (kr - 1) * payload_nbytes // max(1, s)
        return intra + dcn + self._wan_leg_nbytes(payload_nbytes, n_elems,
                                                  s, r)

    def recv_link_bytes(self, payload_nbytes: int, n_elems: int, world: int,
                        topology=None, vote: bool = False) -> LinkBytes:
        """The genuinely mixed (ici, dcn, wan) split: intra-slice legs ride
        ICI, the cross-slice gather rides DCN, the cross-region gather
        rides WAN — *when the schedule's groupings nest inside the physical
        ones*. A mismatched layout degrades tier by tier to the flat
        communicators' worst-boundary critical path, honestly: comm slices
        straddling physical slices price everything at the worst tier the
        axis spans; comm regions straddling physical regions (or a
        two-level schedule on a three-tier fleet) price the whole
        cross-slice traffic at WAN, because some group member's incoming
        link is a region boundary."""
        total = int(self._recv_total_bytes(payload_nbytes, n_elems, world,
                                           vote=vote))
        topo = topology if topology is not None else SINGLE_SLICE
        if not topo.crosses_dcn(world):
            return LinkBytes(ici=total, dcn=0)
        s, kr, r = self._split3(world)
        k = kr * r
        aligned = (k > 1 and topo.slice_size is not None
                   and s <= topo.slice_size and topo.slice_size % s == 0)
        if not aligned:
            # k == 1: the comm thinks the axis is one slice but it
            # physically is not — its "intra-slice" ring crosses the worst
            # boundary the axis spans, exactly the flat-ring indictment.
            if topo.crosses_wan(world):
                return LinkBytes(ici=0, dcn=0, wan=total)
            return LinkBytes(ici=0, dcn=total)
        intra = 2 * payload_nbytes * (s - 1) // max(1, s)
        cross = total - intra
        if not topo.crosses_wan(world):
            # No physical WAN boundary inside this axis: both cross legs
            # (if the schedule even has two) ride DCN.
            return LinkBytes(ici=intra, dcn=cross)
        region_aligned = (r > 1 and topo.region_size is not None
                          and self.region_size <= topo.region_size
                          and topo.region_size % self.region_size == 0)
        if not region_aligned:
            # A two-level schedule on a three-tier fleet (or comm regions
            # straddling physical regions): every cross-slice group spans
            # a region boundary, so the whole cross bill lands on WAN.
            return LinkBytes(ici=intra, dcn=0, wan=cross)
        dcn_leg = (kr - 1) * payload_nbytes // max(1, s)
        return LinkBytes(ici=intra, dcn=dcn_leg, wan=cross - dcn_leg)

    def step(self, x: jax.Array, mem_state, comp_state,
             memory, compressor: Compressor, rng: jax.Array):
        if comp_state is not None:
            raise TypeError(
                f"HierarchicalAllreduce requires a stateless compressor; "
                f"{type(compressor).__name__} carries cross-step state "
                "(init_state != None) that has no per-shard meaning — use "
                "Allgather/Allreduce instead.")
        algebra = _algebra(compressor)
        homo = algebra in ("shared_scale", "sketch")
        exact = bool(getattr(compressor, "summable_payload", False))
        requant = bool(getattr(compressor, "supports_hop_requant", False))
        if not (exact or requant):
            raise TypeError(
                f"HierarchicalAllreduce keeps the payload compressed on "
                "every hop and re-aggregates the per-slice partials, which "
                "needs a payload algebra (exact: none/fp16/randomk; "
                "shared_scale: homoqsgd; sketch: countsketch — exact "
                "payload-space accumulation through BOTH levels) or an "
                "opt-in to per-hop requantization "
                "(supports_hop_requant=True: topk/qsgd/signsgd); "
                f"{type(compressor).__name__} declares neither — its "
                "payload carries structure a partial sum destroys. Use "
                "Allgather (general-purpose) or TwoShotAllreduce instead.")
        w = axis_size(self.axis_name)            # static at trace time
        s, kr, r = self._split3(w)
        k = kr * r
        if self.wan_compressor is not None:
            if exact:
                raise TypeError(
                    f"HierarchicalAllreduce(wan_compressor="
                    f"{type(self.wan_compressor).__name__}) with "
                    f"{type(compressor).__name__}: exact/homomorphic "
                    "payloads cross WAN exactly-summable — that zero-"
                    "requant property is the whole reason to use them, and "
                    "a WAN re-encode would break the payload-space sum "
                    "while adding loss. Drop wan_compressor, or pair it "
                    "with a supports_hop_requant base codec.")
            if not getattr(self.wan_compressor, "supports_hop_requant",
                           False):
                raise TypeError(
                    "HierarchicalAllreduce wan_compressor re-encodes the "
                    "region partial at the region boundary — a hop requant "
                    "one level up — so it must declare "
                    "supports_hop_requant (topk/qsgd/signsgd); "
                    f"{type(self.wan_compressor).__name__} does not.")
        # The full multi-level sum spans W = R·Kr·S ranks (S-term
        # intra-slice partials, Kr of them summed at the slice boundary, R
        # region partials summed across WAN), so the shared-scale
        # accumulator bound is on W — not S — exactly as the static gate
        # prices it.
        if homo:
            _check_payload_sum_world(compressor, w, "HierarchicalAllreduce")
        shape, dtype = x.shape, x.dtype
        compensated, mem_state = memory.compensate(x, mem_state)
        flat = compensated.reshape(-1)
        n = flat.size

        # Shared-scale negotiation hoisted before stage 1: ONE full-axis
        # pmax (not per slice or per pipeline segment — a per-slice scale
        # would break the cross-slice payload sum), so the boundary
        # exchange stays a pure integer add with zero requant regardless
        # of K.
        shared = None
        if algebra == "shared_scale":
            with trace_stage(f"{STAGE_EXCHANGE}/negotiate_scale"):
                shared = compressor.negotiate(flat, self.axis_name,
                                              rng=rng)

        segs = _pipeline_segments(n, self.pipeline)
        if len(segs) == 1:
            out, payloads, ctx_arrays, treedef, static = \
                self._segment_schedule(flat, compressor, rng, shared,
                                       homo, exact, w, s, kr, r)
            # Error feedback covers the stage-1 shard encode exactly; the
            # intra-slice hop requants and the boundary re-encodes are
            # downstream of it (same contract as Ring/TwoShot).
            view_ctx = (treedef, static, ctx_arrays, n, shape, dtype, None)
            mem_state = memory.update(compensated, payloads, view_ctx,
                                      _ChunkedView(compressor), mem_state)
        else:
            # Double-buffered schedule (RingAllreduce.pipeline semantics):
            # each contiguous segment runs the WHOLE multi-level schedule
            # under its own pipeline scope and rng fold, so the
            # intra-slice ppermutes and both boundary gathers of segment p
            # can hide behind segment p±1's encode/decode compute.
            outs, seg_pay, seg_ctx = [], [], []
            for p, (lo, hi) in enumerate(segs):
                with trace_stage(f"{STAGE_PIPELINE}/{p}"):
                    o, pay, arrs, treedef, static = \
                        self._segment_schedule(
                            flat[lo:hi], compressor,
                            jax.random.fold_in(rng, p), shared, homo,
                            exact, w, s, kr, r)
                outs.append(o)
                seg_pay.append(pay)
                seg_ctx.append((treedef, static, arrs, hi - lo,
                                (hi - lo,), flat.dtype, None))
            out = jnp.concatenate(outs)
            view_ctx = (tuple(seg_ctx), n, shape, dtype)
            mem_state = memory.update(compensated, tuple(seg_pay),
                                      view_ctx, _PipelinedView(compressor),
                                      mem_state)
        out = out[:n].reshape(shape).astype(dtype)
        return out, mem_state, comp_state

    def _segment_schedule(self, flat, compressor: Compressor,
                          rng: jax.Array, shared, homo: bool, exact: bool,
                          w: int, s: int, kr: int, r: int):
        """One full multi-level schedule over one contiguous flat segment
        — stage-1 encode, S−1 intra-slice hops, the slice/region boundary
        exchanges, the gather and the decode — shared verbatim by the
        single-segment run (``pipeline=1``: the committed path
        bit-for-bit) and the pipelined segments."""
        k = kr * r
        n = flat.shape[0]
        pad = (-n) % s
        chunks = jnp.pad(flat, (0, pad)).reshape(s, -1)

        with trace_stage(f"{STAGE_EXCHANGE}/hier_stage1_compress"):
            payloads, ctx_arrays, treedef, static = _shard_compress(
                compressor, chunks, rng, "HierarchicalAllreduce",
                shared=shared)

        i = lax.axis_index(self.axis_name)
        local = i % s                            # position within the slice
        # Rotate within each slice only: rank j talks to its ICI neighbor,
        # never across a slice boundary. slice_size=None/one slice makes
        # this the flat ring permutation bit-for-bit.
        perm_intra = [(j, (j // s) * s + ((j % s) + 1) % s)
                      for j in range(w)]
        # Rank groups of the grouped collectives: cross-slice peers share
        # a local index; intra-slice peers share a slice. With a region
        # tier (R > 1) the cross-slice exchange splits level-by-level:
        # dcn_groups are the Kr slices of ONE region sharing a local index
        # (all-DCN), wan_groups one rank per region sharing (slice-in-
        # region, local) — by then every rank of a dcn group holds the
        # identical region partial, so any one member per region
        # represents it and the grouping stays a partition of the axis.
        cross_groups = [[kk * s + ll for kk in range(k)] for ll in range(s)]
        intra_groups = [[kk * s + ll for ll in range(s)] for kk in range(k)]
        if r > 1:
            rz = kr * s
            dcn_groups = [[rho * rz + kk * s + ll for kk in range(kr)]
                          for rho in range(r) for ll in range(s)]
            wan_groups = [[rho * rz + kk * s + ll for rho in range(r)]
                          for kk in range(kr) for ll in range(s)]
        else:
            dcn_groups, wan_groups = cross_groups, None

        def take_payload(stack, c):
            return tuple(jnp.take(t, c, axis=0) for t in stack)

        def shard_ctx(c):
            return _join_ctx(treedef, static,
                             [jnp.take(a, c, axis=0) for a in ctx_arrays])

        def gather_groups(payload, groups, stage):
            with trace_stage(stage):
                return tuple(
                    lax.all_gather(t, self.axis_name, axis=0, tiled=False,
                                   axis_index_groups=groups)
                    for t in payload)

        if exact:
            # Phase 1: payload-space ring reduce-scatter over the slice
            # sub-axis — identical hop logic to RingAllreduce with W -> S.
            # Serves all three algebras: homomorphic payloads (integer
            # levels under the hoisted shared scale, mergeable sketch
            # tables) hop-add with zero requant.
            send = take_payload(payloads, (local - 1) % s)
            for hop in range(s - 1):
                with trace_stage(f"{STAGE_RING_HOP}/{hop}"):
                    recv = tuple(lax.ppermute(t, self.axis_name, perm_intra)
                                 for t in send)
                    own = take_payload(payloads, (local - 2 - hop) % s)
                    # Codec payload-space add: elementwise for plain wire
                    # words (the committed spelling bit-for-bit), a fused
                    # packed-field accumulate for sub-byte homomorphic
                    # payloads (see RingAllreduce).
                    send = compressor.payload_add(recv, own)
            partial = send       # wire-format slice partial of shard `local`
            # Phase 2: the payload algebra makes the cross-slice exchange
            # an exact payload-space sum of the K slice partials — no
            # boundary requant (the requant path's ONE remaining re-encode
            # point, now zero), no extra loss, and only ~payload/S rides
            # DCN.
            if k > 1:
                stacked = gather_groups(
                    partial, dcn_groups,
                    f"{STAGE_EXCHANGE}/hier_cross_slice")
                # payload_sum pins the accumulation to the wire dtype:
                # numpy promotion would silently widen integer level sums
                # to int32 here, but the accumulator width is the codec's
                # declared contract (payload_sum_max_world bounds W so
                # THIS dtype is enough); packed homomorphic payloads
                # reduce in field space through the fused accumulate.
                owned = compressor.payload_sum(stacked)
                if r > 1:
                    # Level 3: the region partials cross WAN still in
                    # payload space — the exact/homomorphic algebra makes
                    # the (R-1)-partial WAN exchange a zero-requant sum,
                    # one tier up from the slice-boundary argument.
                    stacked_w = gather_groups(
                        owned, wan_groups,
                        f"{STAGE_EXCHANGE}/hier_cross_region")
                    owned = compressor.payload_sum(stacked_w)
            else:
                owned = partial
            if compressor.average and not homo:
                if not all(jnp.issubdtype(t.dtype, jnp.inexact)
                           for t in owned):
                    raise TypeError(
                        "HierarchicalAllreduce with average=True requires "
                        f"float payloads; got {[t.dtype for t in owned]} — "
                        "integer-coded payloads cannot carry the mean "
                        "(reference compatibility matrix, "
                        "IMPLEMENTING.md:43-45; shared_scale/sketch "
                        "algebras divide after the final decode instead).")
                owned = tuple(t / w for t in owned)
            # Phase 3: gather the S reduced shards within the slice, still
            # in wire format; gathered[j] is local rank j's shard == shard
            # j, so the stacked stage-1 ctx arrays align by construction.
            gathered = gather_groups(owned, intra_groups,
                                     f"{STAGE_EXCHANGE}/hier_all_gather")
            with trace_stage(STAGE_DECOMPRESS):
                def dec(p, arrs):
                    return compressor.decompress(
                        p, _join_ctx(treedef, static, list(arrs)))

                out = jax.vmap(dec)(gathered, ctx_arrays)
            if homo and compressor.average:
                # One decode for the whole two-level schedule; the mean
                # divides the dense result (int/sketch payloads cannot
                # carry /W).
                out = out / w
        else:
            # Phase 1: decompress -> accumulate -> requantize per intra
            # hop (shared hop keys; the receiver derives the sender's
            # data-free ctx locally — the Ring soundness argument).
            hop_ctx = None
            send = take_payload(payloads, (local - 1) % s)
            partial = None
            for hop in range(s - 1):
                with trace_stage(f"{STAGE_RING_HOP}/{hop}"):
                    recv = tuple(lax.ppermute(t, self.axis_name, perm_intra)
                                 for t in send)
                    rc = (local - 2 - hop) % s
                    rctx = shard_ctx(rc) if hop == 0 else hop_ctx
                    partial = (compressor.decompress(recv, rctx)
                               + compressor.decompress(
                                   take_payload(payloads, rc),
                                   shard_ctx(rc)))
                    if hop < s - 2:
                        pay, hop_ctx, _ = compressor.compress(
                            partial, None,
                            jax.random.fold_in(rng, s + 1 + hop))
                        send = tuple(pay)
            if partial is None:                  # s == 1: one-rank slices
                partial = compressor.decompress(take_payload(payloads, 0),
                                                shard_ctx(0))
            if k > 1:
                # The ONE slice-boundary requant: re-encode the slice
                # partial under a shared key, gather the Kr encoded
                # partials across the region's slices over DCN, decode and
                # aggregate locally (sum, or the majority vote for sign
                # codecs — every rank of a cross-slice group computes the
                # identical result).
                payload_b, ctx_b, _ = compressor.compress(
                    partial, None, jax.random.fold_in(rng, 2 * s))
                stacked = gather_groups(
                    tuple(payload_b), dcn_groups,
                    f"{STAGE_EXCHANGE}/hier_cross_slice")
                # Fused decode+aggregate of the Kr gathered slice partials
                # for wire-path codecs; the staged vmap-decompress +
                # aggregate spelling otherwise (see _gathered_aggregate).
                agg = _gathered_aggregate(compressor, compressor, stacked,
                                          ctx_b, kr)
                if r > 1:
                    # The ONE region-boundary requant, one level up: every
                    # rank of a dcn group now holds the identical region
                    # partial, so re-encode it — through the aggressive
                    # WAN codec when one is armed, else the base codec —
                    # under a shared key, gather the R encoded region
                    # partials across regions over WAN, decode and
                    # aggregate with the BASE codec's semantics (sum, or
                    # the cascaded majority vote). Paid once regardless of
                    # R; a cross-region ring would pay R-1 requants.
                    wan_codec = self.wan_compressor or compressor
                    if (self.wan_compressor is not None
                            and not ctx_is_data_free(
                                self.wan_compressor, agg.size, agg.dtype)):
                        raise TypeError(
                            "HierarchicalAllreduce wan_compressor needs a "
                            "data-free ctx — ranks decode each other's "
                            "region partials with locally derived ctx; "
                            f"{type(self.wan_compressor).__name__}."
                            "compress puts data-derived arrays in ctx.")
                    payload_w, ctx_w, _ = wan_codec.compress(
                        agg.astype(chunks.dtype), None,
                        jax.random.fold_in(rng, 2 * s + 2))
                    stacked_w = gather_groups(
                        tuple(payload_w), wan_groups,
                        f"{STAGE_EXCHANGE}/hier_cross_region")
                    # Base codec supplies the aggregation semantics even
                    # when the aggressive WAN codec did the encode.
                    agg = _gathered_aggregate(compressor, wan_codec,
                                              stacked_w, ctx_w, r)
            else:
                # Singleton stack: sum codecs pass through, vote codecs
                # re-sign the final tally — same as the flat ring.
                agg = compressor.aggregate(partial[None])
            if compressor.average:
                agg = agg / w
            # Final shard encode under a shared key; gather within the
            # slice still in wire format; decode all S shards locally.
            payload2, ctx2, _ = compressor.compress(
                agg.astype(chunks.dtype), None,
                jax.random.fold_in(rng, 2 * s + 1))
            gathered = gather_groups(tuple(payload2), intra_groups,
                                     f"{STAGE_EXCHANGE}/hier_all_gather")
            with trace_stage(STAGE_DECOMPRESS):
                out = jax.vmap(
                    lambda p: compressor.decompress(p, ctx2))(gathered)
        return out.reshape(-1)[:n], payloads, ctx_arrays, treedef, static

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        raise TypeError("HierarchicalAllreduce re-shards the gradient "
                        "before compression; it only supports the full "
                        "step() pipeline, not a bare exchange().")


@dataclasses.dataclass(frozen=True)
class Identity(Communicator):
    """No-op communicator: decompress this rank's own payload.

    No reference analog; used for single-device debugging and as the
    injectable no-comm fake the reference never wrote (SURVEY.md §4).
    """

    def _recv_total_bytes(self, payload_nbytes: int, n_elems: int,
                          world: int, vote: bool = False) -> int:
        return 0

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        return compressor.decompress(payload, ctx)
