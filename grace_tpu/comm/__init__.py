"""Communicators: XLA collectives over a named mesh axis.

TPU-native replacements for the reference's three communicators
(grace_dl/dist/communicator/{allreduce,allgather,broadcast}.py), which issue
eager c10d/Horovod NCCL calls per tensor. Here each communicator is a pure
function of the payload built from `jax.lax` collectives, traced inside
`shard_map`/`pjit` over a device mesh so XLA schedules them on ICI and
overlaps them with compute — no handle tables, no background thread
(cf. patch_files/horovod/torch/mpi_ops.py:68-75,423-439).

Compatibility matrix (reference IMPLEMENTING.md:43-45): ``Allreduce`` only
suits compressors whose payload is dense, same-shaped and summable (none,
fp16, randomk, powersgd); ``Allgather`` is general-purpose; ``Broadcast``
exists for parity and is realised with the same all-gather collective — a
loop of per-root broadcasts (grace_dl/dist/communicator/broadcast.py:18-33)
would serialise W collectives for an identical result.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import Communicator, Compressor, Ctx, Payload

__all__ = ["Allreduce", "Allgather", "Broadcast", "Identity"]


@dataclasses.dataclass(frozen=True)
class Allreduce(Communicator):
    """Sum payloads across ranks, then decompress once.

    Mirrors grace_dl/dist/communicator/allreduce.py:6-13: all-reduce each
    payload tensor, divide by world size if ``compressor.average``, then
    decompress the summed payload. Valid only for linear codecs.
    """

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        summed = tuple(lax.psum(t, self.axis_name) for t in payload)
        if compressor.average and payload:
            if not all(jnp.issubdtype(t.dtype, jnp.inexact) for t in summed):
                raise TypeError(
                    "Allreduce with average=True requires float payloads; "
                    f"got {[t.dtype for t in summed]}. Use Allgather for "
                    "integer-coded compressors (see IMPLEMENTING.md:43-45 "
                    "compatibility matrix in the reference).")
            w = self.world_size()
            summed = tuple(t / w for t in summed)
        return compressor.decompress(summed, ctx)


@dataclasses.dataclass(frozen=True)
class Allgather(Communicator):
    """Gather every rank's payload, decompress per rank, aggregate.

    Mirrors grace_dl/dist/communicator/allgather.py:7-45. The reference's
    variable-size path (gather sizes → pad → split, lines 16-38) is
    unnecessary: payloads are statically shaped under XLA, with invalid lanes
    zero-valued (see compressors with static-capacity payloads). Per-rank
    decompression is vmapped over the gathered world axis and runs as one
    fused XLA computation instead of the reference's Python loop
    (SURVEY.md §3.1 hot spot).
    """

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        if not payload:
            # e.g. PowerSGD: communication already happened inside compress.
            return compressor.decompress(payload, ctx)
        gathered = tuple(
            lax.all_gather(t, self.axis_name, axis=0, tiled=False)
            for t in payload)
        stacked = jax.vmap(lambda p: compressor.decompress(p, ctx))(gathered)
        out = compressor.aggregate(stacked)
        if compressor.average:
            out = out / self.world_size()
        return out


@dataclasses.dataclass(frozen=True)
class Broadcast(Allgather):
    """Parity alias for the reference's broadcast communicator.

    The reference loops over root ranks broadcasting each payload and
    decompressing it (grace_dl/dist/communicator/broadcast.py:18-33) — W
    sequential collectives computing exactly what one all-gather computes.
    On TPU we keep the all-gather realisation; semantics (per-rank decompress
    → aggregate → optional average) are identical.
    """


@dataclasses.dataclass(frozen=True)
class Identity(Communicator):
    """No-op communicator: decompress this rank's own payload.

    No reference analog; used for single-device debugging and as the
    injectable no-comm fake the reference never wrote (SURVEY.md §4).
    """

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        return compressor.decompress(payload, ctx)
