"""Training-step builders: the shard_map harness around the compressed pipeline.

Replaces the reference's L5 integration layer (SURVEY.md §1): where GRACE
patches Horovod's DistributedOptimizer to fire per-parameter hooks during
backward (patch_files/horovod/torch/__init__.py:107-161), grace-tpu builds
one jitted SPMD train step: per-device gradients are computed inside
`shard_map` over the ``'data'`` mesh axis and the optax chain (containing
`grace_transform`) performs the compressed collective exchange. XLA overlaps
the compression collectives with remaining backward compute — the async
send/receive split of the torch backend (grace_dl/torch/__init__.py:37-58)
falls out of the compiler for free.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from grace_tpu.core import DEFAULT_AXIS

__all__ = ["TrainState", "StatefulTrainState", "make_train_step",
           "make_stateful_train_step", "make_eval_step",
           "init_train_state", "init_stateful_train_state"]


class TrainState(NamedTuple):
    params: Any
    opt_state: Any


def make_train_step(loss_fn: Callable[[Any, Any], jax.Array],
                    optimizer: optax.GradientTransformation,
                    mesh: Mesh,
                    axis_name: str = DEFAULT_AXIS,
                    donate: bool = True):
    """Build ``step(state, batch) -> (state, loss)``.

    ``loss_fn(params, batch)`` must return the mean loss over its *local*
    batch shard; gradients are therefore local means, and the communicator's
    ``average`` semantics reproduce the reference's global mean
    (grace_dl/dist/__init__.py:51-52 `/ world_size`).

    ``batch`` is a pytree whose leaves are sharded on their leading dim over
    ``axis_name`` (the DistributedSampler analog, SURVEY.md §2.5).
    """

    def device_step(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        loss = lax.pmean(loss, axis_name)
        return TrainState(params, opt_state), loss

    sharded = jax.shard_map(
        device_step, mesh=mesh,
        in_specs=(P(), P(axis_name)),
        out_specs=(P(), P()),
        check_vma=False)

    donate_argnums = (0,) if donate else ()
    return jax.jit(sharded, donate_argnums=donate_argnums)


class StatefulTrainState(NamedTuple):
    params: Any
    model_state: Any   # e.g. BatchNorm running stats
    opt_state: Any


def make_stateful_train_step(loss_fn: Callable[[Any, Any, Any],
                                               Tuple[jax.Array, Any]],
                             optimizer: optax.GradientTransformation,
                             mesh: Mesh,
                             axis_name: str = DEFAULT_AXIS,
                             donate: bool = True,
                             sync_model_state: bool = True):
    """Like :func:`make_train_step` for models with non-param state (BN stats).

    ``loss_fn(params, model_state, batch) -> (loss, new_model_state)``.
    ``sync_model_state`` pmeans the new model state across ranks so running
    statistics stay replicated (the reference's DDP examples leave BN stats
    rank-local and implicitly use rank 0's at save time; replication is the
    deterministic version of the same thing, and the stats are tiny).
    """

    def device_step(state: StatefulTrainState, batch):
        (loss, mstate), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, state.model_state, batch)
        if sync_model_state:
            mstate = jax.tree_util.tree_map(
                lambda m: lax.pmean(m, axis_name), mstate)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        loss = lax.pmean(loss, axis_name)
        return StatefulTrainState(params, mstate, opt_state), loss

    sharded = jax.shard_map(
        device_step, mesh=mesh,
        in_specs=(P(), P(axis_name)),
        out_specs=(P(), P()),
        check_vma=False)
    donate_argnums = (0,) if donate else ()
    return jax.jit(sharded, donate_argnums=donate_argnums)


def init_stateful_train_state(params: Any, model_state: Any,
                              optimizer: optax.GradientTransformation
                              ) -> StatefulTrainState:
    return StatefulTrainState(params=params, model_state=model_state,
                              opt_state=optimizer.init(params))


def make_eval_step(metric_fn: Callable[[Any, Any], Any], mesh: Mesh,
                   axis_name: str = DEFAULT_AXIS):
    """Build ``eval_step(params, batch) -> mesh-averaged metrics``.

    The cross-rank metric averaging idiom of the reference
    (examples/torch/pytorch_mnist.py:163-166 metric_average via allreduce).
    """

    def device_eval(params, batch):
        metrics = metric_fn(params, batch)
        return jax.tree_util.tree_map(
            lambda m: lax.pmean(m, axis_name), metrics)

    sharded = jax.shard_map(
        device_eval, mesh=mesh,
        in_specs=(P(), P(axis_name)),
        out_specs=P(),
        check_vma=False)
    return jax.jit(sharded)


def init_train_state(params: Any, optimizer: optax.GradientTransformation
                     ) -> TrainState:
    return TrainState(params=params, opt_state=optimizer.init(params))
