"""Training-step builders: the shard_map harness around the compressed pipeline.

Replaces the reference's L5 integration layer (SURVEY.md §1): where GRACE
patches Horovod's DistributedOptimizer to fire per-parameter hooks during
backward (patch_files/horovod/torch/__init__.py:107-161), grace-tpu builds
one jitted SPMD train step: per-device gradients are computed inside
`shard_map` over the ``'data'`` mesh axis and the optax chain (containing
`grace_transform`) performs the compressed collective exchange. XLA overlaps
the compression collectives with remaining backward compute — the async
send/receive split of the torch backend (grace_dl/torch/__init__.py:37-58)
falls out of the compiler for free.

State layout: params / model state / non-grace optimizer state are
replicated; GraceState mem/comp leaves (per-rank residuals/momenta, see
grace_tpu/transform.py) carry a leading world axis sharded over the mesh.
Always build states with :func:`init_train_state` /
:func:`init_stateful_train_state` (passing the mesh) so the layout matches
what the step functions expect.

Resilience wiring: pass a guarded chain
(``grace_tpu.resilience.guarded_chain(grace, optax.sgd(...), ...)``) as the
``optimizer`` — nothing else changes. The guard's skip/rollback/fallback
logic traces into the same jitted shard_map step (its ``GuardState`` rides
inside ``opt_state``; ``partition_specs`` recurses through it to the
GraceState leaves), and the loop reads health via
``grace_tpu.utils.metrics.guard_report(state)`` / reacts via
``grace_tpu.checkpoint.divergence_rollback``.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from grace_tpu.core import DEFAULT_AXIS
from grace_tpu.parallel import replicated, shard_map
from grace_tpu.telemetry.scopes import (STAGE_APPLY, STAGE_CONSENSUS,
                                        STAGE_FWD_BWD, STAGE_OPTIMIZER,
                                        trace_stage)
from grace_tpu.transform import (MeshSpec, add_world_axis, partition_specs,
                                 strip_world_axis)

__all__ = ["TrainState", "StatefulTrainState", "make_train_step",
           "make_stateful_train_step", "make_eval_step",
           "init_train_state", "init_stateful_train_state",
           "init_opt_state", "warmup_schedule"]


class TrainState(NamedTuple):
    params: Any
    opt_state: Any


class StatefulTrainState(NamedTuple):
    params: Any
    model_state: Any   # e.g. BatchNorm running stats
    opt_state: Any


def _apply_param_specs(specs, state, param_specs):
    """Substitute the caller's fsdp param sharding into the derived spec
    pytree: the ``params`` field of a (Stateful)TrainState gets
    ``param_specs`` (a spec pytree matching params, or one PartitionSpec
    for every leaf); everything else keeps the ``partition_specs``
    contract."""
    if param_specs is None:
        return specs
    if isinstance(param_specs, P):
        param_specs = jax.tree_util.tree_map(lambda _: param_specs,
                                             state.params)
    return specs._replace(params=param_specs)


def _lazy_sharded_step(device_step, mesh: Mesh, axis_name, donate: bool,
                       param_specs=None):
    """jit(shard_map(device_step)) with state specs derived from the first
    state actually passed in — the spec pytree depends on where GraceState
    nodes sit inside the (optimizer-dependent) state structure.
    ``axis_name`` may be a :class:`~grace_tpu.transform.MeshSpec`; the
    batch shards over its dp axis and ``param_specs`` (sharded-model
    track) overrides the params portion of the state specs."""
    mesh_spec = MeshSpec.normalize(axis_name)
    cache = {}

    def step(state, batch):
        key = jax.tree_util.tree_structure(state)
        fn = cache.get(key)
        if fn is None:
            specs = _apply_param_specs(
                partition_specs(state, mesh_spec), state, param_specs)
            sharded = shard_map(
                device_step, mesh=mesh,
                in_specs=(specs, P(mesh_spec.dp_axis)),
                out_specs=(specs, P()),
                check_vma=False)
            fn = jax.jit(sharded, donate_argnums=(0,) if donate else ())
            cache[key] = fn
        return fn(state, batch)

    # Callers (bench.py MFU accounting) can reach the underlying jitted fns
    # for AOT introspection (lower().cost_analysis()) without re-wrapping.
    step.jit_cache = cache
    return step


def make_train_step(loss_fn: Callable[[Any, Any], jax.Array],
                    optimizer: optax.GradientTransformation,
                    mesh: Mesh,
                    axis_name=DEFAULT_AXIS,
                    donate: bool = True,
                    remat: bool = False,
                    consensus=None,
                    param_specs=None):
    """Build ``step(state, batch) -> (state, loss)``.

    ``loss_fn(params, batch)`` must return the mean loss over its *local*
    batch shard; gradients are therefore local means, and the communicator's
    ``average`` semantics reproduce the reference's global mean
    (grace_dl/dist/__init__.py:51-52 `/ world_size`).

    ``batch`` is a pytree whose leaves are sharded on their leading dim over
    ``axis_name`` (the DistributedSampler analog, SURVEY.md §2.5).

    ``remat=True`` wraps the loss in ``jax.checkpoint``: activations are
    recomputed during backward instead of held in HBM — the standard
    FLOPs-for-memory trade when activation footprint (not the gradient
    exchange this library compresses) is the limiting factor.

    ``consensus`` (None | True | int ``audit_every`` | dict |
    ``ConsensusConfig``): run the cross-rank consistency audit + self-heal
    (:mod:`grace_tpu.resilience.consensus`) after ``apply_updates``, inside
    the same jitted shard_map step. Requires the grace transform to have
    been built with ``consensus=...`` so ``GraceState`` carries the
    ``AuditState`` (clear in-graph error otherwise).

    ``axis_name`` may be a :class:`~grace_tpu.transform.MeshSpec` for the
    sharded-model (dp×fsdp) track; pass ``param_specs`` (a PartitionSpec
    pytree matching params, or one spec for every leaf) to shard params —
    and the param-shaped slots the consensus audit repairs — over the
    fsdp axis. ``loss_fn`` then sees its *local* param shards and owns
    any cross-shard collectives (tensor-parallel style, over
    ``mesh_spec.fsdp_axis``); the consensus audit and the loss pmean stay
    on the dp axis, so fingerprints match replicas per fsdp shard.
    """
    if remat:
        loss_fn = jax.checkpoint(loss_fn)
    consensus = _normalize_consensus(consensus)
    mesh_spec = MeshSpec.normalize(axis_name)
    dp = mesh_spec.dp_axis

    def device_step(state: TrainState, batch):
        opt_state = strip_world_axis(state.opt_state)
        # Stage scopes name the phases in an XLA device trace (see
        # grace_tpu.telemetry.scopes); the grace transform inside
        # optimizer.update adds its own compress/exchange/decompress spans.
        with trace_stage(STAGE_FWD_BWD):
            loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        with trace_stage(STAGE_OPTIMIZER):
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  state.params)
        with trace_stage(STAGE_APPLY):
            params = optax.apply_updates(state.params, updates)
        if consensus is not None:
            with trace_stage(STAGE_CONSENSUS):
                params, opt_state = _consensus_step(
                    (params, opt_state), consensus, dp)
        loss = lax.pmean(loss, dp)
        return TrainState(params, add_world_axis(opt_state)), loss

    return _lazy_sharded_step(device_step, mesh, mesh_spec, donate,
                              param_specs=param_specs)


def _normalize_consensus(consensus):
    """Lazy import: resilience.consensus imports transform (as this module
    does), so the dependency must stay function-local to avoid a cycle."""
    if consensus is None or consensus is False:
        return None
    from grace_tpu.resilience.consensus import normalize_consensus
    return normalize_consensus(consensus)


def _consensus_step(tree, config, axis_name):
    from grace_tpu.resilience.consensus import consensus_step
    return consensus_step(tree, config, axis_name)


def make_stateful_train_step(loss_fn: Callable[[Any, Any, Any],
                                               Tuple[jax.Array, Any]],
                             optimizer: optax.GradientTransformation,
                             mesh: Mesh,
                             axis_name=DEFAULT_AXIS,
                             donate: bool = True,
                             sync_model_state: bool = True,
                             remat: bool = False,
                             consensus=None,
                             param_specs=None):
    """Like :func:`make_train_step` for models with non-param state (BN stats).

    ``loss_fn(params, model_state, batch) -> (loss, new_model_state)``.
    ``sync_model_state`` pmeans the new model state across ranks so running
    statistics stay replicated (the reference's DDP examples leave BN stats
    rank-local and implicitly use rank 0's at save time; replication is the
    deterministic version of the same thing, and the stats are tiny).
    ``remat``/``consensus`` as in :func:`make_train_step` — the audit
    fingerprints model state too (it is replicated), so BN-stat divergence
    is detected and repaired alongside params.
    """
    if remat:
        loss_fn = jax.checkpoint(loss_fn)
    consensus = _normalize_consensus(consensus)
    mesh_spec = MeshSpec.normalize(axis_name)
    dp = mesh_spec.dp_axis

    def device_step(state: StatefulTrainState, batch):
        opt_state = strip_world_axis(state.opt_state)
        with trace_stage(STAGE_FWD_BWD):
            (loss, mstate), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(
                state.params, state.model_state, batch)
        if sync_model_state:
            mstate = jax.tree_util.tree_map(
                lambda m: lax.pmean(m, dp), mstate)
        with trace_stage(STAGE_OPTIMIZER):
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  state.params)
        with trace_stage(STAGE_APPLY):
            params = optax.apply_updates(state.params, updates)
        if consensus is not None:
            with trace_stage(STAGE_CONSENSUS):
                params, mstate, opt_state = _consensus_step(
                    (params, mstate, opt_state), consensus, dp)
        loss = lax.pmean(loss, dp)
        return (StatefulTrainState(params, mstate, add_world_axis(opt_state)),
                loss)

    return _lazy_sharded_step(device_step, mesh, mesh_spec, donate,
                              param_specs=param_specs)


def init_opt_state(params: Any, optimizer: optax.GradientTransformation,
                   mesh: Mesh, axis_name=DEFAULT_AXIS,
                   param_specs=None) -> Any:
    """Optimizer state in the global layout: grace mem/comp leaves get their
    leading world axis, sharded over the mesh (``P(dp)``, or
    ``P((dp, fsdp))`` on a 2-D :class:`~grace_tpu.transform.MeshSpec`);
    the rest is replicated. With ``param_specs`` (sharded-model track),
    ``optimizer.init`` runs on each device's LOCAL param shard — the
    grace residuals it allocates are therefore per-shard by construction,
    which is the "error feedback lives on the shard owner" layout.

    Public because it is also the elastic re-shard's fresh-init hook
    (:func:`grace_tpu.resilience.elastic.reshard_grace_state`): a world
    resize re-initializes the per-rank GraceState payload by running
    exactly this init on the NEW mesh, then grafts the old replicated
    fields back via :func:`grace_tpu.transform.carry_replicated`."""
    mesh_spec = MeshSpec.normalize(axis_name)
    if param_specs is None:
        in_spec: Any = P()
        local_params = params
    else:
        if isinstance(param_specs, P):
            param_specs = jax.tree_util.tree_map(lambda _: param_specs,
                                                 params)
        in_spec = param_specs

        def shard_of(leaf, spec):
            shape = list(jnp.shape(leaf))
            for d, entry in enumerate(spec):
                if entry is None:
                    continue
                names = entry if isinstance(entry, tuple) else (entry,)
                for n in names:
                    shape[d] //= mesh.shape[n]
            return jax.ShapeDtypeStruct(tuple(shape),
                                        jnp.result_type(leaf))

        local_params = jax.tree_util.tree_map(shard_of, params, param_specs)
    abstract = jax.eval_shape(optimizer.init, local_params)
    specs = partition_specs(abstract, mesh_spec)
    init_fn = shard_map(
        lambda p: add_world_axis(optimizer.init(p)),
        mesh=mesh, in_specs=(in_spec,), out_specs=specs, check_vma=False)
    return jax.jit(init_fn)(params)


# Back-compat private alias (pre-elastic callers).
_init_opt_state = init_opt_state


def init_train_state(params: Any, optimizer: optax.GradientTransformation,
                     mesh: Mesh, axis_name=DEFAULT_AXIS,
                     param_specs=None) -> TrainState:
    if param_specs is None:
        placed = jax.device_put(params, replicated(mesh))
    else:
        from jax.sharding import NamedSharding
        if isinstance(param_specs, P):
            param_specs = jax.tree_util.tree_map(lambda _: param_specs,
                                                 params)
        placed = jax.device_put(
            params, jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), param_specs,
                is_leaf=lambda x: isinstance(x, P)))
    return TrainState(
        params=placed,
        opt_state=_init_opt_state(params, optimizer, mesh, axis_name,
                                  param_specs=param_specs))


def init_stateful_train_state(params: Any, model_state: Any,
                              optimizer: optax.GradientTransformation,
                              mesh: Mesh, axis_name: str = DEFAULT_AXIS
                              ) -> StatefulTrainState:
    return StatefulTrainState(
        params=jax.device_put(params, replicated(mesh)),
        model_state=jax.device_put(model_state, replicated(mesh)),
        opt_state=_init_opt_state(params, optimizer, mesh, axis_name))


def warmup_schedule(base_lr: float, world_size: int, warmup_steps: int,
                    after: Optional[Callable[[Any], Any]] = None):
    """Linear-scaling LR warmup: ramp ``base_lr`` → ``base_lr * world_size``.

    The pure-JAX analog of the reference's LearningRateWarmupCallback
    (examples/tensorflow/tensorflow2_keras_mnist.py:83-88, Goyal et al.
    gradual warmup): large data-parallel batches want the linearly-scaled
    rate ``base_lr * world_size``, reached gradually over ``warmup_steps``
    to avoid early divergence. Returns an optax schedule; ``after(t)``
    optionally supplies the post-warmup schedule as a function of steps
    *since warmup end* (default: hold the scaled rate).

    The boundary step belongs to the post-warmup schedule: ``count ==
    warmup_steps`` returns ``after(0)``, not the warm ramp (pinned by
    tests/test_resilience.py::test_warmup_boundary_handoff). And
    ``warmup_steps=0`` means no warmup at all: ``after(count)`` from step
    0, or the scaled rate if ``after`` is None.
    """
    scaled = base_lr * world_size

    def schedule(count):
        if warmup_steps <= 0:
            return (jnp.asarray(scaled, jnp.float32) if after is None
                    else after(count))
        frac = jnp.minimum(count / jnp.maximum(warmup_steps, 1), 1.0)
        warm = base_lr + (scaled - base_lr) * frac
        if after is None:
            return warm
        return jnp.where(count < warmup_steps, warm,
                         after(count - warmup_steps))

    return schedule


def make_eval_step(metric_fn: Callable[[Any, Any], Any], mesh: Mesh,
                   axis_name: str = DEFAULT_AXIS):
    """Build ``eval_step(params, batch) -> mesh-averaged metrics``.

    The cross-rank metric averaging idiom of the reference
    (examples/torch/pytorch_mnist.py:163-166 metric_average via allreduce).
    """

    def device_eval(params, batch):
        metrics = metric_fn(params, batch)
        return jax.tree_util.tree_map(
            lambda m: lax.pmean(m, axis_name), metrics)

    sharded = shard_map(
        device_eval, mesh=mesh,
        in_specs=(P(), P(axis_name)),
        out_specs=P(),
        check_vma=False)
    return jax.jit(sharded)
