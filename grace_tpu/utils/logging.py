"""Loggers and timers for training loops.

Behavioral parity targets (re-designed, not copied):

* ``Timer`` — segment/total wall-clock timing with a pluggable sync hook
  (reference: examples/dist/CIFAR10-dawndist/core.py:14-27, which used
  ``torch.cuda.synchronize``; on TPU the right hook is
  ``jax.block_until_ready`` on a step output, or ``jax.effects_barrier``).
* ``TableLogger`` — fixed-width column stdout whose header is latched from
  the first row (reference: core.py:33-39).
* ``TSVLogger`` — DAWNBench submission format ``epoch\thours\ttop1Accuracy``
  (reference: dawn.py:72-81).
* rank-0-only emission — the reference guards prints with ``hvd.rank()==0``
  (pytorch_synthetic_benchmark.py:169-172); here the guard is
  ``jax.process_index()==0``.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Mapping, Optional, Sequence, TextIO

import jax

__all__ = ["Timer", "TableLogger", "TSVLogger", "GuardMonitor",
           "ConsensusMonitor", "localtime", "rank_zero_only",
           "rank_zero_print", "run_provenance", "git_commit"]


def localtime() -> str:
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime())


def rank_zero_only(fn: Callable) -> Callable:
    """Decorator: run ``fn`` only on process 0 (multi-host controller idiom)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if jax.process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped


@rank_zero_only
def rank_zero_print(*args, **kwargs) -> None:
    print(*args, **kwargs)


class Timer:
    """Segment timer: each call returns the time since the previous call.

    ``sync`` runs before every reading so asynchronously dispatched device
    work is included — pass ``lambda: jax.block_until_ready(out)`` on a live
    output, or ``jax.effects_barrier``. ``include_in_total=False`` excludes a
    segment (e.g. validation) from ``total_time``, the DAWNBench accounting
    rule the reference follows (core.py:20-26).
    """

    def __init__(self, sync: Optional[Callable[[], None]] = None):
        self.sync = sync or (lambda: None)
        self.sync()
        self._last = time.perf_counter()
        self.total_time = 0.0

    def __call__(self, include_in_total: bool = True) -> float:
        self.sync()
        now = time.perf_counter()
        delta = now - self._last
        self._last = now
        if include_in_total:
            self.total_time += delta
        return delta


class TableLogger:
    """Aligned-column stdout logger; header latched from the first row's keys.

    Later rows may gain or lose keys without breaking the table — exactly
    what happens when telemetry fields appear only after the first flush
    window (warmup rows have no ``grad_norm`` yet). A missing key renders as
    a blank cell; a key the header never saw is skipped, with a one-time
    ``# new columns (ignored): …`` notice per key so the drift is visible
    without re-flowing the table.
    """

    def __init__(self, width: int = 12, stream: Optional[TextIO] = None):
        self.width = width
        self.stream = stream
        self._keys: Optional[Sequence[str]] = None
        self._announced: set = set()

    def _emit(self, line: str) -> None:
        print(line, file=self.stream)

    def append(self, row: Mapping[str, object]) -> None:
        if self._keys is None:
            self._keys = list(row.keys())
            self._emit(" ".join(f"{k:>{self.width}s}" for k in self._keys))
        new = [k for k in row if k not in self._keys
               and k not in self._announced]
        if new:
            self._announced.update(new)
            self._emit(f"# new columns (ignored): {', '.join(new)}")
        cells = []
        for k in self._keys:
            if k not in row:
                cells.append(" " * self.width)
                continue
            v = row[k]
            if isinstance(v, float):
                cells.append(f"{v:{self.width}.4f}")
            else:
                cells.append(f"{v!s:>{self.width}s}")
        self._emit(" ".join(cells))


class TSVLogger:
    """DAWNBench-format log: ``epoch\thours\ttop1Accuracy`` rows.

    ``append`` takes the same row dict as :class:`TableLogger` with keys
    ``epoch``, ``total time`` (seconds), ``test acc`` (fraction in [0,1]).

    ``provenance`` entries are written as leading ``# key: value`` comment
    lines. Evidence files must carry their own provenance (VERDICT round-3
    item 3: a synthetic-data curve was mistaken for the real benchmark):
    at minimum pass ``data`` (``synthetic`` | ``real`` + source) and
    ``platform``; :func:`run_provenance` assembles the standard set.
    """

    HEADER = "epoch\thours\ttop1Accuracy"

    def __init__(self, provenance: Optional[Mapping[str, object]] = None):
        self._prov = dict(provenance or {})
        self._rows = [self.HEADER]

    def append(self, row: Mapping[str, object]) -> None:
        epoch = row["epoch"]
        hours = float(row["total time"]) / 3600.0
        acc = float(row["test acc"]) * 100.0
        self._rows.append(f"{epoch}\t{hours:.8f}\t{acc:.2f}")

    def __str__(self) -> str:
        prov = [f"# {k}: {v}" for k, v in self._prov.items()]
        return "\n".join(prov + self._rows)

    def write(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(str(self) + "\n")


class GuardMonitor:
    """Emit guard-state *transitions*: skipped steps, fallback open/close.

    Feed it the per-step dict from ``grace_tpu.utils.metrics.guard_report``;
    it prints (rank-0 only, via :func:`rank_zero_print` by default) only
    when something changed, so a healthy run stays silent::

        mon = GuardMonitor()
        for i, batch in enumerate(batches):
            state, loss = step(state, batch)
            mon.update(i, guard_report(state))

    ``sink`` (any :class:`grace_tpu.telemetry.Sink`) additionally emits
    each transition as a structured record — ``{"event": "guard_skip" |
    "guard_fallback_engaged" | "guard_rearmed", "step": …, **report}`` —
    into the same JSONL/TensorBoard stream the telemetry reader writes, so
    guard edges line up against the per-step metric rows. Transition
    edges are exact: re-arm fires on the first step whose report shows
    ``fallback_active`` False after a True (pinned by
    tests/test_telemetry.py::test_guard_monitor_transition_edges).
    """

    def __init__(self, printer: Optional[Callable[..., None]] = None,
                 sink=None):
        self._print = printer or rank_zero_print
        self._sink = sink
        self._last: Optional[dict] = None

    def _event(self, name: str, step: int,
               report: Mapping[str, object]) -> None:
        if self._sink is not None:
            self._sink.write({"event": name, "step": step, **report})

    def update(self, step: int, report: Mapping[str, object]) -> None:
        if not report:
            return
        prev, self._last = self._last, dict(report)
        if prev is None:
            return
        if report["notfinite_count"] > prev["notfinite_count"]:
            self._print(f"[guard] step {step}: non-finite/exploding update "
                        f"skipped (total={report['notfinite_count']}, "
                        f"consecutive={report['consecutive']})")
            self._event("guard_skip", step, report)
        if report["fallback_active"] and not prev["fallback_active"]:
            self._print(f"[guard] step {step}: dense fallback engaged for "
                        f"{report['fallback_remaining']} steps")
            self._event("guard_fallback_engaged", step, report)
        if prev["fallback_active"] and not report["fallback_active"]:
            self._print(f"[guard] step {step}: compression re-armed")
            self._event("guard_rearmed", step, report)


class ConsensusMonitor:
    """Emit consensus-auditor *transitions*: repairs and escalations.

    The :class:`GuardMonitor` twin for the cross-rank consistency auditor
    (:mod:`grace_tpu.resilience.consensus`). Feed it the per-step dict from
    :func:`grace_tpu.resilience.consensus.audit_report`; it prints (rank-0
    only) and — via ``sink`` — emits a structured record only when a
    counter moved, so a healthy run stays silent::

        mon = ConsensusMonitor(sink=jsonl_sink)
        for i, batch in enumerate(batches):
            state, loss = step(state, batch)
            mon.update(i, audit_report(state))

    Sink records: ``{"event": "consensus_repair" |
    "consensus_escalation", "step": …, **report}`` — they land in the same
    JSONL stream as the telemetry rows and guard events, so repairs line
    up against the per-step metrics (including the ``audit_bytes`` the
    repair itself cost).
    """

    def __init__(self, printer: Optional[Callable[..., None]] = None,
                 sink=None):
        self._print = printer or rank_zero_print
        self._sink = sink
        self._last: Optional[dict] = None

    def _event(self, name: str, step: int,
               report: Mapping[str, object]) -> None:
        if self._sink is not None:
            self._sink.write({"event": name, "step": step, **report})

    def update(self, step: int, report: Mapping[str, object]) -> None:
        if not report:
            return
        prev, self._last = self._last, dict(report)
        if prev is None:
            return
        if report["repairs"] > prev["repairs"]:
            self._print(f"[consensus] step {step}: replica divergence on "
                        f"rank {report['last_divergent_rank']} repaired "
                        f"(total repairs={report['repairs']})")
            self._event("consensus_repair", step, report)
        if report["escalations"] > prev["escalations"]:
            self._print(f"[consensus] step {step}: rank "
                        f"{report['last_divergent_rank']} re-diverged — "
                        f"escalating to dense fallback "
                        f"(total escalations={report['escalations']})")
            self._event("consensus_escalation", step, report)


def git_commit() -> Optional[str]:
    """Short git commit of the grace-tpu checkout, or None (best-effort).

    Evidence files must be attributable to a revision (VERDICT discipline:
    a number nobody can reproduce is not evidence). Resolved against the
    package's own directory — the process cwd may be anywhere.
    """
    import os
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else None
    except Exception:
        return None


def run_provenance(data: str, **extra: object) -> dict:
    """The standard provenance block for a training-curve evidence file.

    ``data`` names the data source honestly — ``"synthetic"`` or
    ``"real:<path>"``. Platform/device/host, UTC timestamp, and the git
    commit (best-effort, absent outside a checkout) are filled in from the
    live environment; pass anything run-specific via ``extra``
    (e.g. ``argv=" ".join(sys.argv[1:])``).
    """
    dev = jax.devices()[0]
    prov = {
        "data": data,
        "platform": dev.platform,
        "device": getattr(dev, "device_kind", dev.platform),
        "n_devices": len(jax.devices()),
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    rev = git_commit()
    if rev is not None:
        prov["git_commit"] = rev
    prov.update(extra)
    return prov
