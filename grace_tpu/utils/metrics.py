"""Bytes-on-wire accounting for compressed gradient exchange.

The reference framework never measures its own compression — ratios are
quoted from the survey paper and validated in external benchmark repos
(SURVEY.md §6). Here the wire cost is a first-class, statically computable
metric: payload shapes/dtypes come from ``jax.eval_shape`` over
``Compressor.compress``, so the report costs zero FLOPs and works for any
pytree of gradients before a single step runs.

Caveat noted in the report: this counts *logical* payload bytes. XLA may
pad/repack buffers on the wire; treat the numbers as the algorithmic lower
bound (which is also what the reference's survey paper reports).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from grace_tpu.core import Compressor

__all__ = ["LeafReport", "CompressionReport", "payload_nbytes", "wire_report",
           "guard_report"]


def _nbytes(shaped) -> int:
    return int(np.prod(shaped.shape, dtype=np.int64)) * shaped.dtype.itemsize


def payload_nbytes(compressor: Compressor, x: jax.Array | jax.ShapeDtypeStruct
                   ) -> int:
    """Logical wire bytes of ``compressor``'s payload for one tensor ``x``.

    Compressors whose ``compress`` itself performs collectives (PowerSGD)
    cannot be shape-traced outside a bound mesh axis; they declare their
    wire cost analytically via ``Compressor.wire_nbytes``, which takes
    precedence here.
    """
    declared = compressor.wire_nbytes(jnp.shape(x), jnp.result_type(x))
    if declared is not None:
        return declared
    x_spec = jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))

    def encode(x):
        rng = jax.random.key(0)  # shape-only trace; value irrelevant
        payload, _, _ = compressor.compress(x, compressor.init_state(x), rng)
        return payload

    payload = jax.eval_shape(encode, x_spec)
    return sum(_nbytes(t) for t in jax.tree_util.tree_leaves(payload))


@dataclasses.dataclass(frozen=True)
class LeafReport:
    path: str
    dense_bytes: int
    wire_bytes: int

    @property
    def ratio(self) -> float:
        return self.wire_bytes / max(self.dense_bytes, 1)


@dataclasses.dataclass(frozen=True)
class CompressionReport:
    leaves: Tuple[LeafReport, ...]

    @property
    def dense_bytes(self) -> int:
        return sum(l.dense_bytes for l in self.leaves)

    @property
    def wire_bytes(self) -> int:
        return sum(l.wire_bytes for l in self.leaves)

    @property
    def ratio(self) -> float:
        """wire/dense — smaller is better; 1.0 means no compression."""
        return self.wire_bytes / max(self.dense_bytes, 1)

    def summary(self) -> Dict[str, Any]:
        return {"dense_bytes": self.dense_bytes,
                "wire_bytes": self.wire_bytes,
                "ratio": round(self.ratio, 6),
                "n_leaves": len(self.leaves)}

    def __str__(self) -> str:
        s = self.summary()
        return (f"CompressionReport(dense={s['dense_bytes']:,}B, "
                f"wire={s['wire_bytes']:,}B, ratio={s['ratio']:.4f}, "
                f"leaves={s['n_leaves']})")


def wire_report(compressor: Compressor, grads: Any) -> CompressionReport:
    """Per-leaf and total bytes-on-wire for a gradient pytree."""
    flat, _ = jax.tree_util.tree_flatten_with_path(grads)
    leaves = []
    for path, leaf in flat:
        dense = _nbytes(jax.ShapeDtypeStruct(jnp.shape(leaf),
                                             jnp.result_type(leaf)))
        wire = payload_nbytes(compressor, leaf)
        leaves.append(LeafReport(path=jax.tree_util.keystr(path),
                                 dense_bytes=dense, wire_bytes=wire))
    return CompressionReport(leaves=tuple(leaves))


def guard_report(state: Any) -> Dict[str, Any]:
    """Host-side health summary of the non-finite step guard in ``state``.

    Walks any state pytree (e.g. a ``TrainState``) for the
    :class:`~grace_tpu.resilience.guard.GuardState` that
    ``guard_transform`` threads through the optimizer state, and returns::

        {"step", "notfinite_count", "last_bad_step", "consecutive",
         "fallback_remaining", "fallback_active"}

    in one device-to-host transfer — the counters a training loop logs per
    step (see ``grace_tpu.utils.logging.GuardMonitor``) and feeds into
    save-time health decisions (``Checkpointer.save(..., good=...)``).
    Empty dict when no guard is present.
    """
    from grace_tpu.resilience.guard import GuardState

    found: list = []

    def walk(node):
        if isinstance(node, GuardState):
            found.append(node)
        return node

    jax.tree_util.tree_map(walk, state,
                           is_leaf=lambda n: isinstance(n, GuardState))
    if not found:
        return {}
    g = found[0]
    nf, lb, cs, fr, st = (int(v) for v in jax.device_get(
        [g.notfinite_count, g.last_bad_step, g.consecutive,
         g.fallback_remaining, g.step]))
    return {"step": st, "notfinite_count": nf, "last_bad_step": lb,
            "consecutive": cs, "fallback_remaining": fr,
            "fallback_active": fr > 0}


def debug_nan_residuals(state: Any) -> Dict[str, Dict[str, int]]:
    """Non-finite (NaN **and** Inf) census over every floating leaf of a
    state pytree.

    Debug aid for the fused-kernel NaN contract corner (IMPLEMENTING.md,
    "Fused local fast path"): under a NaN gradient the fused chunk-Top-K
    kernel keeps the NaN in the *residual* (re-injected by compensate each
    step) instead of shipping it on the wire like the staged path, so a
    poisoned lane is invisible in the loss. Infs matter just as much — an
    overflow born inside codec arithmetic (e.g. a quantizer scale blowing
    up) lands in the residual as ±Inf, not NaN, and poisons later steps
    identically. Run this periodically over the optimizer/GRACE state to
    surface both: returns ``{leaf_path: {"nan": n, "inf": m}}`` for leaves
    with any non-finite value (``~jnp.isfinite``) — empty dict means clean.
    All per-leaf counts are fetched in ONE device-to-host transfer so a
    state with hundreds of leaves does not serialize hundreds of blocking
    syncs.
    """
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    paths, counts = [], []
    for path, leaf in flat:
        if not (hasattr(leaf, "dtype")
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            continue
        paths.append(jax.tree_util.keystr(path))
        counts.append(jnp.stack([jnp.isnan(leaf).sum(),
                                 jnp.isinf(leaf).sum()]))
    counts = jax.device_get(counts)
    return {p: {"nan": int(c[0]), "inf": int(c[1])}
            for p, c in zip(paths, counts) if int(c[0]) or int(c[1])}
