"""Observability utilities: loggers, timers, compression metrics, profiling.

The reference's observability is scattered ad-hoc through its examples
(SURVEY.md §5): a `Timer` with pluggable device synch
(examples/dist/CIFAR10-dawndist/core.py:14-27), aligned-column stdout
(`TableLogger`, core.py:33-39), DAWNBench TSV output (`TSVLogger`,
dawn.py:72-81), and rank-0-only printing
(examples/torch/pytorch_synthetic_benchmark.py:169-172). grace-tpu promotes
these to a framework module and adds what the reference never measured:
per-algorithm bytes-on-wire / compression-ratio accounting (`wire_report`).
"""

from grace_tpu.utils.logging import (GuardMonitor, TableLogger, Timer,
                                     TSVLogger, git_commit, localtime,
                                     rank_zero_only, rank_zero_print,
                                     run_provenance)
from grace_tpu.utils.metrics import (CompressionReport, LeafReport,
                                     debug_nan_residuals, guard_report,
                                     payload_nbytes, wire_report)
from grace_tpu.utils.profiling import StepTimer, trace

__all__ = [
    "GuardMonitor", "TableLogger", "TSVLogger", "Timer", "git_commit",
    "localtime", "rank_zero_only", "rank_zero_print", "run_provenance",
    "CompressionReport", "LeafReport", "debug_nan_residuals",
    "guard_report", "payload_nbytes", "wire_report",
    "StepTimer", "trace",
]
