"""Profiling helpers: XLA trace capture and honest step timing.

Replaces the reference's print-driven instrumentation (SURVEY.md §5:
`torch.cuda.synchronize()` + wall-clock prints left in
grace_dl/torch/compressor/qsgd.py:14-15 and examples). On TPU the profiler
of record is ``jax.profiler`` (Perfetto/TensorBoard traces of the XLA
schedule, including ICI collective overlap); ``StepTimer`` gives cheap
steady-state throughput numbers with correct async-dispatch handling.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, List, Optional

import jax
import numpy as np

__all__ = ["trace", "StepTimer"]


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a device trace viewable in TensorBoard/Perfetto/XProf."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Per-step wall-clock stats that respect JAX's async dispatch.

    Usage::

        timer = StepTimer(warmup=2)
        for batch in batches:
            with timer.step():
                state, loss = train_step(state, batch)
                timer.sync_on(loss)     # block on a step OUTPUT, not the world

    ``mean_sec``/``p50_sec`` skip the warmup steps (compile + autotune).
    """

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self._times: List[float] = []
        self._sync_target = None

    def sync_on(self, out) -> None:
        self._sync_target = out

    @contextlib.contextmanager
    def step(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        except BaseException:
            self._sync_target = None  # don't let a failed step poison the next
            raise
        if self._sync_target is not None:
            jax.block_until_ready(self._sync_target)
            self._sync_target = None
        self._times.append(time.perf_counter() - t0)

    @property
    def steady(self) -> np.ndarray:
        if not self._times:
            raise RuntimeError("StepTimer has no recorded steps")
        return np.asarray(self._times[self.warmup:] or self._times)

    @property
    def mean_sec(self) -> float:
        return float(self.steady.mean())

    @property
    def p50_sec(self) -> float:
        return float(np.median(self.steady))

    def throughput(self, items_per_step: int) -> float:
        return items_per_step / self.mean_sec

    def confidence95(self, items_per_step: int) -> float:
        """±1.96σ half-width on items/sec (reference's reporting convention,
        examples/torch/pytorch_synthetic_benchmark.py:186-198)."""
        per_step = items_per_step / self.steady
        return float(1.96 * per_step.std())
