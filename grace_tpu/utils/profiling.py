"""Profiling helpers: XLA trace capture and honest step timing.

Replaces the reference's print-driven instrumentation (SURVEY.md §5:
`torch.cuda.synchronize()` + wall-clock prints left in
grace_dl/torch/compressor/qsgd.py:14-15 and examples). On TPU the profiler
of record is ``jax.profiler`` (Perfetto/TensorBoard traces of the XLA
schedule, including ICI collective overlap); ``StepTimer`` gives cheap
steady-state throughput numbers with correct async-dispatch handling.

The runtime recorder built on top of this (step-time percentiles, retrace
detection, memory watermarks, sink emission) lives in
:class:`grace_tpu.profiling.ProfileRecorder`; the offline trace analyzer is
:mod:`grace_tpu.profiling.trace_analysis`.
"""

from __future__ import annotations

import contextlib
import time
import warnings
from typing import Iterator, List, Optional

import jax
import numpy as np

__all__ = ["trace", "StepTimer"]


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a device trace viewable in TensorBoard/Perfetto/XProf."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Per-step wall-clock stats that respect JAX's async dispatch.

    Usage::

        timer = StepTimer(warmup=2)
        for batch in batches:
            with timer.step():
                state, loss = train_step(state, batch)
                timer.sync_on(loss)     # block on a step OUTPUT, not the world

    ``mean_sec``/``p50_sec`` skip the warmup steps (compile + autotune).

    Without ``sync_on`` the timer measures only the *async dispatch* of the
    step — microseconds of Python enqueueing work, not device execution —
    and the resulting "throughput" is fiction. The first such step warns
    once, and :attr:`measured_async_dispatch` stays True so downstream
    consumers (``grace_tpu.profiling.ProfileRecorder`` stamps it on every
    emitted record) can flag the numbers.

    A step body that raises still records its timing row (wall-clock up to
    the raise) and bumps :attr:`failed_steps` — a crash mid-run used to
    silently swallow the row, hiding exactly the slow step that died.
    """

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self.failed_steps = 0
        # True once any completed step was timed without a sync target:
        # the recorded times are dispatch-only and throughput is unusable.
        self.measured_async_dispatch = False
        self._times: List[float] = []
        self._sync_target = None
        self._warned_async = False

    def sync_on(self, out) -> None:
        self._sync_target = out

    def _note_async_dispatch(self) -> None:
        self.measured_async_dispatch = True
        if not self._warned_async:
            self._warned_async = True
            warnings.warn(
                "StepTimer.step() completed without sync_on(): the recorded "
                "time covers only async dispatch, not device execution — "
                "call timer.sync_on(<a step output>) inside the step block "
                "(jax dispatches asynchronously; without a blocking fetch "
                "the step 'finishes' in microseconds).",
                RuntimeWarning, stacklevel=3)

    @contextlib.contextmanager
    def step(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        except BaseException:
            # Record the partial row (the slow step that died is the one a
            # postmortem needs to see) but never let a failed step's sync
            # target poison the next one.
            self._sync_target = None
            self._times.append(time.perf_counter() - t0)
            self.failed_steps += 1
            raise
        if self._sync_target is not None:
            jax.block_until_ready(self._sync_target)
            self._sync_target = None
        else:
            self._note_async_dispatch()
        self._times.append(time.perf_counter() - t0)

    def __len__(self) -> int:
        return len(self._times)

    @property
    def steady(self) -> np.ndarray:
        if not self._times:
            raise RuntimeError("StepTimer has no recorded steps")
        return np.asarray(self._times[self.warmup:] or self._times)

    @property
    def mean_sec(self) -> float:
        return float(self.steady.mean())

    @property
    def p50_sec(self) -> float:
        return float(np.median(self.steady))

    def percentile_sec(self, q: float) -> float:
        """Steady-state percentile, e.g. ``percentile_sec(99)``."""
        return float(np.percentile(self.steady, q))

    def throughput(self, items_per_step: int) -> float:
        return items_per_step / self.mean_sec

    def confidence95(self, items_per_step: int) -> float:
        """±1.96σ half-width on items/sec (reference's reporting convention,
        examples/torch/pytorch_synthetic_benchmark.py:186-198)."""
        per_step = items_per_step / self.steady
        return float(1.96 * per_step.std())
