"""The evidence ledger: one append-only JSONL of provenance records.

Twelve scattered ``*_LAST.json``/``BENCH_*.json`` artifacts each grew
their own provenance idiom (bench rows carry ``n_devices``/``chip``,
chaos_smoke docs only a ``captured_at``). The ledger is the one schema
they all now feed: every record names the capture file it attests, the
sha256 of that file *at record time*, the git rev the capture was taken
at, and whether the headline number is ``measured`` on real devices or
``projected`` through the static wire model. ``tools/graft_gate.py``
audits README/CHANGELOG claims against these records.

Append-only with last-writer-wins per ``id``: a re-run of bench appends a
fresh ``bench-headline-tpu`` record rather than rewriting history, and
:func:`latest_by_id` resolves the current one. Torn trailing lines (a
killed writer) are skipped on load, same policy as the timeline loader.
"""

from __future__ import annotations

import datetime
import hashlib
import json
import os
import subprocess
import sys
from typing import Any, Dict, Iterable, List, Mapping, Optional

__all__ = ["CLAIM_CLASSES", "LEDGER_PATH", "REQUIRED_FIELDS",
           "append_record", "latest_by_id", "load_ledger", "new_record",
           "record_artifact", "repo_root", "sha256_file", "git_head_rev",
           "artifact_rev"]


def repo_root() -> str:
    """Repo root inferred from this file (``grace_tpu/evidence/`` → up 2)."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


LEDGER_PATH = os.path.join(repo_root(), "EVIDENCE", "ledger.jsonl")

CLAIM_CLASSES = ("measured", "projected")

# The pinned schema. `topology` is a dict with at least `world`; `tiers`
# (e.g. ["ici"], ["ici","dcn","wan"]), `slice` and `region` ride along
# when the capture/projection has them. `config` is the grace_params-style
# dict (or config name) the number belongs to. `lint_clean` records
# whether the config passed graft-lint at capture time (None = not
# audited).
REQUIRED_FIELDS = ("id", "metric", "value", "claim_class", "capture",
                   "capture_sha256", "git_rev", "platform", "chip",
                   "n_devices", "topology", "config", "lint_clean",
                   "tool", "timestamp")


def sha256_file(path: str) -> Optional[str]:
    try:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()
    except OSError:
        return None


def _git(args: List[str], root: Optional[str] = None) -> Optional[str]:
    try:
        out = subprocess.run(["git"] + args, cwd=root or repo_root(),
                             capture_output=True, text=True, timeout=10)
    except Exception:
        return None
    if out.returncode != 0:
        return None
    return out.stdout.strip() or None


def git_head_rev(root: Optional[str] = None) -> Optional[str]:
    """Full HEAD rev of the repo, or None on a broken/absent checkout."""
    return _git(["rev-parse", "HEAD"], root)


def artifact_rev(relpath: str, root: Optional[str] = None) -> Optional[str]:
    """Rev of the last commit that touched ``relpath`` — the honest
    provenance rev for a committed pre-ledger artifact (backfill), an
    ancestor of HEAD by construction."""
    return _git(["log", "-n1", "--format=%H", "--", relpath], root)


def _utc_now() -> str:
    return datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")


def new_record(**fields: Any) -> Dict[str, Any]:
    """Build + validate a ledger record. Unknown extra keys are kept (the
    schema is a floor, not a ceiling); missing required keys and bad claim
    classes raise so a writer bug cannot mint half a record."""
    rec = dict(fields)
    rec.setdefault("timestamp", _utc_now())
    missing = [k for k in REQUIRED_FIELDS if k not in rec]
    if missing:
        raise ValueError(f"ledger record missing fields: {missing}")
    if rec["claim_class"] not in CLAIM_CLASSES:
        raise ValueError(
            f"claim_class must be one of {CLAIM_CLASSES}, "
            f"got {rec['claim_class']!r}")
    if not isinstance(rec["id"], str) or not rec["id"]:
        raise ValueError("ledger record needs a non-empty string id")
    topo = rec.get("topology")
    if topo is not None and not isinstance(topo, Mapping):
        raise ValueError("topology must be a dict (world/tiers/slice/"
                         "region) or None")
    return rec


def append_record(record: Mapping[str, Any],
                  path: str = LEDGER_PATH) -> Dict[str, Any]:
    """Validate and append one record; whole-line + fsync so a killed
    writer leaves at worst a torn tail the loader skips."""
    rec = new_record(**dict(record))
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    line = json.dumps(rec, sort_keys=True, default=str)
    with open(path, "a") as f:
        f.write(line + "\n")
        f.flush()
        os.fsync(f.fileno())
    return rec


def load_ledger(path: str = LEDGER_PATH) -> List[Dict[str, Any]]:
    records: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    continue                     # torn tail line
                if isinstance(doc, dict) and doc.get("id"):
                    records.append(doc)
    except OSError:
        return []
    return records


def latest_by_id(records: Iterable[Mapping[str, Any]]) -> Dict[str, Dict]:
    """Append-order last-writer-wins resolution of the current record per
    id."""
    out: Dict[str, Dict] = {}
    for rec in records:
        out[str(rec.get("id"))] = dict(rec)
    return out


def record_artifact(capture_path: str, *, id: str, metric: str,
                    value: Any, claim_class: str, tool: str,
                    platform: Optional[str] = None,
                    chip: Optional[str] = None,
                    n_devices: Optional[int] = None,
                    topology: Optional[Mapping[str, Any]] = None,
                    config: Any = None,
                    lint_clean: Optional[bool] = None,
                    git_rev: Optional[str] = None,
                    ledger_path: str = LEDGER_PATH,
                    **extra: Any) -> Optional[Dict[str, Any]]:
    """The one call every evidence writer makes after landing its JSON
    artifact: hash the capture, stamp the current rev, append. Raise-free
    by design — ledger emission must never take down the measurement that
    produced the evidence — a failure prints to stderr and returns None.
    """
    try:
        root = repo_root()
        capture_abs = (capture_path if os.path.isabs(capture_path)
                       else os.path.join(root, capture_path))
        try:
            capture_rel = os.path.relpath(capture_abs, root)
        except ValueError:                       # different drive (win)
            capture_rel = capture_abs
        if capture_rel.startswith(".."):
            capture_rel = capture_abs            # outside the repo: keep abs
        rec = new_record(
            id=id, metric=metric, value=value, claim_class=claim_class,
            capture=capture_rel, capture_sha256=sha256_file(capture_abs),
            git_rev=git_rev if git_rev is not None else git_head_rev(root),
            platform=platform, chip=chip, n_devices=n_devices,
            topology=dict(topology) if topology is not None else None,
            config=config, lint_clean=lint_clean, tool=tool, **extra)
        return append_record(rec, ledger_path)
    except Exception as e:                       # noqa: BLE001
        print(f"[evidence] ledger append failed for {id!r}: {e}",
              file=sys.stderr, flush=True)
        return None
