"""Provenance evidence: the fourth observability subsystem.

The stack reads bottom-up — telemetry (per-step records), watch (timeline
anomaly detection), prof (device-time attribution) — and this package is
the top layer: *who gets to quote a number, and on what evidence*.

* :mod:`~grace_tpu.evidence.ledger` — the schema'd append-only
  ``EVIDENCE/ledger.jsonl``: one record per published measurement or
  projection, carrying the capture file's sha256, the provenance git rev,
  platform/chip/device-count and claim class (``measured`` vs
  ``projected``). Every evidence writer (bench, bench_all, chaos_smoke,
  graft_tune, tpu_variants, graft_watch) appends here alongside its
  existing JSON artifact.
* :mod:`~grace_tpu.evidence.staleness` — the ONE staleness detector:
  feature-stamp checks (what ``bench.evidence_staleness`` used to own)
  plus the git-ancestry check, shared by ``evidence_summary``,
  ``graft_tune`` and ``graft_gate`` so they cannot disagree.
* :mod:`~grace_tpu.evidence.gate` — the claim gate: README/CHANGELOG
  claim markers (``<!-- evidence: <ledger-id> -->``) verified against the
  ledger (hash match, ``git merge-base --is-ancestor``, class/n_devices
  consistency) and rendered as MEASURED / PROJECTED / STALE badges.
* :mod:`~grace_tpu.evidence.backfill` — migration shim: mints ledger
  records from the committed pre-ledger artifacts, stamped with each
  file's last-touching commit.
* :mod:`~grace_tpu.evidence.incident` — the flight recorder: a telemetry
  :class:`~grace_tpu.telemetry.sinks.Sink` that snapshots the recent
  record ring + watch timeline + adapt rung history (+ attached prof
  stage attribution) into a ledger-attached incident file when a guard
  trips, the adapt controller escalates, or a drain fires.

Everything here is pure host-side stdlib — importable on a box with no
JAX runtime, so the gate can run in CI before anything compiles.
"""

from grace_tpu.evidence.ledger import (CLAIM_CLASSES, LEDGER_PATH,
                                       REQUIRED_FIELDS, append_record,
                                       latest_by_id, load_ledger,
                                       new_record, record_artifact,
                                       repo_root, sha256_file)
from grace_tpu.evidence.staleness import (STALE_BANNER, ancestor_verdict,
                                          evidence_staleness,
                                          feature_staleness, head_rev)
from grace_tpu.evidence.gate import (gate_report, render_badges,
                                     scan_claims, splice_badges,
                                     verify_record)
from grace_tpu.evidence.backfill import backfill_ledger
from grace_tpu.evidence.incident import IncidentRecorder

__all__ = [
    "CLAIM_CLASSES", "LEDGER_PATH", "REQUIRED_FIELDS",
    "append_record", "latest_by_id", "load_ledger", "new_record",
    "record_artifact", "repo_root", "sha256_file",
    "STALE_BANNER", "ancestor_verdict", "evidence_staleness",
    "feature_staleness", "head_rev",
    "gate_report", "render_badges", "scan_claims", "splice_badges",
    "verify_record",
    "backfill_ledger", "IncidentRecorder",
]
