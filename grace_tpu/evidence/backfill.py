"""Migration shim: mint ledger records from the committed pre-ledger
artifacts.

Every artifact that predates the ledger gets a record whose ``git_rev``
is the last commit that touched the file (``git log -n1 -- <path>``) —
an ancestor of HEAD by construction, so honest history backfills clean
and only an actual rewrite or a hand-edited capture renders STALE.

Claim classes are assigned by what the artifact *is*, not what the
README says about it: the committed TPU captures are all single-device
(``n_devices: 1``) and classify MEASURED at world 1; every multi-chip
ratio (xslice, rscatter W256, three-tier W=1024) is minted as a separate
PROJECTED record pointing at the artifact that holds its measured base —
exactly the measured/projected split ROADMAP item 1 demands the headline
stop blurring.

Idempotent: an id whose latest ledger record already names the same
capture sha is skipped, so re-running the shim after an artifact refresh
appends only what changed.

Run it via ``python -m grace_tpu.evidence.backfill`` or
``tools/graft_gate.py --backfill``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Mapping, Optional

from grace_tpu.evidence.ledger import (LEDGER_PATH, artifact_rev,
                                       latest_by_id, load_ledger,
                                       record_artifact, repo_root,
                                       sha256_file)

__all__ = ["backfill_ledger"]


def _load_doc(path: str) -> Optional[Any]:
    """One JSON doc, or the list of docs for JSONL-shaped files (bench's
    BENCH_ALL_CPU.json is concatenated JSON docs, one per line)."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        docs = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                docs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return docs or None


def _tpu_topo(world: int = 1) -> Dict[str, Any]:
    return {"world": world, "tiers": ["ici"], "slice": None, "region": None}


def _cpu_mesh_topo(world: int, slice_size: Optional[int] = None,
                   region: Optional[int] = None) -> Dict[str, Any]:
    tiers = ["ici"]
    if slice_size:
        tiers = ["ici", "dcn"]
    if region:
        tiers = ["ici", "dcn", "wan"]
    return {"world": world, "tiers": tiers, "slice": slice_size,
            "region": region}


def _bench_records(doc: Mapping, name: str) -> List[Dict[str, Any]]:
    """Headline bench docs (BENCH_TPU_LAST / BENCH_ALL_TPU_LAST /
    BENCH_BERT_TPU_LAST share the _write_evidence shape)."""
    base = {
        "bench_tpu": ("bench-headline-tpu", "bench"),
        "bench_all_tpu": ("bench-sweep-tpu", "bench_all"),
        "bench_bert_tpu": ("bench-bert-tpu", "tpu_bert_bench"),
    }[name]
    rid, tool = base
    n_dev = doc.get("n_devices") or 1
    rec = {
        "id": rid, "metric": doc.get("metric"),
        "value": doc.get("vs_baseline"),
        "claim_class": "measured", "tool": tool,
        "platform": doc.get("platform"), "chip": doc.get("chip"),
        "n_devices": n_dev, "topology": _tpu_topo(n_dev),
        "config": None, "lint_clean": None,
        "unit": "vs_dense", "captured_at": doc.get("captured_at"),
        "abs_value": doc.get("value"),
    }
    out = [rec]
    # The multi-chip story each capture carries: a PROJECTED twin at the
    # wire-model world sizes, pointing at the same capture file.
    proj_id = {"bench_tpu": "proj-topk1pct-xslice",
               "bench_all_tpu": "proj-sweep-xslice",
               "bench_bert_tpu": "proj-bert-routed-xslice"}[name]
    proj_metric = {"bench_tpu": "resnet50_topk1pct_xslice_vs_dense",
                   "bench_all_tpu": "resnet50_sweep_xslice_vs_dense",
                   "bench_bert_tpu": "bert_routed_xslice_vs_dense"}[name]
    out.append({
        "id": proj_id, "metric": proj_metric, "value": None,
        "claim_class": "projected", "tool": tool,
        "platform": doc.get("platform"), "chip": doc.get("chip"),
        "n_devices": n_dev,
        "topology": {"world": 256, "tiers": ["ici", "dcn"],
                     "slice": 8, "region": None},
        "config": None, "lint_clean": None,
        "unit": "vs_dense",
        "note": "static wire-model projection from the single-device "
                "capture (bench PROJECTION_MODEL constants)",
        "captured_at": doc.get("captured_at"),
    })
    return out


def _artifact_specs() -> List[Dict[str, Any]]:
    """One entry per committed artifact: capture path + a builder that
    turns the loaded doc into ledger-record dicts."""

    def chaos(doc, rid, metric, value, slice_size=None, region=None):
        world = doc.get("world") or 8
        return [{
            "id": rid, "metric": metric, "value": value,
            "claim_class": "measured", "tool": doc.get("tool",
                                                       "chaos_smoke"),
            "platform": "cpu", "chip": "cpu", "n_devices": world,
            "topology": _cpu_mesh_topo(world, slice_size, region),
            "config": doc.get("argv"), "lint_clean": None,
            "captured_at": doc.get("captured_at"),
        }]

    return [
        {"capture": "BENCH_TPU_LAST.json",
         "build": lambda d: _bench_records(d, "bench_tpu")},
        {"capture": "BENCH_ALL_TPU_LAST.json",
         "build": lambda d: _bench_records(d, "bench_all_tpu")},
        {"capture": "BENCH_BERT_TPU_LAST.json",
         "build": lambda d: _bench_records(d, "bench_bert_tpu")},
        {"capture": "BENCH_ALL_CPU.json",
         "build": lambda docs: [{
             "id": "bench-sweep-cpu", "metric": "resnet50_cpu_sweep_rows",
             "value": len(docs) if isinstance(docs, list) else 1,
             "claim_class": "measured", "tool": "bench_all",
             "platform": "cpu", "chip": "cpu", "n_devices": 8,
             "topology": _cpu_mesh_topo(8), "config": None,
             "lint_clean": None,
             "note": "8-device simulated-CPU mesh e2e sweep",
         }]},
        {"capture": "TPU_VARIANTS.jsonl",
         "build": lambda docs: [{
             "id": "variants-tpu", "metric": "resnet50_variant_rows",
             "value": len(docs) if isinstance(docs, list) else 1,
             "claim_class": "measured", "tool": "tpu_variants",
             "platform": "tpu", "chip": "TPU v5 lite", "n_devices": 1,
             "topology": _tpu_topo(1), "config": None,
             "lint_clean": None,
         }]},
        {"capture": "ADAPT_LAST.json",
         "build": lambda d: chaos(
             d, "adapt-drill", "adapt_ordering_ok",
             bool(d.get("ordering_ok")))},
        {"capture": "ELASTIC_LAST.json",
         "build": lambda d: chaos(
             d, "elastic-drill", "elastic_floor_met",
             bool((d.get("floor") or {}).get("met")),
             slice_size=d.get("slice_size"))},
        {"capture": "REGION_LAST.json",
         "build": lambda d: chaos(
             d, "region-drill", "region_floor_met",
             bool((d.get("floor") or {}).get("met")),
             slice_size=d.get("slice_size"),
             region=d.get("region_size"))},
        {"capture": "WATCH_LAST.json",
         "build": lambda d: [{
             "id": "watch-drill", "metric": "watch_anomalies",
             "value": d.get("anomalies"), "claim_class": "measured",
             "tool": d.get("tool", "graft_watch"), "platform": "cpu",
             "chip": "cpu", "n_devices": 8, "topology": _cpu_mesh_topo(8),
             "config": d.get("artifact"), "lint_clean": None,
             "captured_at": d.get("captured_at"),
         }]},
        {"capture": "TUNE_LAST.json",
         "build": lambda d: [{
             "id": "tune-winner", "metric": "tune_winner_config",
             "value": ((d.get("winner") or {}).get("candidate")),
             "claim_class": "measured", "tool": d.get("tool",
                                                      "graft_tune"),
             "platform": (d.get("provenance") or {}).get("platform"),
             "chip": (d.get("provenance") or {}).get("device"),
             "n_devices": (d.get("provenance") or {}).get("n_devices"),
             "topology": _cpu_mesh_topo(
                 (d.get("provenance") or {}).get("n_devices") or 8),
             "config": (d.get("winner") or {}).get("grace_params"),
             "lint_clean": bool(d.get("ok")),
             "captured_at": d.get("captured_at"),
         }, {
             "id": "proj-tune-w256-static", "metric":
                 "tune_static_ranking_w256",
             "value": None, "claim_class": "projected",
             "tool": d.get("tool", "graft_tune"),
             "platform": (d.get("provenance") or {}).get("platform"),
             "chip": (d.get("provenance") or {}).get("device"),
             "n_devices": (d.get("provenance") or {}).get("n_devices"),
             "topology": {"world": 256, "tiers": ["ici", "dcn"],
                          "slice": 8, "region": None},
             "config": None, "lint_clean": bool(d.get("ok")),
             "note": "static per-link pricing ranking (W256/slice8)",
             "captured_at": d.get("captured_at"),
         }, {
             "id": "proj-three-tier-w1024", "metric":
                 "three_tier_w1024_vs_dense",
             "value": None, "claim_class": "projected",
             "tool": d.get("tool", "graft_tune"),
             "platform": (d.get("provenance") or {}).get("platform"),
             "chip": (d.get("provenance") or {}).get("device"),
             "n_devices": (d.get("provenance") or {}).get("n_devices"),
             "topology": {"world": 1024, "tiers": ["ici", "dcn", "wan"],
                          "slice": 8, "region": 256},
             "config": None, "lint_clean": bool(d.get("ok")),
             "note": "W=1024 three-tier funnel, static wire model "
                     "(4 regions x 256, slices of 8)",
             "captured_at": d.get("captured_at"),
         }]},
        {"capture": "LINT_LAST.json",
         "build": lambda d: [{
             "id": "lint-clean", "metric": "lint_configs_clean",
             "value": d.get("configs_audited"),
             "claim_class": "measured", "tool": d.get("tool",
                                                      "graft_lint"),
             "platform": "host", "chip": None, "n_devices": None,
             "topology": {"world": d.get("world"), "tiers": None,
                          "slice": None, "region": None},
             "config": None,
             "lint_clean": (d.get("errors") == 0
                            and d.get("warnings") == 0),
             "captured_at": d.get("captured_at"),
         }]},
        {"capture": "PROF_LAST.json",
         "build": lambda d: [{
             "id": "prof-canned-trace", "metric":
                 "prof_overlap_fraction",
             "value": d.get("overlap_fraction"),
             "claim_class": "measured", "tool": d.get("tool",
                                                      "perf_report"),
             "platform": "cpu", "chip": "cpu", "n_devices": None,
             "topology": None, "config": d.get("trace"),
             "lint_clean": None, "note": d.get("note"),
             "captured_at": d.get("captured_at"),
         }]},
    ]


def backfill_ledger(root: Optional[str] = None,
                    ledger_path: Optional[str] = None,
                    verbose: bool = False) -> List[Dict[str, Any]]:
    """Mint records for every committed artifact not yet in the ledger.
    Returns the records appended this call."""
    root = root or repo_root()
    ledger_path = ledger_path or os.path.join(root, "EVIDENCE",
                                              "ledger.jsonl")
    current = latest_by_id(load_ledger(ledger_path))
    appended: List[Dict[str, Any]] = []
    for spec in _artifact_specs():
        rel = spec["capture"]
        path = os.path.join(root, rel)
        doc = _load_doc(path)
        if doc is None:
            continue
        sha = sha256_file(path)
        rev = artifact_rev(rel, root)
        for rec in spec["build"](doc):
            prior = current.get(rec["id"])
            if prior is not None and prior.get("capture_sha256") == sha:
                continue                       # already minted for this sha
            out = record_artifact(
                path, ledger_path=ledger_path, git_rev=rev,
                **{k: v for k, v in rec.items() if k != "capture"})
            if out is not None:
                appended.append(out)
                current[out["id"]] = out
                if verbose:
                    print(f"[backfill] {out['id']}: "
                          f"{out['claim_class']} {out['metric']} "
                          f"@ {str(rev)[:12]}")
    return appended


if __name__ == "__main__":
    recs = backfill_ledger(verbose=True)
    print(f"[backfill] appended {len(recs)} record(s) to {LEDGER_PATH}")
