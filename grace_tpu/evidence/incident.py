"""The incident flight recorder.

A telemetry :class:`~grace_tpu.telemetry.sinks.Sink` meant to ride a
``MultiSink`` next to the JSONL evidence sink: it observes the same
record stream the monitors emit, keeps a bounded ring of recent records,
and when a trigger fires — a guard trip (``guard_skip`` /
``guard_fallback_engaged``), an adapt escalation (``adapt_tighten``), a
drain (``elastic_drain*``), or a retune transaction boundary
(``retune_promote`` / ``retune_demote``) — it snapshots everything a
postmortem needs into ONE file:

* the telemetry ring (the last N records of every kind, verbatim),
* the watch-timeline view of that ring (kind classification + counts,
  via :func:`grace_tpu.telemetry.timeline.classify`),
* the adapt rung history (every ``adapt_*`` record seen this run),
* the guard/elastic event history,
* the prof stage attribution, when the caller attached one
  (:meth:`IncidentRecorder.attach_profile`),

written to ``EVIDENCE/incidents/<id>.json`` and attached to the ledger
as a ``measured`` record (tool ``flight_recorder``), so incidents are
first-class evidence with the same hash/ancestry audit as headlines.

Debounced: a guard that skips 50 steps in a row is one incident, not 50
files (``min_gap_steps``), and a pathological run caps at
``max_incidents``.
"""

from __future__ import annotations

import datetime
import json
import os
from collections import deque
from typing import Any, Deque, Dict, List, Mapping, Optional, Tuple

from grace_tpu.evidence.ledger import record_artifact, repo_root

__all__ = ["IncidentRecorder", "DEFAULT_TRIGGERS"]

# Event-name prefixes that open an incident. `adapt_tighten` is the
# controller acting *before* the guard — the flight recorder's whole
# point is capturing the window where that race is decided. A retune
# promotion/demotion is a config transaction boundary: the window around
# it is exactly what a "did the cutover cause this?" postmortem needs.
DEFAULT_TRIGGERS: Tuple[str, ...] = (
    "guard_skip", "guard_fallback_engaged", "adapt_tighten",
    "elastic_drain", "consensus_escalation", "retune_promote",
    "retune_demote")


def _utc_now() -> str:
    return datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")


def _classify(record: Mapping[str, Any]) -> str:
    try:
        from grace_tpu.telemetry.timeline import classify
        return classify(record)
    except Exception:
        return "other"


class IncidentRecorder:
    """Sink-protocol flight recorder (``write``/``close``/context
    manager). Pure host-side; never raises out of ``write`` — a broken
    disk must not take down the training loop it is observing."""

    def __init__(self, out_dir: Optional[str] = None, *,
                 run_tag: str = "run",
                 ring_size: int = 256,
                 min_gap_steps: int = 25,
                 max_incidents: int = 8,
                 triggers: Tuple[str, ...] = DEFAULT_TRIGGERS,
                 ledger_path: Optional[str] = None,
                 provenance: Optional[Mapping[str, Any]] = None):
        self.out_dir = out_dir or os.path.join(repo_root(), "EVIDENCE",
                                               "incidents")
        self.run_tag = run_tag
        self.triggers = tuple(triggers)
        self.min_gap_steps = min_gap_steps
        self.max_incidents = max_incidents
        self.ledger_path = ledger_path
        self.provenance = dict(provenance) if provenance else None
        self._ring: Deque[Dict[str, Any]] = deque(maxlen=ring_size)
        self._adapt: List[Dict[str, Any]] = []
        self._guard: List[Dict[str, Any]] = []
        self._elastic: List[Dict[str, Any]] = []
        self._retune: List[Dict[str, Any]] = []
        self._prof: Optional[Dict[str, Any]] = None
        self._last_trigger_step: Optional[int] = None
        self.incidents: List[str] = []        # written file paths
        self._seq = 0
        self._closed = False

    # -- Sink protocol ---------------------------------------------------
    def write(self, record: Mapping[str, Any]) -> None:
        try:
            rec = dict(record)
            self._ring.append(rec)
            event = str(rec.get("event", ""))
            if event.startswith("adapt"):
                self._adapt.append(rec)
            elif event.startswith("guard"):
                self._guard.append(rec)
            elif event.startswith("elastic"):
                self._elastic.append(rec)
            elif event.startswith("retune"):
                self._retune.append(rec)
            if self._should_trigger(rec, event):
                self._snapshot(rec, event)
        except Exception as e:               # noqa: BLE001
            import sys
            print(f"[evidence] flight recorder write failed: {e}",
                  file=sys.stderr, flush=True)

    def close(self) -> None:
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- postmortem attachments ------------------------------------------
    def attach_profile(self, stage_attribution: Mapping[str, Any]) -> None:
        """Attach a prof stage-attribution dict (perf_report's
        ``stages_ms``/overlap payload); rides every later incident."""
        self._prof = dict(stage_attribution)

    # -- internals -------------------------------------------------------
    def _should_trigger(self, rec: Mapping[str, Any], event: str) -> bool:
        if self._closed or len(self.incidents) >= self.max_incidents:
            return False
        if not any(event.startswith(t) for t in self.triggers):
            return False
        step = rec.get("step")
        if (isinstance(step, (int, float)) and
                self._last_trigger_step is not None and
                step - self._last_trigger_step < self.min_gap_steps):
            return False
        if isinstance(step, (int, float)):
            self._last_trigger_step = int(step)
        return True

    def _timeline_view(self) -> Dict[str, Any]:
        kinds: Dict[str, int] = {}
        events: List[Dict[str, Any]] = []
        for rec in self._ring:
            kind = _classify(rec)
            kinds[kind] = kinds.get(kind, 0) + 1
            if kind not in ("telemetry", "other"):
                events.append({"step": rec.get("step"),
                               "kind": kind,
                               "event": rec.get("event")})
        return {"kind_counts": kinds, "events": events}

    def _snapshot(self, trigger: Dict[str, Any], event: str) -> None:
        self._seq += 1
        step = trigger.get("step")
        inc_id = (f"incident-{self.run_tag}-{self._seq:03d}-"
                  f"{event or 'event'}")
        doc = {
            "id": inc_id,
            "tool": "flight_recorder",
            "trigger": trigger,
            "step": step,
            "telemetry_ring": list(self._ring),
            "watch_timeline": self._timeline_view(),
            "adapt_rungs": list(self._adapt),
            "guard_events": list(self._guard),
            "elastic_events": list(self._elastic),
            "retune_events": list(self._retune),
            "prof": self._prof,
            "provenance": self.provenance,
            "captured_at": _utc_now(),
        }
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, inc_id + ".json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, default=str)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self.incidents.append(path)
        import sys
        print(f"[evidence] incident recorded: {path}", file=sys.stderr,
              flush=True)
        prov = self.provenance or {}
        kwargs = dict(
            id=inc_id, metric="incident_trigger_step",
            value=step, claim_class="measured", tool="flight_recorder",
            platform=prov.get("platform"), chip=prov.get("device"),
            n_devices=prov.get("n_devices"),
            topology=({"world": prov.get("n_devices"), "tiers": None,
                       "slice": None, "region": None}
                      if prov.get("n_devices") else None),
            config=event, lint_clean=None)
        if self.ledger_path:
            kwargs["ledger_path"] = self.ledger_path
            record_artifact(path, **kwargs)
        else:
            # Same in-repo guard as every other writer: a smoke run
            # pointed at a /tmp incident dir must not pollute the repo
            # ledger with records for files that live outside it.
            out_abs = os.path.abspath(self.out_dir)
            root = repo_root()
            if out_abs == root or out_abs.startswith(root + os.sep):
                record_artifact(path, **kwargs)
