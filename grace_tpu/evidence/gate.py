"""The claim gate: README/CHANGELOG headline ratios must cite evidence.

ROADMAP item 1, verbatim: "an ``evidence_gate`` CI mode where README/
CHANGELOG headline ratios must cite a capture whose provenance rev is an
ancestor of HEAD, or the claim renders as STALE."

Mechanics:

* A **claim marker** is an HTML comment naming one or more ledger ids,
  placed in the same paragraph as the headline it backs::

      measures 0.9895× dense single-chip <!-- evidence: bench-headline-tpu -->

* A **quantitative claim line** is any prose line carrying a
  ratio-vs-dense pattern (``0.9897×``, ``1.09–1.11×``, ``>1× vs dense``,
  ``8.7× dense``) — outside fenced code blocks and outside the
  auto-generated ``<!-- evidence:begin/end -->`` block (that block is
  rendered *from* the ledger, so it is evidence by construction).
  Coverage is paragraph-scoped: a contiguous run of non-blank lines with
  at least one marker covers every claim line inside it.

* **Verification** per cited record: the capture file's sha256 must still
  match the recorded one; the record's ``git_rev`` must be an ancestor of
  HEAD (strict policy — an unresolvable rev is STALE here, unlike the
  document detector; see :mod:`~grace_tpu.evidence.staleness`); and the
  claim class must be consistent with the capture's device count — a
  ``measured`` record whose claimed topology world exceeds its
  ``n_devices`` is a **gate failure**, not a footnote (the exact
  single-chip-capture-behind-a-multi-chip-claim dishonesty the ledger
  exists to prevent).

Verdict badges: **MEASURED** / **PROJECTED** / **STALE**.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Mapping, Optional, Tuple

from grace_tpu.evidence.ledger import (LEDGER_PATH, latest_by_id,
                                       load_ledger, repo_root, sha256_file)
from grace_tpu.evidence.staleness import ancestor_verdict

__all__ = ["MARKER_RE", "CLAIM_RE", "scan_claims", "verify_record",
           "gate_report", "render_badges", "splice_badges",
           "GATE_BEGIN", "GATE_END"]

# <!-- evidence: id-one id-two --> — ids split on comma/whitespace.
MARKER_RE = re.compile(r"<!--\s*evidence:\s*([A-Za-z0-9_.,:\s/-]+?)\s*-->")

# Marker "ids" that are block fences, not citations.
_FENCE_IDS = frozenset({"begin", "end"})

# A ratio token: ~0.98×, 1.09–1.11x, >1×, 8.7× — but not "0x1f" hex or
# "2xlarge"-style words (the lookahead kills a trailing word char).
_RATIO = r"[>~]?\d+(?:\.\d+)?(?:\s*[-–]\s*\d+(?:\.\d+)?)?\s*[×x](?![a-wyz0-9])"
# A quantitative headline claim: a ratio on a line that talks about dense.
CLAIM_RE = re.compile(rf"(?:{_RATIO})(?=.*\bdense\b)|(?:\bdense\b.*?{_RATIO})",
                      re.IGNORECASE)

GATE_BEGIN = "<!-- evidence-gate:begin -->"
GATE_END = "<!-- evidence-gate:end -->"


def _marker_ids(line: str) -> List[str]:
    ids: List[str] = []
    for m in MARKER_RE.finditer(line):
        for tok in re.split(r"[,\s]+", m.group(1).strip()):
            if tok and tok not in _FENCE_IDS:
                ids.append(tok)
    return ids


def scan_claims(text: str) -> Dict[str, Any]:
    """Scan one markdown document. Returns ``{"claims": [(lineno, line)],
    "cited_ids": [...], "unmarked": [(lineno, line)]}`` where ``unmarked``
    is the gate-failing subset: claim lines whose paragraph carries no
    marker."""
    lines = text.split("\n")
    fence = False
    in_evidence_block = False
    in_gate_block = False
    # Paragraph id per line: contiguous non-blank runs share an id.
    para_of: List[int] = []
    para = -1
    prev_blank = True
    for raw in lines:
        blank = not raw.strip()
        if blank:
            para_of.append(-1)
        else:
            if prev_blank:
                para += 1
            para_of.append(para)
        prev_blank = blank

    marked_paras = set()
    cited: List[str] = []
    claims: List[Tuple[int, str]] = []
    for i, raw in enumerate(lines):
        stripped = raw.strip()
        if stripped.startswith("```"):
            fence = not fence
            continue
        if "<!-- evidence:begin -->" in raw:
            in_evidence_block = True
        if "<!-- evidence:end -->" in raw:
            in_evidence_block = False
            continue
        # The gate's own rendered block quotes failing claim text; it must
        # not re-trigger the scanner (same exemption as the evidence
        # block: both are generated from the ledger).
        if GATE_BEGIN in raw:
            in_gate_block = True
        if GATE_END in raw:
            in_gate_block = False
            continue
        ids = _marker_ids(raw)
        if ids:
            cited.extend(ids)
            if para_of[i] >= 0:
                marked_paras.add(para_of[i])
            # A marker on its own line also covers the adjacent
            # paragraphs (the "marker directly above the table/heading"
            # idiom).
            for j in (i - 1, i + 1):
                if 0 <= j < len(para_of) and para_of[j] >= 0:
                    marked_paras.add(para_of[j])
        if fence or in_evidence_block or in_gate_block:
            continue
        if stripped.startswith("<!--"):
            continue
        if CLAIM_RE.search(raw):
            claims.append((i + 1, raw.strip()))

    unmarked = [(n, l) for (n, l) in claims
                if para_of[n - 1] not in marked_paras]
    return {"claims": claims, "cited_ids": cited, "unmarked": unmarked}


def verify_record(rec: Optional[Mapping[str, Any]], *,
                  root: Optional[str] = None,
                  head: str = "HEAD") -> Dict[str, Any]:
    """One record → ``{"status": MEASURED|PROJECTED|STALE, "failures":
    [...], "notes": [...]}``. ``rec=None`` means the cited id has no
    ledger record at all."""
    root = root or repo_root()
    failures: List[str] = []
    notes: List[str] = []
    if rec is None:
        return {"status": "STALE", "failures": ["no ledger record"],
                "notes": []}

    capture = rec.get("capture")
    recorded_sha = rec.get("capture_sha256")
    if capture:
        cap_abs = (capture if os.path.isabs(capture)
                   else os.path.join(root, capture))
        actual = sha256_file(cap_abs)
        if actual is None:
            failures.append(f"capture file missing: {capture}")
        elif recorded_sha and actual != recorded_sha:
            failures.append(
                f"capture hash mismatch: {capture} changed since the "
                "record was minted (re-run the writer or re-backfill)")
        elif not recorded_sha:
            notes.append("record carries no capture_sha256")
    else:
        failures.append("record names no capture file")

    verdict = ancestor_verdict(rec.get("git_rev"), root, head)
    if verdict == "not_ancestor":
        failures.append(
            f"git_rev {rec.get('git_rev')} is not an ancestor of {head}")
    elif verdict == "unknown":
        failures.append(
            f"git_rev {rec.get('git_rev')!r} does not resolve in this "
            "clone — ancestry unprovable")
    elif verdict == "no_git":
        notes.append("git unavailable; ancestry unchecked")

    # Class/n_devices consistency: the claim's world is topology.world
    # (what the number is *about*); n_devices is what actually ran.
    n_dev = rec.get("n_devices")
    topo = rec.get("topology") or {}
    world = topo.get("world") if isinstance(topo, Mapping) else None
    if (rec.get("claim_class") == "measured" and
            isinstance(world, (int, float)) and
            isinstance(n_dev, (int, float)) and world > n_dev):
        failures.append(
            f"class mismatch: claim_class 'measured' for a world-{world} "
            f"topology backed by an n_devices={n_dev} capture — that is "
            "a projection and must say so")

    if failures:
        status = "STALE"
    else:
        status = ("MEASURED" if rec.get("claim_class") == "measured"
                  else "PROJECTED")
    return {"status": status, "failures": failures, "notes": notes}


def gate_report(root: Optional[str] = None,
                ledger_path: Optional[str] = None,
                docs: Tuple[str, ...] = ("README.md", "CHANGELOG.md"),
                head: str = "HEAD") -> Dict[str, Any]:
    """Audit every doc's claims against the ledger. ``ok`` is the --ci
    verdict: no unmarked quantitative claims, and no cited record that
    verifies STALE."""
    root = root or repo_root()
    ledger_path = ledger_path or os.path.join(root, "EVIDENCE",
                                              "ledger.jsonl")
    records = latest_by_id(load_ledger(ledger_path))
    report: Dict[str, Any] = {"root": root, "ledger": ledger_path,
                              "docs": {}, "records": {}, "ok": True,
                              "failures": []}
    cited: List[str] = []
    for doc in docs:
        path = os.path.join(root, doc)
        try:
            with open(path) as f:
                scan = scan_claims(f.read())
        except OSError:
            continue
        report["docs"][doc] = scan
        cited.extend(scan["cited_ids"])
        for lineno, line in scan["unmarked"]:
            report["failures"].append(
                f"{doc}:{lineno}: unmarked quantitative claim: {line}")

    for cid in sorted(set(cited)):
        res = verify_record(records.get(cid), root=root, head=head)
        res["record"] = records.get(cid)
        report["records"][cid] = res
        if res["status"] == "STALE":
            for f in res["failures"]:
                report["failures"].append(f"record {cid}: {f}")

    report["ok"] = not report["failures"]
    return report


def render_badges(report: Mapping[str, Any]) -> str:
    """The README badge block: one row per cited record, badge first."""
    lines = [GATE_BEGIN,
             "<!-- generated by tools/graft_gate.py --update-readme; "
             "do not edit by hand -->",
             "",
             "| claim id | verdict | class | metric | value | platform "
             "| n_dev | world | captured rev |",
             "|---|---|---|---|---|---|---|---|---|"]
    for cid, res in sorted(report.get("records", {}).items()):
        rec = res.get("record") or {}
        topo = rec.get("topology") or {}
        rev = str(rec.get("git_rev") or "?")[:12]
        badge = {"MEASURED": "**MEASURED**", "PROJECTED": "*PROJECTED*",
                 "STALE": "~~STALE~~"}.get(res["status"], res["status"])
        val = rec.get("value")
        if isinstance(val, float):
            val = f"{val:g}"
        lines.append(
            f"| `{cid}` | {badge} | {rec.get('claim_class', '?')} "
            f"| {rec.get('metric', '?')} | {val} "
            f"| {rec.get('platform', '?')} | {rec.get('n_devices', '?')} "
            f"| {topo.get('world', '?')} | `{rev}` |")
    fails = report.get("failures") or []
    if fails:
        lines += ["", "Gate failures:", ""]
        lines += [f"- {f}" for f in fails]
    lines += ["", GATE_END]
    return "\n".join(lines)


def splice_badges(readme_path: str, report: Mapping[str, Any]) -> bool:
    """Replace (or append) the badge block between the gate fences.
    Returns True if the file changed."""
    try:
        with open(readme_path) as f:
            text = f.read()
    except OSError:
        return False
    block = render_badges(report)
    if GATE_BEGIN in text and GATE_END in text:
        pre = text.split(GATE_BEGIN)[0]
        post = text.split(GATE_END, 1)[1]
        new = pre + block + post
    else:
        new = text.rstrip("\n") + "\n\n" + block + "\n"
    if new == text:
        return False
    tmp = readme_path + ".tmp"
    with open(tmp, "w") as f:
        f.write(new)
    os.replace(tmp, readme_path)
    return True
