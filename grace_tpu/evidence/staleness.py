"""The ONE staleness detector.

Before this module, three readers each decided freshness for themselves:
``bench.evidence_staleness`` (feature stamps), ``evidence_summary``'s
banner (delegating to bench), and the tuner's carry-along marking. They
agreed only by discipline. Now they all call here, and the claim gate
adds the structural check the feature stamps can't express: the
provenance rev must be an **ancestor of HEAD** (``git merge-base
--is-ancestor``), or the capture was taken on a branch/rewrite whose
numbers this tree never saw.

Two policies on one primitive (:func:`ancestor_verdict`):

* :func:`evidence_staleness` (document policy, what ``bench`` delegates
  to) — adds an ancestry reason only on a *definite* non-ancestor. An
  unresolvable rev (short rev from a shallow clone, a doc copied from
  another checkout) is not evidence of staleness; the feature-stamp
  detectors still apply.
* ``graft_gate`` (ledger policy, in :mod:`~grace_tpu.evidence.gate`) —
  strict: a cited record whose rev cannot be proven an ancestor renders
  STALE. Claims quote the gate, so claims get the strict policy.
"""

from __future__ import annotations

import subprocess
from typing import Any, List, Mapping, Optional

from grace_tpu.evidence.ledger import git_head_rev, repo_root

__all__ = ["STALE_BANNER", "ancestor_verdict", "evidence_staleness",
           "feature_staleness", "ancestry_staleness", "head_rev"]

STALE_BANNER = "STALE — predates PRs 7–10"

head_rev = git_head_rev        # re-export under the reader-facing name


def ancestor_verdict(rev: Optional[str], root: Optional[str] = None,
                     head: str = "HEAD") -> str:
    """``git merge-base --is-ancestor rev head`` → one of:

    * ``"ancestor"`` — rev is reachable from ``head`` (exit 0);
    * ``"not_ancestor"`` — both commits exist, rev is not reachable
      (exit 1): the capture predates a rewrite or lives on a branch;
    * ``"unknown"`` — rev doesn't resolve in this clone (exit 128 etc.);
    * ``"no_git"`` — no usable git at all (CI tarball, broken checkout).
    """
    if not rev:
        return "unknown"
    try:
        out = subprocess.run(
            ["git", "merge-base", "--is-ancestor", str(rev), head],
            cwd=root or repo_root(), capture_output=True, timeout=10)
    except Exception:
        return "no_git"
    if out.returncode == 0:
        return "ancestor"
    if out.returncode == 1:
        return "not_ancestor"
    return "unknown"


def feature_staleness(doc: Any) -> List[str]:
    """Why a persisted TPU evidence document predates the current feature
    set — the detectors are the stamps the perf PRs introduced, so a
    fresh capture clears them all by construction:

    * PR 10 stamps ``pallas_enabled``/``fusion`` into the document-level
      ``run_provenance`` and a first-class ``fusion`` key onto every row —
      a document without them was captured before the bucketed executor
      and the fused pack kernels existed;
    * PR 7's hierarchical communicator: a sweep with no ``hier`` row
      never measured the two-level schedule the W≥64 projections ride on.
    """
    if not isinstance(doc, Mapping):
        return []
    reasons = []
    prov = doc.get("provenance")
    if not isinstance(prov, Mapping):
        reasons.append(
            "no run_provenance block — the capture predates the "
            "document-level provenance stamp (git commit unknown)")
    elif "pallas_enabled" not in prov or "fusion" not in prov:
        reasons.append(
            "provenance lacks the pallas_enabled/fusion stamps (PR 10): "
            "the headline cannot say which executor/kernel path it "
            "measured")
    rows = [r for r in (doc.get("rows") or [])
            if isinstance(r, Mapping) and r.get("config")]
    measured = [r for r in rows if "imgs_per_sec" in r
                or "tokens_per_sec" in r]
    if measured and not any("fusion" in r for r in measured):
        reasons.append(
            "rows predate the first-class fusion row stamp (PR 10)")
    if len(measured) > 2:        # a sweep, not the 2-row headline pair
        comms = {(r.get("grace_params") or {}).get("communicator")
                 for r in measured}
        if not comms & {"hier", "hierarchical", "hier_allreduce"}:
            reasons.append(
                "no hierarchical (ICI×DCN) row — the sweep predates PR 7; "
                "refresh with `bench_all --tuned`")
    return reasons


def ancestry_staleness(rev: Optional[str],
                       root: Optional[str] = None) -> List[str]:
    """Document-policy ancestry reasons: only a *definite* non-ancestor
    counts (see module docstring for why unknown revs pass here but fail
    the gate)."""
    if ancestor_verdict(rev, root) == "not_ancestor":
        return [f"provenance rev {rev} is not an ancestor of HEAD — the "
                "capture predates a history rewrite or was taken on "
                "another branch"]
    return []


def evidence_staleness(doc: Any, root: Optional[str] = None) -> List[str]:
    """The unified document detector ``bench.evidence_staleness`` now
    delegates to: feature stamps + definite-non-ancestor provenance rev.
    Empty list = current. A stale document is still evidence — of the
    machine state at its ``captured_at`` — it just must not be presented
    as the current system's number, which is what the STALE banner
    enforces in ``tools/evidence_summary.py`` and the ``last_tpu``
    carry-along."""
    reasons = feature_staleness(doc)
    if isinstance(doc, Mapping):
        prov = doc.get("provenance")
        if isinstance(prov, Mapping):
            reasons += ancestry_staleness(prov.get("git_commit"), root)
    return reasons
