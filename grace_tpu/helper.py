"""String-keyed factory: the `grace_from_params` compatibility surface.

Reference: grace_dl/dist/helper.py:1-86 (and the torch/tf twins). The params
dict schema is preserved so reference users can port configs verbatim:
``compressor`` / ``memory`` / ``communicator`` selectors plus per-algorithm
hyperparameters (``compress_ratio``, ``quantum_num``, ``threshold``,
``momentum``, ``gradient_clipping``, ``compress_rank``, ``lr``). Differences:

* ``world_size`` is accepted and ignored — world size is a property of the
  device mesh, not configuration.
* ``axis_name`` selects the mesh axis (default ``'data'``).
* The reference's latent Broadcast bug (helper.py:84 omits the required
  ``rank`` ctor arg → TypeError) has no analog: broadcast needs no rank here.
* Returns a :class:`Grace` bundle with ``.transform(seed)`` (optax) instead
  of a stateful Communicator object.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import optax

from grace_tpu import comm
from grace_tpu import compressors as C
from grace_tpu import memories as M
from grace_tpu.core import (DEFAULT_AXIS, Communicator, Compressor,
                            LinkBytes, Memory, Topology,
                            negotiation_bytes_for)
from grace_tpu.transform import MeshSpec, grace_transform, leaf_path_str, \
    normalize_routes, route_for


@dataclasses.dataclass(frozen=True)
class Grace:
    """Bundle of the configured triad; the `grc` object of reference examples."""

    compressor: Compressor
    memory: Memory
    communicator: Communicator
    fusion: Any = None   # None | 'flat' | 'grouped' | bucket bytes
                         # (see grace_transform)
    escape: Any = None   # None | dense Compressor: the resilience escape
                         # hatch (see grace_transform / resilience.guard)
    telemetry: Any = None  # None | True | capacity | dict | TelemetryConfig:
                           # in-graph telemetry ring (grace_tpu.telemetry)
    consensus: Any = None  # None | True | audit_every | dict |
                           # ConsensusConfig: cross-rank consistency audit
                           # (grace_tpu.resilience.consensus). Arms the
                           # AuditState here; pass the same value to
                           # make_train_step(consensus=...) for the hook.
    topology: Any = None   # None | core.Topology: the mesh link layout the
                           # telemetry ring prices its per-link wire split
                           # with (wire_bytes_ici/wire_bytes_dcn). None =
                           # Topology.detect() at wire-plan time; set from
                           # params["slice_size"] by grace_from_params.
    watch: Any = None      # None | True | window | dict | WatchConfig:
                           # graft-watch in-graph cross-rank health
                           # aggregation (grace_tpu.telemetry.aggregate);
                           # requires telemetry.
    mesh: Any = None       # None | axis str | transform.MeshSpec: the mesh
                           # layout (dp axis + optional fsdp axis for the
                           # sharded-model track). Set from
                           # params["fsdp_axis"] by grace_from_params.
    routes: Tuple = ()     # normalized ((pattern, compressor, memory,
                           # communicator), ...): first-class per-leaf
                           # codec routing — embeddings ride aggressive
                           # sparsification while LayerNorm/bias leaves
                           # ride dense/fp16. Set from params["route"].
    adapt: Any = None      # None | resilience.adapt.AdaptConfig with the
                           # BUILT rung compressors (base codec as the
                           # top rung): the graft-adapt in-graph
                           # degradation ladder. Set from
                           # params["adapt"]; requires escape+telemetry.
                           # Stored normalized so the static auditor and
                           # the tuner enumerate the same rungs the
                           # transform dispatches over.

    def transform(self, seed: int = 0) -> optax.GradientTransformation:
        return grace_transform(self.compressor, self.memory,
                               self.communicator, seed=seed,
                               fusion=self.fusion, escape=self.escape,
                               telemetry=self.telemetry,
                               consensus=self.consensus,
                               topology=self.topology,
                               watch=self.watch,
                               mesh=self.mesh,
                               routes=self.routes or None,
                               adapt=self.adapt)


def _pad_powersgd_states(base: Compressor, rungs: Tuple[Compressor, ...]
                         ) -> Tuple[Compressor, Tuple[Compressor, ...]]:
    """Rung-invariant PowerSGD layout for an adapt ladder: every PowerSGD
    codec among the rungs AND the base (the base is the ladder's top rung,
    and the transform allocates comp state from it) gets ``state_rank``
    pinned to the ladder's max rank, so all rungs thread one padded
    ``(m, max_rank)`` Q structure through the adapt ``lax.switch``.
    No-op for ladders without PowerSGD, and for single-entry "ladders"
    (base only, no rungs) where padding buys nothing."""
    ps = [c for c in (*rungs, base)
          if isinstance(c, C.PowerSGDCompressor)]
    if not ps or not rungs:
        return base, tuple(rungs)
    pad = max(c.state_rank or c.rank for c in ps)

    def fix(c):
        if isinstance(c, C.PowerSGDCompressor) and c.state_rank != pad:
            return dataclasses.replace(c, state_rank=pad)
        return c

    return fix(base), tuple(fix(c) for c in rungs)


def _build_compressor(params: Dict[str, Any], axis: str) -> Compressor:
    name = params.get("compressor", "none")
    ratio = params.get("compress_ratio", 0.3)
    if name == "none":
        return C.NoneCompressor()
    if name in ("fp16", "bf16", "bfloat16"):
        return C.FP16Compressor(dtype="float16" if name == "fp16" else "bfloat16")
    if name == "cyclictopk":
        # ScaleCom-style cyclic Top-K: one shared k-index set per step,
        # derived from the replicated rng + step (rank-deterministic,
        # data-free ctx), so the payload is exactly summable
        # (payload_algebra='exact') — the large-W fix for per-rank topk's
        # degradation cliff, with zero negotiation bytes.
        return C.CyclicTopKCompressor(compress_ratio=ratio)
    if name == "topk":
        return C.TopKCompressor(
            compress_ratio=ratio,
            algorithm=params.get("topk_algorithm", "exact"),
            recall_target=params.get("recall_target", 0.95),
            wire_dtype=params.get("wire_dtype", "float32"),
            use_pallas=params.get("use_pallas", "auto"))
    if name == "randomk":
        return C.RandomKCompressor(compress_ratio=ratio)
    if name == "threshold":
        return C.ThresholdCompressor(
            threshold=params.get("threshold", 0.01),
            capacity_ratio=params.get("capacity_ratio", 0.25))
    if name == "qsgd":
        return C.QSGDCompressor(quantum_num=params.get("quantum_num", 64),
                                use_pallas=params.get("use_pallas", "auto"))
    if name == "homoqsgd":
        # Shared-scale homomorphic QSGD (payload_algebra='shared_scale'):
        # quantum_num defaults to the 4-bit qsgd4 family; accum_dtype sizes
        # the integer payload for exact W-rank sums.
        return C.HomoQSGDCompressor(
            quantum_num=params.get("quantum_num", 7),
            accum_dtype=params.get("accum_dtype", "int16"),
            accum_bits=params.get("accum_bits"),
            use_pallas=params.get("use_pallas", "auto"))
    if name == "countsketch":
        return C.CountSketchCompressor(
            compress_ratio=params.get("compress_ratio", 0.25),
            rows=params.get("sketch_rows", 3))
    if name == "terngrad":
        return C.TernGradCompressor()
    if name == "signsgd":
        return C.SignSGDCompressor(use_pallas=params.get("use_pallas",
                                                         "auto"))
    if name == "signum":
        return C.SignumCompressor(momentum=params.get("momentum", 0.9),
                                  use_pallas=params.get("use_pallas",
                                                        "auto"))
    if name == "efsignsgd":
        return C.EFSignSGDCompressor(lr=params.get("lr", 0.1))
    if name == "onebit":
        return C.OneBitCompressor()
    if name == "natural":
        return C.NaturalCompressor()
    if name == "dgc":
        return C.DgcCompressor(compress_ratio=params.get("compress_ratio", 0.01))
    if name == "powersgd":
        return C.PowerSGDCompressor(rank=params.get("compress_rank", 1),
                                    axis_name=axis)
    if name == "u8bit":
        return C.U8bitCompressor()
    if name == "sketch":
        return C.SketchCompressor(bins=params.get("quantum_num", 256))
    if name == "adaq":
        return C.AdaqCompressor(compress_ratio=params.get("compress_ratio", 0.01))
    if name == "inceptionn":
        return C.InceptionNCompressor()
    raise ValueError(f"unknown compressor {name!r}")


def _build_memory(params: Dict[str, Any], axis: str) -> Memory:
    name = params.get("memory", "none")
    if name == "none":
        return M.NoneMemory()
    if name == "residual":
        return M.ResidualMemory(beta=params.get("beta", 1.0),
                                gamma=params.get("gamma", 1.0),
                                state_dtype=params.get("memory_dtype"))
    if name == "efsignsgd":
        return M.EFSignSGDMemory(lr=params.get("lr", 0.1))
    if name == "dgc":
        return M.DgcMemory(momentum=params.get("momentum", 0.9),
                           gradient_clipping=params.get("gradient_clipping",
                                                        False),
                           axis_name=axis)
    if name == "powersgd":
        return M.PowerSGDMemory()
    raise ValueError(f"unknown memory {name!r}")


def _build_communicator(params: Dict[str, Any], axis: str) -> Communicator:
    name = params.get("communicator", "allgather")
    if name == "allreduce":
        return comm.Allreduce(
            axis_name=axis,
            vote_dtype=params.get("vote_dtype", "bfloat16"))
    if name == "allgather":
        return comm.Allgather(axis_name=axis)
    if name == "broadcast":
        return comm.Broadcast(axis_name=axis)
    if name in ("twoshot", "twoshot_allreduce"):
        return comm.TwoShotAllreduce(
            axis_name=axis,
            stage2_feedback=bool(params.get("stage2_feedback", False)))
    if name in ("ring", "ring_allreduce"):
        # pipeline: double-buffered wire schedule — P > 1 splits the flat
        # buffer into P segments whose ring schedules trace as independent
        # chains (flow pass 5's pipelined-ring referee), letting hop k of
        # segment p overlap hop k+1 of segment p-1 on real links.
        return comm.RingAllreduce(axis_name=axis,
                                  pipeline=int(params.get("pipeline", 1)))
    if name in ("rscatter", "reduce_scatter", "rscatter_allreduce"):
        # Compressed reduce-scatter + all-gather over the dp axis: the
        # sharded-model (FSDP) exchange — one all_to_all instead of the
        # ring's W−1 hops; payload-space sums for exact/homomorphic
        # codecs, exactly ONE requant boundary for the rest.
        return comm.ReduceScatterAllreduce(axis_name=axis)
    if name in ("hier", "hierarchical", "hier_allreduce"):
        # slice_size: ranks [k*S, (k+1)*S) form one ICI slice; the
        # two-level ICI×DCN schedule (intra-slice ring reduce-scatter,
        # cross-slice partial exchange, intra-slice all-gather). None
        # collapses to the flat ring (one slice). region_size adds the
        # third (WAN) level; wan_compressor is a nested params dict
        # naming the aggressive cross-region codec.
        wan_params = params.get("wan_compressor")
        wan = (_build_compressor(dict(wan_params), axis)
               if isinstance(wan_params, dict) else None)
        return comm.HierarchicalAllreduce(
            axis_name=axis, slice_size=params.get("slice_size"),
            region_size=params.get("region_size"),
            wan_compressor=wan,
            pipeline=int(params.get("pipeline", 1)))
    if name in ("sign_allreduce", "signallreduce"):
        return comm.SignAllreduce(
            axis_name=axis,
            vote_dtype=params.get("vote_dtype", "bfloat16"))
    if name in ("identity", "none"):
        return comm.Identity(axis_name=axis)
    raise ValueError(f"unknown communicator {name!r}")


def grace_from_params(params: Dict[str, Any]) -> Grace:
    """Configure the triad from the reference's params-dict schema.

    ``fusion`` (None | 'flat' | 'grouped' | int bytes) is a grace-tpu
    extension with no reference analog in the params dict — Horovod's fusion
    buffer was a buried env knob (HOROVOD_FUSION_THRESHOLD); here it is
    first-class.

    ``fsdp_axis`` (grace-tpu extension): name of the mesh axis params and
    optimizer state shard over — declares the 2-D dp×fsdp sharded-model
    layout (:class:`grace_tpu.transform.MeshSpec`); the communicator's
    exchange stays the per-shard reduce over ``axis_name``.

    ``adapt`` (grace-tpu extension): the graft-adapt in-graph adaptive
    compression controller (:mod:`grace_tpu.resilience.adapt`). ``True``
    / int ``window`` / dict of :class:`AdaptConfig` kwargs where
    ``ladder`` is a list of *override dicts* — each merged over this
    config's own params (minus adapt/route) and built into a rung codec,
    safest first; this config's own compressor is always the top
    (steady-state) rung and the dense escape is rung 0. Requires
    ``escape`` and ``telemetry``. Example — a homoqsgd bit-width ladder
    (dense → 8-bit → 4-bit)::

        {"compressor": "homoqsgd", "quantum_num": 7,
         "memory": "residual", "communicator": "ring", "fusion": "flat",
         "escape": "fp16", "telemetry": True,
         "adapt": {"window": 20, "ladder": [{"quantum_num": 127}]}}

    ``route`` (grace-tpu extension): ``[(pattern, overrides), ...]`` —
    first-class per-leaf codec routing. Each ``overrides`` dict is merged
    over this config's own params (minus the route itself) and built into
    a full sub-triad; ``pattern`` is an fnmatch glob matched against the
    gradient leaf's ``"/"``-joined tree path. First match wins; unmatched
    leaves ride the base triad. Example — transformer routing::

        {"compressor": "topk", "compress_ratio": 0.01,
         "topk_algorithm": "chunk", "memory": "residual",
         "communicator": "rscatter",
         "route": [("*ln*", {"compressor": "fp16",
                             "communicator": "allreduce",
                             "memory": "none"}),
                   ("*bias*", {"compressor": "fp16",
                               "communicator": "allreduce",
                               "memory": "none"})]}
    """
    axis = params.get("axis_name", DEFAULT_AXIS)
    fusion = params.get("fusion")
    if fusion in ("none", "None", ""):   # CLI-style spelling of "no fusion"
        fusion = None
    escape = params.get("escape")
    if isinstance(escape, str):
        if escape in ("none", "dense"):
            escape = C.NoneCompressor()
        elif escape in ("fp16", "bf16", "bfloat16"):
            escape = C.FP16Compressor(
                dtype="float16" if escape == "fp16" else "bfloat16")
        else:
            raise ValueError(f"unknown escape compressor {escape!r} — use "
                             "'none'/'dense', 'fp16', or 'bf16'")
    # slice_size/region_size also declare the mesh link layout: the
    # telemetry ring's per-link wire split (wire_bytes_ici/dcn/wan)
    # prices against the Topology they imply. Without them the layout is
    # auto-detected (Topology.detect) — single slice on CPU/simulated
    # meshes.
    slice_size = params.get("slice_size")
    region_size = params.get("region_size")
    fsdp_axis = params.get("fsdp_axis")
    mesh = (MeshSpec(dp_axis=axis, fsdp_axis=str(fsdp_axis))
            if fsdp_axis else None)
    routes: Tuple = ()
    if params.get("route"):
        sub_entries = []
        for entry in params["route"]:
            pattern, overrides = entry
            merged = {k: v for k, v in params.items() if k != "route"}
            # Route overrides REPLACE the base codec selection wholesale:
            # inheriting e.g. the base compress_ratio under an fp16
            # override is fine, but a leftover base "compressor" key must
            # not survive an override that names its own.
            merged.update(dict(overrides))
            sub_entries.append((str(pattern), grace_from_params(merged)))
        routes = normalize_routes(
            sub_entries, _build_communicator(params, axis))
    compressor = _build_compressor(params, axis)
    adapt_cfg = None
    if params.get("adapt"):
        from grace_tpu.resilience.adapt import AdaptConfig, normalize_adapt

        spec = params["adapt"]
        if isinstance(spec, AdaptConfig):
            compressor, ladder = _pad_powersgd_states(
                compressor, tuple(spec.ladder))
            if ladder != tuple(spec.ladder):
                spec = dataclasses.replace(spec, ladder=ladder)
            adapt_cfg = normalize_adapt(spec, compressor)
        else:
            if spec is True:
                kwargs: Dict[str, Any] = {}
            elif isinstance(spec, int):
                kwargs = {"window": spec}
            elif isinstance(spec, dict):
                kwargs = dict(spec)
            else:
                raise TypeError(
                    f"adapt must be True/int/dict/AdaptConfig; got "
                    f"{type(spec).__name__}")
            # Ladder entries are override dicts merged over this config's
            # own params (the route idiom): each builds one rung codec,
            # safest first; the base codec becomes the top rung.
            rungs = []
            for overrides in kwargs.pop("ladder", ()):
                merged = {k: v for k, v in params.items()
                          if k not in ("adapt", "route")}
                merged.update(dict(overrides))
                rungs.append(_build_compressor(merged, axis))
            # Rung-invariant PowerSGD layout: every PowerSGD codec in
            # this ladder (base included — it IS the top rung, and the
            # transform's comp state is allocated from the Grace
            # compressor) stores Q at the ladder's max rank so the adapt
            # lax.switch threads ONE state structure across rungs.
            compressor, rungs = _pad_powersgd_states(
                compressor, tuple(rungs))
            adapt_cfg = normalize_adapt(
                AdaptConfig(ladder=rungs, **kwargs), compressor)
    return Grace(compressor=compressor,
                 memory=_build_memory(params, axis),
                 communicator=_build_communicator(params, axis),
                 fusion=fusion,
                 escape=escape,
                 mesh=mesh,
                 routes=routes,
                 topology=(Topology(
                     slice_size=int(slice_size) if slice_size else None,
                     region_size=int(region_size) if region_size else None)
                           if (slice_size or region_size) else None),
                 # True | ring capacity | {"capacity": ..,
                 # "compression_error": ..} — see grace_transform(telemetry=)
                 telemetry=params.get("telemetry"),
                 # True | audit_every | {"audit_every": .., "escalate_*": ..}
                 # — see grace_transform(consensus=) / resilience.consensus
                 consensus=params.get("consensus"),
                 # True | window | {"window": .., "capacity": ..} — see
                 # grace_transform(watch=) / telemetry.aggregate
                 watch=params.get("watch"),
                 adapt=adapt_cfg)


def route_leaves(grace: Grace, tree):
    """Per-leaf route resolution for a gradient/param pytree:
    ``[(path, struct, compressor, memory, communicator), ...]`` in
    flatten order — the one enumeration the routed wire models (telemetry,
    bench projections, the static auditor's reconciliation) share."""
    import jax
    import jax.numpy as jnp

    base = (grace.compressor, grace.memory, grace.communicator)
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        p = leaf_path_str(path)
        comp, mem, cm = route_for(grace.routes or (), p, base)
        out.append((p, jax.ShapeDtypeStruct(tuple(jnp.shape(leaf)),
                                            jnp.result_type(leaf)),
                    comp, mem, cm))
    return out


def routed_recv_link_bytes(grace: Grace, tree, world: int,
                           topology=None) -> LinkBytes:
    """Per-rank received bytes of one routed step, split by link class:
    the SUM of per-leaf prices through each leaf's own codec and
    communicator (negotiation collectives included) — the routed spelling
    of ``Communicator.recv_link_bytes`` that bench projections and the
    auditor's wire pass reconcile against. Works for unrouted bundles too
    (every leaf resolves to the base triad), so callers need no special
    case."""
    from grace_tpu.utils.metrics import payload_nbytes
    import numpy as np

    ici = dcn = wan = 0
    for _p, s, comp, _mem, cm in route_leaves(grace, tree):
        ne = int(np.prod(s.shape, dtype=np.int64))
        vote = bool(getattr(comp, "vote_aggregate", False))
        lb = cm.recv_link_bytes(payload_nbytes(comp, s), ne, world,
                                topology=topology, vote=vote)
        neg = negotiation_bytes_for(comp, ne, world)
        topo = topology if topology is not None else Topology()
        if neg:
            # The negotiation pmax is a flat full-axis collective: its
            # bytes land on the worst tier the axis spans (flat_tier).
            tier = topo.flat_tier(world)
            lb = lb._replace(**{tier: getattr(lb, tier) + neg})
        ici += lb.ici
        dcn += lb.dcn
        wan += lb.wan
    return LinkBytes(ici=ici, dcn=dcn, wan=wan)
