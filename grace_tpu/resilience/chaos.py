"""Chaos harness: deterministic, seedable fault injectors.

Faults are expressed as :class:`~grace_tpu.core.Compressor` /
:class:`~grace_tpu.core.Communicator` wrappers, so they slot into any
existing pipeline (``grace_from_params`` triads, ``guard_transform`` chains,
bare ``Communicator.step`` calls) without touching the code under test.
Everything is a pure function of the rng key the transform already threads
through the pipeline: the same run with the same seeds produces the same
faults, bit-for-bit, which is what makes guard regressions reproducible.

Fault classes (ScaleCom-style stability probes, PAPERS.md):

* **NaN/Inf implants** — overwrite one random element of the gradient with
  NaN/Inf at a per-(step, leaf) probability, optionally on exactly one mesh
  rank (``rank=``, gated in-graph via ``lax.axis_index`` so it is legal
  inside ``shard_map``).
* **Payload bit-flips** — flip one random bit of one random element of each
  wire payload tensor (bitcast → xor → bitcast), modelling interconnect /
  DMA corruption that checksums missed.
* **Stale residuals** — suppress this step's error-feedback state update so
  the memory replays last step's residual, modelling a lost/duplicated
  update in a sharded state store.
* **Single-rank SDC in params/opt-state** (:class:`ChaosParams`) — a
  host-side wrapper that, *between* steps, flips one bit of one element of
  a replicated state leaf in exactly ONE device's buffer. This models
  silent data corruption (bad HBM, a cosmic-ray bitflip) landing in state
  that every rank assumes is shared: the corruption is perfectly finite,
  the exchanged updates stay rank-identical, so the PR-1 guard never trips
  — the fault class the consensus auditor
  (:mod:`grace_tpu.resilience.consensus`) exists to catch.

The wrappers deliberately do NOT forward the fused-kernel hooks
(``fused_feedback_compress`` / ``fused_aggregate_decompress``): the fused
paths would bypass the injection points, silently turning the chaos run
into a clean one.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from grace_tpu.core import (Communicator, Compressor, Ctx, Memory, Payload,
                            State)

__all__ = ["ChaosCompressor", "ChaosCommunicator", "ChaosParams"]

_UINT = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


def _gate(rank: Optional[int], axis_name: str) -> jax.Array:
    """True on the faulted rank (all ranks when ``rank`` is None)."""
    if rank is None:
        return jnp.ones((), jnp.bool_)
    return lax.axis_index(axis_name) == rank


def _implant(x: jax.Array, key: jax.Array, value) -> jax.Array:
    """``x`` with one random element overwritten by ``value``."""
    if x.size == 0:
        return x
    pos = jax.random.randint(key, (), 0, x.size)
    flat = x.reshape(-1)
    return flat.at[pos].set(jnp.asarray(value, x.dtype)).reshape(x.shape)


def _flip_one_bit(t: jax.Array, key: jax.Array) -> jax.Array:
    """``t`` with one random bit of one random element flipped."""
    if t.size == 0 or t.dtype == jnp.bool_:
        return t
    uint = _UINT[t.dtype.itemsize]
    kpos, kbit = jax.random.split(key)
    pos = jax.random.randint(kpos, (), 0, t.size)
    bit = jax.random.randint(kbit, (), 0, t.dtype.itemsize * 8)
    flat = lax.bitcast_convert_type(t, uint).reshape(-1)
    flipped = flat.at[pos].set(
        flat[pos] ^ (jnp.asarray(1, uint) << bit.astype(uint)))
    return lax.bitcast_convert_type(flipped.reshape(t.shape), t.dtype)


@dataclasses.dataclass(frozen=True)
class ChaosCompressor(Compressor):
    """Fault-injecting wrapper around any compressor.

    ``nan_prob``/``inf_prob`` implant into the *input* tensor before the
    inner codec sees it (a poisoned gradient); ``bitflip_prob`` corrupts
    each *payload* tensor after encoding (wire corruption). Probabilities
    are per (step, leaf) — the rng handed to ``compress`` is already folded
    per step and leaf by ``grace_transform``, and ``seed`` decorrelates the
    fault stream from the codec's own randomness.

    ``drift_scale`` models a *degrading encoder* instead of a corrupting
    one: on the gated rank, every inexact payload lane is attenuated by
    ``(1 - drift_scale)`` on every step — values stay perfectly finite
    (the PR-1 guard is structurally blind) and the damage lands in
    per-rank state (residuals/compression error are legitimately
    per-rank, so the PR-3 consensus audit is blind by design). What it
    *does* move is that rank's compression error and error-feedback
    residual norm away from the fleet — exactly the single-rank skew
    signal graft-watch (:mod:`grace_tpu.telemetry.aggregate`) exists to
    flag first. Only meaningful for codecs whose payload carries value
    lanes — float lanes (topk/threshold/qsgd-style) are attenuated
    directly, and a shared-scale codec's integer level lanes are
    attenuated on their quantization lattice (an integer lane is only a
    value lane when the algebra says so; anywhere else integers are
    indices and pass through untouched). Sign-only payloads pass through
    scaling unchanged in effect.
    """

    inner: Compressor
    nan_prob: float = 0.0
    inf_prob: float = 0.0
    bitflip_prob: float = 0.0
    drift_scale: float = 0.0
    rank: Optional[int] = None
    axis_name: str = "data"
    seed: int = 0

    # -- delegated compressor contract --------------------------------------
    @property
    def average(self):  # type: ignore[override]
        return self.inner.average

    @property
    def tensors_size_are_same(self):  # type: ignore[override]
        return self.inner.tensors_size_are_same

    @property
    def vote_aggregate(self):  # type: ignore[override]
        return self.inner.vote_aggregate

    @property
    def payload_algebra(self):  # type: ignore[override]
        # Delegated like supports_hop_requant (summable_payload then derives
        # from it via the base property): the injector must ride whatever
        # accumulation path the inner codec qualifies for — including the
        # payload-space homomorphic summation of shared-scale/sketch codecs
        # — or the chaos matrix could never cover the zero-requant
        # schedules. Bitflip/drift faults then land in the SUMMED payload
        # exactly as a corrupting wire or degrading encoder would.
        return self.inner.payload_algebra

    @property
    def supports_hop_requant(self):  # type: ignore[override]
        # Delegated like summable_payload: the injector must be able to
        # ride whatever schedule the inner codec qualifies for (the
        # ring/hier capability gates read this) — a wrapper that silently
        # un-qualified topk from the hop-pipelined paths would make the
        # chaos matrix untestable over exactly the communicators that
        # matter. Hop re-encodes call this wrapper's compress too, so the
        # gated rank's faults apply at every requant point — which is what
        # a degrading encoder on that rank would really do.
        return self.inner.supports_hop_requant

    def init_state(self, x: jax.Array) -> State:
        return self.inner.init_state(x)

    def wire_nbytes(self, shape, dtype):
        return self.inner.wire_nbytes(shape, dtype)

    @property
    def negotiates(self):  # type: ignore[override]
        # Delegated like payload_algebra: a routed/negotiating codec under
        # chaos must still get its pre-encode collective hoisted.
        return getattr(self.inner, "negotiates", False)

    # Shared-scale protocol, delegated whole: the negotiation collective,
    # its wire price, and the overflow bound are the inner codec's — chaos
    # only perturbs values, never the algebra's bookkeeping.
    def negotiate(self, x: jax.Array, axis_name: str, rng=None):
        return self.inner.negotiate(x, axis_name, rng=rng)

    def negotiation_nbytes(self, world: int) -> int:
        return self.inner.negotiation_nbytes(world)

    def payload_sum_max_world(self):
        return self.inner.payload_sum_max_world()

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        return self.inner.decompress(payload, ctx)

    def aggregate(self, stacked: jax.Array) -> jax.Array:
        return self.inner.aggregate(stacked)

    # Wire-path hooks, delegated whole (ISSUE 19): these run on RECEIVED
    # payloads, downstream of every injection point (faults land in
    # compress — poisoned input, bitflipped/drifted payloads — and hop
    # re-encodes go through this wrapper's compress too), so forwarding
    # them cannot bypass a fault the way forwarding the fused
    # feedback/aggregate hooks would. Not forwarding them WOULD corrupt
    # the run for real: the base payload_add/payload_sum tuple-add is
    # garbage on a packed sub-byte payload (the packed homoqsgd
    # accumulate must unpack→add→repack), so a chaos-wrapped packed codec
    # must ride the inner codec's own accumulate spelling.
    def payload_add(self, a: Payload, b: Payload) -> Payload:
        return self.inner.payload_add(a, b)

    def payload_sum(self, stacked: Payload) -> Payload:
        return self.inner.payload_sum(stacked)

    def decode_accumulate(self, payloads, ctxs):
        return self.inner.decode_accumulate(payloads, ctxs)

    def wire_fused(self) -> bool:
        return self.inner.wire_fused()

    @property
    def packed_wire(self):
        # Wire-format facts, delegated like payload_algebra: the tuner's
        # variant generator and flow pass 6's sub-byte audit read these
        # off whatever compressor the config carries.
        return getattr(self.inner, "packed_wire", False)

    @property
    def pack_width(self):
        return getattr(self.inner, "pack_width", None)

    @property
    def accum_bits(self):
        return getattr(self.inner, "accum_bits", None)

    # -- faulted encode ------------------------------------------------------
    def compress(self, x: jax.Array, state: State, rng: jax.Array,
                 shared=None) -> tuple[Payload, Ctx, State]:
        ckey = jax.random.fold_in(rng, self.seed)
        gate = _gate(self.rank, self.axis_name)
        if self.nan_prob:
            khit, kpos, ckey = jax.random.split(ckey, 3)
            hit = jax.random.bernoulli(khit, self.nan_prob) & gate
            x = jnp.where(hit, _implant(x, kpos, jnp.nan), x)
        if self.inf_prob:
            khit, kpos, ckey = jax.random.split(ckey, 3)
            hit = jax.random.bernoulli(khit, self.inf_prob) & gate
            x = jnp.where(hit, _implant(x, kpos, jnp.inf), x)
        payload, ctx, new_state = (
            self.inner.compress(x, state, rng) if shared is None
            else self.inner.compress(x, state, rng, shared=shared))
        if self.bitflip_prob:
            corrupted = []
            for t in payload:
                khit, kflip, ckey = jax.random.split(ckey, 3)
                hit = jax.random.bernoulli(khit, self.bitflip_prob) & gate
                corrupted.append(jnp.where(hit, _flip_one_bit(t, kflip), t))
            payload = tuple(corrupted)
        if self.drift_scale:
            scale = jnp.where(gate, 1.0 - self.drift_scale, 1.0)
            shared_scale = (getattr(self.inner, "payload_algebra", None)
                            == "shared_scale")

            def _attenuate(t):
                if jnp.issubdtype(t.dtype, jnp.inexact):
                    return t * jnp.asarray(scale, t.dtype)
                if shared_scale and jnp.issubdtype(t.dtype, jnp.integer):
                    # A shared-scale codec's integer lanes ARE its value
                    # lanes (levels against the negotiated scale — never
                    # indices), so the degrading encoder attenuates them
                    # too: scaled on the quantization lattice, which
                    # stays finite, sums homomorphically, and moves the
                    # gated rank's compression error exactly like the
                    # float-lane attenuation does for topk/qsgd.
                    return jnp.round(
                        t.astype(jnp.float32) * scale).astype(t.dtype)
                return t

            payload = tuple(_attenuate(t) for t in payload)
        return payload, ctx, new_state


@dataclasses.dataclass(frozen=True)
class ChaosCommunicator(Communicator):
    """Fault-injecting wrapper around any communicator.

    Injects at the pipeline level, where the full 6-stage step is visible:
    ``nan_prob``/``inf_prob`` poison the incoming per-rank gradient before
    compensate/compress (the classic bad-batch fault), ``stale_prob`` drops
    this step's memory-state update so the residual goes stale. The wrapped
    communicator performs the actual exchange unchanged.
    """

    inner: Optional[Communicator] = None
    nan_prob: float = 0.0
    inf_prob: float = 0.0
    stale_prob: float = 0.0
    rank: Optional[int] = None
    seed: int = 0

    def __post_init__(self):
        if self.inner is None:
            raise TypeError("ChaosCommunicator requires inner=Communicator")
        # Mirror the wrapped communicator's mesh axis so world_size() and
        # rank gating agree with the collectives the inner one issues.
        object.__setattr__(self, "axis_name", self.inner.axis_name)

    @property
    def shard_parallel(self):  # type: ignore[override]
        # A chaos-wrapped ring/two-shot/hier step is still shard-parallel:
        # the build-time fusion gate must see the inner schedule.
        return getattr(self.inner, "shard_parallel", False)

    def _recv_total_bytes(self, payload_nbytes: int, n_elems: int,
                          world: int, vote: bool = False) -> int:
        # Fault injection moves no extra wire bytes — telemetry under chaos
        # must price the INNER schedule (the base-class gather formula
        # happened to match the allgather smoke config; a wrapped
        # ring/hier would silently report gather-cost bytes).
        return self.inner._recv_total_bytes(payload_nbytes, n_elems, world,
                                            vote=vote)

    def recv_link_bytes(self, payload_nbytes: int, n_elems: int, world: int,
                        topology=None, vote: bool = False):
        return self.inner.recv_link_bytes(payload_nbytes, n_elems, world,
                                          topology=topology, vote=vote)

    def step(self, x: jax.Array, mem_state: State, comp_state: State,
             memory: Memory, compressor: Compressor, rng: jax.Array
             ) -> tuple[jax.Array, State, State]:
        ckey = jax.random.fold_in(rng, self.seed)
        gate = _gate(self.rank, self.axis_name)
        if self.nan_prob:
            khit, kpos, ckey = jax.random.split(ckey, 3)
            hit = jax.random.bernoulli(khit, self.nan_prob) & gate
            x = jnp.where(hit, _implant(x, kpos, jnp.nan), x)
        if self.inf_prob:
            khit, kpos, ckey = jax.random.split(ckey, 3)
            hit = jax.random.bernoulli(khit, self.inf_prob) & gate
            x = jnp.where(hit, _implant(x, kpos, jnp.inf), x)
        out, new_mem, new_comp = self.inner.step(
            x, mem_state, comp_state, memory, compressor, rng)
        if self.stale_prob:
            khit, ckey = jax.random.split(ckey)
            stale = jax.random.bernoulli(khit, self.stale_prob) & gate
            new_mem = jax.tree_util.tree_map(
                lambda old, new: jnp.where(stale, old, new),
                mem_state, new_mem)
        return out, new_mem, new_comp

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        return self.inner.exchange(payload, ctx, compressor)


@dataclasses.dataclass
class ChaosParams:
    """Host-side single-rank SDC injector for params / optimizer state.

    The Compressor/Communicator wrappers above corrupt *in-flight* values;
    this one corrupts *state at rest*, between steps, on exactly one
    device's copy of a replicated leaf — the silent-corruption fault the
    in-graph guard is structurally blind to (finite values, rank-identical
    updates). Usage::

        chaos = ChaosParams(rank=3, at_steps=(10,), seed=7)
        for i, batch in enumerate(batches):
            state = chaos(state, i)        # maybe-corrupt BEFORE the step
            state, loss = step(state, batch)

    Mechanics: on a hit step, pick one floating leaf of ``target`` (an
    attribute name on the state NamedTuple, e.g. ``"params"`` /
    ``"opt_state"``; ``None`` corrupts anywhere in the whole state), one
    element, one bit — all from ``numpy.random.default_rng(seed ^ step)``
    so runs are reproducible — and flip that bit in device ``rank``'s
    buffer only, reassembling the array with
    ``jax.make_array_from_single_device_arrays`` under its original
    (replicated) sharding. The other replicas keep their bytes, so the
    array *claims* replication while its buffers disagree: exactly what
    SDC looks like to SPMD code. Every injection is appended to
    :attr:`injections` as ``(step, leaf_index, element, bit)``.
    """

    rank: int = 0
    at_steps: tuple = ()
    prob: float = 0.0
    seed: int = 0
    target: Optional[str] = "params"

    def __post_init__(self):
        self.injections: list = []

    def _hit(self, step: int, rng) -> bool:
        if step in tuple(self.at_steps):
            return True
        return bool(self.prob) and rng.random() < self.prob

    def __call__(self, state, step: int):
        rng = np.random.default_rng((self.seed << 20) ^ step)
        if not self._hit(step, rng):
            return state
        sub = state if self.target is None else getattr(state, self.target)
        leaves, treedef = jax.tree_util.tree_flatten(sub)
        float_idx = [i for i, l in enumerate(leaves)
                     if hasattr(l, "dtype")
                     and jnp.issubdtype(l.dtype, jnp.floating)
                     and l.size > 0]
        if not float_idx:
            return state
        li = int(rng.choice(float_idx))
        arr = leaves[li]
        shards = list(arr.addressable_shards)
        if self.rank >= len(shards):
            raise ValueError(
                f"ChaosParams(rank={self.rank}) but the target leaf has "
                f"only {len(shards)} addressable shards — SDC injection "
                "needs a replicated leaf with one shard per device.")
        # Position drawn within the target device's OWN buffer: for a
        # replicated leaf that is the whole array (the historical
        # behavior, byte-identical — same bound, same rng stream); for an
        # fsdp-SHARDED leaf (2-D mesh) the buffer is that device's shard,
        # so the flip corrupts one rank's copy of the shard its dp peers
        # also hold — exactly the divergence the per-fsdp-shard consensus
        # audit must catch.
        pos = int(rng.integers(int(np.prod(shards[self.rank].data.shape,
                                           dtype=np.int64))))
        bit = int(rng.integers(np.dtype(arr.dtype).itemsize * 8))
        uint = np.dtype(f"uint{np.dtype(arr.dtype).itemsize * 8}")
        bufs = []
        for si, s in enumerate(shards):
            data = np.array(s.data)           # per-device copy
            if si == self.rank:
                flat = data.reshape(-1).view(uint)
                flat[pos] ^= uint.type(1) << uint.type(bit)
            bufs.append(jax.device_put(data, s.device))
        leaves[li] = jax.make_array_from_single_device_arrays(
            arr.shape, arr.sharding, bufs)
        self.injections.append((step, li, pos, bit))
        sub = jax.tree_util.tree_unflatten(treedef, leaves)
        if self.target is None:
            return sub
        return state._replace(**{self.target: sub})
