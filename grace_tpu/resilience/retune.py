"""graft-retune: fault-tolerant online re-tuning — config promotion as a
two-phase transaction with automatic rollback.

The adaptive controller (graft-adapt, PR 15) moves along a FIXED ladder
in-graph; the tuner (graft-tune, PR 14) picks a config offline. Neither
answers the production question this module exists for: the workload
drifted — gradients stopped looking like what the stamped config was
tuned on — and the fleet should move to a *different* config without a
restart and without betting the run on an unproven winner. Restarts are
exactly what the resilience stack spent five PRs avoiding; an unproven
winner is exactly what the tuner's funnel exists to prevent. So the
promotion is a **transaction**, built from pieces the stack already
proved, with the elastic drain watchdog's bounded-timeout discipline on
every leg:

* **Drift watch** (:meth:`RetuneController.observe`): windowed
  compression-error means against a baseline learned from the run's own
  healthy windows. Only SUSTAINED drift (``drift_windows`` consecutive
  hot windows) arms a re-tune — one bad window is noise the error
  feedback already absorbs.

* **Decide** (:meth:`RetuneController.propose`): the tuner's static
  funnel + bounded measured shortlist re-run against the live mesh
  (:func:`grace_tpu.tuning.online.online_funnel`); a hung candidate
  measurement lands in the funnel as ``verdict='measure_timeout'``
  instead of stalling the controller.

* **PREPARE** (:meth:`RetuneController.prepare`) — everything that can
  reject the candidate happens BEFORE any live state changes:

  1. lint-audit the candidate config ad-hoc
     (:func:`grace_tpu.analysis.configs.audit_config`) — a config the
     static auditor rejects is never staged;
  2. build the new transform and a fresh state under it, then migrate
     the live :class:`~grace_tpu.transform.GraceState` across configs
     (:func:`~grace_tpu.transform.migrate_grace_state`): replicated
     fields carry bit-exactly, residuals carry where gradient-shaped,
     PowerSGD factors warm-start by column overlap (the rung-invariant
     padded layout makes same-family moves a pure carry), everything
     else takes the PR-3 fresh init;
  3. validate the migrated state against flow pass 7's static footprint
     model at the live world
     (:func:`~grace_tpu.resilience.elastic.validate_resharded`);
  4. checkpoint the last-known-good incumbent state while the fleet is
     whole (``good=True`` — the demotion target), under the bounded
     watchdog.

* **COMMIT** (:meth:`RetuneController.commit`): consensus-gated cutover
  at a drain boundary — one forced fingerprint audit over the migrated
  state (:func:`~grace_tpu.resilience.elastic.rejoin_barrier`) so every
  rank enters the new config bit-identical, priced and recorded like a
  rejoin. The OLD config is retained as the demotion target; the new one
  enters **probation**.

* **Probation** (:meth:`RetuneController.watch` /
  :meth:`RetuneController.demote`): for ``probation_steps`` after the
  cutover, any guard trip or consensus escalation demotes automatically
  — restore the last-known-good checkpoint under the OLD config,
  bit-exact (the PREPARE-time digest is re-checked on restore). A quiet
  probation clears the transaction and the new config becomes the
  incumbent.

Every leg — measure, checkpoint, commit, restore — runs under
:meth:`RetuneController._watchdog`: bounded timeout, retries with
DOUBLED timeout (backoff), a ``retune_timeout`` record per stall, and a
proceed-with-last-known-good exit (abort the promotion / keep the
incumbent / fall back to a fresh old-config init) instead of a hang.
This is PR 16's drain watchdog generalized from one leg to the whole
transaction: the controller can be slow, wrong, or unlucky — it cannot
wedge the run.

Event vocabulary (timeline kind ``retune``): ``retune_drift``,
``retune_measure``, ``retune_prepare``, ``retune_abort``,
``retune_promote``, ``retune_probation_clear``, ``retune_demote``,
``retune_timeout``. ``retune_promote`` / ``retune_demote`` are incident
triggers (:mod:`grace_tpu.evidence.incident`).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from grace_tpu.core import DEFAULT_AXIS
from grace_tpu.resilience.consensus import normalize_consensus

__all__ = ["StagedPromotion", "RetuneController"]


def state_digest(state) -> str:
    """Order-stable byte digest of every leaf in ``state`` — the
    bit-exactness witness for transactional rollback: recorded at
    PREPARE over the incumbent state, re-computed over the restored
    state at demotion, equal iff the rollback lost nothing."""
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        arr = np.asarray(jax.device_get(leaf))
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


@dataclasses.dataclass
class StagedPromotion:
    """Everything PREPARE staged, nothing of which is live yet. COMMIT
    consumes it; an abort just drops it (the incumbent state was never
    touched — migration built a NEW tree)."""

    step: int
    old_params: Dict[str, Any]
    new_params: Dict[str, Any]
    grace: Any
    tx: Any
    state: Any                       # migrated TrainState, not yet live
    migration: Dict[str, Any]
    footprint_matches: Optional[bool]
    lint_errors: int
    checkpointed: bool
    lkg_digest: Optional[str]


class RetuneController:
    """Host-side orchestrator of the drift → decide → PREPARE → COMMIT →
    probation → (clear | demote) transaction.

    ``build`` is the run's own chain factory,
    ``build(grace_params) -> (grace, tx)`` — the controller rebuilds
    BOTH sides of every cutover through it, so old and new optimizer
    chains share one pytree structure (the migration map's contract) and
    the guard/consensus wrapping the run trains with is exactly what a
    promoted config trains with. ``params`` is the incumbent's
    grace-params dict (the first demotion target).

    ``consensus`` arms the COMMIT barrier (required for a consensus-
    gated cutover; ``None`` degrades to an unaudited swap for
    single-host tests). ``checkpointer`` is a
    :class:`~grace_tpu.checkpoint.Checkpointer`; without one PREPARE
    cannot record a demotion target and demotion falls back to a fresh
    old-config init (degraded, recorded as ``restored=False``).

    ``leg_timeout_s``/``leg_retries`` bound every transition leg;
    ``None`` runs legs inline (tests that want determinism without
    threads).
    """

    def __init__(self, *, build: Callable[[Dict[str, Any]], Tuple[Any, Any]],
                 params: Dict[str, Any],
                 consensus=None, checkpointer=None, sink=None,
                 window: int = 8, drift_factor: float = 2.0,
                 drift_error: Optional[float] = None,
                 drift_windows: int = 2,
                 probation_steps: int = 24,
                 demote_on: Tuple[str, ...] = ("guard_skip",
                                               "guard_fallback_engaged",
                                               "consensus_escalation"),
                 leg_timeout_s: Optional[float] = None,
                 leg_retries: int = 1,
                 audit_world: int = 8,
                 axis_name: str = DEFAULT_AXIS):
        self.build = build
        self.params = dict(params)
        self.consensus = (normalize_consensus(consensus)
                          if consensus not in (None, False) else None)
        self.checkpointer = checkpointer
        self.sink = sink
        if int(window) < 1:
            raise ValueError(f"window must be >= 1; got {window}")
        self.window = int(window)
        if float(drift_factor) <= 1.0:
            raise ValueError(f"drift_factor must be > 1 (a factor <= 1 "
                             f"re-tunes on healthy noise); got {drift_factor}")
        self.drift_factor = float(drift_factor)
        self.drift_error = (float(drift_error)
                            if drift_error is not None else None)
        self.drift_windows = max(1, int(drift_windows))
        self.probation_steps = int(probation_steps)
        self.demote_on = tuple(demote_on)
        if leg_timeout_s is not None and float(leg_timeout_s) <= 0:
            raise ValueError(f"leg_timeout_s must be positive; "
                             f"got {leg_timeout_s}")
        self.leg_timeout_s = (float(leg_timeout_s)
                              if leg_timeout_s is not None else None)
        if int(leg_retries) < 0:
            raise ValueError(f"leg_retries must be >= 0; got {leg_retries}")
        self.leg_retries = int(leg_retries)
        self.audit_world = int(audit_world)
        self.axis_name = axis_name

        self.phase = "idle"          # idle | prepared | probation
        self.events: List[dict] = []
        self._staged: Optional[StagedPromotion] = None
        self._probation_until: Optional[int] = None
        self._demotion_params: Optional[Dict[str, Any]] = None
        self._lkg_digest: Optional[str] = None
        self._win: List[float] = []
        self._baseline: Optional[float] = None
        self._hot = 0

    # -- plumbing -----------------------------------------------------------
    def _emit(self, event: str, step: int, **payload) -> dict:
        rec = {"event": event, "step": int(step), **payload}
        self.events.append(rec)
        if self.sink is not None:
            self.sink.write(rec)
        return rec

    def _watchdog(self, leg: str, step: int, fn):
        """Run one transition leg bounded: ``(ok, result, timeouts)``.

        The elastic drain watchdog's exact discipline
        (:meth:`~grace_tpu.resilience.elastic.ElasticController._drain_checkpoint`)
        applied to an arbitrary leg: daemon worker, ``done.wait``,
        doubled timeout per retry, one ``retune_timeout`` record per
        stall, and the hung thread abandoned — callers translate
        ``ok=False`` into their leg's proceed-with-last-known-good exit.
        Exceptions from ``fn`` propagate unchanged and are never retried.
        """
        if self.leg_timeout_s is None:
            return True, fn(), 0
        import threading

        timeout = self.leg_timeout_s
        timeouts = 0
        for trial in range(self.leg_retries + 1):
            done = threading.Event()
            out: List[Any] = []
            errs: List[BaseException] = []

            def run():
                try:
                    out.append(fn())
                except BaseException as e:   # noqa: BLE001 — re-raised below
                    errs.append(e)
                finally:
                    done.set()

            threading.Thread(target=run, daemon=True,
                             name=f"grace-retune-{leg}-{trial}").start()
            if done.wait(timeout):
                if errs:
                    raise errs[0]
                return True, out[0], timeouts
            timeouts += 1
            self._emit("retune_timeout", step, leg=leg, attempt=trial + 1,
                       timeout_s=float(timeout),
                       retries_left=self.leg_retries - trial)
            timeout *= 2.0
        return False, None, timeouts

    def _reset_drift(self) -> None:
        self._win.clear()
        self._baseline = None
        self._hot = 0

    # -- drift watch --------------------------------------------------------
    def observe(self, step: int,
                compression_error: Optional[float]) -> bool:
        """Feed one step's compression error (host float from the
        telemetry reader); returns True the first time drift is
        SUSTAINED — ``drift_windows`` consecutive window means above
        ``drift_factor``× the learned baseline (or above the absolute
        ``drift_error`` override). The first full window IS the
        baseline: the controller calibrates on the run's own healthy
        traffic, not on a magic constant."""
        if self.phase != "idle" or compression_error is None:
            return False
        self._win.append(float(compression_error))
        if len(self._win) < self.window:
            return False
        mean = sum(self._win) / len(self._win)
        self._win.clear()
        if self._baseline is None:
            self._baseline = mean
            return False
        drifting = mean > self._baseline * self.drift_factor
        if self.drift_error is not None:
            drifting = drifting or mean > self.drift_error
        if not drifting:
            self._hot = 0
            return False
        self._hot += 1
        if self._hot < self.drift_windows:
            return False
        self._hot = 0
        self._emit("retune_drift", step, window_mean=mean,
                   baseline=self._baseline,
                   drift_factor=self.drift_factor,
                   drift_windows=self.drift_windows)
        return True

    # -- decide -------------------------------------------------------------
    def propose(self, step: int, mesh, topology, **funnel_kwargs
                ) -> Optional[Dict[str, Any]]:
        """Re-run the tuner's funnel against the live mesh (bounded) and
        return the :func:`~grace_tpu.tuning.online.online_funnel` doc,
        or None when the whole decision leg timed out / produced no
        winner — both mean "stay on the incumbent"."""
        from grace_tpu.tuning.online import online_funnel

        ok, doc, timeouts = self._watchdog(
            "measure", step,
            lambda: online_funnel(topology, mesh, **funnel_kwargs))
        if not ok:
            self._emit("retune_abort", step, leg="measure",
                       reason="measure leg exceeded its bounded wait — "
                              "keeping the incumbent config",
                       timeouts=timeouts)
            return None
        measured = doc["measured"]
        self._emit("retune_measure", step, winner=doc["winner"],
                   measured=len(measured["rows"]),
                   skipped=len(measured["skipped"]),
                   measure_timeouts=sum(
                       1 for s in measured["skipped"]
                       if s.get("verdict") == "measure_timeout"),
                   timeouts=timeouts)
        if doc["winner"] is None:
            return None
        return doc

    # -- PREPARE ------------------------------------------------------------
    def prepare(self, step: int, state, mesh,
                candidate_params: Dict[str, Any]
                ) -> Optional[StagedPromotion]:
        """Stage a promotion without touching live state; returns the
        staged transaction, or None when any PREPARE gate rejected the
        candidate (the run continues on the incumbent untouched)."""
        if self.phase == "probation":
            raise RuntimeError("prepare() during probation — clear or "
                               "demote the in-flight promotion first.")
        from grace_tpu.analysis.configs import audit_config
        from grace_tpu.train import init_train_state
        from grace_tpu.transform import migrate_grace_state

        candidate_params = dict(candidate_params)
        world = len(mesh.devices.flatten())

        # Gate 1: the static auditor. A config the seven lint passes
        # reject offline is never staged online. Escape/adapt-carrying
        # candidates skip wire_reconciliation exactly like their registry
        # entries do: a dense fallback or a ladder makes "the" wire cost
        # multi-modal by design (telemetry prices the flip per rung).
        from grace_tpu.analysis.passes import PASS_NAMES
        passes = tuple(PASS_NAMES)
        if candidate_params.get("escape") or candidate_params.get("adapt"):
            passes = tuple(p for p in PASS_NAMES
                           if p != "wire_reconciliation")
        findings = audit_config({"name": "retune-candidate",
                                 "params": dict(candidate_params),
                                 "passes": passes},
                                world=self.audit_world)
        errors = [f for f in findings if f.severity == "error"]
        if errors:
            self._emit("retune_abort", step, leg="lint",
                       reason=errors[0].message[:200],
                       lint_errors=len(errors))
            return None

        # Gate 2: build + migrate. The fresh init is a NEW tree — the
        # incumbent state is read, never written, so an abort below
        # costs nothing.
        grace, tx = self.build(candidate_params)
        fresh = init_train_state(state.params, tx, mesh, self.axis_name)
        try:
            migrated_opt, mig = migrate_grace_state(state.opt_state,
                                                    fresh.opt_state)
        except ValueError as e:
            self._emit("retune_abort", step, leg="migrate",
                       reason=str(e)[:200])
            return None
        staged_state = state._replace(opt_state=migrated_opt)

        # Gate 3: the migrated state must match the static footprint
        # model at the live world under the NEW config — the elastic
        # re-shard's validation, reused across configs.
        from grace_tpu.resilience.elastic import validate_resharded
        try:
            footprint = validate_resharded(staged_state, grace,
                                           state.params, world)["matches"]
        except ValueError as e:
            self._emit("retune_abort", step, leg="footprint",
                       reason=str(e)[:200])
            return None

        # Leg 4 (bounded): checkpoint the incumbent while the fleet is
        # whole — the demotion target. A stalled backend does not block
        # the promotion (an older good checkpoint may exist on disk),
        # it only degrades the rollback guarantee, and the event says so.
        checkpointed, ck_timeouts = False, 0
        lkg_digest = None
        if self.checkpointer is not None:
            lkg_digest = state_digest(state)

            def save():
                self.checkpointer.save(step, state, force=True, good=True)
                self.checkpointer.wait()

            checkpointed, _, ck_timeouts = self._watchdog(
                "prepare_checkpoint", step, save)

        staged = StagedPromotion(
            step=step, old_params=dict(self.params),
            new_params=candidate_params, grace=grace, tx=tx,
            state=staged_state, migration=mig,
            footprint_matches=footprint, lint_errors=0,
            checkpointed=checkpointed, lkg_digest=lkg_digest)
        self._staged = staged
        self.phase = "prepared"
        self._emit("retune_prepare", step,
                   candidate=candidate_params.get("compressor"),
                   lint_errors=0, footprint_matches=footprint,
                   checkpointed=checkpointed,
                   checkpoint_timeouts=ck_timeouts,
                   mem_carried=mig["mem"]["carried"],
                   mem_overlap=mig["mem"]["overlap"],
                   mem_fresh=mig["mem"]["fresh"],
                   comp_carried=mig["comp"]["carried"],
                   comp_overlap=mig["comp"]["overlap"],
                   comp_fresh=mig["comp"]["fresh"])
        return staged

    # -- COMMIT -------------------------------------------------------------
    def commit(self, step: int, mesh):
        """Consensus-gated cutover of the staged promotion at a drain
        boundary. Returns ``(state, (grace, tx), event)`` with the
        migrated state now live and probation armed — or None when the
        commit leg timed out (staged promotion dropped, incumbent keeps
        running: the abort path IS the last-known-good path, because
        PREPARE never touched live state)."""
        if self.phase != "prepared" or self._staged is None:
            raise RuntimeError("commit() without a staged promotion — "
                               "call prepare() first.")
        staged = self._staged

        def cutover():
            if self.consensus is None:
                return staged.state, None
            from grace_tpu.resilience.elastic import rejoin_barrier
            return rejoin_barrier(staged.state, self.consensus, mesh,
                                  self.axis_name)

        ok, result, timeouts = self._watchdog("commit", step, cutover)
        if not ok:
            self._staged = None
            self.phase = "idle"
            self._emit("retune_abort", step, leg="commit",
                       reason="commit barrier exceeded its bounded wait "
                              "— promotion dropped, incumbent config "
                              "keeps running",
                       timeouts=timeouts)
            return None
        state, report = result
        self._demotion_params = staged.old_params
        self._lkg_digest = staged.lkg_digest
        self.params = dict(staged.new_params)
        self._probation_until = step + self.probation_steps
        self.phase = "probation"
        self._reset_drift()
        barrier = {}
        if report is not None:
            barrier = {k: report[k] for k in
                       ("repairs", "barrier_repairs", "audits",
                        "replica_variants", "fingerprint_bytes",
                        "repair_bytes") if k in report}
        event = self._emit("retune_promote", step,
                           old=staged.old_params.get("compressor"),
                           new=staged.new_params.get("compressor"),
                           probation_until=self._probation_until,
                           commit_timeouts=timeouts, **barrier)
        self._staged = None
        return state, (staged.grace, staged.tx), event

    # -- probation ----------------------------------------------------------
    def watch(self, step: int, records) -> Optional[str]:
        """Feed the run's sink records during probation; returns the
        triggering event name the moment any guard trip / consensus
        escalation demands demotion (call :meth:`demote`), else None.
        A probation window that expires quiet clears the transaction —
        the promoted config becomes the incumbent for good."""
        if self.phase != "probation":
            return None
        for rec in records or ():
            ev = str(rec.get("event", ""))
            if any(ev == t or ev.startswith(t + "_") for t in self.demote_on):
                return ev
        if (self._probation_until is not None
                and step >= self._probation_until):
            self.phase = "idle"
            self._probation_until = None
            self._emit("retune_probation_clear", step,
                       config=self.params.get("compressor"))
        return None

    def demote(self, step: int, state, mesh, *, trigger: str):
        """Automatic rollback: restore the last-known-good checkpoint
        under the OLD config, bit-exact (digest-checked against the
        PREPARE-time witness). A stalled or absent restore falls back to
        a fresh old-config init carrying the CURRENT params — degraded
        (residuals restart, probation steps kept) but alive, and the
        event records ``restored=False``. Returns
        ``(state, (grace, tx), event)``."""
        if self.phase != "probation" or self._demotion_params is None:
            raise RuntimeError("demote() without a probationary promotion.")
        old_params = self._demotion_params
        grace, tx = self.build(old_params)
        from grace_tpu.train import init_train_state

        restored_state = None
        restored, timeouts, bit_exact = False, 0, None
        if self.checkpointer is not None:
            def restore():
                target = init_train_state(state.params, tx, mesh,
                                          self.axis_name)
                return self.checkpointer.restore_last_good(target)

            ok, out, timeouts = self._watchdog("demote_restore", step,
                                               restore)
            if ok:
                restored_state, restored = out, True
                if self._lkg_digest is not None:
                    bit_exact = state_digest(restored_state) == \
                        self._lkg_digest
        if restored_state is None:
            restored_state = init_train_state(state.params, tx, mesh,
                                              self.axis_name)
        self.params = dict(old_params)
        self._demotion_params = None
        self._lkg_digest = None
        self._probation_until = None
        self.phase = "idle"
        self._reset_drift()
        event = self._emit("retune_demote", step, trigger=trigger,
                           restored=restored, bit_exact=bit_exact,
                           restore_timeouts=timeouts,
                           config=old_params.get("compressor"))
        return restored_state, (grace, tx), event
