"""graft-adapt: in-graph adaptive compression controller.

The resilience stack before this module was binary: a config either runs
its static codec, or the PR-1 guard slams it into the M-step dense
fallback. ROADMAP item 3 asks for the middle rungs — a controller that
*degrades gracefully*, tightening codec aggressiveness while the gradient
signal is turbulent (warmup, error spikes, a single rank's encoder
drifting) and loosening back toward the aggressive steady-state codec when
things go quiet. Both halves of the loop already exist: graft-watch's
replicated cross-rank error columns are the input channel, and the
PR-13 aggregation-homomorphic payloads make codec swaps cheap mid-run
(THC, PAPERS.md — bit-width switching over shared-scale payloads needs no
state migration; ACCORDION shows the rate-schedule side). This module is
the missing actuator.

**The degradation ladder.** An :class:`AdaptConfig` declares an ordered
tuple of codecs from safest to most aggressive; rung 0 is always the dense
escape (``grace_transform(escape=...)`` — the same codec+psum the guard's
fallback window uses), rungs ``1..R-1`` are the declared
:attr:`~AdaptConfig.ladder` (e.g. homoqsgd 8 bits → 4 bits, topk ratio
×4 → ×1), and the transform's own base codec is always the top rung — the
steady state a quiet run converges to. Every update executes exactly one
rung via ``lax.switch`` on the replicated rung index, so the whole ladder
is one compiled program and every rung's schedule is statically traced
(and therefore statically audited — flow pass 6 sees every reachable
rung, including each shared-scale rung's ``payload_sum_max_world`` bound).

**The controller is a replicated lax.cond, not a host loop.** Every step,
each rank's local relative compression error (the telemetry ring's
``compression_error`` scalar, computed against the *active* rung's codec)
is reduced cross-rank with one scalar ``pmean`` + one scalar ``pmax`` —
the graft-watch gather idiom at scalar size, so the windowed signal is a
*replicated in-graph fact*: every rank provably accumulates the same
``err_sum``/``err_peak``, and the window-boundary decision (a ``lax.cond``
on the replicated step counter, exactly the consensus/watch gate) moves
every rank's rung identically. graft-lint's collective-consistency pass
verifies the branch-divergent ``lax.switch`` predicate is replicated —
the same proof obligation the dense-escape cond discharges.

**Robustness-first semantics**:

* **tighten before the guard would trip** — a spike in the windowed mean
  (``tighten_error``) or in the worst rank's error (``tighten_peak`` —
  the drifting-rank channel graft-watch flags) steps DOWN one rung within
  one window;
* **hysteresis** — loosening requires ``quiet_windows`` consecutive quiet
  windows (windowed mean below ``loosen_error`` < ``tighten_error``), so
  the controller probes back up slowly and can never flap at window rate;
* **a guard trip is evidence the ladder floor is too loose**
  (escalate-and-hold) — any step spent under the guard's fallback flag
  tightens one extra rung at the next boundary AND arms a
  ``hold_windows``-window freeze on loosening;
* **atomic with guard rollback and consensus repair** — the policy state
  (:class:`AdaptState`) lives in ``GraceState.adapt``, replicated
  (``partition_specs`` P(), fingerprinted by the consensus audit, repaired
  by the masked broadcast, rolled back bitwise by the guard), and a world
  resize re-initializes it (:func:`grace_tpu.resilience.elastic.
  reshard_grace_state`) — the windowed statistics and operating rung were
  learned at the old world's signal profile.

Wire honesty: telemetry prices the state-dependent bytes with a per-rung
wire plan (the dense-fallback flip generalized — ``adapt_rung`` names the
rung each row's ``wire_bytes``/ici/dcn were priced at) and the signal
reductions' cost is surfaced as ``adapt_bytes``, folded into the effective
wire accounting like ``watch_bytes``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["AdaptConfig", "AdaptState", "normalize_adapt", "adapt_init",
           "adapt_signal", "adapt_signal_bytes", "adapt_advance",
           "adapt_report", "AdaptMonitor"]

# Non-finite local errors (a poisoned gradient the guard will roll back
# anyway) clamp to this finite spike so the accumulators stay finite and
# the boundary decision reads "tighten", never NaN-poisons the policy.
_ERR_CLAMP = 1e6


class AdaptState(NamedTuple):
    """Replicated controller state, threaded through ``GraceState.adapt``.

    Every field is a scalar derived from replicated inputs (the step
    counter, the fallback flag, and full-axis pmean/pmax outputs), so all
    ranks hold bit-identical policy state — which is what lets the
    ``lax.switch`` rung dispatch stay deadlock-free, the consensus audit
    fingerprint it, and the masked-broadcast repair restore it.
    """

    rung: jax.Array          # int32: commanded rung (0 = dense escape)
    err_sum: jax.Array       # f32: window sum of replicated mean rel error
    err_peak: jax.Array      # f32: window max of worst-rank rel error
    fb_steps: jax.Array      # int32: steps this window spent under the
                             # guard's fallback flag (the escalate evidence)
    quiet: jax.Array         # int32: consecutive quiet windows
    hold: jax.Array          # int32: loosen-freeze windows remaining
    tightens: jax.Array      # int32: total tighten transitions
    loosens: jax.Array       # int32: total loosen transitions
    escalations: jax.Array   # int32: guard-evidence escalate-and-holds
    last_change_step: jax.Array  # int32: GraceState.count at last move, -1


@dataclasses.dataclass(frozen=True)
class AdaptConfig:
    """Static controller knobs + the declared degradation ladder.

    ``ladder`` — the non-dense rungs as built :class:`~grace_tpu.core.
    Compressor` instances, safest first, most aggressive (the steady
    state) last; the transform's base codec is always the top rung
    (:func:`normalize_adapt` appends it when missing), and rung 0 — the
    dense escape — is implicit. Every rung must thread the same mem/comp
    state structure as the base codec (the ``lax.switch`` branches return
    one state type). PowerSGD rank ladders satisfy this through the
    rung-invariant padded layout: every rung carries
    ``state_rank = max(ranks)`` so all rungs store one ``(m, max_rank)``
    Q and operate on their leading ``rank`` columns
    (``grace_from_params`` pins this automatically; hand-built ladders
    that skip it are rejected with a clear error at trace time).

    ``window`` — steps between decisions (the ``lax.cond`` gate on the
    replicated step counter, the consensus/watch idiom).
    ``tighten_error``/``tighten_peak`` — windowed mean / worst-rank
    relative-compression-error thresholds above which the controller
    steps down one rung at the boundary. ``loosen_error`` — the quiet
    threshold (must sit strictly below ``tighten_error``: that gap IS the
    hysteresis band). ``quiet_windows`` — consecutive quiet windows
    required before loosening one rung. ``hold_windows`` — loosen freeze
    armed by guard-trip evidence (escalate-and-hold).
    ``start_rung`` — initial rung (default: the top — start aggressive,
    tighten on evidence; set lower for warmup-cautious runs).
    """

    ladder: Tuple[Any, ...] = ()
    window: int = 10
    tighten_error: float = 0.5
    tighten_peak: float = 0.75
    loosen_error: float = 0.25
    quiet_windows: int = 2
    hold_windows: int = 4
    start_rung: Optional[int] = None

    def __post_init__(self):
        if self.window < 1:
            raise ValueError(f"adapt window must be >= 1; got {self.window}")
        if not (0.0 < self.loosen_error < self.tighten_error):
            raise ValueError(
                f"adapt thresholds must satisfy 0 < loosen_error "
                f"({self.loosen_error}) < tighten_error "
                f"({self.tighten_error}) — the gap between them is the "
                "hysteresis band; equal thresholds would let the "
                "controller flap a rung per window")
        if self.tighten_peak < self.tighten_error:
            raise ValueError(
                f"tighten_peak ({self.tighten_peak}) must be >= "
                f"tighten_error ({self.tighten_error}) — the worst-rank "
                "channel is a coarser alarm than the mean, not a finer "
                "one")
        if self.quiet_windows < 1:
            raise ValueError(f"quiet_windows must be >= 1; "
                             f"got {self.quiet_windows}")
        if self.hold_windows < 0:
            raise ValueError(f"hold_windows must be >= 0; "
                             f"got {self.hold_windows}")

    @property
    def n_rungs(self) -> int:
        """Total reachable rungs including the implicit dense rung 0."""
        return len(self.ladder) + 1

    @property
    def top_rung(self) -> int:
        return len(self.ladder)


def normalize_adapt(adapt, base_compressor) -> Optional[AdaptConfig]:
    """Accept the ergonomic spellings of the adapt knob, mirroring
    telemetry/consensus/watch: None/False (off), True (defaults), int
    (window), dict (config kwargs; ``ladder`` holds built Compressor
    instances), or an AdaptConfig. The transform's base codec is appended
    as the ladder's top rung when the declared ladder does not already end
    with it — the steady state is always the config's own codec."""
    if adapt is None or adapt is False:
        return None
    if adapt is True:
        cfg = AdaptConfig()
    elif isinstance(adapt, AdaptConfig):
        cfg = adapt
    elif isinstance(adapt, int):
        cfg = AdaptConfig(window=adapt)
    elif isinstance(adapt, dict):
        cfg = AdaptConfig(**{k: (tuple(v) if k == "ladder" else v)
                             for k, v in adapt.items()})
    else:
        raise TypeError(f"adapt must be None/bool/int/dict/AdaptConfig; "
                        f"got {type(adapt).__name__}")
    ladder = tuple(cfg.ladder)
    if not ladder or ladder[-1] != base_compressor:
        ladder = ladder + (base_compressor,)
    cfg = dataclasses.replace(cfg, ladder=ladder)
    if cfg.start_rung is not None and not (0 <= cfg.start_rung
                                           <= cfg.top_rung):
        raise ValueError(
            f"start_rung {cfg.start_rung} outside the ladder's rung range "
            f"[0, {cfg.top_rung}]")
    return cfg


def adapt_init(config: AdaptConfig) -> AdaptState:
    zero = jnp.zeros((), jnp.int32)
    start = (config.start_rung if config.start_rung is not None
             else config.top_rung)
    return AdaptState(
        rung=jnp.asarray(start, jnp.int32),
        err_sum=jnp.zeros((), jnp.float32),
        err_peak=jnp.zeros((), jnp.float32),
        fb_steps=zero, quiet=zero, hold=zero,
        tightens=zero, loosens=zero, escalations=zero,
        last_change_step=zero - 1)


def adapt_signal(local_err, axis_name: str):
    """The controller's one collective pair: replicated (mean, worst-rank)
    of each rank's local relative compression error — one scalar ``pmean``
    + one scalar ``pmax`` per step, the graft-watch gather idiom at scalar
    size. Outside a bound mesh axis (single-process use) the local value
    stands in for both."""
    err = jnp.asarray(local_err, jnp.float32)
    try:
        return lax.pmean(err, axis_name), lax.pmax(err, axis_name)
    except NameError:               # unbound axis: no mesh, no peers
        return err, err


def adapt_signal_bytes(world: int) -> int:
    """Per-rank received bytes of one step's signal reductions (one f32
    pmean + one f32 pmax, each a full-axis ring reduction moving
    ``2·n·(W−1)/W``) — the number folded into the telemetry row's
    effective wire accounting as ``adapt_bytes``, and the number the
    auditor's traced-collective count sees (well inside the scalar
    atol)."""
    return 2 * (2 * 4 * max(0, world - 1) // max(1, world))


def adapt_advance(state: AdaptState, config: AdaptConfig, count,
                  fallback, err_mean, err_peak) -> AdaptState:
    """One step of the controller: accumulate the replicated window signal
    every step; on the window boundary (``lax.cond`` on the replicated
    step counter) decide the next rung. Pure state math — the branches
    carry no collectives; the signal reductions already ran in
    :func:`adapt_signal`."""
    clamp = jnp.asarray(_ERR_CLAMP, jnp.float32)
    em = jnp.minimum(jnp.nan_to_num(
        jnp.asarray(err_mean, jnp.float32),
        nan=_ERR_CLAMP, posinf=_ERR_CLAMP, neginf=_ERR_CLAMP), clamp)
    ep = jnp.minimum(jnp.nan_to_num(
        jnp.asarray(err_peak, jnp.float32),
        nan=_ERR_CLAMP, posinf=_ERR_CLAMP, neginf=_ERR_CLAMP), clamp)
    fb = jnp.asarray(fallback, jnp.bool_).astype(jnp.int32)
    state = state._replace(err_sum=state.err_sum + em,
                           err_peak=jnp.maximum(state.err_peak, ep),
                           fb_steps=state.fb_steps + fb)
    due = jnp.equal(jnp.mod(count + 1, config.window), 0)
    return lax.cond(due, lambda s: _decide(s, config, count),
                    lambda s: s, state)


def _decide(a: AdaptState, config: AdaptConfig, count) -> AdaptState:
    one = jnp.ones((), jnp.int32)
    top = jnp.asarray(config.top_rung, jnp.int32)
    wmean = a.err_sum / jnp.asarray(float(config.window), jnp.float32)

    # Tighten: a windowed mean spike, a worst-rank spike (the drifting-rank
    # channel), or guard-trip evidence — each steps DOWN one rung, within
    # one window of the symptom.
    spike = (wmean > config.tighten_error) | (a.err_peak
                                              > config.tighten_peak)
    guard_evidence = a.fb_steps > 0
    tighten = spike | guard_evidence
    rung = jnp.where(tighten, jnp.maximum(a.rung - one, 0), a.rung)

    # Escalate-and-hold: a guard trip says the ladder floor was too loose
    # — freeze loosening for the next hold_windows boundaries; otherwise
    # the hold decays one per boundary. The loosen check below reads the
    # PRE-decay hold, so hold_windows means hold_windows FULL frozen
    # windows after the escalation boundary.
    hold = jnp.where(guard_evidence,
                     jnp.asarray(config.hold_windows, jnp.int32),
                     jnp.maximum(a.hold - one, 0))

    # Hysteresis: quiet windows accumulate only below loosen_error (which
    # sits strictly below tighten_error), and loosening needs
    # quiet_windows of them with no hold in force.
    quiet_now = (~tighten) & (wmean < config.loosen_error)
    quiet = jnp.where(tighten, 0, jnp.where(quiet_now, a.quiet + one, 0))
    loosen = ((~tighten) & (quiet >= config.quiet_windows)
              & (a.hold == 0) & (rung < top))
    rung = jnp.where(loosen, rung + one, rung)
    quiet = jnp.where(loosen, 0, quiet)

    moved = tighten | loosen
    return AdaptState(
        rung=rung,
        err_sum=jnp.zeros((), jnp.float32),
        err_peak=jnp.zeros((), jnp.float32),
        fb_steps=jnp.zeros((), jnp.int32),
        quiet=quiet, hold=hold,
        tightens=a.tightens + tighten.astype(jnp.int32),
        loosens=a.loosens + loosen.astype(jnp.int32),
        escalations=a.escalations + guard_evidence.astype(jnp.int32),
        last_change_step=jnp.where(moved, jnp.asarray(count, jnp.int32),
                                   a.last_change_step))


# ---------------------------------------------------------------------------
# host-side reporting
# ---------------------------------------------------------------------------

def adapt_report(state: Any) -> dict:
    """Host-side summary of the adaptive controller in any state pytree:
    the first armed :class:`AdaptState`'s counters in one device-to-host
    transfer (the ``audit_report`` twin). Empty dict when no adapt-armed
    GraceState is present."""
    from grace_tpu.transform import GraceState

    found: list = []

    def walk(node):
        if isinstance(node, GraceState) and node.adapt is not None:
            found.append(node.adapt)
        return node

    jax.tree_util.tree_map(walk, state,
                           is_leaf=lambda n: isinstance(n, GraceState))
    if not found:
        return {}
    a = found[0]
    vals = jax.device_get([a.rung, a.tightens, a.loosens, a.escalations,
                           a.hold, a.quiet, a.last_change_step])
    rung, ti, lo, es, hold, quiet, last = (
        int(np.asarray(v).reshape(-1)[0]) for v in vals)
    return {"rung": rung, "tightens": ti, "loosens": lo,
            "escalations": es, "hold": hold, "quiet": quiet,
            "last_change_step": last}


class AdaptMonitor:
    """Streaming consumer of flushed telemetry rows; emits ``adapt_tighten``
    / ``adapt_loosen`` sink records on rung transitions.

    The in-graph controller leaves its trail in the telemetry ring's
    ``adapt_rung`` column (the effective rung each row's wire bytes were
    priced at); this monitor diffs consecutive rows and writes one flat
    event per transition into the same sink funnel as the guard/consensus
    events — which is what lets ``chaos_smoke --adapt`` prove the
    timeline ordering (adapt_tighten strictly precedes the first guard
    event) from the artifact alone. Rows inside a guard fallback window
    (``fallback`` truthy) are skipped: the escape routing forces the
    effective rung to 0 there, which is the guard's move, not a policy
    transition.
    """

    def __init__(self, sink=None):
        self.sink = sink
        self.events: list = []
        self._last_rung: Optional[int] = None

    def observe(self, records) -> list:
        out: list = []
        for rec in records:
            if not isinstance(rec, dict) or rec.get("event") is not None:
                continue
            rung = rec.get("adapt_rung")
            if rung is None or float(rung) < 0:
                continue
            if rec.get("fallback"):
                continue
            rung = int(rung)
            if self._last_rung is not None and rung != self._last_rung:
                kind = ("adapt_tighten" if rung < self._last_rung
                        else "adapt_loosen")
                ev = {"event": kind, "step": rec.get("step"),
                      "rung": rung, "from_rung": self._last_rung}
                out.append(ev)
                self.events.append(ev)
                if self.sink is not None:
                    self.sink.write(ev)
            self._last_rung = rung
        return out
