"""Cross-rank consistency audit + in-graph self-healing for replica divergence.

Error-feedback compression is only correct if every replica holds consistent
state: params, the downstream optimizer state, and the replicated GraceState
scalars (count, rng_key, fallback) must be **bit-identical** across ranks —
ScaleCom and PowerSGD (PAPERS.md) both hinge on exactly this cross-worker
state consistency, because every rank derives its compression decisions from
state it assumes is shared. ``GraceState.mem``/``comp`` residuals are
legitimately per-rank, but everything else drifting on a single rank is a
*silent* fault class the PR-1 guard cannot see:

* the guard checks the **post-exchange update** for NaN/Inf/norm bounds —
  a bit-flipped parameter is perfectly finite, and because the exchange
  aggregates gradients, the *updates* stay rank-identical while the
  *params* stay diverged forever;
* a single-rank SDC (bitflip in params/opt-state, the fault
  :class:`~grace_tpu.resilience.chaos.ChaosParams` injects) therefore
  desynchronizes replicas permanently without ever tripping the guard.

This module closes that gap with three in-graph pieces:

**Fingerprint** (:func:`fingerprint_tree`): fold the replicated state into a
small per-rank vector — a segmented *float fold* (value sums, magnitude-
sensitive) plus a position-weighted *bit-pattern checksum* (so ``-0.0`` vs
``+0.0`` and differing NaN payloads cannot alias; the final comparison is
done entirely on the bit vectors, which also sidesteps NaN != NaN). Cost:
one pass over the state every ``audit_every`` steps, gated by ``lax.cond``
on ``GraceState.count`` so healthy non-audit steps pay ~nothing.

**Audit**: ``all_gather`` the fingerprints over the world axis (a few dozen
uint32 words per rank) and compare. Equality on every rank ⇒ the audit is a
bit-identical no-op (the untaken repair cond). The gathered matrix is
identical on every rank, so the majority/reference-rank election and every
branch decision below it are replicated — all ranks take the same branches
and the repair collectives rendezvous.

**Repair** (in-graph, atomic against params/opt/mem/telemetry):

* elect the reference rank = lowest mesh index among the ranks whose
  fingerprint matches the most others (majority vote; with one corrupted
  rank out of W, the W-1 healthy ranks win);
* broadcast the reference rank's replicated state to everyone via the
  bit-exact :func:`~grace_tpu.comm.masked_broadcast` (axis_index-masked
  psum in integer bit space — a float psum would flip ``-0.0 + 0.0``);
* **zero the divergent rank's residuals** instead of broadcasting them:
  residuals are per-rank data, so there is nothing consistent to broadcast,
  and a residual on a corrupted rank is itself suspect. Zeroing is safe by
  the error-feedback contract — the memory re-accumulates exactly the
  compression error it would have tracked, costing at most a few steps of
  feedback quality (see IMPLEMENTING.md, "Why repair zeroes residuals");
* bump the replicated :class:`~grace_tpu.transform.AuditState` counters;
* **escalate** if the same rank re-diverges within ``escalate_window``
  steps of its last repair: a repeat offender suggests sticky corruption
  (bad HBM, a wedged core), so the repair path arms the PR-1 dense escape
  hatch — ``GraceState.fallback`` is set and a co-resident
  ``GuardState.fallback_remaining`` is raised to ``escalate_steps``, giving
  the existing guard countdown ownership of the dense window. Without a
  guard in the chain the flag simply stays set (permanent dense fallback —
  degraded but safe).

Wiring: build the transform with ``grace_transform(consensus=True)`` (or
``grace_from_params({"consensus": ...})``) to thread the
:class:`~grace_tpu.transform.AuditState`, and pass the config to
``make_train_step(consensus=ConsensusConfig(...))`` — the hook runs after
``apply_updates`` inside the jitted shard_map step, where params, optimizer
state, and the mesh axis are all in scope.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from grace_tpu.comm import masked_broadcast
from grace_tpu.core import DEFAULT_AXIS, axis_size
from grace_tpu.telemetry.state import FIELD_INDEX, TelemetryState
from grace_tpu.transform import AuditState, GraceState

__all__ = ["ConsensusConfig", "normalize_consensus", "fingerprint_tree",
           "consensus_step", "force_audit", "audit_report"]

_UINT = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}
# Knuth multiplicative-hash constants for the position-weighted fold.
_PRIME_POS = np.uint32(2654435761)
_PRIME_LEAF = np.uint32(2246822519)
_SALT = np.uint32(374761393)


@dataclasses.dataclass(frozen=True)
class ConsensusConfig:
    """Static knobs of the consistency auditor (hashable, jit-safe).

    ``audit_every`` — steps between audits (the ``lax.cond`` gate on
    ``GraceState.count``). ``segments`` — fingerprint granularity: leaves
    are folded into ``segments`` buckets, each contributing one float-fold
    word and one bit-checksum word (vector length ``2 * segments``).
    ``zero_residuals`` — zero the divergent rank's ``GraceState.mem`` on
    repair (see module docstring; disable only for diagnosis).
    ``escalate_window``/``escalate_steps`` — if the *same* rank re-diverges
    within ``escalate_window`` steps of its last repair, arm the dense
    escape hatch for ``escalate_steps`` steps (requires
    ``grace_transform(escape=...)`` for the dense routing, and a
    ``guard_transform`` in the chain for the countdown). Must be set
    together; None disables escalation.
    """

    audit_every: int = 50
    segments: int = 8
    zero_residuals: bool = True
    escalate_window: Optional[int] = None
    escalate_steps: Optional[int] = None

    def __post_init__(self):
        if self.audit_every < 1:
            raise ValueError(f"audit_every must be >= 1; "
                             f"got {self.audit_every}")
        if self.segments < 1:
            raise ValueError(f"segments must be >= 1; got {self.segments}")
        if (self.escalate_window is None) != (self.escalate_steps is None):
            raise ValueError("escalate_window and escalate_steps must be "
                             "set together")
        if self.escalate_steps is not None and self.escalate_steps < 1:
            raise ValueError(f"escalate_steps must be >= 1; "
                             f"got {self.escalate_steps}")


def normalize_consensus(consensus) -> Optional[ConsensusConfig]:
    """Accept the ergonomic spellings of the consensus knob: None/False
    (off), True (defaults), int (audit_every), dict (config kwargs), or a
    ConsensusConfig — mirroring the telemetry knob."""
    if consensus is None or consensus is False:
        return None
    if consensus is True:
        return ConsensusConfig()
    if isinstance(consensus, ConsensusConfig):
        return consensus
    if isinstance(consensus, int):
        return ConsensusConfig(audit_every=consensus)
    if isinstance(consensus, dict):
        return ConsensusConfig(**consensus)
    raise TypeError(f"consensus must be None/bool/int/dict/ConsensusConfig; "
                    f"got {type(consensus).__name__}")


# ---------------------------------------------------------------------------
# tree plumbing
# ---------------------------------------------------------------------------

def _is_grace(x) -> bool:
    return isinstance(x, GraceState)


def _grace_nodes(tree) -> list:
    found: list = []

    def walk(node):
        if _is_grace(node):
            found.append(node)
        return node

    jax.tree_util.tree_map(walk, tree, is_leaf=_is_grace)
    return found


def replicated_view(tree):
    """``tree`` with the per-rank GraceState payloads (mem/comp/telem/
    watch) dropped: exactly the leaves that must be bit-identical across
    ranks — params, downstream optimizer state, guard counters, and the
    replicated GraceState scalars (count, rng_key, fallback, audit, and
    the graft-adapt policy state — a diverged rung would desync the
    ladder dispatch, so it is inside the fingerprint's jurisdiction). The
    graft-watch ring is per-rank by design (its skew columns differ per
    rank by construction), so fingerprinting it would read healthy skew as
    divergence."""

    def strip(node):
        if _is_grace(node):
            return node._replace(mem=None, comp=None, telem=None,
                                 watch=None)
        return node

    return jax.tree_util.tree_map(strip, tree, is_leaf=_is_grace)


def _word_stream(x: jax.Array) -> jax.Array:
    """Flatten any array to a 1-D uint32 word stream of its bit pattern."""
    x = jnp.asarray(x)
    if x.size == 0:
        return jnp.zeros((0,), jnp.uint32)
    if x.dtype == jnp.bool_:
        return x.ravel().astype(jnp.uint32)
    bits = lax.bitcast_convert_type(x, _UINT[x.dtype.itemsize]).ravel()
    if x.dtype.itemsize == 8:
        lo = (bits & np.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (bits >> np.uint64(32)).astype(jnp.uint32)
        return jnp.concatenate([lo, hi])
    return bits.astype(jnp.uint32)


def fingerprint_tree(tree, segments: int = 8) -> jax.Array:
    """Per-rank fingerprint of a pytree: a ``(2 * segments,)`` uint32 vector.

    Leaf ``i`` folds into segment ``i % segments`` twice:

    * **bit checksum** — the leaf's bit pattern as uint32 words, each word
      multiplied by a position-and-leaf-salted odd weight and summed mod
      2^32. Position weighting means swapped elements don't alias; leaf
      salting means identical leaves at different tree positions don't
      cancel. Catches any bit-level difference, including ``-0.0`` vs
      ``+0.0`` and NaN-payload changes that value comparison cannot see.
    * **float fold** — plain float32 value sum of inexact leaves, a
      magnitude-sensitive second opinion; compared via its own bit pattern
      (so a NaN-poisoned fold still compares deterministically).

    Pure per-rank math — no collectives; deterministic for a given tree, so
    ranks holding bit-identical state produce bit-identical fingerprints.
    """
    bitsum = jnp.zeros((segments,), jnp.uint32)
    valsum = jnp.zeros((segments,), jnp.float32)
    for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
        leaf = jnp.asarray(leaf)
        if leaf.size == 0:
            continue
        seg = i % segments
        words = _word_stream(leaf)
        # Python-int arithmetic, masked: numpy scalar * warns on wraparound.
        salt = np.uint32((i * int(_PRIME_LEAF) + int(_SALT)) & 0xFFFFFFFF)
        weights = (jnp.arange(words.size, dtype=jnp.uint32) * _PRIME_POS
                   | np.uint32(1))
        bitsum = bitsum.at[seg].add(jnp.sum((words ^ salt) * weights,
                                            dtype=jnp.uint32))
        if jnp.issubdtype(leaf.dtype, jnp.inexact):
            valsum = valsum.at[seg].add(
                jnp.sum(leaf.astype(jnp.float32)))
    return jnp.concatenate(
        [bitsum, lax.bitcast_convert_type(valsum, jnp.uint32)])


def _tree_nbytes(tree) -> int:
    """Static logical byte count of every array leaf (trace-time Python)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        leaf = jnp.asarray(leaf)
        total += int(np.prod(leaf.shape, dtype=np.int64)) * leaf.dtype.itemsize
    return total


# ---------------------------------------------------------------------------
# the audit + repair step
# ---------------------------------------------------------------------------

def consensus_step(tree, consensus, axis_name: str = DEFAULT_AXIS):
    """Audit-and-repair hook over a full per-device train-state pytree.

    Called inside the jitted shard_map step (``make_train_step(consensus=)``
    does this after ``apply_updates``); ``tree`` is any pytree containing at
    least one consensus-armed GraceState (params, model state, optimizer
    state bundled together). Every ``audit_every`` steps — gated by
    ``lax.cond`` on a replicated step counter (the guard's always-advancing
    ``step`` when present, else ``GraceState.count``) so other steps pay
    ~nothing — fingerprints the replicated state, compares across
    ``axis_name``, and on divergence repairs in-graph (see module
    docstring). Bit-identical to a no-op when replicas agree.
    """
    from grace_tpu.resilience.guard import GuardState

    config = normalize_consensus(consensus)
    if config is None:
        return tree
    armed = _require_armed(tree)
    # Audit clock: the guard's step counter when a guard wraps the chain —
    # it advances on EVERY step, including guard-skipped ones, so a fault
    # that makes every step roll back (frozen GraceState.count) cannot
    # starve the audit that would repair it. GraceState.count otherwise.
    guards: list = []
    jax.tree_util.tree_map(
        lambda n: guards.append(n) if isinstance(n, GuardState) else n,
        tree, is_leaf=lambda n: isinstance(n, GuardState))
    clock = guards[0].step if guards else armed[0].count
    due = jnp.equal(jnp.mod(clock, config.audit_every), 0)
    return lax.cond(due,
                    lambda t: _audit(t, config, axis_name),
                    lambda t: t,
                    tree)


def _require_armed(tree) -> list:
    graces = _grace_nodes(tree)
    armed = [g for g in graces if g.audit is not None]
    if not armed:
        raise ValueError(
            "consensus auditing is configured but the state carries no "
            "AuditState — build the grace transform with consensus=... "
            "(grace_from_params({'consensus': ...})) and re-init the "
            "optimizer state, or restore a checkpoint written with a "
            "consensus-armed transform.")
    return armed


def force_audit(tree, consensus, axis_name: str = DEFAULT_AXIS):
    """One UNGATED audit-and-repair pass over ``tree`` — the scheduled
    :func:`consensus_step` without its every-``audit_every`` ``lax.cond``.

    This is the elastic **rejoin barrier**'s admission gate
    (:func:`grace_tpu.resilience.elastic.rejoin_barrier`): a rank rejoining
    the fleet — typically restored from a last-known-good checkpoint taken
    *before* the fleet kept training — must fingerprint-match the reference
    replica before its gradients count. The barrier cannot wait for the
    next scheduled audit (up to ``audit_every`` steps of a stale replica
    voting in every collective), so it forces the audit at admission time:
    fingerprint → all_gather → election → masked-broadcast repair of the
    replicated state, with the divergent (rejoining) rank's residuals
    zeroed per the PR-3 rationale. Bit-identical to a no-op when the
    rejoiner already matches. Must run where ``axis_name`` is bound.
    """
    config = normalize_consensus(consensus)
    if config is None:
        raise ValueError(
            "force_audit needs an armed consensus config (True / "
            "audit_every / ConsensusConfig) — None/False disables the "
            "auditor, which cannot gate a rejoin.")
    _require_armed(tree)
    return _audit(tree, config, axis_name)


def _audit(tree, config: ConsensusConfig, axis_name: str):
    w = axis_size(axis_name)                     # static at trace time
    fp = fingerprint_tree(replicated_view(tree), config.segments)
    fps = lax.all_gather(fp, axis_name, axis=0, tiled=False)   # (W, 2S)

    # Pairwise agreement matrix; identical on every rank (fps is gathered),
    # so the election and every branch below are replicated decisions.
    eq = jnp.all(fps[:, None, :] == fps[None, :, :], axis=-1)  # (W, W)
    matches = jnp.sum(eq, axis=1)                              # (W,)
    best = jnp.max(matches)
    ref = jnp.argmax(matches == best)        # lowest index among majority
    any_div = best < w
    # First rank disagreeing with the reference (replicated); -1 if none.
    divergent_rank = jnp.where(any_div,
                               jnp.argmax(~eq[ref]).astype(jnp.int32),
                               jnp.asarray(-1, jnp.int32))
    me = lax.axis_index(axis_name)
    diverged_me = ~eq[me, ref]               # per-rank: am I the outlier?

    count = _grace_nodes(tree)[0].count
    repair_bytes = _tree_nbytes(replicated_view(tree))
    fp_bytes = int(w) * 2 * config.segments * 4

    def repair(t):
        return _repair(t, ref, diverged_me, config, axis_name)

    repaired = lax.cond(any_div, repair, lambda t: t, tree)
    repaired = _advance_audit(repaired, config, count, any_div,
                              divergent_rank)
    extra = (jnp.asarray(float(fp_bytes), jnp.float32)
             + jnp.where(any_div, jnp.asarray(float(repair_bytes),
                                              jnp.float32), 0.0))
    return _account_audit_bytes(repaired, count, extra)


def _repair(tree, ref, diverged_me, config: ConsensusConfig,
            axis_name: str):
    """Broadcast the reference rank's replicated state bit-exactly; zero the
    divergent rank's residuals. Per-rank telemetry rings and compressor
    state pass through untouched (rings are observational; compressor state
    is per-rank by contract, and e.g. PowerSGD's Q must stay a valid
    iterate, which zeros are not — the residual zeroing alone restores the
    error-feedback invariant)."""

    def zero_if_diverged(m):
        return jnp.where(diverged_me, jnp.zeros_like(m), m)

    def fix(node):
        if _is_grace(node):
            mem = node.mem
            if config.zero_residuals:
                mem = jax.tree_util.tree_map(zero_if_diverged, mem)
            return node._replace(
                count=masked_broadcast(node.count, ref, axis_name),
                rng_key=masked_broadcast(node.rng_key, ref, axis_name),
                mem=mem,
                fallback=masked_broadcast(node.fallback, ref, axis_name),
                audit=jax.tree_util.tree_map(
                    lambda a: masked_broadcast(a, ref, axis_name),
                    node.audit),
                # graft-adapt policy state is replicated by contract —
                # a divergent rung would desync the ladder's lax.switch
                # at the next step, so the repair restores it bit-exactly
                # alongside the other replicated scalars.
                adapt=jax.tree_util.tree_map(
                    lambda a: masked_broadcast(a, ref, axis_name),
                    node.adapt))
        return masked_broadcast(node, ref, axis_name)

    return jax.tree_util.tree_map(fix, tree, is_leaf=_is_grace)


def _advance_audit(tree, config: ConsensusConfig, count, any_div,
                   divergent_rank):
    """Bump the replicated AuditState bookkeeping and, when the same rank
    re-diverges within the escalation window, arm the dense escape hatch."""
    from grace_tpu.resilience.guard import GuardState

    escalate = jnp.zeros((), jnp.bool_)
    if config.escalate_window is not None:
        prev = [g.audit for g in _grace_nodes(tree) if g.audit is not None][0]
        same_rank = any_div & (divergent_rank == prev.last_divergent_rank)
        within = (count - prev.last_repair_step) <= config.escalate_window
        escalate = same_rank & within

    one = jnp.ones((), jnp.int32)

    def next_audit(a: AuditState) -> AuditState:
        return AuditState(
            audits=a.audits + one,
            repairs=a.repairs + any_div.astype(jnp.int32),
            escalations=a.escalations + escalate.astype(jnp.int32),
            last_divergent_rank=jnp.where(any_div, divergent_rank,
                                          a.last_divergent_rank),
            last_repair_step=jnp.where(any_div, count.astype(jnp.int32),
                                       a.last_repair_step))

    def fix_grace(node):
        if _is_grace(node):
            audit = (next_audit(node.audit)
                     if node.audit is not None else None)
            fallback = node.fallback
            if config.escalate_window is not None:
                fallback = jnp.asarray(fallback, jnp.bool_) | escalate
            return node._replace(audit=audit, fallback=fallback)
        return node

    tree = jax.tree_util.tree_map(fix_grace, tree, is_leaf=_is_grace)

    if config.escalate_window is not None:
        steps = jnp.asarray(config.escalate_steps, jnp.int32)

        def fix_guard(node):
            if isinstance(node, GuardState):
                return node._replace(fallback_remaining=jnp.where(
                    escalate,
                    jnp.maximum(node.fallback_remaining, steps),
                    node.fallback_remaining))
            return node

        tree = jax.tree_util.tree_map(
            fix_guard, tree, is_leaf=lambda n: isinstance(n, GuardState))
    return tree


def _account_audit_bytes(tree, count, extra):
    """Fold the audit's wire cost (fingerprint exchange + any repair
    broadcast) into the telemetry row of the step that just ran, so the
    reported effective bytes stay honest on audit steps. The row slot is
    guarded by its step id — under the guard a rolled-back step leaves the
    ring pointing at older data, which must not absorb the cost."""
    wire_i = FIELD_INDEX["wire_bytes"]
    audit_i = FIELD_INDEX["audit_bytes"]
    row_step = (count - 1).astype(jnp.int32)

    def fix(node):
        if _is_grace(node) and isinstance(node.telem, TelemetryState):
            t = node.telem
            slot = jnp.mod(row_step, t.steps.shape[0])
            add = jnp.where(t.steps[slot] == row_step, extra, 0.0)
            rings = t.rings.at[slot, wire_i].add(add)
            rings = rings.at[slot, audit_i].add(add)
            return node._replace(telem=TelemetryState(rings=rings,
                                                      steps=t.steps))
        return node

    return jax.tree_util.tree_map(fix, tree, is_leaf=_is_grace)


# ---------------------------------------------------------------------------
# host-side reporting
# ---------------------------------------------------------------------------

def audit_report(state: Any) -> dict:
    """Host-side summary of the consensus auditor in any state pytree.

    Mirrors :func:`grace_tpu.utils.metrics.guard_report`: walks the tree
    for the first armed :class:`~grace_tpu.transform.AuditState` and
    returns its counters in one device-to-host transfer::

        {"audits", "repairs", "escalations",
         "last_divergent_rank", "last_repair_step"}

    Empty dict when no consensus-armed GraceState is present.
    """
    audits = [g.audit for g in _grace_nodes(state) if g.audit is not None]
    if not audits:
        return {}
    a = audits[0]
    au, rp, es, dr, rs = (np.asarray(v).reshape(-1)[0] for v in
                          jax.device_get([a.audits, a.repairs,
                                          a.escalations,
                                          a.last_divergent_rank,
                                          a.last_repair_step]))
    return {"audits": int(au), "repairs": int(rp), "escalations": int(es),
            "last_divergent_rank": int(dr), "last_repair_step": int(rs)}
