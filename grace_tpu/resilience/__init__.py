"""In-graph training resilience: step guard, graceful degradation, chaos.

Three pieces, designed to compose with the existing triad without touching
it (SURVEY.md has no counterpart — the reference assumes a fault-free run):

* :func:`guard_transform` — optax wrapper around the *whole* chain that
  detects non-finite / exploding post-exchange updates in-graph and skips
  the step atomically (params, optimizer state, and every GraceState
  mem/comp leaf roll back together). See ``resilience/guard.py`` for why
  ``optax.apply_if_finite`` cannot do this for error-feedback state.
* the dense escape hatch — ``grace_transform(escape=...)`` +
  ``fallback_after``/``fallback_steps`` on the guard: after K consecutive
  bad steps the exchange degrades to a dense (none/fp16 + psum) all-reduce
  for M cooldown steps, then compression re-arms.
* :mod:`~grace_tpu.resilience.chaos` — deterministic fault injectors
  (NaN/Inf implants, payload bit-flips, single-rank faults, stale
  residuals) as Compressor/Communicator wrappers, plus
  :class:`ChaosParams`, a host-side single-rank SDC injector for
  params/opt-state at rest.
* :mod:`~grace_tpu.resilience.consensus` — the cross-rank consistency
  auditor + in-graph self-healing (fingerprint → compare → masked-psum
  repair → escalate), for the silent single-rank divergence the guard's
  post-exchange checks are structurally blind to.
* :mod:`~grace_tpu.resilience.elastic` — preemption-tolerant elastic
  training: graft-watch-driven drain, world-resize GraceState re-sharding
  (replicated fields carried bit-exactly, per-rank residuals/rings
  re-initialized at the new W), slice-granular hierarchical shrink, and
  the consensus-gated rejoin barrier.
* :mod:`~grace_tpu.resilience.adapt` — the graft-adapt in-graph adaptive
  compression controller: a replicated degradation ladder between the
  static codec and the dense escape, tightening within one window of an
  error spike (before the guard would trip) and loosening with
  hysteresis when gradients go quiet.
* :mod:`~grace_tpu.resilience.retune` — graft-retune fault-tolerant
  online re-tuning: config promotion as a two-phase transaction
  (lint-audited, state-migrated, footprint-validated PREPARE;
  consensus-gated COMMIT) with a probation window that demotes
  bit-exactly on any guard trip or consensus escalation, every leg
  under the elastic drain watchdog's bounded-timeout discipline.
"""

from __future__ import annotations

from typing import Optional

import optax

from grace_tpu.resilience.adapt import (AdaptConfig, AdaptMonitor,
                                        AdaptState, adapt_report,
                                        normalize_adapt)
from grace_tpu.resilience.chaos import (ChaosCommunicator, ChaosCompressor,
                                        ChaosParams)
from grace_tpu.resilience.consensus import (ConsensusConfig, audit_report,
                                            consensus_step, fingerprint_tree,
                                            force_audit, normalize_consensus)
from grace_tpu.resilience.elastic import (ElasticController, ResizePlan,
                                          implant_stale_replica, plan_resize,
                                          rejoin_barrier, replica_variants,
                                          reshard_grace_state,
                                          validate_resharded)
from grace_tpu.resilience.guard import (GUARD_ROLLBACK_EXCLUDED,
                                        GUARD_SCAN_EXCLUDED_TYPES,
                                        GuardState, guard_transform)
from grace_tpu.resilience.retune import (RetuneController, StagedPromotion,
                                         state_digest)

__all__ = ["GUARD_ROLLBACK_EXCLUDED", "GUARD_SCAN_EXCLUDED_TYPES",
           "GuardState", "guard_transform", "guarded_chain",
           "ChaosCompressor", "ChaosCommunicator", "ChaosParams",
           "ConsensusConfig", "consensus_step", "fingerprint_tree",
           "force_audit", "audit_report", "normalize_consensus",
           "ElasticController", "ResizePlan", "plan_resize",
           "reshard_grace_state", "validate_resharded", "rejoin_barrier",
           "implant_stale_replica", "replica_variants",
           "AdaptConfig", "AdaptState", "AdaptMonitor", "adapt_report",
           "normalize_adapt",
           "RetuneController", "StagedPromotion", "state_digest"]


def guarded_chain(grace, *txs: optax.GradientTransformation,
                  seed: int = 0,
                  max_norm: Optional[float] = None,
                  check_state: bool = True,
                  fallback_after: Optional[int] = None,
                  fallback_steps: Optional[int] = None
                  ) -> optax.GradientTransformation:
    """``guard_transform(optax.chain(grace.transform(seed), *txs))`` with the
    guard's cross-rank flag reduction wired to the grace mesh axis.

    ``grace`` is a :class:`~grace_tpu.helper.Grace` bundle; configure its
    ``escape`` field (e.g. ``escape='fp16'`` in ``grace_from_params``) to
    arm the dense fallback window that ``fallback_after``/``fallback_steps``
    control.
    """
    inner = optax.chain(grace.transform(seed=seed), *txs)
    # On a 2-D dp×fsdp mesh the bad-step OR must span the WHOLE mesh (a
    # tuple of axis names — lax.psum reduces over both): per-rank state
    # scans can disagree across fsdp shards too, and the fallback window
    # must open fleet-wide or the per-shard exchanges desync.
    mesh = getattr(grace, "mesh", None)
    axes = (tuple(mesh.axes) if getattr(mesh, "is_2d", False)
            else grace.communicator.axis_name)
    return guard_transform(inner,
                           max_norm=max_norm,
                           check_state=check_state,
                           fallback_after=fallback_after,
                           fallback_steps=fallback_steps,
                           axis_name=axes)
