"""graft-elastic: preemption-tolerant elastic training.

Production training runs on spot/preemptible capacity: ranks — and on
multislice pods, whole ICI slices — come and go mid-run. Every resilience
layer so far (guard, consensus repair, graft-watch early warning) assumes
the world size W is fixed for the life of the run. This module makes W a
*resizable* property, built from the pieces the stack already proved:

* **Early warning → drain** (:class:`ElasticController`): graft-watch's
  ``watch_anomaly`` records flag a degrading rank *before* it dies (PR-8's
  measured lead over guard/consensus). The controller treats repeated skew
  episodes on one rank as the pre-death signal and triggers a
  last-known-good :class:`~grace_tpu.checkpoint.Checkpointer` save while
  every rank is still alive to participate — the drain.

* **World resize → re-shard** (:func:`reshard_grace_state`): GraceState is
  two different kinds of data. The replicated fields (count, rng_key,
  fallback, audit — plus params, downstream optimizer state, and guard
  counters) are world-independent facts that carry forward **bit-exactly**
  (:func:`grace_tpu.transform.carry_replicated`). The per-rank fields
  (mem error-feedback residuals, comp compressor state, telemetry/watch
  rings) are sharded one-row-per-rank and are **re-initialized at the new
  world, never re-partitioned**: a departed rank's residual describes
  compression error *that rank's* shard stream accumulated — no surviving
  rank can inherit it without double-counting feedback, and a rejoining
  rank's residual is stale by exactly the steps it missed. Zero-and-
  re-accumulate is safe by the error-feedback contract — the PR-3 repair
  rationale, applied to the whole fleet (see IMPLEMENTING.md, "Why
  re-shard re-initializes residuals"). Compressor state is re-built by
  ``init_state`` (zeros are NOT a valid PowerSGD Q — same PR-3 argument),
  and the rings are re-allocated with their wraparound counters reset.
  The re-init is validated statically for free against flow pass 7's
  ``footprint_model`` at the new world (:func:`validate_resharded`).

* **Slice- and region-granular shrink**: under the hierarchical
  ICI×DCN[×WAN] communicator, losing a whole slice is a K→K−1 DCN-level
  resize that never touches intra-slice state, and losing a whole region
  is an R→R−1 WAN-level resize that never touches intra-region state —
  :meth:`grace_tpu.core.Topology.shrink` keeps ``slice_size`` for
  whole-slice losses, keeps both tiers for whole-region losses (dropping
  the WAN tier when a single region remains), and collapses to flat for
  partial ones; :meth:`grace_tpu.comm.HierarchicalAllreduce.shrunk`
  rebuilds the communicator to match (the WAN codec is dropped with its
  tier). A region-wide failure domain — one metro's power event taking S·K
  ranks at once — is ONE drain → resize → rejoin transition, not S·K
  independent rank losses: :meth:`ElasticController.region_scope` widens
  the drain to the whole region once a quorum of its ranks carries skew
  episodes.

* **Rejoin barrier** (:func:`rejoin_barrier`): a rank rejoining at W was
  restored from a checkpoint the fleet has since trained past — its
  replicated state is *legitimately* stale, which is exactly the fault
  class the PR-3 consensus auditor repairs. The barrier forces one ungated
  audit (:func:`grace_tpu.resilience.consensus.force_audit`) at admission:
  the rejoiner must fingerprint-match the reference replica or receive the
  bit-exact masked-broadcast repair (residuals zeroed) *before its
  gradients count*. Repairs == rejoins and bit-identical replicas after
  the barrier are the acceptance facts ``chaos_smoke --elastic`` asserts.

The wire cost of the barrier is priced like the scheduled audit's
(fingerprint exchange + any repair broadcast) and stamped into the
``elastic_rejoin`` event record, so resize events carry honest byte
accounting into the same JSONL stream as telemetry/guard/consensus events
(timeline kind ``elastic``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from grace_tpu.core import DEFAULT_AXIS, Topology
from grace_tpu.parallel import (local_world_size, replicated, shard_map)
from grace_tpu.resilience.consensus import (_tree_nbytes, audit_report,
                                            force_audit, normalize_consensus,
                                            replicated_view)
from grace_tpu.transform import (GraceState, add_world_axis,
                                 carry_replicated, partition_specs,
                                 strip_world_axis)

__all__ = ["ResizePlan", "plan_resize", "reshard_grace_state",
           "validate_resharded", "rejoin_barrier", "implant_stale_replica",
           "replica_variants", "ElasticController"]


def _is_grace(x) -> bool:
    return isinstance(x, GraceState)


def _reinit_adapt(carried_tree, fresh_tree):
    """Swap the carried graft-adapt policy state for the fresh init's —
    the one replicated GraceState field a world resize deliberately does
    NOT carry (see :func:`reshard_grace_state`)."""

    def graft(carried, fresh):
        if _is_grace(carried):
            return carried._replace(adapt=fresh.adapt)
        return carried

    return jax.tree_util.tree_map(graft, carried_tree, fresh_tree,
                                  is_leaf=_is_grace)


def _grace_world(tree) -> Optional[int]:
    """Leading world-axis extent of the first per-rank GraceState leaf in
    ``tree`` (global layout), or None when no sized per-rank leaf exists."""
    worlds: List[int] = []

    def visit(node):
        if _is_grace(node):
            for leaf in jax.tree_util.tree_leaves(
                    (node.mem, node.comp, node.telem, node.watch)):
                if hasattr(leaf, "shape") and len(leaf.shape) >= 1:
                    worlds.append(int(leaf.shape[0]))
        return node

    jax.tree_util.tree_map(visit, tree, is_leaf=_is_grace)
    return worlds[0] if worlds else None


# ---------------------------------------------------------------------------
# resize planning
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ResizePlan:
    """One world resize, decided before any state is touched.

    ``survivors`` are old-world rank indices in ascending order — the new
    world's rank k is old rank ``survivors[k]`` (contiguous renumbering,
    the layout :meth:`Topology.shrink` prices; for whole-region losses the
    renumbering is region-granular — every surviving region carries its
    ranks across intact). ``topology`` is the surviving link layout:
    whole-slice losses keep ``slice_size`` (K→K−1), whole-region losses
    keep both tiers (R→R−1; the WAN tier is dropped when one region
    remains), partial losses collapse to flat.
    """

    old_world: int
    new_world: int
    lost_ranks: Tuple[int, ...]
    survivors: Tuple[int, ...]
    topology: Topology
    whole_slices: bool
    whole_regions: bool = False


def plan_resize(world: int, lost_ranks,
                topology: Optional[Topology] = None) -> ResizePlan:
    """Plan the W→W′ resize that removes ``lost_ranks``.

    Pure decision logic — validates the loss against the link layout
    (:meth:`Topology.shrink`) and fixes the survivor renumbering; no
    device state is touched until :func:`reshard_grace_state` executes
    the plan.
    """
    topo = topology if topology is not None else Topology()
    lost = tuple(sorted(set(int(r) for r in lost_ranks)))
    new_topo, new_world = topo.shrink(world, lost)
    lost_set = set(lost)
    survivors = tuple(r for r in range(world) if r not in lost_set)
    whole = (topo.slice_size is not None
             and new_topo.slice_size == topo.slice_size)
    whole_regions = False
    if lost and topo.region_size is not None and world % topo.region_size == 0:
        rz = topo.region_size
        touched = sorted({r // rz for r in lost})
        whole_regions = all(rho * rz + i in lost_set
                            for rho in touched for i in range(rz))
    return ResizePlan(old_world=world, new_world=new_world,
                      lost_ranks=lost, survivors=survivors,
                      topology=new_topo, whole_slices=whole,
                      whole_regions=whole_regions)


# ---------------------------------------------------------------------------
# the re-shard
# ---------------------------------------------------------------------------

def reshard_grace_state(state, optimizer, old_mesh, new_mesh,
                        axis_name: str = DEFAULT_AXIS):
    """Re-shard a global train state from ``old_mesh``'s world to
    ``new_mesh``'s.

    ``state`` is a :class:`~grace_tpu.train.TrainState` /
    :class:`~grace_tpu.train.StatefulTrainState` (or any NamedTuple with
    ``params`` [, ``model_state``] and ``opt_state``) in the global layout
    ``init_train_state`` builds. ``optimizer`` is the optax chain for the
    NEW world — rebuild the grace transform for the post-resize topology
    first (that rebuild is also the wire model's single invalidation
    point; see ``grace_transform(topology=...)``).

    Replicated data — params, model state, non-grace optimizer state,
    guard counters, and the replicated GraceState fields — carries forward
    **bit-exactly** onto the new mesh. Per-rank GraceState data (mem /
    comp / telem / watch) is **re-initialized at the new world** by the
    new transform's own ``init`` (residuals zeroed, compressor state
    freshly built — zeros are not a valid PowerSGD Q —, rings re-allocated
    with step counters reset), never re-partitioned. Validate the result
    against the static footprint model with :func:`validate_resharded`.
    """
    from grace_tpu.train import init_opt_state

    old_world = local_world_size(old_mesh, axis_name)
    new_world = local_world_size(new_mesh, axis_name)
    state_world = _grace_world(state.opt_state)
    if state_world is not None and state_world != old_world:
        raise ValueError(
            f"reshard_grace_state: the state's per-rank GraceState leaves "
            f"carry world axis {state_world} but old_mesh has "
            f"{old_world} ranks on '{axis_name}' — pass the mesh the state "
            "was built on (states built without init_train_state lack the "
            "global world axis entirely).")

    def put(x):
        return jax.device_put(np.asarray(x), replicated(new_mesh))

    params = jax.tree_util.tree_map(put, jax.device_get(state.params))
    fresh_opt = init_opt_state(params, optimizer, new_mesh, axis_name)
    # Only the replicated payload of the old state crosses the resize —
    # strip the per-rank fields BEFORE the host transfer so a large
    # residual set at old W is never fetched just to be discarded.
    old_light = jax.device_get(replicated_view(state.opt_state))
    new_opt = carry_replicated(old_light, fresh_opt, convert=put)
    # graft-adapt policy state is replicated, so carry_replicated grafted
    # the OLD controller across — but its windowed signal statistics and
    # operating rung were learned at the old world's error profile (a
    # W-rank mean/peak is not a W'-rank mean/peak), so the resize
    # re-initializes it from the NEW transform's init: the ladder
    # restarts at its configured start rung, robustness-first, exactly
    # like the re-zeroed residuals.
    new_opt = _reinit_adapt(new_opt, fresh_opt)
    fields: Dict[str, Any] = {"params": params, "opt_state": new_opt}
    if hasattr(state, "model_state"):
        fields["model_state"] = jax.tree_util.tree_map(
            put, jax.device_get(state.model_state))
    return type(state)(**fields)


def validate_resharded(state, grace_or_tx, params, world: int) -> dict:
    """Check a (re-)sharded state against flow pass 7's ``footprint_model``
    at ``world`` — the same static model graft-lint's ``memory_footprint``
    pass audits configs with and the profiling recorder's live check uses,
    so re-init correctness is checked by machinery that exists anyway.

    Raises ``ValueError`` naming the first component (mem/comp/telem)
    whose live bytes disagree with the model — the signature of a state
    initialized at the wrong world or under a different codec/fusion
    config. Returns ``{"live", "model", "matches": True}`` on success.
    """
    from grace_tpu.analysis.flow import footprint_model
    from grace_tpu.profiling import grace_state_footprint

    live = grace_state_footprint(state)
    model = footprint_model(grace_or_tx, params, world=world)
    bad = {k: (live[k], model[k])
           for k in ("mem_bytes", "comp_bytes", "telem_bytes")
           if live[k] != model[k]}
    if bad:
        detail = ", ".join(f"{k}: live {lv} != model {mv}"
                           for k, (lv, mv) in sorted(bad.items()))
        raise ValueError(
            f"re-sharded GraceState does not match the static footprint "
            f"model at world {world} ({detail}) — the state was "
            "re-initialized at a different world or under a different "
            "codec/fusion/telemetry config than the one being validated.")
    return {"live": live, "model": model, "matches": True}


# ---------------------------------------------------------------------------
# the rejoin barrier
# ---------------------------------------------------------------------------

def barrier_wire_bytes(state, consensus, world: int) -> Dict[str, int]:
    """Static wire price of one rejoin barrier at ``world``: the
    fingerprint exchange every rank pays, and the repair broadcast paid
    only when a rejoiner diverges — the same two terms the scheduled
    audit folds into ``audit_bytes``, surfaced here so resize events
    carry honest byte accounting."""
    config = normalize_consensus(consensus)
    fp = int(world) * 2 * config.segments * 4
    if hasattr(state, "opt_state"):
        tree = ((state.params, state.model_state, state.opt_state)
                if hasattr(state, "model_state")
                else (state.params, state.opt_state))
    else:
        tree = state
    return {"fingerprint_bytes": fp,
            "repair_bytes": _tree_nbytes(replicated_view(tree))}


def rejoin_barrier(state, consensus, mesh,
                   axis_name: str = DEFAULT_AXIS, check: bool = True):
    """Admission gate for a world grown back to W: force one consensus
    audit over ``state`` on ``mesh``, repairing any rank whose replicated
    state (typically the rejoiner's, restored from a pre-departure
    checkpoint) diverges from the reference replica. Returns
    ``(state, report)`` where ``report`` is the post-barrier
    :func:`~grace_tpu.resilience.consensus.audit_report` extended with
    ``replica_variants`` (max distinct byte patterns over params replicas
    — 1 == bit-identical) and the barrier's wire pricing.

    ``check=True`` raises if replicas are still not bit-identical after
    the repair — a rejoiner the masked broadcast could not reconcile must
    not be admitted to the next collective.
    """
    from jax.sharding import PartitionSpec as P

    config = normalize_consensus(consensus)
    if config is None:
        raise ValueError("rejoin_barrier requires an armed consensus "
                         "config — the fingerprint audit IS the gate.")
    has_model = hasattr(state, "model_state")

    def device_step(st):
        opt = strip_world_axis(st.opt_state)
        if has_model:
            params, mstate, opt = force_audit(
                (st.params, st.model_state, opt), config, axis_name)
            return type(st)(params, mstate, add_world_axis(opt))
        params, opt = force_audit((st.params, opt), config, axis_name)
        return type(st)(params, add_world_axis(opt))

    specs = partition_specs(state, axis_name)
    fn = jax.jit(shard_map(device_step, mesh=mesh, in_specs=(specs,),
                           out_specs=specs, check_vma=False))
    pre_repairs = audit_report(state).get("repairs", 0)
    new_state = fn(state)
    report = dict(audit_report(new_state))
    # The barrier's own repair count — audit_report is cumulative over the
    # run, and a fleet that already self-healed earlier must not make a
    # clean rejoin look repaired (repairs == rejoins is the acceptance
    # identity chaos_smoke asserts on exactly this field).
    report["barrier_repairs"] = report.get("repairs", 0) - pre_repairs
    report["replica_variants"] = replica_variants(new_state.params)
    report.update(barrier_wire_bytes(
        new_state, config, local_world_size(mesh, axis_name)))
    if check and report["replica_variants"] > 1:
        raise RuntimeError(
            "rejoin barrier failed: params replicas still hold "
            f"{report['replica_variants']} distinct byte patterns after "
            "the forced audit — the rejoining rank must not be admitted. "
            f"(report: {report})")
    return new_state, report


def replica_variants(tree) -> int:
    """Max over leaves of the number of distinct per-device byte patterns
    — 1 means every replica is bit-identical (the post-barrier
    invariant). Only counts leaves that expose addressable shards."""
    worst = 1
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if not shards:
            continue
        worst = max(worst, len({np.asarray(s.data).tobytes()
                                for s in shards}))
    return worst


def implant_stale_replica(state, rank: int, stale_params):
    """Overwrite device ``rank``'s replica of every params leaf with the
    values from ``stale_params`` — the rejoin simulation primitive (the
    ChaosParams mechanics, aimed at staleness instead of bitflips): in a
    real elastic run the rejoining process restores yesterday's checkpoint
    and joins the collective; in a single-process simulation this builds
    exactly that divergence, which :func:`rejoin_barrier` must repair."""
    leaves, treedef = jax.tree_util.tree_flatten(state.params)
    stale_leaves = jax.tree_util.tree_leaves(stale_params)
    if len(leaves) != len(stale_leaves):
        raise ValueError(
            f"stale params have {len(stale_leaves)} leaves but the live "
            f"state has {len(leaves)} — restore the stale checkpoint into "
            "the same params structure first.")
    out = []
    for live, stale in zip(leaves, stale_leaves):
        shards = list(live.addressable_shards)
        if rank >= len(shards):
            raise ValueError(
                f"implant_stale_replica(rank={rank}) but the leaf has only "
                f"{len(shards)} addressable shards — params must be "
                "replicated with one shard per device.")
        stale_np = np.asarray(jax.device_get(stale))
        bufs = []
        for si, s in enumerate(shards):
            data = stale_np if si == rank else np.array(s.data)
            bufs.append(jax.device_put(data, s.device))
        out.append(jax.make_array_from_single_device_arrays(
            live.shape, live.sharding, bufs))
    return state._replace(params=jax.tree_util.tree_unflatten(treedef, out))


# ---------------------------------------------------------------------------
# the host-loop controller
# ---------------------------------------------------------------------------

class ElasticController:
    """Host-side orchestrator of the drain → resize → rejoin lifecycle.

    Wires the existing layers together without owning any of them: feed it
    the ``watch_anomaly`` records the
    :class:`~grace_tpu.telemetry.anomaly.WatchMonitor` emits
    (:meth:`observe`), and it elects a drain candidate once one rank
    accumulates ``anomaly_threshold`` skew episodes — the pre-death signal
    a degrading-but-alive rank gives before guard or consensus ever react.
    :meth:`drain` saves the last-known-good checkpoint while the fleet is
    whole; :meth:`resize` executes a :class:`ResizePlan` via
    :func:`reshard_grace_state` + :func:`validate_resharded`; and
    :meth:`rejoin` runs the consensus-gated admission barrier. Every
    transition is appended to :attr:`events` and — when ``sink`` is set —
    emitted as an ``elastic_drain`` / ``elastic_resize`` /
    ``elastic_rejoin`` record into the same JSONL stream as telemetry,
    guard, and consensus events (timeline kind ``elastic``).

    When the controller knows the fleet's link layout (``topology`` with a
    ``region_size``), a region-wide skew episode — a metro-level network
    or power event degrading every rank behind one WAN boundary at once —
    is recognized by :meth:`region_scope` and handled as ONE drain →
    resize → rejoin transition over the whole region, not ``region_size``
    independent rank losses (every rank in the scope is marked drained,
    so later threshold crossings inside the same region are absorbed).

    The drain's checkpoint save runs under a bounded watchdog when
    ``drain_timeout_s`` is set: a stalled checkpoint backend must not
    wedge the drain while the flagged rank keeps degrading, so each stall
    emits an ``elastic_drain_timeout`` record, retries with doubled
    timeout up to ``drain_retries`` extra attempts, and finally proceeds
    with the last known good checkpoint already on disk.
    """

    def __init__(self, *, consensus=None, checkpointer=None, sink=None,
                 anomaly_threshold: int = 2,
                 anomaly_metrics=("compression_error", "residual_norm"),
                 topology: Optional[Topology] = None,
                 region_quorum: float = 0.5,
                 drain_timeout_s: Optional[float] = None,
                 drain_retries: int = 1,
                 axis_name: str = DEFAULT_AXIS):
        self.consensus = normalize_consensus(consensus) \
            if consensus not in (None, False) else None
        self.checkpointer = checkpointer
        self.sink = sink
        self.anomaly_threshold = int(anomaly_threshold)
        # Only codec-health skews count toward the drain signal by default:
        # grad_norm skews are real data heterogeneity on fixed shards (the
        # chaos_smoke --watch misattribution rationale), not a dying rank.
        self.anomaly_metrics = tuple(anomaly_metrics)
        self.topology = topology
        if not (0.0 < float(region_quorum) <= 1.0):
            raise ValueError(f"region_quorum must be in (0, 1]; "
                             f"got {region_quorum}")
        self.region_quorum = float(region_quorum)
        if drain_timeout_s is not None and float(drain_timeout_s) <= 0:
            raise ValueError(f"drain_timeout_s must be positive; "
                             f"got {drain_timeout_s}")
        self.drain_timeout_s = (float(drain_timeout_s)
                                if drain_timeout_s is not None else None)
        if int(drain_retries) < 0:
            raise ValueError(f"drain_retries must be >= 0; "
                             f"got {drain_retries}")
        self.drain_retries = int(drain_retries)
        self.axis_name = axis_name
        self.events: List[dict] = []
        self.episodes: Dict[int, int] = {}
        self.drained_ranks: set = set()

    def _emit(self, event: str, step: int, **payload) -> dict:
        rec = {"event": event, "step": int(step), **payload}
        self.events.append(rec)
        if self.sink is not None:
            self.sink.write(rec)
        return rec

    # -- early warning ------------------------------------------------------
    def observe(self, step: int, anomalies) -> Optional[int]:
        """Feed new ``watch_anomaly`` dicts; returns the rank to drain the
        first time one rank's skew-episode count crosses the threshold
        (None otherwise — call :meth:`drain` with the returned rank)."""
        for a in anomalies or ():
            if a.get("kind") != "skew":
                continue
            if (self.anomaly_metrics
                    and a.get("metric") not in self.anomaly_metrics):
                continue
            rank = a.get("rank")
            if rank is None or int(rank) < 0:
                continue
            rank = int(rank)
            self.episodes[rank] = self.episodes.get(rank, 0) + 1
            if (self.episodes[rank] >= self.anomaly_threshold
                    and rank not in self.drained_ranks):
                self.drained_ranks.add(rank)
                return rank
        return None

    def region_scope(self, rank: int) -> Tuple[int, ...]:
        """The drain scope the flagged rank implies: the whole region's
        rank tuple when the controller knows a region layout and at least
        ``region_quorum`` of the region's ranks carry skew episodes (ONE
        failing domain — drain once, resize R→R−1), else ``(rank,)``."""
        rank = int(rank)
        topo = self.topology
        if topo is None or getattr(topo, "region_size", None) is None:
            return (rank,)
        rz = int(topo.region_size)
        rho = rank // rz
        members = tuple(range(rho * rz, (rho + 1) * rz))
        hot = sum(1 for m in members if self.episodes.get(m, 0) > 0)
        need = max(1, int(np.ceil(self.region_quorum * rz)))
        return members if hot >= need else (rank,)

    # -- lifecycle ----------------------------------------------------------
    def _drain_checkpoint(self, step: int, state) -> Tuple[bool, int]:
        """Save+wait the last-known-good checkpoint under a watchdog.

        Returns ``(checkpointed, timeouts)``. With ``drain_timeout_s``
        unset the save blocks indefinitely (the pre-region behavior).
        With it set, each attempt gets a bounded window; a stall emits an
        ``elastic_drain_timeout`` record and retries with doubled timeout
        (backoff) up to ``drain_retries`` extra attempts before giving up
        and proceeding with the last known good checkpoint on disk. The
        stalled attempt's thread is a daemon — a wedged backend is left
        behind, never joined on the drain path.
        """
        def attempt():
            self.checkpointer.save(step, state, force=True, good=True)
            self.checkpointer.wait()

        if self.drain_timeout_s is None:
            attempt()
            return True, 0

        import threading
        timeout = self.drain_timeout_s
        timeouts = 0
        for trial in range(self.drain_retries + 1):
            done = threading.Event()
            errs: List[BaseException] = []

            def run():
                try:
                    attempt()
                except BaseException as e:   # noqa: BLE001 — re-raised below
                    errs.append(e)
                finally:
                    done.set()

            threading.Thread(target=run, daemon=True).start()
            if done.wait(timeout):
                if errs:
                    raise errs[0]
                return True, timeouts
            timeouts += 1
            last_good = None
            if hasattr(self.checkpointer, "last_good_step"):
                try:
                    last_good = self.checkpointer.last_good_step()
                except Exception:
                    last_good = None
            self._emit("elastic_drain_timeout", step, attempt=trial + 1,
                       timeout_s=float(timeout),
                       retries_left=self.drain_retries - trial,
                       last_good_step=last_good)
            timeout *= 2.0
        return False, timeouts

    def drain(self, step: int, state, rank: int, scope=None) -> dict:
        """Pre-death drain: save the last-known-good checkpoint while the
        flagged scope is still participating, so the resize restores from
        a state every healthy rank agreed on. ``scope`` widens the drain
        beyond the flagged rank (pass :meth:`region_scope`'s result for
        region-wide episodes); every rank in it is marked drained so the
        same failing domain never triggers a second transition."""
        scope = (tuple(int(r) for r in scope)
                 if scope is not None else (int(rank),))
        self.drained_ranks.update(scope)
        checkpointed, timeouts = (self._drain_checkpoint(step, state)
                                  if self.checkpointer is not None
                                  else (False, 0))
        return self._emit("elastic_drain", step, rank=int(rank),
                          scope=list(scope),
                          episodes=self.episodes.get(int(rank), 0),
                          checkpointed=checkpointed,
                          drain_timeouts=timeouts)

    def resize(self, step: int, state, optimizer, old_mesh, new_mesh,
               plan: ResizePlan, grace=None, params=None) -> Tuple[Any,
                                                                   dict]:
        """Execute a resize plan: re-shard onto ``new_mesh`` and (when
        ``grace`` and ``params`` are given) validate the re-init against
        the static footprint model at the new world."""
        new_state = reshard_grace_state(state, optimizer, old_mesh,
                                        new_mesh, self.axis_name)
        footprint_ok = None
        if grace is not None and params is not None:
            footprint_ok = validate_resharded(
                new_state, grace, params, plan.new_world)["matches"]
        event = self._emit(
            "elastic_resize", step,
            old_world=plan.old_world, new_world=plan.new_world,
            lost_ranks=list(plan.lost_ranks),
            slice_size=plan.topology.slice_size,
            region_size=plan.topology.region_size,
            whole_slices=plan.whole_slices,
            whole_regions=plan.whole_regions,
            footprint_matches=footprint_ok)
        return new_state, event

    def rejoin(self, step: int, state, mesh) -> Tuple[Any, dict]:
        """Run the consensus-gated rejoin barrier over the grown world."""
        if self.consensus is None:
            raise ValueError("ElasticController.rejoin needs an armed "
                             "consensus config (the fingerprint audit IS "
                             "the admission gate).")
        new_state, report = rejoin_barrier(state, self.consensus, mesh,
                                           self.axis_name)
        self._emit("elastic_rejoin", step, **{
            k: report[k] for k in ("repairs", "barrier_repairs", "audits",
                                   "last_divergent_rank",
                                   "replica_variants",
                                   "fingerprint_bytes", "repair_bytes")})
        return new_state, report
