"""In-graph non-finite step guard with error-feedback rollback.

Error-feedback compression makes training *stateful*: a NaN/Inf that reaches
a residual memory (``GraceState.mem``) is re-injected by ``compensate`` on
every later step, so one bad batch permanently poisons EF-SignSGD/DGC/TopK
runs. The GRACE reference has no defense, and ``optax.apply_if_finite`` is
structurally unable to provide one here:

* it inspects each rank's **local, pre-exchange** gradients — poison that
  arrives *through the exchange* (another rank's payload, or overflow born
  inside the codec arithmetic) is invisible to it, yet lands in this rank's
  residual via ``memory.update``;
* worse, under SPMD a local check can **disagree across ranks** (only the
  faulty rank sees its NaN before the collective), so ranks would take
  different branches around a collective — divergent state at best, a
  collective deadlock at worst;
* it knows nothing of ``GraceState``: it cannot re-route the exchange
  through a dense path, and it cannot coordinate the rollback of residuals
  with the rollback of downstream optimizer state.

:func:`guard_transform` instead wraps the **whole** optax chain (grace
transform + optimizer) and checks the **post-exchange** update pytree —
which is rank-identical by construction, because the collective already
mixed every rank's payload. On a bad step the entire inner state (params
via zeroed updates, optimizer state, and every GraceState mem/comp leaf)
rolls back **atomically** with ``jnp.where`` selects, so residuals never
absorb a poisoned compensation. All of it is traced into the jitted step —
no host round-trip, usable inside ``shard_map``.

Degradation policy: ``fallback_after`` (K) consecutive bad steps flip the
``fallback`` flag inside every GraceState (see
:func:`grace_tpu.transform.set_fallback_flag`), routing the next
``fallback_steps`` (M) exchanges through the dense escape hatch configured
via ``grace_transform(escape=...)``; afterwards compression re-arms.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

from grace_tpu.telemetry.aggregate import WatchState
from grace_tpu.telemetry.state import TelemetryState
from grace_tpu.transform import set_fallback_flag

__all__ = ["GuardState", "guard_transform", "GUARD_ROLLBACK_EXCLUDED",
           "GUARD_SCAN_EXCLUDED_TYPES"]

# The declared rollback-exclusion contract, introspectable instead of
# living in comments: state leaves whose path contains one of these
# segments are *deliberately* written through on a bad step rather than
# restored bitwise by the rollback selects. The first five are the guard's
# own bookkeeping (GuardState counters — recording the bad step IS their
# job), and ``fallback`` is the GraceState degradation flag
# ``set_fallback_flag`` writes AFTER the rollback (routing the next
# exchange dense is a forward decision, not rolled-back history). Every
# other state leaf — params, optimizer state, every GraceState mem/comp/
# telem/watch/count/rng_key/audit/adapt leaf — must be covered by a
# rollback select, which is exactly what graft-sound's
# ``rollback_coverage`` pass proves at trace time.
GUARD_ROLLBACK_EXCLUDED = ("notfinite_count", "last_bad_step",
                           "consecutive", "fallback_remaining", "step",
                           "fallback")

# The check_state scan exclusion: the pytree node types holding the
# GraceState fields named by transform.GRACE_OBSERVATIONAL_FIELDS
# (telem -> TelemetryState, watch -> WatchState). Kept as types because the
# strip is structural; tests pin the field<->type correspondence so the
# two spellings of the one contract cannot drift.
GUARD_SCAN_EXCLUDED_TYPES = (TelemetryState, WatchState)


def _strip_telemetry(tree):
    """Drop TelemetryState and graft-watch WatchState nodes (the
    ``GRACE_OBSERVATIONAL_FIELDS`` contract — see
    :data:`GUARD_SCAN_EXCLUDED_TYPES`): both rings are *observational*
    (they record e.g. the norm — or the cross-rank skew — of a poisoned
    gradient verbatim), so their contents must never flip a step bad on
    their own — the pipeline values they mirror are already scanned
    directly. The rings still roll back with the rest of the inner state
    on a bad step, so poisoned rows never survive into a flush."""
    observational = GUARD_SCAN_EXCLUDED_TYPES
    return jax.tree_util.tree_map(
        lambda n: None if isinstance(n, observational) else n,
        tree, is_leaf=lambda n: isinstance(n, observational))


class GuardState(NamedTuple):
    inner: Any                    # wrapped chain's state (holds GraceState)
    notfinite_count: jax.Array    # int32: total skipped (bad) steps
    last_bad_step: jax.Array      # int32: step index of last bad step, -1
    consecutive: jax.Array        # int32: current run of consecutive bad steps
    fallback_remaining: jax.Array # int32: dense escape-hatch steps left
    step: jax.Array               # int32: guard-local step counter


def _nonfinite(tree) -> jax.Array:
    """Scalar bool: any non-finite value in any inexact leaf of ``tree``."""
    flags = [jnp.any(~jnp.isfinite(l))
             for l in jax.tree_util.tree_leaves(tree)
             if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.inexact)]
    if not flags:
        return jnp.zeros((), jnp.bool_)
    return jnp.stack(flags).any()


def guard_transform(inner: optax.GradientTransformation,
                    *,
                    max_norm: Optional[float] = None,
                    check_state: bool = True,
                    fallback_after: Optional[int] = None,
                    fallback_steps: Optional[int] = None,
                    axis_name: Optional[str] = None
                    ) -> optax.GradientTransformation:
    """Wrap a full optax chain with the in-graph non-finite step guard.

    Usage (the guard must wrap the WHOLE chain so grace residuals and
    downstream optimizer state roll back together)::

        tx = guard_transform(
            optax.chain(grace_transform(comp, mem, communicator,
                                        escape=FP16Compressor()),
                        optax.sgd(0.1)),
            fallback_after=3, fallback_steps=8, axis_name='data')

    A step is **bad** when the final update pytree contains NaN/Inf, when
    its global norm exceeds ``max_norm`` (if set), or — with ``check_state``
    (default) — when any inexact leaf of the *new* inner state is
    non-finite (catches poison that a saturating codec, e.g. a sign vote,
    swallowed on the wire but still wrote into a residual; telemetry rings
    are excluded — see ``_strip_telemetry``). Bad steps emit
    zero updates and keep the previous inner state bitwise; healthy steps
    pass both through bitwise-unchanged, so an uninjected guarded run is
    bit-identical to the unguarded one.

    ``axis_name``: OR-reduce the bad flag over that mesh axis. The update
    check alone is rank-identical already (post-exchange values are), but
    ``check_state`` scans per-rank residuals, which CAN disagree across
    ranks — set ``axis_name`` whenever the guard runs inside ``shard_map``
    so every rank takes the same branch.

    ``fallback_after``/``fallback_steps`` (K/M): after K consecutive bad
    steps, set the GraceState ``fallback`` flag for the next M steps. The
    flag only has an effect when the inner grace transform was built with
    ``escape=...``; it is harmless otherwise.
    """
    if (fallback_after is None) != (fallback_steps is None):
        raise ValueError("fallback_after (K) and fallback_steps (M) must be "
                         "set together")
    degrade = fallback_after is not None

    def init(params) -> GuardState:
        zero = jnp.zeros((), jnp.int32)
        return GuardState(inner=inner.init(params),
                          notfinite_count=zero,
                          last_bad_step=zero - 1,
                          consecutive=zero,
                          fallback_remaining=zero,
                          step=zero)

    def update(updates, state: GuardState, params=None):
        new_updates, new_inner = inner.update(updates, state.inner, params)

        bad = _nonfinite(new_updates)
        if max_norm is not None:
            bad = bad | (optax.global_norm(new_updates) > max_norm)
        if check_state:
            bad = bad | _nonfinite(_strip_telemetry(new_inner))
        if axis_name is not None:
            bad = lax.psum(bad.astype(jnp.int32), axis_name) > 0

        # Atomic skip: zero updates + full inner-state rollback. where(False)
        # selects the new value exactly, so healthy steps are bitwise clean.
        rolled = jax.tree_util.tree_map(
            lambda old, new: jnp.where(bad, old, new),
            state.inner, new_inner)
        out_updates = jax.tree_util.tree_map(
            lambda u: jnp.where(bad, jnp.zeros_like(u), u), new_updates)

        bad_i = bad.astype(jnp.int32)
        notfinite = state.notfinite_count + bad_i
        last_bad = jnp.where(bad, state.step, state.last_bad_step)
        consecutive = jnp.where(bad, state.consecutive + 1, 0)
        # One dense step (if any) was consumed by the update that just ran.
        active = (state.fallback_remaining > 0).astype(jnp.int32)
        remaining = state.fallback_remaining - active
        if degrade:
            trip = (consecutive >= fallback_after) & (remaining == 0)
            remaining = jnp.where(trip, fallback_steps, remaining)
            consecutive = jnp.where(trip, 0, consecutive)
        rolled = set_fallback_flag(rolled, remaining > 0)

        return out_updates, GuardState(inner=rolled,
                                       notfinite_count=notfinite,
                                       last_bad_step=last_bad,
                                       consecutive=consecutive,
                                       fallback_remaining=remaining,
                                       step=state.step + 1)

    return optax.GradientTransformation(init, update)
