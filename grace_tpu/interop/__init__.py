"""Frontend interop: run the TPU grace pipeline under foreign frameworks.

Replaces the reference's entire Horovod patch surface (SURVEY.md §2.7): where
GRACE ships a 507-line patch against Horovod 0.18.2 that threads a `grace`
object through every gradient code path, grace-tpu needs no patch — the
compressed exchange is a jitted JAX program, and frontends hand it their
gradients through a narrow numpy bridge:

* :class:`~grace_tpu.interop.bridge.GraceBridge` — framework-agnostic core:
  one flat gradient buffer in, aggregated buffer out, compression state held
  on device between steps.
* :mod:`grace_tpu.interop.torch` — ``DistributedOptimizer`` with the
  reference's API and safety semantics (hooks, ``backward_passes_per_step``,
  ``skip_synchronize``, ``zero_grad`` guard), plus
  ``broadcast_parameters`` / ``broadcast_optimizer_state``.
* :mod:`grace_tpu.interop.tensorflow` — ``DistributedGradientTape`` analog
  (import-gated; TF is optional).
"""

from grace_tpu.interop.bridge import GraceBridge

__all__ = ["GraceBridge"]
