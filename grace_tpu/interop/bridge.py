"""Framework-agnostic gradient bridge: numpy in, aggregated numpy out.

The reference's torch backend launches one async NCCL op per parameter from
inside backward hooks (grace_dl/torch/__init__.py:50-58). On TPU the whole
pipeline — compensate → compress → exchange over the mesh → decompress →
aggregate — is ONE jitted XLA program over a single fused gradient buffer
(frontend gradients are bucketed host-side anyway, so fusion is free). The
bridge owns the compression state (GraceState, world axis sharded over the
mesh, see grace_tpu/transform.py) and keeps it on device between calls.

Process model — identical to Horovod's (one process per accelerator,
SURVEY.md §2.5): under `jax.distributed`, each process contributes its local
gradient as its shard of a global ``(world, n)`` array. If a process owns
several mesh devices, its gradient is replicated across them; for
``average=True`` compressors the duplicated rows drop out of the mean, and
majority votes are unchanged (uniform duplication), so semantics match the
one-process-per-chip layout. Sum-semantics compressors with ``average=False``
would be scaled by the duplication factor — the bridge warns in that case.

The async split of the reference (`send_step` during backward /
`receive_step` at `optimizer.step`, grace_dl/torch/__init__.py:37-58) maps
to JAX dispatch: :meth:`exchange` returns immediately with a live device
array (the XLA computation runs asynchronously); :func:`numpy` / blocking
reads realise it — that is the `synchronize` point.
"""

from __future__ import annotations

import warnings
from collections import Counter
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from grace_tpu.helper import Grace
from grace_tpu.parallel import data_parallel_mesh, shard_map
from grace_tpu.transform import (add_world_axis, partition_specs,
                                 strip_world_axis)

__all__ = ["GraceBridge"]


class GraceBridge:
    """Jitted grace pipeline for one flat gradient buffer of fixed size.

    Usage (per process)::

        bridge = GraceBridge(grace_from_params({...}), n=total_grad_elems)
        agg = bridge.exchange(flat_local_grads)   # async device value
        out = np.asarray(agg)                     # blocks; aggregated grads
    """

    def __init__(self, grace: Grace, n: int, mesh: Optional[Mesh] = None,
                 seed: int = 0, dtype=jnp.float32):
        self.grace = grace
        self.n = int(n)
        self.dtype = jnp.dtype(dtype)
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.axis = grace.communicator.axis_name
        if self.axis not in self.mesh.shape:
            raise ValueError(f"mesh has no axis {self.axis!r}; "
                             f"axes: {tuple(self.mesh.shape)}")
        self.world = self.mesh.shape[self.axis]
        rows_per_proc = Counter(d.process_index
                                for d in self.mesh.devices.flat)
        self._local_rows = max(1, rows_per_proc.get(jax.process_index(), 0))
        if max(rows_per_proc.values()) > 1 and not grace.compressor.average:
            uniform = len(set(rows_per_proc.values())) == 1
            if getattr(grace.compressor, "vote_aggregate", False):
                # A *uniform* duplication factor leaves a majority vote
                # unchanged (every process casts k identical ballots, the
                # re-signed sum is scale-free). Unequal factors weight the
                # vote by local device count — warn on EVERY process, the
                # biased aggregate reaches all of them.
                if not uniform:
                    warnings.warn(
                        "GraceBridge: processes feed unequal numbers of mesh "
                        f"devices ({sorted(rows_per_proc.values())}); each "
                        "process's identical sign votes are duplicated by "
                        "its local device count, biasing the majority vote "
                        "toward larger processes. Use one process per device "
                        "for an unweighted vote.")
            else:
                warnings.warn(
                    "GraceBridge: some process feeds multiple mesh devices "
                    "and the compressor has average=False (sum semantics): "
                    "duplicated rows scale the aggregate (per-process "
                    f"duplication factors {sorted(rows_per_proc.values())}). "
                    "Use one process per device for exact sum semantics.")

        tx = grace.transform(seed=seed)
        template = jnp.zeros((self.n,), self.dtype)

        # Global-layout state: grace mem/comp leaves sharded over the axis.
        abstract = jax.eval_shape(tx.init, [template])
        specs = partition_specs(abstract, self.axis)
        init_fn = shard_map(
            lambda t: add_world_axis(tx.init([t[0]])),
            mesh=self.mesh, in_specs=(P(self.axis),), out_specs=specs,
            check_vma=False)
        self._state = jax.jit(init_fn)(
            jnp.zeros((self.world, self.n), self.dtype))

        def device_step(state, local):
            # local: this device's (1, n) row of the (world, n) gradient
            out, new_state = tx.update([local[0]], strip_world_axis(state))
            return add_world_axis(new_state), out[0]

        sharded = shard_map(
            device_step, mesh=self.mesh,
            in_specs=(specs, P(self.axis)),
            out_specs=(specs, P()),
            check_vma=False)
        self._fn = jax.jit(sharded, donate_argnums=(0,))

        def device_step_row(state, row):
            # row: the full (n,) gradient, replicated — the single-process
            # case where every "rank" carries this process's gradient. Avoids
            # materializing world× duplicated rows over the host link.
            out, new_state = tx.update([row], strip_world_axis(state))
            return add_world_axis(new_state), out[0]

        sharded_row = shard_map(
            device_step_row, mesh=self.mesh,
            in_specs=(specs, P()),
            out_specs=(specs, P()),
            check_vma=False)
        self._fn_row = jax.jit(sharded_row, donate_argnums=(0,))
        self._grad_sharding = NamedSharding(self.mesh, P(self.axis))
        self._row_sharding = NamedSharding(self.mesh, P())

    # -- wire-in ------------------------------------------------------------
    def exchange_global(self, global_grads) -> jax.Array:
        """Exchange a fully formed (world, n) gradient array (tests/power
        users: lets a single process feed distinct per-rank gradients)."""
        global_grads = jnp.asarray(global_grads, self.dtype)
        if global_grads.shape != (self.world, self.n):
            raise ValueError(f"expected ({self.world}, {self.n}), "
                             f"got {global_grads.shape}")
        self._state, out = self._fn(self._state, global_grads)
        return out

    def exchange(self, local_flat_grads: np.ndarray) -> jax.Array:
        """Start the compressed exchange for this process's gradients.

        Returns the aggregated flat gradient as a live (async) device array;
        convert with ``np.asarray`` to block — the reference's
        `receive_step`/`synchronize` point.
        """
        local = np.asarray(local_flat_grads, self.dtype)
        if local.shape != (self.n,):
            raise ValueError(f"expected flat gradients of shape ({self.n},), "
                             f"got {local.shape}")
        if jax.process_count() == 1:
            # Transfer the n-element row once; every mesh device reads the
            # same replicated row (no world× host-side duplication).
            row = jax.device_put(local, self._row_sharding)
            self._state, out = self._fn_row(self._state, row)
            return out
        rows = np.broadcast_to(local, (self._local_rows, self.n))
        global_grads = jax.make_array_from_process_local_data(
            self._grad_sharding, rows, (self.world, self.n))
        self._state, out = self._fn(self._state, global_grads)
        return out

    # -- state management ---------------------------------------------------
    @property
    def state(self):
        """Compression state (GraceState pytree, world-axis layout) — expose
        for checkpointing; the reference never persisted this (SURVEY.md §5).

        Serialize (or ``jax.device_get``) before the next :meth:`exchange`:
        the jitted step donates the previous state buffers, so a live
        reference held across an exchange is deleted."""
        return self._state

    @state.setter
    def state(self, value):
        # Fail at assignment, not at the first exchange deep inside XLA:
        # a restored checkpoint must match this bridge's state template
        # (same n, same compressor config) structurally and shape-wise.
        expect = jax.tree_util.tree_map(
            lambda x: (jnp.shape(x), jnp.result_type(x)), self._state)
        got = jax.tree_util.tree_map(
            lambda x: (jnp.shape(x), jnp.result_type(x)), value)
        if expect != got:
            raise ValueError(
                "restored grace state does not match this bridge's layout "
                f"(n={self.n}, world={self.world}); expected "
                f"{expect}, got {got}")
        self._state = value
