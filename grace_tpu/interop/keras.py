"""Keras frontend: DistributedOptimizer + callbacks + grace-aware load_model.

Analog of the reference's Keras glue (patch_files/horovod/_keras/__init__.py:
20-80 `create_distributed_optimizer`, patch_files/horovod/tensorflow/keras/
__init__.py:41-63 `DistributedOptimizer`, :121-150 `load_model`) and the
callbacks its Keras example drives (examples/tensorflow/
tensorflow2_keras_mnist.py:69-89: BroadcastGlobalVariablesCallback,
MetricAverageCallback, LearningRateWarmupCallback).

Design differences, deliberate:

* The reference intercepts the TF1-era ``get_gradients``; Keras 3 optimizers
  funnel every update through ``apply`` (``apply_gradients`` delegates to
  it), so that is the single hook point here.
* The compressed exchange itself is the same fused JAX/XLA program as every
  other frontend (one ``tf.numpy_function`` callout over a flat buffer, see
  grace_tpu/interop/tensorflow.py) — usable under ``model.fit`` graph mode.
* ``load_model`` maps optimizer class names to grace-wrapped subclasses via
  ``custom_objects``, exactly the reference's trick, so a checkpoint saved
  with a plain optimizer deserializes straight into a distributed one.
"""

from __future__ import annotations

import jax
import numpy as np

from grace_tpu.helper import Grace
from grace_tpu.interop.tensorflow import (TFExchanger, _broadcast_array,
                                          broadcast_variables)

__all__ = ["DistributedOptimizer", "load_model",
           "BroadcastGlobalVariablesCallback", "MetricAverageCallback",
           "LearningRateWarmupCallback"]


def _require_keras():
    try:
        import keras
        return keras
    except ImportError as e:  # pragma: no cover - image ships keras
        raise ImportError(
            "grace_tpu.interop.keras requires the optional keras/tensorflow "
            "dependency") from e


def _distributed_subclass(base_cls, grace: Grace, mesh, seed: int):
    """Subclass a Keras optimizer class so ``apply`` first routes gradients
    through the compressed exchange (reference: _keras/__init__.py:53-57
    overriding get_gradients)."""

    class _Distributed(base_cls):
        _grace_exchanger = None

        def apply(self, grads, trainable_variables=None):
            if self._grace_exchanger is None:
                type(self)._grace_exchanger = TFExchanger(grace, mesh=mesh,
                                                          seed=seed)
            grads = self._grace_exchanger.exchange(list(grads))
            return super().apply(grads, trainable_variables)

    _Distributed.__name__ = base_cls.__name__
    _Distributed.__qualname__ = f"Distributed{base_cls.__name__}"
    return _Distributed


def DistributedOptimizer(optimizer, grace: Grace, mesh=None, seed: int = 0):
    """Wrap a built keras optimizer in the grace exchange.

    Returns a new optimizer of a dynamic subclass of ``type(optimizer)``
    (reference: tensorflow/keras/__init__.py:41-63), reconstructed from
    ``optimizer.get_config()`` — hyperparameters, schedules and all.
    """
    keras = _require_keras()
    if not isinstance(optimizer, keras.optimizers.Optimizer):
        raise TypeError(f"expected a keras optimizer, got {type(optimizer)}")
    cls = _distributed_subclass(type(optimizer), grace, mesh, seed)
    return cls.from_config(optimizer.get_config())


def load_model(filepath, grace: Grace, mesh=None, seed: int = 0, **kwargs):
    """``keras.saving.load_model`` that revives the saved optimizer as a
    grace DistributedOptimizer (reference: tensorflow/keras/__init__.py:
    121-150).

    The reference intercepts deserialization via ``custom_objects``; Keras 3
    only consults that table for custom-registered classes, so instead the
    model is loaded normally and its optimizer is wrapped in place, with all
    restored slot state (iterations, momenta, ...) transferred so a resumed
    run continues exactly where the checkpoint left off."""
    keras = _require_keras()
    model = keras.saving.load_model(filepath, **kwargs)
    opt = getattr(model, "optimizer", None)
    if opt is not None:
        wrapped = DistributedOptimizer(opt, grace, mesh=mesh, seed=seed)
        if getattr(opt, "built", False):
            wrapped.build(model.trainable_variables)
            for src, dst in zip(opt.variables, wrapped.variables):
                dst.assign(src)
        model.optimizer = wrapped
    return model


# ---------------------------------------------------------------------------
# Callbacks (reference: examples/tensorflow/tensorflow2_keras_mnist.py:69-89)
# ---------------------------------------------------------------------------

def _callback_base():
    return _require_keras().callbacks.Callback


class BroadcastGlobalVariablesCallback(_callback_base()):
    """Sync model + optimizer variables from ``root_rank`` before training so
    all processes start from identical state."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_batch_end(self, batch, logs=None):
        # After the first step, like the reference's tape example
        # (tensorflow2_mnist.py:82-84): variables (incl. lazily created
        # optimizer slots) all exist by then.
        if not self._done:
            broadcast_variables(self.model.variables, self.root_rank)
            if self.model.optimizer is not None:
                broadcast_variables(self.model.optimizer.variables,
                                    self.root_rank)
            self._done = True


class MetricAverageCallback(_callback_base()):
    """Average epoch-end metrics over all processes (reference example line
    79: metrics computed on each worker's shard are only meaningful
    averaged). Single-process: no-op."""

    def _average(self, logs):
        if not logs or jax.process_count() == 1:
            return logs
        from jax.experimental import multihost_utils
        keys = sorted(k for k, v in logs.items()
                      if isinstance(v, (int, float, np.floating, np.integer)))
        if not keys:
            return logs
        local = np.asarray([float(logs[k]) for k in keys], np.float32)
        gathered = np.asarray(multihost_utils.process_allgather(local))
        for i, k in enumerate(keys):
            logs[k] = float(gathered[:, i].mean())
        return logs

    def on_epoch_end(self, epoch, logs=None):
        self._average(logs)


class LearningRateWarmupCallback(_callback_base()):
    """Linearly ramp the learning rate from its configured value to
    ``value x world_size`` over ``warmup_epochs`` (the large-batch warmup of
    Goyal et al., as shipped by the reference example's callback list,
    tensorflow2_keras_mnist.py:80-89), then hold the scaled rate."""

    def __init__(self, world_size: int, warmup_epochs: int = 5,
                 verbose: bool = False):
        super().__init__()
        self.world_size = int(world_size)
        self.warmup_epochs = int(warmup_epochs)
        self.verbose = verbose
        self._base_lr = None

    def on_train_begin(self, logs=None):
        self._base_lr = float(
            np.asarray(self.model.optimizer.learning_rate))

    def on_epoch_begin(self, epoch, logs=None):
        progress = min(1.0, (epoch + 1) / max(1, self.warmup_epochs))
        factor = 1.0 + (self.world_size - 1.0) * progress
        lr = self._base_lr * factor
        self.model.optimizer.learning_rate = lr
        if self.verbose:
            print(f"LearningRateWarmup: epoch {epoch}: lr -> {lr:.6g}")
