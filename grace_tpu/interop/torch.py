"""PyTorch frontend: DistributedOptimizer over the TPU grace pipeline.

API and safety semantics mirror the reference's patched Horovod optimizer
(patch_files/horovod/torch/__init__.py:46-250) — same constructor shape,
``named_parameters`` validation, ``backward_passes_per_step`` gradient
accumulation, ``synchronize``/``skip_synchronize`` protocol, ``zero_grad``
race guard — but the mechanism is TPU-native: instead of one async NCCL op
per parameter launched from per-parameter hooks, all gradients are fused
into one flat buffer and pushed through a single jitted XLA program
(:class:`~grace_tpu.interop.bridge.GraceBridge`). The hook fired by the LAST
ready gradient launches the exchange, so the XLA computation overlaps any
remaining host-side work; ``synchronize()`` blocks on the result — the same
send/receive split as grace_dl/torch/__init__.py:50-58, with one op instead
of N.

``broadcast_parameters`` / ``broadcast_optimizer_state`` replace the
reference's init-time Horovod broadcasts
(patch_files/horovod/torch/__init__.py:253-403) with
`jax.experimental.multihost_utils.broadcast_one_to_all`.
"""

from __future__ import annotations

import warnings
from contextlib import contextmanager
from typing import Iterable, Optional, Tuple

import jax
import numpy as np

from grace_tpu.helper import Grace

__all__ = ["DistributedOptimizer", "broadcast_parameters",
           "broadcast_optimizer_state"]


def _find_duplicates(names):
    seen, dups = set(), set()
    for n in names:
        if n in seen:
            dups.add(n)
        seen.add(n)
    return dups


class _DistributedOptimizer:
    """Mixin injected over the user's optimizer class (dynamic subclass,
    same trick as the reference factory, torch/__init__.py:245-250)."""

    def _grace_init(self, named_parameters, grace: Grace, mesh, seed,
                    backward_passes_per_step):
        import torch  # local import: keep grace_tpu core torch-free

        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [(f"grace.noname.{i}", v)
                                for param_group in self.param_groups
                                for i, v in enumerate(param_group["params"])]
        if any(not isinstance(p, tuple) for p in named_parameters):
            raise ValueError("named_parameters should be a sequence of "
                             "tuples (name, parameter), usually produced by "
                             "model.named_parameters().")
        dups = _find_duplicates(k for k, _ in named_parameters)
        if dups:
            raise ValueError("Parameter names in named_parameters must be "
                             "unique. Found duplicates: %s"
                             % ", ".join(sorted(dups)))
        all_ids = {id(v) for g in self.param_groups for v in g["params"]}
        named_ids = {id(v) for _, v in named_parameters}
        if all_ids - named_ids:
            raise ValueError("named_parameters was specified, but one or "
                             "more model parameters were not named.")

        # Deterministic cross-process ordering: sort by name, exactly like
        # the reference (torch/__init__.py:80-83).
        self._grace_params = [p for _, p in sorted(named_parameters)
                              if p.requires_grad]
        self._param_names = {id(p): n for n, p in named_parameters}
        self._sizes = [p.numel() for p in self._grace_params]
        self._shapes = [tuple(p.shape) for p in self._grace_params]
        n_total = sum(self._sizes)

        from grace_tpu.interop.bridge import GraceBridge
        self._bridge = GraceBridge(grace, n=n_total, mesh=mesh, seed=seed)

        self.backward_passes_per_step = backward_passes_per_step
        self._delay = {id(p): backward_passes_per_step
                       for p in self._grace_params}
        self._pending = None          # in-flight aggregated device array
        self._synchronized = False
        self._should_synchronize = True
        self._hook_handles = [
            p.register_post_accumulate_grad_hook(self._make_hook())
            for p in self._grace_params]
        self._torch = torch

    # -- backward-path machinery -------------------------------------------
    def _make_hook(self):
        def hook(p):
            if self._pending is not None:
                raise AssertionError(
                    "Gradients were computed more than "
                    "backward_passes_per_step times before call to step(). "
                    "Increase backward_passes_per_step to accumulate "
                    "gradients locally.")
            assert self._delay[id(p)] > 0
            self._delay[id(p)] -= 1
            if all(d == 0 for d in self._delay.values()):
                self._launch()
        return hook

    def _flat_grads(self) -> np.ndarray:
        torch = self._torch
        chunks = [
            (p.grad if p.grad is not None
             else torch.zeros_like(p)).detach().reshape(-1).to(torch.float32)
            for p in self._grace_params]
        return torch.cat(chunks).cpu().numpy()

    def _launch(self):
        """Start the fused exchange (async); called by the last grad hook."""
        self._pending = self._bridge.exchange(self._flat_grads())

    def synchronize(self):
        """Block on the exchange and write aggregated grads back."""
        if self._pending is None:
            self._launch()   # e.g. manual use without full backward
        # np.array (copy): torch.from_numpy needs a writable buffer, and the
        # realized jax array is read-only.
        out = np.array(self._pending)     # blocks on the XLA computation
        self._pending = None
        torch = self._torch
        off = 0
        for p, size, shape in zip(self._grace_params, self._sizes,
                                  self._shapes):
            piece = torch.from_numpy(out[off:off + size]).reshape(shape)
            if p.grad is None:
                p.grad = torch.zeros_like(p)
            p.grad.copy_(piece.to(p.grad.dtype))
            off += size
        self._delay = {id(p): self.backward_passes_per_step
                       for p in self._grace_params}
        self._synchronized = True

    def set_backward_passes_per_step(self, passes: int):
        self.backward_passes_per_step = passes
        self._delay = {k: passes for k in self._delay}

    @contextmanager
    def skip_synchronize(self):
        """Use after a manual ``synchronize()`` so ``step()`` won't redo it
        (reference protocol, torch/__init__.py:163-177)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    # -- optimizer protocol -------------------------------------------------
    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                warnings.warn(
                    "optimizer.step() called without "
                    "optimizer.skip_synchronize() context after "
                    "optimizer.synchronize(). This can cause training "
                    "slowdown. Consider the skip_synchronize() context.")
            self.synchronize()
        self._synchronized = False
        return super().step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._pending is not None or any(
                d != self.backward_passes_per_step
                for d in self._delay.values()):
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step() or optimizer.synchronize(). This is "
                "prohibited as it can cause a race condition.")
        return super().zero_grad(*args, **kwargs)

    @property
    def grace_state(self):
        """On-device compression state — include it in checkpoints."""
        return self._bridge.state

    @grace_state.setter
    def grace_state(self, value):
        self._bridge.state = value


def DistributedOptimizer(optimizer, grace: Grace, named_parameters=None,
                         backward_passes_per_step: int = 1,
                         mesh=None, seed: int = 0):
    """Wrap a ``torch.optim.Optimizer`` with compressed TPU gradient exchange.

    Drop-in for the reference's ``hvd.DistributedOptimizer(opt, grace, …)``
    (patch_files/horovod/torch/__init__.py:204-250): dynamically subclasses
    the user's optimizer class so isinstance checks and attribute access keep
    working, then rebinds the instance.
    """
    cls = type(optimizer.__class__.__name__, (_DistributedOptimizer,
                                              optimizer.__class__), {})
    optimizer.__class__ = cls
    optimizer._grace_init(named_parameters, grace, mesh, seed,
                          backward_passes_per_step)
    return optimizer


# ---------------------------------------------------------------------------
# Init-time state synchronisation (reference: torch/__init__.py:253-403)
# ---------------------------------------------------------------------------

def _broadcast_array(x: np.ndarray, root_rank: int) -> np.ndarray:
    from jax.experimental import multihost_utils
    if jax.process_count() == 1:
        return x
    return np.asarray(multihost_utils.broadcast_one_to_all(
        x, is_source=jax.process_index() == root_rank))


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast ``model.state_dict()`` (or (name, tensor) iterable) from
    ``root_rank`` to all processes, in place."""
    import torch
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = sorted(params)
    for _, t in items:
        if not isinstance(t, torch.Tensor):
            continue
        synced = _broadcast_array(t.detach().cpu().numpy(), root_rank)
        with torch.no_grad():
            t.copy_(torch.from_numpy(np.array(synced)).to(t.dtype))


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Broadcast optimizer state (incl. scalar hyperparameters) from
    ``root_rank``. Scalars travel as 0-d arrays and are restored to their
    original Python types — the reference needed 120 lines of type-callback
    machinery for this (torch/__init__.py:330-403)."""
    import torch
    state = optimizer.state_dict()

    def sync(v):
        if isinstance(v, torch.Tensor):
            out = _broadcast_array(v.detach().cpu().numpy(), root_rank)
            return torch.from_numpy(np.array(out)).to(v.dtype)
        if isinstance(v, bool):
            return bool(_broadcast_array(np.asarray(int(v)), root_rank))
        if isinstance(v, (int, float)):
            out = _broadcast_array(np.asarray(v), root_rank)
            return type(v)(out)
        if isinstance(v, dict):
            return {k: sync(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return type(v)(sync(x) for x in v)
        return v   # non-numeric config (str/None): assumed identical

    optimizer.load_state_dict(sync(state))
