"""PyTorch frontend: DistributedOptimizer over the TPU grace pipeline.

API and safety semantics mirror the reference's patched Horovod optimizer
(patch_files/horovod/torch/__init__.py:46-250) — same constructor shape,
``named_parameters`` validation, ``backward_passes_per_step`` gradient
accumulation, ``synchronize``/``skip_synchronize`` protocol, ``zero_grad``
race guard — but the mechanism is TPU-native: gradients are fused into
flat buckets, each pushed through one jitted XLA program
(:class:`~grace_tpu.interop.bridge.GraceBridge`).

Backward overlap (VERDICT round-3 weak item 5): the reference's per-
parameter async NCCL sends overlap communication with the rest of
backward (patch_files/horovod/torch/__init__.py:118-141). Here the same
overlap comes from *bucketing*: parameters are walked in reverse
registration order (autograd fires post-accumulate hooks roughly
last-layer-first — the DDP heuristic) and packed into contiguous
``bucket_cap_mb`` buckets; the hook that fills a bucket dispatches that
bucket's exchange immediately, so its XLA program runs while autograd is
still producing earlier layers' gradients. Buckets always launch in
bucket order (a filled bucket waits for its predecessors), keeping the
collective order identical on every process. ``synchronize()`` drains
them in order — the same send/receive split as
grace_dl/torch/__init__.py:50-58, with ~n/bucket_cap ops instead of n.
``bucket_cap_mb=None`` restores the single fused launch-at-last-hook.

``broadcast_parameters`` / ``broadcast_optimizer_state`` replace the
reference's init-time Horovod broadcasts
(patch_files/horovod/torch/__init__.py:253-403) with
`jax.experimental.multihost_utils.broadcast_one_to_all`.
"""

from __future__ import annotations

import warnings
from contextlib import contextmanager
from typing import Iterable, Optional, Tuple

import jax
import numpy as np

from grace_tpu.helper import Grace

__all__ = ["DistributedOptimizer", "broadcast_parameters",
           "broadcast_optimizer_state"]


def _find_duplicates(names):
    seen, dups = set(), set()
    for n in names:
        if n in seen:
            dups.add(n)
        seen.add(n)
    return dups


class _DistributedOptimizer:
    """Mixin injected over the user's optimizer class (dynamic subclass,
    same trick as the reference factory, torch/__init__.py:245-250)."""

    def _grace_init(self, named_parameters, grace: Grace, mesh, seed,
                    backward_passes_per_step, bucket_cap_mb):
        import torch  # local import: keep grace_tpu core torch-free

        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [(f"grace.noname.{i}", v)
                                for param_group in self.param_groups
                                for i, v in enumerate(param_group["params"])]
        if any(not isinstance(p, tuple) for p in named_parameters):
            raise ValueError("named_parameters should be a sequence of "
                             "tuples (name, parameter), usually produced by "
                             "model.named_parameters().")
        dups = _find_duplicates(k for k, _ in named_parameters)
        if dups:
            raise ValueError("Parameter names in named_parameters must be "
                             "unique. Found duplicates: %s"
                             % ", ".join(sorted(dups)))
        all_ids = {id(v) for g in self.param_groups for v in g["params"]}
        named_ids = {id(v) for _, v in named_parameters}
        if all_ids - named_ids:
            raise ValueError("named_parameters was specified, but one or "
                             "more model parameters were not named.")

        # Deterministic cross-process ordering. The reference sorts by name
        # (torch/__init__.py:80-83) purely for determinism; bucketing wants
        # *reverse registration* order instead, so buckets fill contiguously
        # as autograd fires hooks last-layer-first. model.named_parameters()
        # yields registration order identically on every process, which is
        # the same guarantee the name-sort provided.
        self._grace_params = [p for _, p in reversed(named_parameters)
                              if p.requires_grad]
        self._param_names = {id(p): n for n, p in named_parameters}
        self._sizes = [p.numel() for p in self._grace_params]
        self._shapes = [tuple(p.shape) for p in self._grace_params]

        # Contiguous buckets of <= bucket_cap_mb f32 bytes (None = one
        # bucket, the fused launch-at-last-hook mode).
        cap = (float("inf") if not bucket_cap_mb
               else float(bucket_cap_mb) * 2**20)
        buckets, cur, cur_bytes = [], [], 0
        for p in self._grace_params:
            if cur and cur_bytes + p.numel() * 4 > cap:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(p)
            cur_bytes += p.numel() * 4
        if cur:
            buckets.append(cur)
        self._buckets = buckets
        self._bucket_of = {id(p): bi for bi, b in enumerate(buckets)
                           for p in b}

        from grace_tpu.interop.bridge import GraceBridge
        # seed + bi: distinct rng streams per bucket, identical across
        # processes (rank-consistent compression needs only the latter).
        self._bridges = [
            GraceBridge(grace, n=sum(p.numel() for p in b), mesh=mesh,
                        seed=seed + bi)
            for bi, b in enumerate(buckets)]

        self.backward_passes_per_step = backward_passes_per_step
        self._delay = {id(p): backward_passes_per_step
                       for p in self._grace_params}
        self._bucket_left = [len(b) for b in buckets]
        self._pending_b = [None] * len(buckets)
        self._next_launch = 0
        self._synchronized = False
        self._should_synchronize = True
        self._hook_handles = [
            p.register_post_accumulate_grad_hook(self._make_hook())
            for p in self._grace_params]
        self._torch = torch

    # -- backward-path machinery -------------------------------------------
    @property
    def _pending(self):
        """In-flight aggregated device arrays, or None if none launched."""
        live = [p for p in self._pending_b if p is not None]
        return live or None

    def _make_hook(self):
        def hook(p):
            if self._delay[id(p)] <= 0:
                raise AssertionError(
                    "Gradients were computed more than "
                    "backward_passes_per_step times before call to step(). "
                    "Increase backward_passes_per_step to accumulate "
                    "gradients locally.")
            self._delay[id(p)] -= 1
            if self._delay[id(p)] == 0:
                bi = self._bucket_of[id(p)]
                self._bucket_left[bi] -= 1
                if self._bucket_left[bi] == 0:
                    self._launch_ready()
        return hook

    def _flat_grads(self, bi: int) -> np.ndarray:
        torch = self._torch
        chunks = [
            (p.grad if p.grad is not None
             else torch.zeros_like(p)).detach().reshape(-1).to(torch.float32)
            for p in self._buckets[bi]]
        return torch.cat(chunks).cpu().numpy()

    def _launch_ready(self):
        """Dispatch every full not-yet-launched bucket, strictly in bucket
        order: the collective sequence must be identical on all processes
        even if autograd's hook order differs, so a filled bucket waits for
        its predecessors rather than jumping the queue."""
        while (self._next_launch < len(self._buckets)
               and self._bucket_left[self._next_launch] == 0):
            bi = self._next_launch
            self._pending_b[bi] = self._bridges[bi].exchange(
                self._flat_grads(bi))
            self._next_launch += 1

    def synchronize(self):
        """Block on the exchanges and write aggregated grads back."""
        for bi in range(len(self._buckets)):
            if self._pending_b[bi] is None:   # manual use w/o full backward
                self._pending_b[bi] = self._bridges[bi].exchange(
                    self._flat_grads(bi))
        torch = self._torch
        for bi, bucket in enumerate(self._buckets):
            # np.array (copy): torch.from_numpy needs a writable buffer,
            # and the realized jax array is read-only.
            out = np.array(self._pending_b[bi])   # blocks on this bucket
            self._pending_b[bi] = None
            off = 0
            for p in bucket:
                size, shape = p.numel(), tuple(p.shape)
                piece = torch.from_numpy(out[off:off + size]).reshape(shape)
                if p.grad is None:
                    p.grad = torch.zeros_like(p)
                p.grad.copy_(piece.to(p.grad.dtype))
                off += size
        self._delay = {id(p): self.backward_passes_per_step
                       for p in self._grace_params}
        self._bucket_left = [len(b) for b in self._buckets]
        self._next_launch = 0
        self._synchronized = True

    def set_backward_passes_per_step(self, passes: int):
        if self._pending is not None or any(
                d != self.backward_passes_per_step
                for d in self._delay.values()):
            # Resetting the counters here would let the next backward
            # re-launch over the in-flight buckets, silently dropping their
            # aggregated gradients and double-advancing residual state.
            raise AssertionError(
                "set_backward_passes_per_step() called with gradients in "
                "flight; call synchronize() or step() first.")
        self.backward_passes_per_step = passes
        self._delay = {k: passes for k in self._delay}
        self._bucket_left = [len(b) for b in self._buckets]
        self._next_launch = 0

    @contextmanager
    def skip_synchronize(self):
        """Use after a manual ``synchronize()`` so ``step()`` won't redo it
        (reference protocol, torch/__init__.py:163-177)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    # -- optimizer protocol -------------------------------------------------
    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                warnings.warn(
                    "optimizer.step() called without "
                    "optimizer.skip_synchronize() context after "
                    "optimizer.synchronize(). This can cause training "
                    "slowdown. Consider the skip_synchronize() context.")
            self.synchronize()
        self._synchronized = False
        return super().step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._pending is not None or any(
                d != self.backward_passes_per_step
                for d in self._delay.values()):
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step() or optimizer.synchronize(). This is "
                "prohibited as it can cause a race condition.")
        return super().zero_grad(*args, **kwargs)

    @property
    def grace_state(self):
        """On-device compression state, one entry per bucket — include it
        in checkpoints."""
        return tuple(b.state for b in self._bridges)

    @grace_state.setter
    def grace_state(self, value):
        if len(self._bridges) == 1 and not isinstance(value, (tuple, list)):
            value = (value,)          # round-3 single-bucket checkpoints
        if len(value) != len(self._bridges):
            raise ValueError(f"grace_state has {len(value)} entries for "
                             f"{len(self._bridges)} buckets")
        for b, v in zip(self._bridges, value):
            b.state = v


def DistributedOptimizer(optimizer, grace: Grace, named_parameters=None,
                         backward_passes_per_step: int = 1,
                         mesh=None, seed: int = 0,
                         bucket_cap_mb: Optional[float] = 32.0):
    """Wrap a ``torch.optim.Optimizer`` with compressed TPU gradient exchange.

    Drop-in for the reference's ``hvd.DistributedOptimizer(opt, grace, …)``
    (patch_files/horovod/torch/__init__.py:204-250): dynamically subclasses
    the user's optimizer class so isinstance checks and attribute access keep
    working, then rebinds the instance. ``bucket_cap_mb`` controls the
    backward-overlap bucketing (module docstring); ``None`` = one fused
    bucket launched at the last gradient hook.
    """
    cls = type(optimizer.__class__.__name__, (_DistributedOptimizer,
                                              optimizer.__class__), {})
    optimizer.__class__ = cls
    optimizer._grace_init(named_parameters, grace, mesh, seed,
                          backward_passes_per_step, bucket_cap_mb)
    return optimizer


# ---------------------------------------------------------------------------
# Init-time state synchronisation (reference: torch/__init__.py:253-403)
# ---------------------------------------------------------------------------

def _broadcast_array(x: np.ndarray, root_rank: int) -> np.ndarray:
    from jax.experimental import multihost_utils
    if jax.process_count() == 1:
        return x
    return np.asarray(multihost_utils.broadcast_one_to_all(
        x, is_source=jax.process_index() == root_rank))


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast ``model.state_dict()`` (or (name, tensor) iterable) from
    ``root_rank`` to all processes, in place."""
    import torch
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = sorted(params)
    for _, t in items:
        if not isinstance(t, torch.Tensor):
            continue
        synced = _broadcast_array(t.detach().cpu().numpy(), root_rank)
        with torch.no_grad():
            t.copy_(torch.from_numpy(np.array(synced)).to(t.dtype))


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """Broadcast optimizer state (incl. scalar hyperparameters) from
    ``root_rank``. Scalars travel as 0-d arrays and are restored to their
    original Python types — the reference needed 120 lines of type-callback
    machinery for this (torch/__init__.py:330-403)."""
    import torch
    state = optimizer.state_dict()

    def sync(v):
        if isinstance(v, torch.Tensor):
            out = _broadcast_array(v.detach().cpu().numpy(), root_rank)
            return torch.from_numpy(np.array(out)).to(v.dtype)
        if isinstance(v, bool):
            return bool(_broadcast_array(np.asarray(int(v)), root_rank))
        if isinstance(v, (int, float)):
            out = _broadcast_array(np.asarray(v), root_rank)
            return type(v)(out)
        if isinstance(v, dict):
            return {k: sync(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return type(v)(sync(x) for x in v)
        return v   # non-numeric config (str/None): assumed identical

    optimizer.load_state_dict(sync(state))
