"""TensorFlow 2 frontend: DistributedGradientTape over the TPU pipeline.

Analog of the reference's patched `hvd.DistributedGradientTape(tape, grace)`
(patch_files/horovod/tensorflow/__init__.py:314-365): wrap a `tf.GradientTape`
so `tape.gradient(...)` returns globally aggregated, compressed-exchanged
gradients. TF is an optional dependency — everything here is import-gated,
but when TF is installed (as in this image) the full path is live and tested
(tests/test_interop.py, examples/tf2_mnist.py).

Execution model: the reference's TF2 patch runs GRACE ops *inside* the TF
graph (SURVEY.md §3.2). Here the compressed exchange is a jitted JAX/XLA
program on the TPU mesh; it embeds into TF graphs as a single host callout
(`tf.numpy_function`) over one fused flat gradient buffer — so the wrapper
works both eagerly and inside `@tf.function` / `model.fit`. The per-tensor
graph-op plumbing of the reference collapses into one bucketed exchange,
exactly like the torch frontend (grace_tpu/interop/torch.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from grace_tpu.helper import Grace

__all__ = ["DistributedGradientTape", "TFExchanger", "broadcast_variables",
           "exchanger_for"]


def _require_tf():
    try:
        import tensorflow as tf
        return tf
    except ImportError as e:  # pragma: no cover - image ships TF
        raise ImportError(
            "grace_tpu.interop.tensorflow requires the optional tensorflow "
            "dependency") from e


class TFExchanger:
    """Embeds the jitted grace exchange into TF graphs.

    Flattens a gradient list into one fp32 buffer in-graph, routes it through
    a lazily constructed :class:`GraceBridge` via ``tf.numpy_function`` (a
    stateful host callout, legal under ``@tf.function``), and splits the
    aggregated result back to the original shapes/dtypes. ``IndexedSlices``
    are densified first — same behavior as the reference's dense allreduce
    branch (patch_files/horovod/tensorflow/__init__.py:37-77).
    """

    def __init__(self, grace: Grace, mesh=None, seed: int = 0):
        self._grace = grace
        self._mesh = mesh
        self._seed = seed
        self._bridge = None
        self._pending_state = None   # restored state queued until build

    def _host_exchange(self, flat: np.ndarray) -> np.ndarray:
        from grace_tpu.interop.bridge import GraceBridge
        if self._bridge is None or self._bridge.n != flat.size:
            self._bridge = GraceBridge(self._grace, n=flat.size,
                                       mesh=self._mesh, seed=self._seed)
            if self._pending_state is not None:
                self._bridge.state = self._pending_state
                self._pending_state = None
        return np.asarray(self._bridge.exchange(flat), np.float32)

    @property
    def grace_state(self):
        """On-device compression state (None before the first exchange) —
        include it in checkpoints; assign to restore. Restoring before the
        first exchange is queued and applied when the bridge is built."""
        if self._bridge is None:
            return self._pending_state
        return self._bridge.state

    @grace_state.setter
    def grace_state(self, value):
        if self._bridge is None:
            self._pending_state = value
        else:
            self._bridge.state = value

    def exchange(self, grads):
        """list of tf.Tensor/IndexedSlices/None -> same-structure aggregated."""
        tf = _require_tf()
        dense = [None if g is None else tf.convert_to_tensor(g)
                 for g in grads]
        live = [g for g in dense if g is not None]
        if not live:
            return list(grads)
        sizes = [int(np.prod(g.shape)) for g in live]
        n = int(sum(sizes))
        flat = tf.concat(
            [tf.reshape(tf.cast(g, tf.float32), [-1]) for g in live], axis=0)
        out = tf.numpy_function(self._host_exchange, [flat], tf.float32,
                                stateful=True)
        out = tf.ensure_shape(out, [n])
        pieces = tf.split(out, sizes)
        results, it = [], iter(zip(live, pieces))
        for g in dense:
            if g is None:
                results.append(None)
            else:
                orig, piece = next(it)
                results.append(tf.cast(tf.reshape(piece, orig.shape),
                                       orig.dtype))
        return results


_EXCHANGERS: dict = {}   # id(grace) -> (weakref(grace), {(mesh, seed): ex})


def _shared_exchanger(grace: Grace, mesh, seed: int) -> TFExchanger:
    """One TFExchanger per Grace *instance* (per mesh/seed), process-wide.

    The reference idiom wraps the tape anew every training step
    (examples/tensorflow/tensorflow2_mnist.py:71); a per-wrap exchanger
    would rebuild its GraceBridge each step — recompiling the jitted
    exchange AND resetting error-feedback state. Sharing keeps residuals/
    momenta alive across steps exactly like the reference's process-lifetime
    Memory dicts.

    Keyed by object identity, not equality: two independently built Grace
    configs compare equal (frozen dataclasses), but each user-constructed
    bundle carries its own error-feedback state — one Grace per model, as in
    the reference where state lives in the user's communicator object. A
    weakref finalizer evicts entries when the Grace is garbage-collected, so
    sweeping many configs in one process doesn't pin model-sized residual
    buffers forever.

    ``mesh=None`` is normalized to the default data-parallel mesh, so
    ``exchanger_for(grc)`` finds the exchanger of a tape built with an
    explicit-but-equal mesh (Mesh equality is by devices+axes) instead of
    silently creating a fresh one.
    """
    if mesh is None:
        from grace_tpu.parallel import data_parallel_mesh
        mesh = data_parallel_mesh()
    key = id(grace)
    entry = _EXCHANGERS.get(key)
    if entry is None or entry[0]() is not grace:   # new object or id reuse
        import weakref
        ref = weakref.ref(grace, lambda _, k=key: _EXCHANGERS.pop(k, None))
        entry = _EXCHANGERS[key] = (ref, {})
    sub = entry[1]
    ex = sub.get((mesh, seed))
    if ex is None:
        ex = sub[(mesh, seed)] = TFExchanger(grace, mesh=mesh, seed=seed)
    return ex


def exchanger_for(grace: Grace, mesh=None, seed: int = 0) -> TFExchanger:
    """The process-wide exchanger a DistributedGradientTape with these
    arguments uses — access its ``grace_state`` for checkpoint/resume of the
    compression state (see TRAINING.md)."""
    return _shared_exchanger(grace, mesh, seed)


def DistributedGradientTape(gradtape, grace: Grace, mesh=None, seed: int = 0):
    """Wrap ``tf.GradientTape`` so ``gradient()`` returns aggregated grads."""
    _require_tf()
    exchanger = _shared_exchanger(grace, mesh, seed)

    class _Wrapped(type(gradtape)):
        def __init__(self):
            self.__dict__.update(gradtape.__dict__)
            self._grace = grace
            self._exchanger = exchanger

        def gradient(self, target, sources, output_gradients=None):
            # tf.GradientTape.gradient mirrors the structure of `sources`:
            # a lone tensor source yields a lone gradient, not a list.
            single = not isinstance(sources, (list, tuple))
            grads = super().gradient(target, sources, output_gradients)
            if single:
                grads = [grads]
            results = self._exchanger.exchange(list(grads))
            return results[0] if single else results

    wrapped = _Wrapped.__new__(_Wrapped)
    _Wrapped.__init__(wrapped)
    return wrapped


# ---------------------------------------------------------------------------
# Init-time variable sync (reference: BroadcastGlobalVariablesHook /
# examples/tensorflow/tensorflow2_mnist.py:82-84)
# ---------------------------------------------------------------------------

def _broadcast_array(x: np.ndarray, root_rank: int) -> np.ndarray:
    from jax.experimental import multihost_utils
    if jax.process_count() == 1:
        return x
    return np.asarray(multihost_utils.broadcast_one_to_all(
        x, is_source=jax.process_index() == root_rank))


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Broadcast TF/Keras variables from ``root_rank`` to all processes,
    in place. Single-process: no-op (already consistent)."""
    _require_tf()
    for v in variables:
        synced = _broadcast_array(np.asarray(v), root_rank)
        v.assign(synced.reshape(v.shape))
