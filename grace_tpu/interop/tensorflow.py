"""TensorFlow 2 frontend: DistributedGradientTape over the TPU pipeline.

Analog of the reference's patched `hvd.DistributedGradientTape(tape, grace)`
(patch_files/horovod/tensorflow/__init__.py:314-365): wrap a `tf.GradientTape`
so `tape.gradient(...)` returns globally aggregated, compressed-exchanged
gradients. The mechanism is the same numpy bridge as the torch frontend —
TF is an optional dependency (import-gated; this image ships without it).

Note the execution model difference from the reference: the TF2 patch runs
GRACE ops *inside* the TF graph (SURVEY.md §3.2); here the exchange runs in
JAX/XLA on the TPU mesh and the TF side only sees numpy values, so this
wrapper must be used in eager mode (no @tf.function around the exchange).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from grace_tpu.helper import Grace

__all__ = ["DistributedGradientTape"]


def DistributedGradientTape(gradtape, grace: Grace, mesh=None, seed: int = 0):
    """Wrap ``tf.GradientTape`` so ``gradient()`` returns aggregated grads."""
    try:
        import tensorflow as tf  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "grace_tpu.interop.tensorflow requires the optional tensorflow "
            "dependency, which is not installed in this environment."
        ) from e

    from grace_tpu.interop.bridge import GraceBridge

    class _Wrapped(type(gradtape)):
        def __init__(self):
            self.__dict__.update(gradtape.__dict__)
            self._grace = grace
            self._bridge = None
            self._mesh = mesh
            self._seed = seed

        def gradient(self, target, sources, output_gradients=None):
            # tf.GradientTape.gradient mirrors the structure of `sources`:
            # a lone tensor source yields a lone gradient, not a list.
            single = not isinstance(sources, (list, tuple))
            grads = super().gradient(target, sources, output_gradients)
            if single:
                grads = [grads]
            flats, shapes, sizes, dtypes = [], [], [], []
            for g in grads:
                arr = np.zeros(0, np.float32) if g is None else \
                    np.asarray(tf.convert_to_tensor(g), np.float32).ravel()
                flats.append(arr)
                shapes.append(None if g is None else tuple(g.shape))
                dtypes.append(None if g is None else g.dtype)
                sizes.append(arr.size)
            flat = np.concatenate(flats) if flats else np.zeros(0, np.float32)
            if self._bridge is None:
                self._bridge = GraceBridge(self._grace, n=flat.size,
                                           mesh=self._mesh, seed=self._seed)
            out = np.asarray(self._bridge.exchange(flat))
            results, off = [], 0
            for shape, size, dtype in zip(shapes, sizes, dtypes):
                if shape is None:
                    results.append(None)
                else:
                    results.append(tf.constant(
                        out[off:off + size].reshape(shape), dtype=dtype))
                off += size
            return results[0] if single else results

    wrapped = _Wrapped.__new__(_Wrapped)
    _Wrapped.__init__(wrapped)
    return wrapped
