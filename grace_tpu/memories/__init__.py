"""Error-feedback memories as explicit state pytrees.

Reference: grace_dl/dist/memory/*.py — name-keyed dicts of residual buffers
mutated in place. Here each memory is a frozen dataclass whose per-leaf
state is returned functionally, so the whole pipeline jits and the state
checkpoints with orbax alongside the parameters (the reference silently
resets error feedback on resume; SURVEY.md §5 checkpoint row).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import DEFAULT_AXIS, Compressor, Ctx, Memory, Payload, State

__all__ = ["NoneMemory", "ResidualMemory", "EFSignSGDMemory", "DgcMemory",
           "PowerSGDMemory"]


@dataclasses.dataclass(frozen=True)
class NoneMemory(Memory):
    """No-op memory (grace_dl/dist/memory/none.py:4-11)."""


@dataclasses.dataclass(frozen=True)
class ResidualMemory(Memory):
    """Classic error feedback (grace_dl/dist/memory/residual.py:4-20).

    compensate: ``β·residual + γ·grad``; update: ``residual = compensated −
    decompress(payload)``. First step has zero residual (reference: dict-miss
    path returns the raw tensor, equivalent since β·0 + γ·g = γ·g... the
    reference actually skips the γ scaling on the miss; with the default
    γ=1.0 the behaviors coincide, and for γ≠1 a uniformly-scaled first step
    is the saner semantics).

    ``state_dtype`` (TPU-first extension, no reference analog): store the
    residual in a narrower dtype than the gradients — ``'bfloat16'``
    halves the largest per-step state tensor's HBM traffic (102 MB → 51 MB
    on a fused ResNet-50 buffer). The rounding error this introduces goes
    through the same feedback loop that already absorbs the compression
    error (identical argument to Top-K's ``wire_dtype='bfloat16'``).
    Compensate math still runs in the gradient dtype. A non-f32 state
    automatically takes the staged pipeline (the fused Pallas gate
    rejects it).
    """

    beta: float = 1.0
    gamma: float = 1.0
    state_dtype: str | None = None   # None = gradient dtype

    def __post_init__(self):
        if self.state_dtype is not None:
            jnp.dtype(self.state_dtype)   # fail fast on a typo

    @property
    def linear_feedback_coeffs(self):
        """Declares ``compensate = beta*state + gamma*x`` with
        ``update = compensated - decompress`` — the contract the
        Communicator.step fused fast path (core.py) relies on."""
        return (self.beta, self.gamma)

    def init_state(self, x: jax.Array) -> State:
        dt = self.state_dtype or jnp.result_type(x)
        return jnp.zeros(jnp.shape(x), dt)

    def compensate(self, x: jax.Array, state: State):
        return self.beta * state.astype(x.dtype) + self.gamma * x, state

    def update(self, compensated: jax.Array, payload: Payload, ctx: Ctx,
               compressor: Compressor, state: State) -> State:
        resid = compensated - compressor.decompress(payload, ctx)
        return resid.astype(state.dtype)


@dataclasses.dataclass(frozen=True)
class EFSignSGDMemory(Memory):
    """EF-SignSGD memory (grace_dl/dist/memory/efsignsgd.py:4-19).

    compensate: ``residual + lr·grad`` — the lr scaling is undone by the
    paired compressor's aggregate (÷lr).
    """

    lr: float = 0.1

    @property
    def linear_feedback_coeffs(self):
        """``compensate = 1.0*state + lr*x`` (see ResidualMemory)."""
        return (1.0, self.lr)

    def init_state(self, x: jax.Array) -> State:
        return jnp.zeros_like(x)

    def compensate(self, x: jax.Array, state: State):
        return state + self.lr * x, state

    def update(self, compensated: jax.Array, payload: Payload, ctx: Ctx,
               compressor: Compressor, state: State) -> State:
        return compensated - compressor.decompress(payload, ctx)


@dataclasses.dataclass(frozen=True)
class DgcMemory(Memory):
    """DGC momentum-corrected memory (grace_dl/dist/memory/dgc.py:7-39).

    compensate: optional global-norm gradient clipping (the all-reduce of the
    squared sum becomes ``lax.psum`` over the mesh axis), then momentum
    accumulation ``u = m·u + g`` and gradient accumulation ``v = v + u``.
    update: zero both accumulators at the transmitted coordinates. The
    transmitted mask is reconstructed from the payload's (values, indices) —
    the reference smuggles it through ctx (dgc.py:42) which would break the
    replicated-ctx contract here.
    """

    momentum: float = 0.9
    gradient_clipping: bool = False
    axis_name: str = DEFAULT_AXIS

    def init_state(self, x: jax.Array) -> State:
        return {"residual": jnp.zeros_like(x), "gradient": jnp.zeros_like(x)}

    def compensate(self, x: jax.Array, state: State):
        if self.gradient_clipping:
            sq_sum = lax.psum(jnp.sum(x * x), self.axis_name)
            w = lax.psum(1, self.axis_name)
            clip = jnp.sqrt(sq_sum / w)
            x = jnp.clip(x, -clip, clip)
        residual = self.momentum * state["residual"] + x
        gradient = state["gradient"] + residual
        return gradient, {"residual": residual, "gradient": gradient}

    def update(self, compensated: jax.Array, payload: Payload, ctx: Ctx,
               compressor: Compressor, state: State) -> State:
        # Zero accumulators at transmitted lanes. Layout-agnostic: unsent
        # (and zero-valued) lanes decompress to exactly 0, so the mask needs
        # no knowledge of the compressor's ctx tuple.
        keep = (compressor.decompress(payload, ctx) == 0).astype(
            compensated.dtype)
        return {"residual": state["residual"] * keep,
                "gradient": state["gradient"] * keep}


@dataclasses.dataclass(frozen=True)
class PowerSGDMemory(Memory):
    """PowerSGD error feedback (grace_dl/dist/memory/powersgd.py:6-37).

    Holds only the residual; the Q factor lives in the compressor's own
    state (see grace_tpu/compressors/powersgd.py for why the reference's
    shared ``q_memory`` dict coupling is dissolved). 1-D tensors bypass
    (reference compensate lines 14-15).
    """

    def init_state(self, x: jax.Array) -> State:
        return None if x.ndim <= 1 else jnp.zeros_like(x)

    def compensate(self, x: jax.Array, state: State):
        if state is None:
            return x, state
        return x + state, state

    def update(self, compensated: jax.Array, payload: Payload, ctx: Ctx,
               compressor: Compressor, state: State) -> State:
        if state is None:
            return state
        return compensated - compressor.decompress(payload, ctx)
