"""grace-tpu: TPU-native gradient compression for data-parallel training.

A ground-up JAX/XLA re-design of the GRACE framework (sands-lab/grace): the
Communicator / Compressor / Memory decomposition of compressed data-parallel
training, the full algorithm catalog, and drop-in optax integration — with
collectives over named TPU mesh axes instead of NCCL/MPI, pure jitted codecs
instead of stateful per-tensor Python, and explicit state pytrees instead of
name-keyed dicts. See SURVEY.md at the repo root for the full mapping to the
reference.
"""

from grace_tpu.core import Communicator, Compressor, Memory
from grace_tpu.comm import (Allgather, Allreduce, Broadcast,
                            HierarchicalAllreduce, Identity, RingAllreduce,
                            SignAllreduce, TwoShotAllreduce,
                            masked_broadcast)
from grace_tpu.helper import Grace, grace_from_params
from grace_tpu.resilience import (ChaosCommunicator, ChaosCompressor,
                                  ChaosParams, ConsensusConfig, GuardState,
                                  audit_report, consensus_step,
                                  guard_transform, guarded_chain)
from grace_tpu.telemetry import (JSONLSink, MultiSink, TelemetryConfig,
                                 TelemetryReader, TelemetryState,
                                 TensorBoardSink, trace_stage)
from grace_tpu.transform import GraceState, grace_transform
from grace_tpu.train import (TrainState, init_train_state, make_eval_step,
                             make_train_step)
from grace_tpu.parallel import data_parallel_mesh, make_mesh

__version__ = "0.1.0"

__all__ = [
    "Communicator", "Compressor", "Memory",
    "Allreduce", "Allgather", "Broadcast", "Identity", "SignAllreduce",
    "TwoShotAllreduce", "RingAllreduce", "HierarchicalAllreduce",
    "Grace", "grace_from_params", "grace_transform", "GraceState",
    "GuardState", "guard_transform", "guarded_chain",
    "ChaosCompressor", "ChaosCommunicator", "ChaosParams",
    "ConsensusConfig", "consensus_step", "audit_report", "masked_broadcast",
    "TelemetryConfig", "TelemetryState", "TelemetryReader",
    "JSONLSink", "TensorBoardSink", "MultiSink", "trace_stage",
    "TrainState", "init_train_state", "make_train_step", "make_eval_step",
    "data_parallel_mesh", "make_mesh",
    "__version__",
]
