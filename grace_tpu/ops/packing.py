"""Bit-packing primitives for sub-byte wire payloads.

The reference ships sign masks as one uint8 per sign
(grace_dl/dist/compressor/signsgd.py:16) and has a 2-bit packing helper only
in its TF backend (grace_dl/tensorflow/compressor/packing.py). On TPU the
wire (ICI/DCN) win only materialises if we actually pack, so grace-tpu packs
1-bit masks 8/byte and 2-bit codes 4/byte everywhere, with pure jnp bitwise
ops that XLA fuses into the surrounding codec.

All functions are shape-polymorphic at trace time only via the static
``n`` argument (XLA needs static shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def pack_widths():
    """The declared (bits-per-code, pack, unpack) contract of this module:
    every packer here must round-trip codes up to ``2**bits - 1`` and emit
    exactly ``ceil(n*bits/8)`` bytes. The static auditor's numeric-safety
    pass (:mod:`grace_tpu.analysis.flow`) verifies the declaration against
    the live functions whenever an audited codec ships a sub-byte packed
    payload — a widened code or a narrowed pack is a lint error, not a
    silently corrupted wire word. A function so a new packer added here is
    automatically under audit the moment it joins the tuple.

    The 1-bit entry is signsgd/signum's sign mask, the 2-bit entry
    terngrad-style codes (and QSGD/homoqsgd at ``quantum_num <= 1``), the
    3-bit entry the LSB-first bitstream QSGD/homoqsgd ship at
    ``quantum_num <= 3``, the 4-bit entry QSGD's sub-byte wire format
    (``quantum_num <= 7``: two's-complement nibbles, low nibble first) —
    the widths the fused Pallas compress-and-pack kernels
    (:mod:`grace_tpu.ops.pallas_quant`) emit directly, so the kernels'
    wire layout is pinned to these reference packers by the bit-identity
    tests AND re-audited here on every lint run."""
    return ((1, pack_bits, unpack_bits), (2, pack_2bit, unpack_2bit),
            (3, pack_3bit, unpack_3bit), (4, pack_4bit, unpack_4bit))


def pack_bits(bits: jax.Array) -> jax.Array:
    """Pack a 1-D boolean/0-1 array into uint8, 8 values per byte (LSB first)."""
    n = bits.shape[0]
    nbytes = _ceil_div(n, 8)
    padded = jnp.zeros((nbytes * 8,), jnp.uint8).at[:n].set(bits.astype(jnp.uint8))
    lanes = padded.reshape(nbytes, 8)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    # Lanes occupy disjoint bits, so a sum equals the bitwise OR.
    return jnp.sum(lanes << shifts, axis=1, dtype=jnp.uint8)


def unpack_bits(packed: jax.Array, n: int) -> jax.Array:
    """Inverse of :func:`pack_bits`; returns a bool array of length ``n``."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (packed[:, None] >> shifts) & jnp.uint8(1)
    return bits.reshape(-1)[:n].astype(jnp.bool_)


def pack_2bit(codes: jax.Array) -> jax.Array:
    """Pack a 1-D array of 2-bit codes (values 0..3) into uint8, 4 per byte."""
    n = codes.shape[0]
    nbytes = _ceil_div(n, 4)
    padded = jnp.zeros((nbytes * 4,), jnp.uint8).at[:n].set(codes.astype(jnp.uint8))
    lanes = padded.reshape(nbytes, 4)
    shifts = jnp.arange(0, 8, 2, dtype=jnp.uint8)
    return jnp.sum(lanes << shifts, axis=1, dtype=jnp.uint8)


def unpack_2bit(packed: jax.Array, n: int) -> jax.Array:
    """Inverse of :func:`pack_2bit`; returns uint8 codes of length ``n``."""
    shifts = jnp.arange(0, 8, 2, dtype=jnp.uint8)
    codes = (packed[:, None] >> shifts) & jnp.uint8(3)
    return codes.reshape(-1)[:n]


def pack_3bit(codes: jax.Array) -> jax.Array:
    """Pack a 1-D array of 3-bit codes (values 0..7) into uint8 —
    ``ceil(3n/8)`` bytes, LSB-first bitstream: bit ``b`` of code ``l``
    lands at global bit ``3l + b``, and bit ``k`` of byte ``j`` is global
    bit ``8j + k``. Unlike the power-of-two widths, 3-bit codes straddle
    byte boundaries, so the layout is defined on the bitstream (not on
    shifted lanes within one byte) — which is exactly what keeps the
    declared ``ceil(n*bits/8)`` byte-count contract exact at every
    length."""
    n = codes.shape[0]
    nbytes = _ceil_div(3 * n, 8)
    shifts = jnp.arange(3, dtype=jnp.uint8)
    bits = ((codes.astype(jnp.uint8)[:, None] >> shifts)
            & jnp.uint8(1)).reshape(-1)
    padded = jnp.zeros((nbytes * 8,), jnp.uint8).at[:3 * n].set(bits)
    lanes = padded.reshape(nbytes, 8)
    byte_shifts = jnp.arange(8, dtype=jnp.uint8)
    return jnp.sum(lanes << byte_shifts, axis=1, dtype=jnp.uint8)


def unpack_3bit(packed: jax.Array, n: int) -> jax.Array:
    """Inverse of :func:`pack_3bit`; returns uint8 codes of length ``n``."""
    byte_shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = ((packed[:, None] >> byte_shifts) & jnp.uint8(1)).reshape(-1)
    trip = bits[:3 * n].reshape(n, 3)
    shifts = jnp.arange(3, dtype=jnp.uint8)
    return jnp.sum(trip << shifts, axis=1, dtype=jnp.uint8)


def pack_4bit(codes: jax.Array) -> jax.Array:
    """Pack a 1-D array of 4-bit codes (values 0..15) into uint8, 2 per
    byte (low nibble first — the layout the fused Pallas quantize-and-pack
    kernel emits)."""
    n = codes.shape[0]
    nbytes = _ceil_div(n, 2)
    padded = jnp.zeros((nbytes * 2,), jnp.uint8).at[:n].set(
        codes.astype(jnp.uint8))
    lanes = padded.reshape(nbytes, 2)
    shifts = jnp.arange(0, 8, 4, dtype=jnp.uint8)
    return jnp.sum(lanes << shifts, axis=1, dtype=jnp.uint8)


def unpack_4bit(packed: jax.Array, n: int) -> jax.Array:
    """Inverse of :func:`pack_4bit`; returns uint8 codes of length ``n``."""
    shifts = jnp.arange(0, 8, 4, dtype=jnp.uint8)
    codes = (packed[:, None] >> shifts) & jnp.uint8(15)
    return codes.reshape(-1)[:n]
