"""Shared sparse-codec primitive: scatter (values, indices) into a dense tensor.

The reference repeats this scatter in every sparsifying compressor
(e.g. grace_dl/dist/compressor/topk.py:14-18 `desparsify`); here it is the
one shared implementation used by topk/randomk/threshold/dgc/adaq.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def scatter_dense(values: jax.Array, indices: jax.Array, numel: int,
                  shape: tuple) -> jax.Array:
    """Place ``values`` at flat ``indices`` of a zero tensor of ``shape``.

    Fixed-capacity payloads rely on invalid lanes carrying value 0, which a
    scatter-set writes harmlessly (every index is in range; duplicates do
    not occur by construction — top_k/permutation indices are unique).
    """
    flat = jnp.zeros((numel,), values.dtype).at[indices].set(values)
    return flat.reshape(shape)


def chunkwise_dense(values: jax.Array, win_row: jax.Array, rows: int,
                    numel: int, shape: tuple) -> jax.Array:
    """Scatter-free dense build for chunk-structured sparsity.

    For payloads where exactly one element per column of the (rows, k)
    row-major view of the flat tensor is kept (TopKCompressor
    ``algorithm='chunk'``), the dense tensor is a one-hot row-select per
    column — a single fused elementwise comparison instead of a scatter.
    TPU scatter serializes (measured: it dominates the Top-K pipeline on a
    25.5M-element fused gradient); this build is pure VPU work at the same
    O(n) cost as one elementwise pass.

    ``values``/``win_row`` have length k; element c lands at flat index
    ``win_row[c] * k + c``. Padding columns introduced at compress time
    carry value 0, so rows*k > numel overhang truncates harmlessly.
    """
    mask = jnp.arange(rows, dtype=win_row.dtype)[:, None] == win_row[None, :]
    dense = jnp.where(mask, values[None, :], jnp.zeros((), values.dtype))
    return dense.reshape(-1)[:numel].reshape(shape)
