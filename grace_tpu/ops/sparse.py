"""Shared sparse-codec primitive: scatter (values, indices) into a dense tensor.

The reference repeats this scatter in every sparsifying compressor
(e.g. grace_dl/dist/compressor/topk.py:14-18 `desparsify`); here it is the
one shared implementation used by topk/randomk/threshold/dgc/adaq.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def scatter_dense(values: jax.Array, indices: jax.Array, numel: int,
                  shape: tuple) -> jax.Array:
    """Place ``values`` at flat ``indices`` of a zero tensor of ``shape``.

    Fixed-capacity payloads rely on invalid lanes carrying value 0, which a
    scatter-set writes harmlessly (every index is in range; duplicates do
    not occur by construction — top_k/permutation indices are unique).
    """
    flat = jnp.zeros((numel,), values.dtype).at[indices].set(values)
    return flat.reshape(shape)
