import os
import warnings

from grace_tpu.ops.packing import (pack_2bit, pack_bits, unpack_2bit,
                                   unpack_bits)
from grace_tpu.ops.sparse import scatter_dense

__all__ = ["pack_bits", "unpack_bits", "pack_2bit", "unpack_2bit",
           "scatter_dense", "pallas_disabled"]


def pallas_disabled(explicit: bool = False) -> bool:
    """Operational escape hatch: GRACE_DISABLE_PALLAS forces every Pallas
    kernel off (set by tools/tpu_watch.sh when the on-chip smoke test
    fails) so a Mosaic compile failure cannot take down a whole run.
    Warns when it defeats an explicit ``use_pallas=True`` — a forgotten
    export would otherwise turn the kernel equivalence tests into vacuous
    staged-vs-staged comparisons. Conventional false spellings ('', '0',
    'false', 'no', 'off') mean NOT disabled."""
    if os.environ.get("GRACE_DISABLE_PALLAS", "").strip().lower() in (
            "", "0", "false", "no", "off"):
        return False
    if explicit:
        warnings.warn("GRACE_DISABLE_PALLAS is set: overriding explicit "
                      "use_pallas=True; Pallas kernels will NOT run",
                      RuntimeWarning, stacklevel=3)
    return True
