import os
import warnings

from grace_tpu.ops.packing import (pack_2bit, pack_3bit, pack_4bit,
                                   pack_bits, unpack_2bit, unpack_3bit,
                                   unpack_4bit, unpack_bits)
from grace_tpu.ops.sparse import scatter_dense

__all__ = ["pack_bits", "unpack_bits", "pack_2bit", "unpack_2bit",
           "pack_3bit", "unpack_3bit", "pack_4bit", "unpack_4bit",
           "scatter_dense", "pallas_disabled", "pallas_mode"]


def _env_true(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() not in (
        "", "0", "false", "no", "off")


def pallas_disabled(explicit: bool = False, kernel: str = "") -> bool:
    """Operational escape hatch: GRACE_DISABLE_PALLAS forces every Pallas
    kernel off (set by tools/tpu_watch.sh when the on-chip smoke test
    fails) so a Mosaic compile failure cannot take down a whole run.
    ``kernel`` scopes the check: GRACE_DISABLE_PALLAS_<KERNEL> (e.g.
    ``_QUANT``, ``_TOPK``) disables only that kernel family, so one
    failing Mosaic compile does not force unrelated kernels onto their
    staged paths (the round-4 smoke failure in the quant kernel disabled
    the headline Top-K kernels too). Warns when it defeats an explicit
    ``use_pallas=True`` — a forgotten export would otherwise turn the
    kernel equivalence tests into vacuous staged-vs-staged comparisons.
    Conventional false spellings ('', '0', 'false', 'no', 'off') mean NOT
    disabled."""
    var = None
    if _env_true("GRACE_DISABLE_PALLAS"):
        var = "GRACE_DISABLE_PALLAS"
    elif kernel and _env_true("GRACE_DISABLE_PALLAS_" + kernel.upper()):
        var = "GRACE_DISABLE_PALLAS_" + kernel.upper()
    if var is None:
        return False
    if explicit:
        warnings.warn(f"{var} is set: overriding explicit "
                      "use_pallas=True; Pallas kernels will NOT run",
                      RuntimeWarning, stacklevel=3)
    return True


def pallas_mode(use_pallas, kernel: str = "quant"):
    """The ONE fused-kernel selection rule: ``(enabled, interpret)`` for a
    ``use_pallas`` knob (True / False / 'auto') and a kernel family.

    Every fused-kernel call site — the encode kernels
    (:mod:`grace_tpu.ops.pallas_quant`, family ``"quant"``) AND the
    decode/accumulate wire-path kernels
    (:mod:`grace_tpu.ops.pallas_wire`, family ``"wire"``) — resolves its
    path through this helper, so ``GRACE_DISABLE_PALLAS``, the per-family
    ``GRACE_DISABLE_PALLAS_<KERNEL>`` overrides, ``use_pallas='auto'``
    (kernel on real TPU, staged elsewhere) and the off-TPU interpret-mode
    fallback behave identically everywhere. Before this helper existed the
    codecs each carried a private copy of the rule and
    ``GRACE_DISABLE_PALLAS_QUANT`` only gated the encode side — a wire
    kernel added with its own copy would have been an env-var blind spot.
    """
    import jax

    if pallas_disabled(explicit=use_pallas is True, kernel=kernel):
        return False, False
    if use_pallas == "auto":
        return jax.default_backend() == "tpu", False
    if use_pallas is True:
        return True, jax.default_backend() != "tpu"
    return False, False
