from grace_tpu.ops.packing import (pack_2bit, pack_bits, unpack_2bit,
                                   unpack_bits)
from grace_tpu.ops.sparse import scatter_dense

__all__ = ["pack_bits", "unpack_bits", "pack_2bit", "unpack_2bit",
           "scatter_dense"]
