"""Pallas TPU kernels for the kernel-resident wire path (ROADMAP item 2).

PR 10's kernels fused the ENCODE side (compress-and-pack); every ring hop,
hier slice boundary, and rscatter owned-chunk sum still decoded /
accumulated as staged unpack → cast → add HLO — per-hop traffic that
materializes full-width intermediates in HBM, exactly what EQuARX
(PAPERS.md) eliminates by fusing quantized aggregation inside XLA and
what THC's payload-space aggregation shows pays off most at narrow pack
widths. This module is the decode-side twin of
:mod:`grace_tpu.ops.pallas_quant`:

* :func:`decode_accumulate` — K packed payloads (ring hop: K=2, recv +
  own; hier slice boundary: K = gathered slice count) are unpacked,
  sign-extended, scaled and accumulated into ONE f32 partial inside one
  kernel: 2 (or K) packed HBM reads + 1 full-width HBM write, no staged
  intermediates. Handles the qsgd two's-complement widths {2, 3, 4} and
  the 1-bit sign mask (``sign=True``; ``vote=True`` additionally applies
  the majority-vote re-sign at the end — the hier boundary's aggregate).
* :func:`packed_int_accumulate` — the exact payload-space accumulate for
  ``shared_scale`` packed payloads (homoqsgd at ``accum_bits`` ∈
  {2, 3, 4}): unpack → integer add → repack in one kernel, bytes in /
  bytes out. Exactness is the communicators' ``payload_sum_max_world``
  gate: every partial sum of W levels in ``[-q, q]`` fits the field iff
  ``W·q <= 2^(bits-1) - 1`` — the same ONE constant flow pass 6 and the
  tuner's numeric gate check statically.

Bit-identity contract (the acceptance bar, pinned in tests/test_wire.py):
each kernel equals its staged path — sequential
``decompress(payload_k)`` adds in stack order (the exact expression the
communicators run), same f32 operations in the same order — so fusing
changes WHERE the arithmetic runs, never WHAT it computes. The scale
passed in is the PRE-DIVIDED ``norm / quantum_num`` computed by the
caller with the staged path's own expression, so even the scalar
division contributes identical bits.

Unpacking without gathers: the pack-matrix trick from ``pallas_quant``
run in reverse. Every code lane's byte is a single known source lane, so
a constant matrix with ONE nonzero per column — ``M[byte(l), l] =
2^(-shift(l))`` — turns "route each byte to its code lanes, pre-shifted"
into one MXU dot (``bytes @ M``), and the code is then
``mod(floor(·), 2^width)`` elementwise. All values are integers ≤ 255
times exact powers of two: exact in f32. The 3-bit width straddles byte
boundaries, so it decodes per BIT (``M3[byte(g), g] = 2^(-(g%8))``,
``bit = mod(floor(·), 2)``) and reassembles codes with a second
constant dot (``bits @ C``, ``C[3l+b, l] = 2^b``) — the decode twin of
the bit-plane pack in ``pallas_quant._pack_matrix3_np``.

The selection rule for every caller is :func:`grace_tpu.ops.pallas_mode`
with kernel family ``"wire"`` (``GRACE_DISABLE_PALLAS`` /
``GRACE_DISABLE_PALLAS_WIRE`` honored, ``use_pallas='auto'`` = kernel on
real TPU, staged elsewhere, interpret mode off-TPU when forced).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from grace_tpu.ops.pallas_quant import (LANES, ROWS_PER_BLOCK,
                                        _interpret_mode, _pack_matrix3_np,
                                        _pack_matrix_np)

__all__ = ["decode_accumulate", "packed_int_accumulate", "hop_hbm_bytes",
           "WIRE_WIDTHS"]

# The pack widths this module's kernels decode: the sign mask plus the
# qsgd/homoqsgd two's-complement fields (grace_tpu.ops.packing declares
# the reference layouts).
WIRE_WIDTHS = (1, 2, 3, 4)


@functools.lru_cache(maxsize=8)
def _decode_matrix_np(width: int, code_lanes: int):
    """Unpack matrix for widths dividing 8: ``M[l // per_byte, l] =
    2^(-width·(l % per_byte))`` — one nonzero per column, so ``bytes @ M``
    lands every code lane's source byte pre-shifted; ``mod(floor(·),
    2^width)`` masks it to the code."""
    import numpy as np

    per_byte = 8 // width
    m = np.zeros((code_lanes // per_byte, code_lanes), np.float32)
    for lane in range(code_lanes):
        m[lane // per_byte, lane] = 2.0 ** (-(width * (lane % per_byte)))
    return m


@functools.lru_cache(maxsize=4)
def _decode_matrix3_np(code_lanes: int):
    """The 3-bit decode pair: ``M3`` routes byte ``g//8`` to bit lane
    ``g`` pre-shifted by ``2^(-(g%8))`` (bit = ``mod(floor(·), 2)``), and
    ``C[3l+b, l] = 2^b`` reassembles the three planes into codes."""
    import numpy as np

    m = np.zeros((3 * code_lanes // 8, 3 * code_lanes), np.float32)
    for g in range(3 * code_lanes):
        m[g // 8, g] = 2.0 ** (-(g % 8))
    c = np.zeros((3 * code_lanes, code_lanes), np.float32)
    for lane in range(code_lanes):
        for b in range(3):
            c[3 * lane + b, lane] = float(1 << b)
    return m, c


def _unpack_block(bytes_f32, dec_ref, c_ref, width: int):
    """(rows, bytes) f32 -> (rows, LANES) f32 codes in [0, 2^width)."""
    e = jax.lax.dot_general(bytes_f32, dec_ref[:], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if width == 3:
        bits = jnp.mod(jnp.floor(e), 2.0)
        return jax.lax.dot_general(bits, c_ref[:], (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
    return jnp.mod(jnp.floor(e), float(1 << width))


def _make_decode_accum_kernel(width: int, k_payloads: int, sign: bool,
                              vote: bool):
    mask = float(1 << width)
    half = float(1 << (width - 1))

    def kernel(scale_ref, dec_ref, c_ref, x_ref, out_ref):
        acc = None
        for k in range(k_payloads):
            # uint8 -> f32 via the int32 hop (Mosaic has no direct
            # uint->float cast — same constraint as the PRNG bits in
            # pallas_quant._signed_levels).
            b = x_ref[k].astype(jnp.int32).astype(jnp.float32)
            code = _unpack_block(b, dec_ref, c_ref, width)
            if sign:
                val = code * 2.0 - 1.0
            else:
                level = code - mask * (code >= half).astype(jnp.float32)
                val = scale_ref[k] * level
            acc = val if acc is None else acc + val
        if vote:
            acc = (acc >= 0).astype(jnp.float32) * 2.0 - 1.0
        out_ref[:] = acc

    return kernel


def _block_layout(width: int, numel: int):
    """(padded_rows, byte_lanes, padded_nbytes): the (rows, LANES) code
    grid padded to whole ROWS_PER_BLOCK tiles, and its byte image.
    ``LANES·width`` is a multiple of 8 for every wire width, so each code
    row's bitstream starts byte-aligned and the per-row byte blocks
    concatenate into the packers' global byte stream exactly."""
    block = ROWS_PER_BLOCK * LANES
    padded_codes = numel + (-numel % block)
    rows = padded_codes // LANES
    byte_lanes = LANES * width // 8
    return rows, byte_lanes, rows * byte_lanes


def _stack_bytes(stacked: jax.Array, width: int, numel: int):
    rows, byte_lanes, padded_nbytes = _block_layout(width, numel)
    k = stacked.shape[0]
    padded = jnp.zeros((k, padded_nbytes), jnp.uint8
                       ).at[:, :stacked.shape[1]].set(stacked)
    return padded.reshape(k, rows, byte_lanes), rows, byte_lanes


def _decode_constants(width: int):
    if width == 3:
        m, c = _decode_matrix3_np(LANES)
        return jnp.asarray(m), jnp.asarray(c)
    m = _decode_matrix_np(width, LANES)
    # The 3-bit reassembly dot is dead for the other widths; a (1, 1)
    # placeholder keeps ONE kernel signature across widths.
    import numpy as np

    return jnp.asarray(m), jnp.zeros((1, 1), np.float32)


@functools.partial(jax.jit, static_argnames=("numel", "width", "sign",
                                             "vote", "interpret"))
def decode_accumulate(stacked: jax.Array, scales: jax.Array, numel: int,
                      width: int, sign: bool = False, vote: bool = False,
                      interpret: bool = False) -> jax.Array:
    """Fused decode→accumulate: K packed payloads -> one f32 partial.

    ``stacked`` is (K, nbytes) uint8 — the K payloads' packed bytes in
    accumulation order (ring hop: (recv, own)); ``scales`` (K,) f32 is
    each payload's PRE-DIVIDED decode scale (``norm_k / quantum_num``,
    computed by the caller with the staged path's own expression;
    ignored when ``sign=True``). Returns the length-``numel`` f32
    partial, bit-identical to sequential staged
    ``decompress(payload_0) + decompress(payload_1) + …``.

    ``sign=True`` decodes 1-bit masks to ±1 and sums (the signsgd ring
    hop's partial); ``vote=True`` additionally re-signs the sum
    (``(Σ >= 0)·2 − 1`` — the majority-vote aggregate the hier slice
    boundary applies, ties resolving +1 exactly like
    ``SignSGDCompressor.aggregate``).
    """
    if width not in WIRE_WIDTHS:
        raise ValueError(f"width must be one of {WIRE_WIDTHS}; got {width}")
    if sign and width != 1:
        raise ValueError("sign decode is the 1-bit mask path")
    if vote and not sign:
        raise ValueError("vote re-sign only applies to the sign path")
    k = stacked.shape[0]
    x3d, rows, byte_lanes = _stack_bytes(stacked, width, numel)
    dec, c3 = _decode_constants(width)
    out = pl.pallas_call(
        _make_decode_accum_kernel(width, k, sign, vote),
        grid=(rows // ROWS_PER_BLOCK,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(dec.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(c3.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, ROWS_PER_BLOCK, byte_lanes),
                         lambda i: (0, i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ROWS_PER_BLOCK, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        interpret=_interpret_mode(interpret),
    )(scales.reshape(-1).astype(jnp.float32), dec, c3, x3d)
    return out.reshape(-1)[:numel]


def _make_packed_accum_kernel(width: int, k_payloads: int):
    mask = float(1 << width)
    half = float(1 << (width - 1))

    def kernel(dec_ref, c_ref, packw_ref, x_ref, out_ref):
        acc = None
        for k in range(k_payloads):
            b = x_ref[k].astype(jnp.int32).astype(jnp.float32)
            code = _unpack_block(b, dec_ref, c_ref, width)
            level = code - mask * (code >= half).astype(jnp.float32)
            acc = level if acc is None else acc + level
        # Fold the (gate-bounded, field-exact) integer sum back into the
        # two's-complement code range and repack with the encode side's
        # pack matrices.
        codes = acc + mask * (acc < 0).astype(jnp.float32)
        if width == 3:
            from grace_tpu.ops.pallas_quant import _pack_lanes3
            out_ref[:] = _pack_lanes3(codes, packw_ref)
        else:
            from grace_tpu.ops.pallas_quant import _pack_lanes
            out_ref[:] = _pack_lanes(codes, packw_ref)

    return kernel


@functools.partial(jax.jit, static_argnames=("numel", "width", "interpret"))
def packed_int_accumulate(stacked: jax.Array, numel: int, width: int,
                          interpret: bool = False) -> jax.Array:
    """Exact payload-space accumulate for packed ``shared_scale`` levels:
    K packed payloads in, ONE packed payload of the integer level sums
    out — unpack → add → repack never leaves VMEM. Exact iff the summed
    levels fit the ``width``-bit two's-complement field, which is
    precisely the ``payload_sum_max_world`` bound the communicators'
    runtime gate and flow pass 6 enforce from the same constant."""
    if width not in (2, 3, 4):
        raise ValueError(f"width must be 2, 3 or 4; got {width}")
    k = stacked.shape[0]
    nbytes = stacked.shape[1]
    x3d, rows, byte_lanes = _stack_bytes(stacked, width, numel)
    dec, c3 = _decode_constants(width)
    packw = (jnp.asarray(_pack_matrix3_np(LANES)) if width == 3
             else jnp.asarray(_pack_matrix_np(width, LANES)))
    out = pl.pallas_call(
        _make_packed_accum_kernel(width, k),
        grid=(rows // ROWS_PER_BLOCK,),
        in_specs=[
            pl.BlockSpec(dec.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(c3.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(packw.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, ROWS_PER_BLOCK, byte_lanes),
                         lambda i: (0, i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ROWS_PER_BLOCK, byte_lanes),
                               lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, byte_lanes), jnp.uint8),
        interpret=_interpret_mode(interpret),
    )(dec, c3, packw, x3d)
    return out.reshape(-1)[:nbytes]


def hop_hbm_bytes(numel: int, width: int, fused: bool) -> int:
    """The documented HBM-traffic model of ONE ring hop's
    decode→accumulate→requant at pack width ``width`` (f32 element width
    4 B) — the projection behind the wire-path ≥2× device-time target
    (ROADMAP item 2), pinned by tests/test_wire.py and stamped into
    WIRE_LAST.json. Hop device time on TPU is HBM-bandwidth-bound (every
    op is elementwise or a tiny constant dot), so bytes moved is the
    honest static proxy until the item-1 capture campaign measures stage
    attribution on silicon.

    Staged path (what the pre-PR-19 hop traced to): each of the 2
    payloads materializes unpacked codes (1 B/elem, write+read),
    sign-extended int levels (1 B, write+read), and the decoded f32
    tensor (4 B, write+read) — plus the packed reads, the f32 partial
    write+read, and the requant encode's staged quantize (f32
    read/write) and pack (code write+read, packed write).

    Fused path: the decode_accumulate kernel reads 2 packed payloads and
    writes ONE f32 partial; the fused compress-and-pack encode kernel
    (PR 10) reads the partial and writes the packed requant payload.
    """
    packed = -(-numel * width // 8)
    f32 = 4 * numel
    if fused:
        return (2 * packed + f32) + (f32 + packed)
    staged_decode = 2 * (packed + 2 * numel + 2 * numel + 2 * f32)
    partial = 2 * f32                       # accumulate write + read
    staged_requant = 2 * f32 + 2 * numel + packed
    return staged_decode + partial + staged_requant
