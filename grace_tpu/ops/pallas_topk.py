"""Pallas TPU kernel: fused error-feedback + chunk-mode Top-K compress.

The chunk Top-K local pipeline (compensate -> select -> extract wire values
-> residual update; reference semantics grace_dl/dist/communicator pipeline,
grace_dl/dist/__init__.py:47-52) is pure elementwise/reduction work over the
fused gradient buffer, but expressed in jnp it streams the n-element buffer
through HBM ~6 times (compensated, padded body, |body| argmax, masked value
sum, one-hot dense, residual subtract — XLA fuses some neighbors but the
measured compressed-step overhead on a 25.5M buffer was still ~10 ms vs a
~3-pass roofline, BENCH_TPU_LAST.json 2026-07-31). This kernel does the
whole thing in ONE pass: read grad + residual tiles into VMEM, write the
new residual tile plus the k-sized wire values/rows.

Layout: the flat buffer is viewed as (rows, k) row-major — strided chunk c
is column c, exactly the TopKCompressor 'chunk' wire format. To avoid
materializing a zero-padded copy of the whole buffer (which would re-add
two full HBM passes), the buffer is split into a FREE row-major reshape of
the ``n // k`` full rows plus one k-sized zero-padded tail row; the kernel
reduces over both. beta/gamma feedback coefficients are static jit args
folded into the kernel, so the only HBM traffic is: read grad + residual,
write residual + the two k-sized wire planes, plus one n-sized reassembly
write of the residual halves.

Selection rule (must match TopKCompressor._chunk_compress exactly): the
winner of column c is the FIRST row attaining the column max of |comp| —
main rows in order, then the tail row. Tail padding lanes (columns >= n
mod k) hold 0 and can only tie, and ties resolve to an earlier real row,
so wire indices stay < n. If a column max is NaN no equality fires and the
guard picks row 0 — defined, in-range behavior under poisoned gradients
(the NaN stays in the residual either way, so it remains visible).

Used by ``TopKCompressor.fused_feedback_compress`` via the
``Communicator.step`` fused fast path; runs in interpreter mode on CPU so
the test suite exercises the same code path everywhere (single-device
meshes only — see the interpret guard in TopKCompressor).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_mode(interpret: bool):
    """pallas_call interpret= across JAX versions: newer Pallas wants a
    pltpu.InterpretParams() instance, older (e.g. 0.4.37) a plain bool."""
    if not interpret:
        return False
    if hasattr(pltpu, "InterpretParams"):
        return pltpu.InterpretParams()
    return True

# Per-block VMEM budget across ALL of a kernel's f32 block buffers (Mosaic
# pads each buffer's sublane count to 8 and double-buffers; the 4 MiB
# budget leaves that headroom within ~16 MiB VMEM); lane blocks must be
# multiples of 128. If the budget cannot fit even bc=128 (tiny compress
# ratios => many rows; huge worlds), the *_block_cols gate returns 0 and
# callers fall back to the unfused XLA path instead of blowing VMEM.
_VMEM_BUDGET = 4 * 2**20
_MAX_BC = 2048


def _block_cols(*buffer_rows: int) -> int:
    units = sum(-(-r // 8) * 8 for r in buffer_rows)
    bc = _VMEM_BUDGET // (4 * units)
    return min(_MAX_BC, (bc // 128) * 128)        # 0 => does not fit


def compress_block_cols(main_rows: int) -> int:
    """bc for chunk_compress_feedback: grad/resid main+tail inputs, resid
    main+tail outputs, two k-wide wire planes."""
    return _block_cols(main_rows, main_rows, main_rows, 1, 1, 1, 1, 1)


def aggregate_block_cols(main_rows: int, world: int) -> int:
    """bc for chunk_aggregate_dense: (world, bc) vals+win inputs, main+tail
    outputs — world-aware, a pod-scale W inflates the input blocks."""
    return _block_cols(world, world, main_rows, 1)


def _make_kernel(main_rows: int, has_resid: bool, beta: float, gamma: float,
                 wire_bf16: bool):
    def kernel(*refs):
        refs = list(refs)
        g_ref, t_ref = refs[0], refs[1]
        if has_resid:
            r_ref, rt_ref = refs[2], refs[3]
        vals_ref, row_ref, resid_ref, resid_t_ref = refs[-4:]

        comp = g_ref[:] * gamma                      # (mr, bc)
        tcomp = t_ref[:] * gamma                     # (1, bc)
        if has_resid:
            comp = comp + r_ref[:] * beta
            tcomp = tcomp + rt_ref[:] * beta
        a = jnp.abs(comp)
        at = jnp.abs(tcomp)
        m = jnp.maximum(jnp.max(a, axis=0, keepdims=True), at)   # (1, bc)
        row_iota = jax.lax.broadcasted_iota(jnp.int32, comp.shape, 0)
        # First-max among main rows; sentinel main_rows if none matches.
        win_main = jnp.min(jnp.where(a == m, row_iota, main_rows), axis=0,
                           keepdims=True)            # (1, bc)
        tail_hit = at == m
        # Column winner: first main-row max, else the tail row, else (NaN
        # column: no equality fires anywhere) row 0 — always a real lane.
        win = jnp.where(win_main < main_rows, win_main,
                        jnp.where(tail_hit, main_rows, 0))
        hot = row_iota == win
        hot_tail = win == main_rows
        vals = (jnp.sum(jnp.where(hot, comp, 0.0), axis=0, keepdims=True)
                + jnp.where(hot_tail, tcomp, 0.0))
        if wire_bf16:
            vals = vals.astype(jnp.bfloat16)
            # Residual absorbs the bf16 wire rounding, same as the unfused
            # path where update decompresses the bf16 payload.
            dense = vals.astype(comp.dtype)
        else:
            dense = vals
        resid_ref[:] = comp - jnp.where(hot, dense, 0.0)
        resid_t_ref[:] = tcomp - jnp.where(hot_tail, dense, 0.0)
        vals_ref[:] = vals
        row_ref[:] = win

    return kernel


@functools.partial(jax.jit, static_argnames=("k", "beta", "gamma",
                                             "wire_bf16", "interpret"))
def chunk_compress_feedback(flat: jax.Array, residual, k: int,
                            beta: float = 1.0, gamma: float = 1.0,
                            wire_bf16: bool = False, interpret: bool = False):
    """Fused ``comp = gamma*flat + beta*residual`` -> chunk-Top-K select ->
    ``(values, win_row, new_residual)``.

    ``residual`` may be None (no-feedback variant: the returned residual is
    the keep-complement of the scaled gradient; callers that don't need it
    just drop it). Requires f32 inputs and ``flat.size >= 2*k``; callers
    must check :func:`block_cols` first. Semantics are bit-identical to
    TopKCompressor._chunk_compress followed by ResidualMemory.update.
    """
    n = flat.size
    main_rows = n // k                      # >= 2 by the caller's n >= 2k
    rem = n - main_rows * k
    bc = compress_block_cols(main_rows)
    if bc <= 0:
        raise ValueError(
            f"chunk_compress_feedback: {main_rows} rows do not fit the VMEM "
            "block budget — gate on compress_block_cols() > 0")

    def two_d(buf):
        main = buf[:main_rows * k].reshape(main_rows, k)   # free reshape
        tail = jnp.zeros((1, k), buf.dtype)
        if rem:
            tail = tail.at[0, :rem].set(buf[main_rows * k:])
        return main, tail

    operands = list(two_d(flat))
    if residual is not None:
        operands += list(two_d(residual))

    main_spec = pl.BlockSpec((main_rows, bc), lambda j: (0, j),
                             memory_space=pltpu.VMEM)
    tail_spec = pl.BlockSpec((1, bc), lambda j: (0, j),
                             memory_space=pltpu.VMEM)
    wire_dtype = jnp.bfloat16 if wire_bf16 else jnp.float32
    vals, win, resid_main, resid_tail = pl.pallas_call(
        _make_kernel(main_rows, residual is not None, beta, gamma, wire_bf16),
        grid=(pl.cdiv(k, bc),),
        in_specs=[main_spec, tail_spec] * (2 if residual is not None else 1),
        out_specs=[tail_spec, tail_spec, main_spec, tail_spec],
        out_shape=[
            jax.ShapeDtypeStruct((1, k), wire_dtype),
            jax.ShapeDtypeStruct((1, k), jnp.int32),
            jax.ShapeDtypeStruct((main_rows, k), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
        ],
        interpret=_interpret_mode(interpret),
    )(*operands)
    new_resid = resid_main.reshape(-1)
    if rem:
        new_resid = jnp.concatenate([new_resid, resid_tail[0, :rem]])
    return vals.reshape(k), win.reshape(k), new_resid


# ---------------------------------------------------------------------------
# Exchange-side kernel: W gathered chunk payloads -> aggregated dense tensor
# ---------------------------------------------------------------------------

# Beyond this world size the per-rank accumulation runs as a lax.fori_loop
# instead of a static unroll: worlds in the hundreds can pass the VMEM gate
# (e.g. world=256 with ~100 rows still yields bc=384) but a 256-way unroll
# makes a very long Mosaic program with a correspondingly long compile.
_AGG_UNROLL_MAX = 32


def _make_agg_kernel(main_rows: int, world: int, average: bool):
    def kernel(vals_ref, win_ref, out_ref, tail_ref):
        v = vals_ref[:].astype(jnp.float32)          # (world, bc)
        w = win_ref[:]                               # (world, bc)
        row_iota = jax.lax.broadcasted_iota(
            jnp.int32, (main_rows, v.shape[1]), 0)
        acc0 = jnp.zeros((main_rows, v.shape[1]), jnp.float32)
        tail0 = jnp.zeros((1, v.shape[1]), jnp.float32)

        def add_rank(vi, wi, carry):
            acc, tail = carry
            acc = acc + jnp.where(row_iota == wi, vi, 0.0)
            tail = tail + jnp.where(wi == main_rows, vi, 0.0)
            return acc, tail

        if world <= _AGG_UNROLL_MAX:                 # static unroll, VPU adds
            acc, tail = acc0, tail0
            for i in range(world):
                acc, tail = add_rank(v[i][None, :], w[i][None, :],
                                     (acc, tail))
        else:
            def body(i, carry):
                vi = jax.lax.dynamic_slice_in_dim(v, i, 1, axis=0)
                wi = jax.lax.dynamic_slice_in_dim(w, i, 1, axis=0)
                return add_rank(vi, wi, carry)

            acc, tail = jax.lax.fori_loop(0, world, body, (acc0, tail0))
        if average:
            acc = acc / world
            tail = tail / world
        out_ref[:] = acc
        tail_ref[:] = tail

    return kernel


@functools.partial(jax.jit, static_argnames=("k", "n", "average",
                                             "interpret"))
def chunk_aggregate_dense(vals: jax.Array, win: jax.Array, k: int, n: int,
                          average: bool = True, interpret: bool = False
                          ) -> jax.Array:
    """Aggregate ``world`` gathered chunk payloads into one dense tensor.

    ``vals``/``win`` are (world, k) stacks of wire values and winning-row
    ids (flat index = win*k + column). The staged XLA path materializes
    ``world`` one-hot dense buffers and sums them (~world+1 HBM passes over
    n); this kernel reads the (world, k) wire planes once and writes the
    summed (optionally world-averaged) dense tensor in a single n-sized
    pass — the exchange-side twin of :func:`chunk_compress_feedback`.
    A payload row may carry win == n//k (the tail row); out-of-range rows
    beyond that cannot occur by the compress-side invariant.
    """
    main_rows = n // k
    rem = n - main_rows * k
    world = vals.shape[0]
    bc = aggregate_block_cols(main_rows, world)
    if bc <= 0:
        raise ValueError(
            f"chunk_aggregate_dense: {main_rows} rows x world={world} do "
            "not fit the VMEM block budget — gate on "
            "aggregate_block_cols() > 0")

    wspec = pl.BlockSpec((world, bc), lambda j: (0, j),
                         memory_space=pltpu.VMEM)
    out_main, out_tail = pl.pallas_call(
        _make_agg_kernel(main_rows, world, average),
        grid=(pl.cdiv(k, bc),),
        in_specs=[wspec, wspec],
        out_specs=[pl.BlockSpec((main_rows, bc), lambda j: (0, j),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, bc), lambda j: (0, j),
                                memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((main_rows, k), jnp.float32),
                   jax.ShapeDtypeStruct((1, k), jnp.float32)],
        interpret=_interpret_mode(interpret),
    )(vals, win)
    out = out_main.reshape(-1)
    if rem:
        out = jnp.concatenate([out, out_tail[0, :rem]])
    return out
