"""Pallas TPU kernels: fused stochastic quantization and compress-and-pack.

The QSGD family (reference grace_dl/dist/compressor/qsgd.py:19-23) needs a
uniform random draw per element for stochastic rounding. Expressed in plain
jnp, XLA materializes the threefry random tensor and streams it through HBM
alongside the gradient; this kernel keeps the whole quantize step — scale,
floor, random draw, round, sign fold — in VMEM with the TPU's in-core PRNG
(`pltpu.prng_random_bits`), one HBM read + one (8× smaller) HBM write.

Layout: the flat tensor is processed as (rows, 256) f32 blocks (sublane
multiple of 8, lane 128×2), grid over row-tiles. Padding lanes quantize
garbage that callers slice off.

Used by ``QSGDCompressor(use_pallas=True)``; runs in interpreter mode on
CPU so the test suite exercises the same code path everywhere.

**Fused compress-and-pack** (the EQuARX regime — quantize/pack fused into
the kernel that produces the wire payload, arXiv:2506.17615):
:func:`quantize_pack_stochastic` and :func:`sign_pack` emit the packed
sub-byte wire words *directly* — the payload leaves VMEM wire-ready
(ceil(n·bits/8) uint8 bytes) instead of staging full-width codes through
HBM for a separate jnp packing pass. The byte layout is pinned to the
reference packers' :func:`grace_tpu.ops.packing.pack_widths` contracts
(LSB-first within a byte, low nibble first), verified bit-exactly by
tests/test_pallas_quant.py, and re-audited by the static analyzer's
numeric-safety pass whenever a codec ships a packed payload. Packing is
expressed as a small matmul against a constant 0/1·2^k matrix — groups of
``8/bits`` consecutive lanes reduce onto one output byte lane on the MXU
(all values ≤ 255, exact in f32 accumulation), which keeps the lane-
dimension reduction a single dot instead of a Mosaic-hostile strided
gather.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_mode(interpret: bool):
    """pallas_call interpret= across JAX versions: newer Pallas wants a
    pltpu.InterpretParams() instance, older (e.g. 0.4.37) a plain bool."""
    if not interpret:
        return False
    if hasattr(pltpu, "InterpretParams"):
        return pltpu.InterpretParams()
    return True


LANES = 256          # last-dim tile (2 × 128 lanes)
ROWS_PER_BLOCK = 64  # sublane tile multiple


def _hash_bits(seed, shape):
    """Counter-based uint32 hash (xorshift-multiply) over element indices.

    Used when the hardware PRNG is unavailable (CPU interpreter mode, where
    `pltpu.prng_random_bits` silently returns zeros) — same numerics as the
    TPU path, just a different bit source, so the full quantization logic is
    testable off-TPU.
    """
    rows = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    h = (rows * jnp.uint32(shape[1]) + cols) * jnp.uint32(2654435761)
    h = h + seed.astype(jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x45D9F3B)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x45D9F3B)
    return h ^ (h >> 16)


def _signed_levels(x, scale, block_seed, hw_prng: bool):
    """The QSGD stochastic-rounding core, shared VERBATIM by the plain
    quantize kernel and the fused quantize-and-pack kernel — bit-identity
    between 'quantize then pack' and 'fused compress-and-pack' holds
    because both run literally this expression over the same block/seed
    layout."""
    level_float = jnp.abs(x) * scale
    previous = jnp.floor(level_float)
    if hw_prng:
        pltpu.prng_seed(block_seed)
        bits = pltpu.prng_random_bits(x.shape).astype(jnp.uint32)
    else:
        bits = _hash_bits(block_seed, x.shape)
    # Top 24 bits -> uniform [0, 1) with full f32 mantissa coverage.
    # Mosaic has no uint32->f32 cast (observed on-chip: NotImplementedError
    # "Unsupported cast: uint32 -> float32"); bits>>8 < 2^24 fits int32
    # exactly, so the int32 hop is lossless.
    u = ((bits >> 8).astype(jnp.int32).astype(jnp.float32)
         * (1.0 / (1 << 24)))
    level = previous + (u < level_float - previous).astype(jnp.float32)
    return level * jnp.sign(x)


def _make_quantize_kernel(hw_prng: bool):
    def kernel(seed_ref, scale_ref, x_ref, out_ref):
        block_seed = seed_ref[0] + pl.program_id(0)
        signed = _signed_levels(x_ref[:], scale_ref[0], block_seed, hw_prng)
        out_ref[:] = signed.astype(out_ref.dtype)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("quantum_num", "out_dtype", "interpret"))
def quantize_stochastic(flat: jax.Array, norm: jax.Array, seed: jax.Array,
                        quantum_num: int, out_dtype=jnp.int8,
                        interpret: bool = False) -> jax.Array:
    """Stochastically quantize ``flat`` (1-D f32) to signed integer levels.

    level ~ floor(q/||x|| * |x|) + Bernoulli(frac), sign folded in — the
    QSGD encoding. ``norm`` is the (precomputed) L2 norm; ``seed`` an int32
    scalar. Returns int levels, same length as ``flat``.
    """
    n = flat.size
    block = ROWS_PER_BLOCK * LANES
    n_pad = -n % block
    padded = jnp.pad(flat.astype(jnp.float32), (0, n_pad))
    rows = padded.size // LANES
    x2d = padded.reshape(rows, LANES)
    scale = jnp.where(norm > 0, quantum_num / norm, 0.0).astype(jnp.float32)

    out = pl.pallas_call(
        _make_quantize_kernel(hw_prng=not interpret),
        grid=(rows // ROWS_PER_BLOCK,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((ROWS_PER_BLOCK, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ROWS_PER_BLOCK, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), out_dtype),
        interpret=_interpret_mode(interpret),
    )(seed.reshape(1).astype(jnp.int32), scale.reshape(1), x2d)
    return out.reshape(-1)[:n]


# ---------------------------------------------------------------------------
# fused compress-and-pack
# ---------------------------------------------------------------------------

# Sign-pack block: 1024 input lanes reduce 8:1 onto 128 output byte lanes
# (a full lane tile for the uint8 output); 32 sublanes hit the uint8
# (32, 128) minimum output tile exactly.
SIGN_ROWS = 32
SIGN_LANES = 1024


@functools.lru_cache(maxsize=8)
def _pack_matrix_np(width: int, in_lanes: int):
    import numpy as np

    per_byte = 8 // width
    w = np.zeros((in_lanes, in_lanes // per_byte), np.float32)
    for lane in range(in_lanes):
        w[lane, lane // per_byte] = float(1 << (width * (lane % per_byte)))
    return w


@functools.lru_cache(maxsize=4)
def _pack_matrix3_np(in_lanes: int):
    """3-bit bit-plane pack matrix: row ``b·L + l`` (bit ``b`` of code
    ``l``) routes to output byte ``(3l+b)//8`` with weight ``2^((3l+b)%8)``
    — :func:`grace_tpu.ops.packing.pack_3bit`'s LSB-first bitstream. 3
    does not divide 8, so codes straddle byte boundaries and the per-code
    shift trick of :func:`_pack_matrix_np` cannot apply; decomposing each
    code into its three bit planes first makes the pack three dots (one
    per plane) against row-slices of this one constant — every output
    byte still sums 8 disjoint weighted bits, ≤ 255, exact in f32."""
    import numpy as np

    w = np.zeros((3 * in_lanes, 3 * in_lanes // 8), np.float32)
    for b in range(3):
        for lane in range(in_lanes):
            gb = 3 * lane + b
            w[b * in_lanes + lane, gb // 8] = float(1 << (gb % 8))
    return w


def _pack_matrix(width: int, in_lanes: int) -> jax.Array:
    """The constant pack matrix: ``W[l, l // (8//width)] = 2^(width·(l mod
    8//width))``, zero elsewhere. ``codes @ W`` sums each group of
    ``8/width`` consecutive lanes' codes shifted into their byte position —
    exactly :mod:`grace_tpu.ops.packing`'s LSB-first layout, as one MXU dot
    (every product ≤ 240 and every byte sum ≤ 255: exact in f32). The
    numpy constant is cached; the device constant is minted per trace (a
    cached jnp array would leak a tracer across jits)."""
    return jnp.asarray(_pack_matrix_np(width, in_lanes))


def _pack_lanes(codes, packw_ref):
    """Pack f32 integer codes (rows, L) -> (rows, L·width/8) uint8 via the
    pack-matrix dot. int32 hop on the way out: Mosaic's f32->uint8 path is
    the same cast class the PRNG bits needed in reverse."""
    packed = jax.lax.dot_general(codes, packw_ref[:],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    return packed.astype(jnp.int32).astype(jnp.uint8)


def _pack_lanes3(codes, packw_ref):
    """Pack f32 integer codes (rows, L) -> (rows, 3L/8) uint8 in
    :func:`grace_tpu.ops.packing.pack_3bit`'s bitstream layout: three
    bit-plane dots against row-slices of the :func:`_pack_matrix3_np`
    constant, summed (disjoint output bits, so the sum is the OR)."""
    lanes = codes.shape[-1]
    w = packw_ref[:]
    acc = None
    for b in range(3):
        plane = jnp.mod(jnp.floor(codes * (1.0 / (1 << b))), 2.0)
        part = jax.lax.dot_general(plane, w[b * lanes:(b + 1) * lanes],
                                   (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        acc = part if acc is None else acc + part
    return acc.astype(jnp.int32).astype(jnp.uint8)


def _make_quantize_pack_kernel(hw_prng: bool, width: int):
    def kernel(seed_ref, scale_ref, q_ref, packw_ref, x_ref, out_ref):
        block_seed = seed_ref[0] + pl.program_id(0)
        signed = _signed_levels(x_ref[:], scale_ref[0], block_seed, hw_prng)
        # Two's-complement field: clamp to ±quantum_num (stochastic
        # overshoot past +q would not fit the field's 2^(width-1)-1
        # ceiling), then fold negatives into the upper half of the code
        # range. First element lands in the lowest bits — the
        # packing.pack_{2,3,4}bit layouts.
        q = q_ref[0].astype(jnp.float32)
        signed = jnp.clip(signed, -q, q)
        codes = signed + float(1 << width) * (signed < 0).astype(jnp.float32)
        if width == 3:
            out_ref[:] = _pack_lanes3(codes, packw_ref)
        else:
            out_ref[:] = _pack_lanes(codes, packw_ref)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("quantum_num", "width", "interpret"))
def quantize_pack_stochastic(flat: jax.Array, norm: jax.Array,
                             seed: jax.Array, quantum_num: int,
                             width: int = 4,
                             interpret: bool = False) -> jax.Array:
    """Fused QSGD compress-and-pack: stochastically quantize ``flat`` (1-D
    f32) to signed levels in ``[-quantum_num, quantum_num]`` and emit the
    packed ``width``-bit two's-complement wire words in one kernel — the
    payload leaves VMEM wire-ready (``ceil(n·width/8)`` uint8 bytes).

    ``width`` ∈ {2, 3, 4}; requires ``quantum_num <= 2^(width-1) - 1``
    (the two's-complement field's magnitude ceiling: 1 / 3 / 7).
    Bit-identity contract (pinned in tests/test_pallas_quant.py): equals
    :func:`quantize_stochastic` at the same seed followed by clamp →
    two's-complement fold → :func:`grace_tpu.ops.packing.pack_2bit` /
    ``pack_3bit`` / ``pack_4bit`` — same block layout, same PRNG stream,
    same rounding expression, so fusing the pack changes WHERE the bytes
    are produced, never WHAT they are. (3·LANES is a multiple of 8, so
    every block row's 3-bit bitstream starts byte-aligned and the
    per-block pack concatenates into the global bitstream exactly.)
    """
    if width not in (2, 3, 4):
        raise ValueError(f"width must be 2, 3 or 4; got {width}")
    if quantum_num > (1 << (width - 1)) - 1:
        raise ValueError(
            f"quantize_pack_stochastic packs {width}-bit two's-complement "
            f"levels (magnitude <= {(1 << (width - 1)) - 1}); "
            f"quantum_num={quantum_num} cannot fit — use a wider pack or "
            "quantize_stochastic (int8/int16 wire) instead.")
    n = flat.size
    block = ROWS_PER_BLOCK * LANES
    n_pad = -n % block
    # Zero padding quantizes to level 0 -> code 0, matching the reference
    # packers' zero-padded final byte, so a shared trailing byte is still
    # identical.
    padded = jnp.pad(flat.astype(jnp.float32), (0, n_pad))
    rows = padded.size // LANES
    x2d = padded.reshape(rows, LANES)
    scale = jnp.where(norm > 0, quantum_num / norm, 0.0).astype(jnp.float32)
    out_lanes = LANES * width // 8
    packw = (jnp.asarray(_pack_matrix3_np(LANES)) if width == 3
             else _pack_matrix(width, LANES))

    out = pl.pallas_call(
        _make_quantize_pack_kernel(hw_prng=not interpret, width=width),
        grid=(rows // ROWS_PER_BLOCK,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(packw.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ROWS_PER_BLOCK, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ROWS_PER_BLOCK, out_lanes), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, out_lanes), jnp.uint8),
        interpret=_interpret_mode(interpret),
    )(seed.reshape(1).astype(jnp.int32), scale.reshape(1),
      jnp.asarray(quantum_num, jnp.int32).reshape(1), packw, x2d)
    return out.reshape(-1)[: -(-n * width // 8)]


def _sign_pack_kernel(packw_ref, x_ref, out_ref):
    bits = (x_ref[:] >= 0).astype(jnp.float32)
    out_ref[:] = _pack_lanes(bits, packw_ref)


@functools.partial(jax.jit, static_argnames=("interpret",))
def sign_pack(flat: jax.Array, interpret: bool = False) -> jax.Array:
    """Fused signSGD compress-and-pack: the sign mask of ``flat`` (1-D, any
    float dtype) packed 8 signs/byte in one kernel — bit-identical to
    ``packing.pack_bits(flat >= 0)`` (pinned in tests), deterministic, so
    kernel and staged paths agree everywhere, not just in distribution.
    """
    n = flat.size
    block = SIGN_ROWS * SIGN_LANES
    n_pad = -n % block
    # Pad with -1.0: a negative pad lane contributes a 0 bit, exactly like
    # pack_bits' zero padding, so a shared final byte is still identical.
    # (float32 cast preserves sign for every input dtype incl. -0.0, whose
    # >= 0 is True on both paths.)
    padded = jnp.pad(flat.astype(jnp.float32), (0, n_pad),
                     constant_values=-1.0)
    rows = padded.size // SIGN_LANES
    x2d = padded.reshape(rows, SIGN_LANES)
    out = pl.pallas_call(
        _sign_pack_kernel,
        grid=(rows // SIGN_ROWS,),
        in_specs=[
            pl.BlockSpec((SIGN_LANES, SIGN_LANES // 8), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((SIGN_ROWS, SIGN_LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((SIGN_ROWS, SIGN_LANES // 8),
                               lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, SIGN_LANES // 8), jnp.uint8),
        interpret=_interpret_mode(interpret),
    )(_pack_matrix(1, SIGN_LANES), x2d)
    return out.reshape(-1)[: -(-n // 8)]
