"""Pallas TPU kernel: fused stochastic quantization.

The QSGD family (reference grace_dl/dist/compressor/qsgd.py:19-23) needs a
uniform random draw per element for stochastic rounding. Expressed in plain
jnp, XLA materializes the threefry random tensor and streams it through HBM
alongside the gradient; this kernel keeps the whole quantize step — scale,
floor, random draw, round, sign fold — in VMEM with the TPU's in-core PRNG
(`pltpu.prng_random_bits`), one HBM read + one (8× smaller) HBM write.

Layout: the flat tensor is processed as (rows, 256) f32 blocks (sublane
multiple of 8, lane 128×2), grid over row-tiles. Padding lanes quantize
garbage that callers slice off.

Used by ``QSGDCompressor(use_pallas=True)``; runs in interpreter mode on
CPU so the test suite exercises the same code path everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_mode(interpret: bool):
    """pallas_call interpret= across JAX versions: newer Pallas wants a
    pltpu.InterpretParams() instance, older (e.g. 0.4.37) a plain bool."""
    if not interpret:
        return False
    if hasattr(pltpu, "InterpretParams"):
        return pltpu.InterpretParams()
    return True


LANES = 256          # last-dim tile (2 × 128 lanes)
ROWS_PER_BLOCK = 64  # sublane tile multiple


def _hash_bits(seed, shape):
    """Counter-based uint32 hash (xorshift-multiply) over element indices.

    Used when the hardware PRNG is unavailable (CPU interpreter mode, where
    `pltpu.prng_random_bits` silently returns zeros) — same numerics as the
    TPU path, just a different bit source, so the full quantization logic is
    testable off-TPU.
    """
    rows = jax.lax.broadcasted_iota(jnp.uint32, shape, 0)
    cols = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    h = (rows * jnp.uint32(shape[1]) + cols) * jnp.uint32(2654435761)
    h = h + seed.astype(jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x45D9F3B)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x45D9F3B)
    return h ^ (h >> 16)


def _make_quantize_kernel(hw_prng: bool):
    def kernel(seed_ref, scale_ref, x_ref, out_ref):
        block_seed = seed_ref[0] + pl.program_id(0)
        x = x_ref[:]
        level_float = jnp.abs(x) * scale_ref[0]
        previous = jnp.floor(level_float)
        if hw_prng:
            pltpu.prng_seed(block_seed)
            bits = pltpu.prng_random_bits(x.shape).astype(jnp.uint32)
        else:
            bits = _hash_bits(block_seed, x.shape)
        # Top 24 bits -> uniform [0, 1) with full f32 mantissa coverage.
        # Mosaic has no uint32->f32 cast (observed on-chip: NotImplementedError
        # "Unsupported cast: uint32 -> float32"); bits>>8 < 2^24 fits int32
        # exactly, so the int32 hop is lossless.
        u = ((bits >> 8).astype(jnp.int32).astype(jnp.float32)
             * (1.0 / (1 << 24)))
        level = previous + (u < level_float - previous).astype(jnp.float32)
        out_ref[:] = (level * jnp.sign(x)).astype(out_ref.dtype)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("quantum_num", "out_dtype", "interpret"))
def quantize_stochastic(flat: jax.Array, norm: jax.Array, seed: jax.Array,
                        quantum_num: int, out_dtype=jnp.int8,
                        interpret: bool = False) -> jax.Array:
    """Stochastically quantize ``flat`` (1-D f32) to signed integer levels.

    level ~ floor(q/||x|| * |x|) + Bernoulli(frac), sign folded in — the
    QSGD encoding. ``norm`` is the (precomputed) L2 norm; ``seed`` an int32
    scalar. Returns int levels, same length as ``flat``.
    """
    n = flat.size
    block = ROWS_PER_BLOCK * LANES
    n_pad = -n % block
    padded = jnp.pad(flat.astype(jnp.float32), (0, n_pad))
    rows = padded.size // LANES
    x2d = padded.reshape(rows, LANES)
    scale = jnp.where(norm > 0, quantum_num / norm, 0.0).astype(jnp.float32)

    out = pl.pallas_call(
        _make_quantize_kernel(hw_prng=not interpret),
        grid=(rows // ROWS_PER_BLOCK,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((ROWS_PER_BLOCK, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ROWS_PER_BLOCK, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), out_dtype),
        interpret=_interpret_mode(interpret),
    )(seed.reshape(1).astype(jnp.int32), scale.reshape(1), x2d)
    return out.reshape(-1)[:n]
