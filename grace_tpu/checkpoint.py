"""Checkpoint/resume with orbax — including compression state.

The reference checkpoints only model variables, via per-framework example
code (tf.train.Checkpoint on rank 0, tensorflow2_mnist.py:96-99; Keras
ModelCheckpoint; nothing at all for torch), and **never checkpoints
compression state** — residual memories, PowerSGD's Q factor and Signum
momentum silently reset on resume, losing accumulated error feedback
(SURVEY.md §5, checkpoint row). grace-tpu closes that gap by construction:
`GraceState` is a plain-array pytree inside the optimizer state, so the whole
`TrainState`/`StatefulTrainState` (params + model state + optimizer state
including every residual buffer) round-trips through one orbax save.

Multi-host: orbax coordinates across processes internally (each process
writes its addressable shards); there is no rank-0-only guard to write by
hand, unlike the reference's ``if hvd.rank()==0`` idiom.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

__all__ = ["Checkpointer", "save_checkpoint", "restore_checkpoint",
           "latest_step"]


class Checkpointer:
    """Thin wrapper over ``ocp.CheckpointManager`` for train states.

    Usage::

        ckpt = Checkpointer(dir, max_to_keep=3)
        ckpt.save(step, state)                  # async; returns immediately
        state = ckpt.restore(abstract_state)    # latest, or step=N
        ckpt.close()                            # wait for pending writes
    """

    def __init__(self, directory: str | os.PathLike,
                 max_to_keep: Optional[int] = 3,
                 save_interval_steps: int = 1):
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps)
        self._mgr = ocp.CheckpointManager(os.path.abspath(str(directory)),
                                          options=options)

    @property
    def directory(self) -> str:
        return str(self._mgr.directory)

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        """Save ``state`` (any pytree of arrays/scalars) at ``step``."""
        return self._mgr.save(step, args=ocp.args.StandardSave(state),
                              force=force)

    def restore(self, target: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure of ``target``.

        ``target`` may be a concrete state (its arrays give shape/dtype/
        sharding) or an abstract one built with ``jax.eval_shape``. Restores
        the latest step when ``step`` is None.
        """
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self.directory}")
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                          target)
        return self._mgr.restore(step,
                                 args=ocp.args.StandardRestore(abstract))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return self._mgr.all_steps()

    def wait(self) -> None:
        """Block until async saves complete."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def save_checkpoint(directory: str | os.PathLike, state: Any,
                    step: int) -> None:
    """One-shot synchronous save (convenience for scripts/tests)."""
    with Checkpointer(directory, max_to_keep=None) as ckpt:
        ckpt.save(step, state, force=True)


def restore_checkpoint(directory: str | os.PathLike, target: Any,
                       step: Optional[int] = None) -> Any:
    """One-shot restore of the latest (or given) step into ``target``'s shape."""
    if not os.path.isdir(directory):
        # Don't let CheckpointManager create directories on a read path.
        raise FileNotFoundError(f"no checkpoint directory at {directory}")
    with Checkpointer(directory) as ckpt:
        return ckpt.restore(target, step=step)


def latest_step(directory: str | os.PathLike) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    with Checkpointer(directory) as ckpt:
        return ckpt.latest_step()
