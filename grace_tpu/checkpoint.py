"""Checkpoint/resume with orbax — including compression state.

The reference checkpoints only model variables, via per-framework example
code (tf.train.Checkpoint on rank 0, tensorflow2_mnist.py:96-99; Keras
ModelCheckpoint; nothing at all for torch), and **never checkpoints
compression state** — residual memories, PowerSGD's Q factor and Signum
momentum silently reset on resume, losing accumulated error feedback
(SURVEY.md §5, checkpoint row). grace-tpu closes that gap by construction:
`GraceState` is a plain-array pytree inside the optimizer state, so the whole
`TrainState`/`StatefulTrainState` (params + model state + optimizer state
including every residual buffer) round-trips through one orbax save.

Multi-host: orbax coordinates across processes internally (each process
writes its addressable shards); there is no rank-0-only guard to write by
hand, unlike the reference's ``if hvd.rank()==0`` idiom.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Optional, Tuple

import jax
import orbax.checkpoint as ocp

__all__ = ["Checkpointer", "WorldSizeMismatch", "save_checkpoint",
           "restore_checkpoint", "latest_step", "divergence_rollback"]


class WorldSizeMismatch(ValueError):
    """A checkpoint's leaf shapes differ from the target's only in the
    leading (world) axis — the signature of restoring a state saved at a
    different world size W. GraceState mem/comp/telem/watch leaves carry a
    leading world axis in the global layout, so an elastic resize changes
    exactly that dim on exactly those leaves. The fix is never to force the
    restore: re-shard with
    :func:`grace_tpu.resilience.elastic.reshard_grace_state` (restore at
    the checkpoint's own world first), or build the target at the
    checkpoint's world."""

# Transient-IO retry policy for save-path writes (shared by the orbax save
# dispatch and the last-known-good sidecar): a preempted node's NFS blip or
# an ENOSPC race should not silently drop a checkpoint the divergence-
# rollback path later depends on. Bounded exponential backoff; the final
# failure propagates.
_IO_RETRIES = 3
_IO_BACKOFF_S = 0.1


def _retry_io(fn: Callable[[], Any], what: str,
              retries: int = _IO_RETRIES,
              backoff_s: float = _IO_BACKOFF_S) -> Any:
    """Run ``fn``, retrying transient ``OSError``s with exponential backoff.

    Only OS-level errors are retried — anything else (structure mismatch,
    orbax value errors) is a programming error and raises immediately.
    """
    for attempt in range(retries):
        try:
            return fn()
        except OSError:
            if attempt == retries - 1:
                raise
            time.sleep(backoff_s * (2 ** attempt))


def _path_names(entry) -> str:
    """Normalize one pytree key-path entry to its bare name, so dict-based
    checkpoint metadata compares against NamedTuple-based targets."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _structure_paths(tree) -> set:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {"/".join(_path_names(e) for e in path) for path, _ in flat}


def _first_structure_mismatch(stored, target) -> Optional[Tuple[str, str]]:
    """(path, which-side) of the first leaf present in only one structure."""
    s_paths = _structure_paths(stored)
    t_paths = _structure_paths(target)
    only_target = sorted(t_paths - s_paths)
    only_stored = sorted(s_paths - t_paths)
    if only_target:
        return only_target[0], "target"
    if only_stored:
        return only_stored[0], "checkpoint"
    return None


def _leaf_meta(tree) -> dict:
    """path → (shape tuple | None, dtype str | None) for every leaf that
    exposes shape/dtype (orbax ArrayMetadata, concrete arrays, and
    eval_shape structs all do; scalars and opaque leaves report None and
    are skipped by the value-level diff)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    meta = {}
    for path, leaf in flat:
        p = "/".join(_path_names(e) for e in path)
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        meta[p] = (tuple(shape) if shape is not None else None,
                   str(dtype) if dtype is not None else None)
    return meta


def _first_leaf_mismatch(stored, target) -> Optional[Tuple[str, tuple,
                                                           tuple]]:
    """First same-path leaf whose shape or dtype differs between the two
    structures: ``(path, (stored_shape, stored_dtype), (target_shape,
    target_dtype))``. Only runs when the tree *structures* already agree —
    the leaf-level refinement of :func:`_first_structure_mismatch`, so a
    world-size change (same tree, different leading dims) gets a named
    leaf and both shapes instead of an opaque orbax shape error."""
    s_meta = _leaf_meta(stored)
    t_meta = _leaf_meta(target)
    for path in sorted(s_meta.keys() & t_meta.keys()):
        (s_shape, s_dtype), (t_shape, t_dtype) = s_meta[path], t_meta[path]
        if s_shape is None or t_shape is None:
            continue
        if s_shape != t_shape or (s_dtype is not None and t_dtype is not None
                                  and s_dtype != t_dtype):
            return path, (s_shape, s_dtype), (t_shape, t_dtype)
    return None


def _looks_like_world_resize(s_shape: tuple, t_shape: tuple) -> bool:
    """Same trailing dims, different leading dim — the global GraceState
    layout's world axis is the leading axis, so this is the world-size
    signature (the 'leading axis ratio equals old_W/new_W' case)."""
    return (len(s_shape) == len(t_shape) and len(s_shape) >= 1
            and s_shape[0] != t_shape[0] and s_shape[1:] == t_shape[1:])


class Checkpointer:
    """Thin wrapper over ``ocp.CheckpointManager`` for train states.

    Usage::

        ckpt = Checkpointer(dir, max_to_keep=3)
        ckpt.save(step, state, good=True)       # async; returns immediately
        state = ckpt.restore(abstract_state)    # latest, or step=N
        state = ckpt.restore_last_good(abstract_state)   # divergence recovery
        ckpt.close()                            # wait for pending writes

    ``good`` records per-step health metadata (a sidecar JSON next to the
    orbax steps, written by process 0): a step saved with ``good=True`` is a
    candidate for :meth:`restore_last_good`, the entry point of the
    divergence-rollback path (see :func:`divergence_rollback`). The caller
    decides what "good" means — typically "the guard reported no skipped
    steps and a finite loss since the previous save" (see
    ``grace_tpu.utils.metrics.guard_report``).
    """

    _GOOD_FILE = "last_known_good.json"

    def __init__(self, directory: str | os.PathLike,
                 max_to_keep: Optional[int] = 3,
                 save_interval_steps: int = 1):
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps)
        # Registering the handler up front (rather than letting the first
        # save() do it lazily) is what makes item_metadata() work on a
        # freshly opened manager — the restore-side structure check needs it.
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(str(directory)), options=options,
            item_handlers=ocp.StandardCheckpointHandler())

    @property
    def directory(self) -> str:
        return str(self._mgr.directory)

    # -- last-known-good tracking -------------------------------------------
    @property
    def _good_path(self) -> str:
        return os.path.join(self.directory, self._GOOD_FILE)

    def _read_good(self) -> list:
        try:
            with open(self._good_path) as f:
                return list(json.load(f)["good_steps"])
        except (FileNotFoundError, json.JSONDecodeError, KeyError):
            return []

    def _write_good(self, steps: list) -> None:
        """Atomic, retryable sidecar write: temp file + fsync + os.replace.

        A preemption mid-write leaves at worst a stale ``.tmp`` next to an
        intact previous record — never a torn ``last_known_good.json``,
        which would blind :meth:`restore_last_good` exactly when the
        divergence-rollback path needs it. Transient IO errors retry with
        bounded backoff (:func:`_retry_io`).
        """
        if jax.process_index() != 0:
            return
        payload = json.dumps(
            {"good_steps": sorted(set(int(s) for s in steps))})
        tmp = self._good_path + ".tmp"

        def write():
            with open(tmp, "w") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._good_path)

        _retry_io(write, "last-known-good sidecar")

    def save(self, step: int, state: Any, force: bool = False,
             good: Optional[bool] = None) -> bool:
        """Save ``state`` (any pytree of arrays/scalars) at ``step``.

        ``good`` marks (True) or unmarks (False) this step as known-good in
        the per-step metadata; ``None`` leaves the record untouched.

        Atomicity/durability: orbax itself stages each step into a
        temporary directory and renames on commit, so a preemption mid-save
        never exposes a torn step; the save *dispatch* and the known-good
        sidecar here additionally retry transient ``OSError``s with bounded
        backoff, so one IO blip doesn't silently drop the rollback
        candidate.
        """
        saved = _retry_io(
            lambda: self._mgr.save(step, args=ocp.args.StandardSave(state),
                                   force=force),
            f"checkpoint save at step {step}")
        if good is not None and saved:
            self.mark_good(step, good)
        return saved

    def mark_good(self, step: int, good: bool = True) -> None:
        """(Un)mark an already-saved step as known-good — e.g. after a
        validation pass finished long after the save was issued."""
        steps = [s for s in self._read_good() if s != step]
        if good:
            steps.append(step)
        self._write_good(steps)

    def last_good_step(self) -> Optional[int]:
        """Newest step recorded good that still exists on disk (retention
        may have garbage-collected older good steps)."""
        existing = set(self._mgr.all_steps())
        good = [s for s in self._read_good() if s in existing]
        return max(good) if good else None

    def restore_last_good(self, target: Any) -> Any:
        """Restore the newest known-good step (see :meth:`save` ``good=``)."""
        step = self.last_good_step()
        if step is None:
            raise FileNotFoundError(
                f"no known-good checkpoint under {self.directory} — save "
                "with good=True to record rollback candidates")
        return self.restore(target, step=step)

    # -- restore ------------------------------------------------------------
    def restore(self, target: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure of ``target``.

        ``target`` may be a concrete state (its arrays give shape/dtype/
        sharding) or an abstract one built with ``jax.eval_shape``. Restores
        the latest step when ``step`` is None.

        A checkpoint whose tree structure does not match ``target`` (e.g.
        resume after an optimizer/model config change) raises a ``ValueError``
        naming the first mismatching leaf path, instead of orbax's raw
        internal traceback.
        """
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self.directory}")
        self._check_structure(step, target)
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct,
                                          target)
        try:
            return self._mgr.restore(step,
                                     args=ocp.args.StandardRestore(abstract))
        except (ValueError, KeyError, TypeError) as e:
            # Structure pre-check is name-based and conservative; anything
            # it missed (or metadata it could not read) lands here.
            raise ValueError(
                f"checkpoint step {step} under {self.directory} does not "
                f"restore into the given target structure — did the "
                f"optimizer or model config change since it was written? "
                f"(orbax: {e})") from e

    def _check_structure(self, step: int, target: Any) -> None:
        try:
            stored = self._mgr.item_metadata(step)
        except Exception:
            return   # metadata unavailable: let restore itself decide
        if stored is None:
            return
        mismatch = _first_structure_mismatch(stored, target)
        if mismatch is not None:
            path, side = mismatch
            other = "checkpoint" if side == "target" else "target"
            raise ValueError(
                f"checkpoint structure mismatch at leaf '{path}': present "
                f"in the {side} but not in the {other} (checkpoint step "
                f"{step} under {self.directory}). Restore with a target "
                "built from the same optimizer/model config the checkpoint "
                "was written with.")
        # Same tree, different leaves: name the first offender instead of
        # letting orbax fail with a raw shape traceback — and recognize
        # the elastic world-resize signature specifically.
        leaf = _first_leaf_mismatch(stored, target)
        if leaf is not None:
            path, (s_shape, s_dtype), (t_shape, t_dtype) = leaf
            if _looks_like_world_resize(s_shape, t_shape):
                raise WorldSizeMismatch(
                    f"checkpoint leaf '{path}' was saved with shape "
                    f"{s_shape} but the target expects {t_shape} — same "
                    "trailing dims, different leading axis: this looks "
                    f"like a world-size change (checkpoint world "
                    f"{s_shape[0]}, target world {t_shape[0]}; step {step} "
                    f"under {self.directory}). Restore into a target built "
                    f"at world {s_shape[0]}, then re-shard with "
                    "grace_tpu.resilience.elastic.reshard_grace_state — "
                    "per-rank state is re-initialized at the new world, "
                    "never re-partitioned.")
            raise ValueError(
                f"checkpoint leaf '{path}' does not match the target: "
                f"saved shape {s_shape} dtype {s_dtype}, target shape "
                f"{t_shape} dtype {t_dtype} (checkpoint step {step} under "
                f"{self.directory}). Restore with a target built from the "
                "same optimizer/model config the checkpoint was written "
                "with.")

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return self._mgr.all_steps()

    def wait(self) -> None:
        """Block until async saves complete."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def save_checkpoint(directory: str | os.PathLike, state: Any,
                    step: int) -> None:
    """One-shot synchronous save (convenience for scripts/tests)."""
    with Checkpointer(directory, max_to_keep=None) as ckpt:
        ckpt.save(step, state, force=True)


def restore_checkpoint(directory: str | os.PathLike, target: Any,
                       step: Optional[int] = None) -> Any:
    """One-shot restore of the latest (or given) step into ``target``'s shape."""
    if not os.path.isdir(directory):
        # Don't let CheckpointManager create directories on a read path.
        raise FileNotFoundError(f"no checkpoint directory at {directory}")
    with Checkpointer(directory) as ckpt:
        return ckpt.restore(target, step=step)


def latest_step(directory: str | os.PathLike) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    with Checkpointer(directory) as ckpt:
        return ckpt.latest_step()


def divergence_rollback(ckpt: Checkpointer, target: Any, *,
                        failed_step: int, skip_window: int = 1
                        ) -> Tuple[Any, int, int]:
    """Train-loop recovery from sustained divergence: restore + data skip.

    When the in-graph guard reports sustained non-finite steps (e.g.
    ``guard_report(state)['consecutive']`` beyond the loop's patience) the
    loop calls this instead of continuing::

        state, good_step, resume_at = divergence_rollback(
            ckpt, state, failed_step=i, skip_window=8)
        data_cursor = resume_at   # jump PAST the offending batches

    Returns ``(state, good_step, resume_at)``: the last-known-good state
    (see ``Checkpointer.save(..., good=True)``), the step it came from, and
    ``failed_step + skip_window`` — the data cursor that skips the window
    that poisoned the run, so the retry does not replay the same bad batch
    sequence straight into a second divergence.
    """
    state = ckpt.restore_last_good(target)
    good_step = ckpt.last_good_step()
    return state, good_step, failed_step + skip_window
