"""Cyclic local-selection Top-K — ScaleCom's scalable sparsification.

Per-rank independent Top-K degrades at scale twice over (ScaleCom,
arXiv:2104.11125 — PAPERS.md): the union of W ranks' index sets grows
toward W·k (the gather cost cliff), and the aggregate keeps shrinking
toward the intersection of everyone's preferences. ScaleCom's CLT-k fix:
each step ONE rank's *local* selection decides the index set for the
whole fleet, and the deciding rank cycles — error feedback re-injects
every other rank's unselected mass, so over a cycle all ranks'
preferences are heard, while the per-step index set stays exactly k.

Mapped onto this repo's negotiation machinery (the PR-13 hoist):

1. **negotiate** — the leader for this (step, leaf) is derived from the
   replicated rng key (rank-identical by the transform's rng contract;
   a pseudo-random rotation with the same coverage as ScaleCom's
   round-robin, needing no step counter in a stateless codec). The
   leader's local top-k indices are :func:`~grace_tpu.comm.
   masked_broadcast` to every rank — ONE small integer collective,
   priced via :meth:`negotiation_nbytes`.
2. **encode** — every rank ships its values AT THE SHARED INDICES.
3. **aggregate** — because the index set is rank-identical, payloads sum
   **exactly in payload space** (``payload_algebra='exact'``): Allreduce
   psums k values instead of gathering W·k, and no schedule ever pays a
   requant. This is the property per-rank Top-K structurally cannot
   have (its per-rank index sets are why ``topk`` declares no algebra).

Residual coverage: a non-leader's large coordinates that the leader
missed land in error-feedback memory verbatim and re-compete next step —
ScaleCom §III's convergence argument. The codec is stateless; without a
bound mesh axis (Identity/single-process) it falls back to local
selection, which decodes its own payload exactly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import Compressor, Ctx, Payload, State, axis_size
from grace_tpu.compressors.topk import static_k
from grace_tpu.ops.sparse import scatter_dense


@dataclasses.dataclass(frozen=True)
class CyclicTopKCompressor(Compressor):
    # The negotiated shared index set is exactly what makes the payload
    # linear: sum-of-payloads decodes to sum-of-decodes bit-for-bit (same
    # scatter coordinates on every rank), so every payload-space schedule
    # (Allreduce psum, ring/rscatter hop adds) is exact. Per-rank topk
    # cannot claim this; the negotiation is the price of the algebra.
    payload_algebra = "exact"
    # Re-selecting over a partial sum would change the index set mid-
    # schedule and desync it from the negotiated ctx — the exact payload
    # algebra already gives every hop-pipelined schedule a lossless path.
    supports_hop_requant = False
    # Non-scale negotiation (a leader's index set): communicators hoist
    # negotiate() before the stage-1 encode via core.needs_negotiation.
    negotiates = True

    compress_ratio: float = 0.01

    def negotiate(self, x: jax.Array, axis_name: str, rng=None):
        """Leader election + index broadcast: the rank picked from the
        replicated ``rng`` computes local top-k indices; every rank
        receives them bit-exactly (integer masked-broadcast psum)."""
        from grace_tpu.comm import masked_broadcast

        w = axis_size(axis_name)
        flat = x.reshape(-1)
        k = static_k(flat.size, self.compress_ratio)
        if rng is None:
            leader = jnp.zeros((), jnp.int32)
        else:
            # Replicated key -> replicated leader; rotates per (step,
            # leaf) with ScaleCom-round-robin coverage in distribution.
            leader = jax.random.randint(jax.random.fold_in(rng, 0x5ca1e),
                                        (), 0, w, dtype=jnp.int32)
        _, idx = lax.top_k(jnp.abs(flat), k)
        return masked_broadcast(idx.astype(jnp.int32), leader, axis_name)

    def negotiation_nbytes_for(self, n_elems: int, world: int) -> int:
        """Per-rank received bytes of one index broadcast for an
        ``n_elems``-element leaf — the leaf-aware spelling the telemetry
        wire plan and the auditor's model use."""
        k = static_k(int(n_elems), self.compress_ratio)
        return 2 * 4 * k * max(0, world - 1) // max(1, world)

    def compress(self, x: jax.Array, state: State, rng: jax.Array,
                 shared: jax.Array | None = None
                 ) -> tuple[Payload, Ctx, State]:
        """Ship values at the negotiated indices (``shared``); fall back
        to rank-local selection when no negotiation ran (Identity /
        single-process — decodes this rank's own payload exactly, it
        just isn't the shared-index algebra)."""
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        k = static_k(numel, self.compress_ratio)
        if shared is None:
            _, idx = lax.top_k(jnp.abs(flat), k)
            idx = idx.astype(jnp.int32)
        else:
            idx = shared.astype(jnp.int32)
        values = flat[idx]
        # idx rides in ctx, not the payload: it is rank-identical (the
        # whole point of the negotiation), so payload-space sums touch
        # values only and decode against one shared scatter map.
        return (values,), (idx, numel, shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        (values,) = payload
        idx, numel, shape, dtype = ctx
        return scatter_dense(values.astype(dtype), idx, numel, shape)
