"""Cyclic local-selection Top-K — ScaleCom's scalable sparsification,
re-derived with a rank-deterministic cyclic schedule.

Per-rank independent Top-K degrades at scale twice over (ScaleCom,
arXiv:2104.11125 — PAPERS.md): the union of W ranks' index sets grows
toward W·k (the gather cost cliff), and the aggregate keeps shrinking
toward the intersection of everyone's preferences. ScaleCom's CLT-k fix:
ONE shared index set per step, so the per-step set stays exactly k and
payloads sum exactly; error feedback re-injects every rank's unselected
mass, so over a cycle all coordinates are heard.

The original port (PR 13) realized the shared set as a *negotiation*: a
rotating leader's local top-k indices masked-broadcast fleet-wide. That
bought the exact algebra but chained the ctx to one rank's DATA — the
index set could not be re-derived locally, so every decode path that
reconstructs ctx per shard (compressed ring / reduce-scatter hops, the
hier WAN gather) rejected the codec (``ctx_is_data_free`` gate), and the
broadcast itself was a priced extra collective.

This revision keeps the exact shared-set algebra and drops the data
dependence (ROADMAP item 4): the index set is a **cyclic strided window
derived from the replicated rng** — the transform folds the step counter
into the key, so the schedule is "rng + step", rotating its phase every
step with ScaleCom-round-robin coverage in distribution. Every rank
(and every hop of a sharded schedule) derives the identical set from the
key alone:

1. **select** — ``start = randint(fold_in(rng, salt), 0, numel)``,
   ``stride = numel // k``; the set is ``(start + i·stride) mod numel``.
   Strided rather than contiguous so one window spans the whole tensor —
   adjacent coordinates (a conv kernel's neighborhood) land in different
   windows and the k slots sample uniformly across the leaf each step.
2. **encode** — every rank ships its values at the shared indices.
3. **aggregate** — the set is rank-identical by construction, so payloads
   sum **exactly in payload space** (``payload_algebra='exact'``), and —
   new here — the ctx is data-free, so the hop-pipelined and hierarchical
   schedules accept the codec and rebuild the index set locally per
   shard.

What changed vs ScaleCom's CLT-k: the per-step set is schedule-driven
(cyclic coverage guaranteed by construction) instead of magnitude-driven
through a leader (coverage in expectation, bias toward the leader's large
coordinates). Error feedback makes both convergent — unselected mass
re-competes every step — and the schedule costs ZERO negotiation bytes:
there is nothing to broadcast.

The codec is stateless and needs no mesh axis at selection time; under
Identity/single-process it decodes its own payload exactly.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.compressors.topk import static_k
from grace_tpu.ops.sparse import scatter_dense


@dataclasses.dataclass(frozen=True)
class CyclicTopKCompressor(Compressor):
    # The shared index set is exactly what makes the payload linear:
    # sum-of-payloads decodes to sum-of-decodes bit-for-bit (same scatter
    # coordinates on every rank), so every payload-space schedule
    # (Allreduce psum, ring/rscatter hop adds, hier gathers) is exact.
    # Per-rank topk cannot claim this.
    payload_algebra = "exact"
    # Re-selecting over a partial sum would change the index set mid-
    # schedule — the exact payload algebra already gives every
    # hop-pipelined schedule a lossless path, so nothing requants.
    supports_hop_requant = False

    compress_ratio: float = 0.01

    def _schedule(self, rng: jax.Array, numel: int) -> jax.Array:
        """The cyclic window for this (step, leaf): k distinct indices
        derived from the replicated key alone. The transform's rng
        contract (``fold_in(base_key, count)`` then per-leaf fold) makes
        this rank-identical AND step-rotating with no codec state."""
        k = static_k(numel, self.compress_ratio)
        start = jax.random.randint(jax.random.fold_in(rng, 0x5ca1e),
                                   (), 0, numel, dtype=jnp.int32)
        stride = jnp.int32(max(1, numel // k))
        # (k-1)·stride < numel for stride = numel // k, so the k strided
        # offsets are distinct modulo numel — a permutation-free proof
        # the scatter never collides.
        return (start + jnp.arange(k, dtype=jnp.int32) * stride) % numel

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        idx = self._schedule(rng, numel)
        values = flat[idx]
        # idx rides in ctx, not the payload: it is rank-identical and
        # data-free (derived from the replicated rng), so payload-space
        # sums touch values only and ANY rank/hop can rebuild the same
        # scatter map from the key — the data-free-ctx decode contract.
        return (values,), (idx, numel, shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        (values,) = payload
        idx, numel, shape, dtype = ctx
        return scatter_dense(values.astype(dtype), idx, numel, shape)
