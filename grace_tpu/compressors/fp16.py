"""Half-precision downcast compressor.

Reference: grace_dl/dist/compressor/fp16.py:6-22 (cast to fp16, cast back;
ctx records the original dtype). TPU-first addition: ``dtype='bfloat16'`` is
the default — bf16 is the TPU's native half format (MXU input type, no
overflow cliff at 65504) — with ``'float16'`` available for bit-parity with
the reference.
"""

from __future__ import annotations

import dataclasses

import jax

from grace_tpu.core import Compressor, Ctx, Payload, State


@dataclasses.dataclass(frozen=True)
class FP16Compressor(Compressor):
    dtype: str = "bfloat16"
    # Downcast is linear: half-precision payloads add meaningfully (the
    # accumulation dtype's saturation is flow pass 6's fp16 cliff, not a
    # composition failure).
    payload_algebra = "exact"
    # Linear codec: the exact payload-space ring path applies; no requant.
    supports_hop_requant = False

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        return (x.astype(self.dtype),), x.dtype, state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        (x,) = payload
        return x.astype(ctx)
