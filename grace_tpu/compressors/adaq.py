"""AdaQ: adaptive two-sided quantization (Dryden et al., MLHPC 2016).

Reference: grace_dl/tensorflow/compressor/adaq.py:6-93 — run a DGC-style
sampled-threshold selection *separately* on the positive and negative
halves, transmit each half's selected indices plus one mean per half, and
reconstruct every selected coordinate as its half-mean. The reference
bitcasts means+sizes+indices into one variable-length int32 blob
(adaq.py:65-72); under XLA static shapes each half instead ships a fixed
capacity of indices with a packed validity bitmask (values are implicit:
the half-mean), which is also 8× cheaper than shipping per-lane values.

Threshold refinement follows the reference's while loop (≤20 iterations,
accept [0.8k, 1.25k], multiply by 1.25 / 0.9 — adaq.py:35-49) including its
final ``selected < 1`` rescue step.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.packing import pack_bits, unpack_bits


@dataclasses.dataclass(frozen=True)
class AdaqCompressor(Compressor):
    tensors_size_are_same = False
    # Per-rank group means over per-rank selections: payloads decode
    # against rank-local structure a sum (or partial sum) destroys — no
    # payload algebra.
    payload_algebra = None
    supports_hop_requant = False

    compress_ratio: float = 0.01
    sample_ratio: float = 0.01
    max_refinements: int = 20

    def _half(self, masked: jax.Array, count: jax.Array, numel: int,
              rng: jax.Array):
        """Select ~ratio·count entries of one half; masked has zeros elsewhere."""
        abs_masked = jnp.abs(masked)
        num_samples = max(1, int(numel * self.sample_ratio))
        sample_idx = jax.random.randint(rng, (num_samples,), 0, numel)
        sample = abs_masked[sample_idx]
        # static stand-in for the reference's dynamic ceil(count·0.01·ratio):
        # sample the expected half population (numel/2).
        k_sample = max(1, int(numel * 0.5 * self.sample_ratio
                              * self.compress_ratio))
        top_sample, _ = lax.top_k(sample, k_sample)
        thr0 = top_sample[-1]
        target = jnp.ceil(count * self.compress_ratio)

        def count_sel(thr):
            return jnp.sum(abs_masked > thr)

        def cond(carry):
            i, thr, sel = carry
            out_of_band = (sel > 1.25 * target) | (sel < 0.8 * target)
            return (i < self.max_refinements) & out_of_band

        def body(carry):
            i, thr, sel = carry
            thr = jnp.where(sel > 1.25 * target, 1.25 * thr, 0.9 * thr)
            return i + 1, thr, count_sel(thr)

        _, thr, sel = lax.while_loop(cond, body, (0, thr0, count_sel(thr0)))
        thr = jnp.where(sel < 1, 0.8 * thr, thr)

        sel_mask = abs_masked > thr
        mean = (jnp.sum(jnp.where(sel_mask, masked, 0))
                / jnp.maximum(jnp.sum(sel_mask), 1))
        cap = max(1, min(numel, int(numel * 0.5 * self.compress_ratio * 2) + 1))
        mags, indices = lax.top_k(abs_masked, cap)
        valid = mags > thr
        return mean, indices.astype(jnp.int32), pack_bits(valid)

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        rng_p, rng_m = jax.random.split(rng)
        plus = jnp.where(flat > 0, flat, 0)
        minus = jnp.where(flat < 0, flat, 0)
        p_mean, p_idx, p_valid = self._half(plus, jnp.sum(flat > 0), numel, rng_p)
        m_mean, m_idx, m_valid = self._half(minus, jnp.sum(flat < 0), numel, rng_m)
        payload = (p_mean, p_idx, p_valid, m_mean, m_idx, m_valid)
        return payload, (numel, shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        p_mean, p_idx, p_valid, m_mean, m_idx, m_valid = payload
        numel, shape, dtype = ctx
        cap = p_idx.shape[0]
        out = jnp.zeros((numel,), dtype)
        pv = jnp.where(unpack_bits(p_valid, cap), p_mean, 0).astype(dtype)
        mv = jnp.where(unpack_bits(m_valid, cap), m_mean, 0).astype(dtype)
        out = out.at[p_idx].add(pv)
        out = out.at[m_idx].add(mv)
        return out.reshape(shape)
