"""SketchML quantile-sketch compression (Jiang et al., SIGMOD 2018).

Reference: grace_dl/tensorflow/compressor/sketch.py:6-39 — quantile edges
over the tensor, per-element bin ids, per-bin means; decompress gathers the
bin means. TF's `tfp.stats.quantiles`/`find_bins`/`unsorted_segment_mean`
become `jnp.quantile`/`searchsorted`/`segment_sum` (bin count is static, so
segment reduction compiles cleanly).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from grace_tpu.core import Compressor, Ctx, Payload, State


@dataclasses.dataclass(frozen=True)
class SketchCompressor(Compressor):
    # Bin indices against per-rank quantile edges: no payload algebra
    # (the bins themselves shift per rank — the MERGEABLE sketch is
    # CountSketchCompressor) and no bounded re-encode over a partial sum.
    payload_algebra = None
    supports_hop_requant = False

    bins: int = 64

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape = x.shape
        flat = x.reshape(-1)
        qs = jnp.linspace(0.0, 1.0, self.bins + 1)
        edges = jnp.quantile(flat, qs)
        # interior edges -> bin ids in [0, bins)
        ids = jnp.clip(jnp.searchsorted(edges[1:-1], flat, side="right"),
                       0, self.bins - 1)
        sums = jax.ops.segment_sum(flat, ids, num_segments=self.bins)
        counts = jax.ops.segment_sum(jnp.ones_like(flat), ids,
                                     num_segments=self.bins)
        means = sums / jnp.maximum(counts, 1.0)
        id_dtype = jnp.uint8 if self.bins <= 256 else jnp.uint16
        return (ids.astype(id_dtype), means), (shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        ids, means = payload
        shape, dtype = ctx
        return means[ids.astype(jnp.int32)].reshape(shape).astype(dtype)
