"""INCEPTIONN-style error-bounded floating-point compression (MICRO 2018).

Reference: grace_dl/tensorflow/compressor/inceptionn.py:8-188 — route each
value by exponent into a 32/16/8-bit lane, encode sign + marker-prefixed
truncated mantissa, pack the 2-bit lane mask 4/byte, and emit three
variable-length value streams. That wire format is irreducibly
data-dependent, which XLA's static-shape model cannot express
(SURVEY.md §7 hard part 1), so this is a **redesign with the same
error-bounded semantics and a static wire format**:

* every in-range value is encoded as a 16-bit marker code: sign bit,
  then the mantissa truncated by ``n_shift = 127 − exp`` with a marker bit
  prepended so the decoder recovers the exponent from the code's own
  magnitude (the reference's find-the-marker-bit trick, inceptionn.py:
  124-148, realized as floor(log2(code)));
* values with exponent below the error bound produce code 0 (dropped);
  the bound is clamped to 2^-14 — deeper truncation cannot keep the
  marker inside 16 bits (the reference's 8-bit lane silently zeroes such
  codes; here the bound is explicit);
* values ≥ 1.0 (exp ≥ 127, unencodable by right-shift) go exact into a
  fixed-capacity fp32 overflow lane chosen by magnitude top-k; overflow
  beyond capacity clamps to the largest 16-bit-lane value (~1.0).

Wire cost: 2 bytes/value + overflow lane ≈ ≥2× compression, vs the
reference's 1–4 bytes/value adaptive stream.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from grace_tpu.core import Compressor, Ctx, Payload, State

_MANT_BITS = 23
_MARKER = np.uint32(1 << 22)  # np, not jnp: a module-level jnp
# scalar would initialize the jax backend at import time, foreclosing
# platform selection (e.g. the CPU-mesh pinning in tests/dryrun).


def _floor_log2(x: jax.Array) -> jax.Array:
    """floor(log2(x)) for uint32 x in [1, 2^24), exact via float32 exponent."""
    f = x.astype(jnp.float32)
    return ((lax.bitcast_convert_type(f, jnp.uint32) >> _MANT_BITS)
            .astype(jnp.int32) - 127)


@dataclasses.dataclass(frozen=True)
class InceptionNCompressor(Compressor):
    tensors_size_are_same = False
    # Variable-width exponent bit packing: code words don't sum (no
    # algebra) and a partial sum has no bounded re-encode through the
    # packing.
    payload_algebra = None
    supports_hop_requant = False

    error_bound: float = 1e-4
    overflow_ratio: float = 0.0625

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1).astype(jnp.float32)
        bits = lax.bitcast_convert_type(flat, jnp.uint32)
        sign = bits >> 31
        exp = ((bits >> _MANT_BITS) & 0xFF).astype(jnp.int32)
        mantissa = bits & jnp.uint32((1 << _MANT_BITS) - 1)

        # drop everything below the error bound; 16-bit marker codes cannot
        # truncate deeper than n_shift = 14.
        eb_exp = max(113, 127 + int(math.floor(math.log2(self.error_bound))))

        # 16-bit lane (exponent in [eb_exp, 127)): reference encode scheme
        # (inceptionn.py:41-53) — marker-prefixed mantissa shifted by
        # n_shift, sign in the MSB.
        n_shift = jnp.clip(127 - exp, 1, 14).astype(jnp.uint32)
        body = ((mantissa >> 1) | _MARKER) >> n_shift          # bits <= 21
        code = ((sign << 15) | (body >> 7)).astype(jnp.uint16)
        in_band = (exp >= eb_exp) & (exp < 127)
        v16 = jnp.where(in_band, code, 0).astype(jnp.uint16)
        # overflow values (exp >= 127) clamp to just-under-1.0 in the 16-bit
        # lane unless the fp32 lane picks them up (decompress overwrites).
        max_code = jnp.uint32(0x7FFF)  # n_shift=1 marker + all-ones mantissa
        v16 = jnp.where(exp >= 127,
                        ((sign << 15) | max_code).astype(jnp.uint16), v16)

        cap = max(1, int(numel * self.overflow_ratio))
        mags, idx = lax.top_k(jnp.abs(flat), min(cap, numel))
        idx = idx.astype(jnp.int32)
        v32 = jnp.where(mags >= 1.0, flat[idx], 0.0)
        return (v16, v32, idx), (numel, shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        v16, v32, idx = payload
        numel, shape, dtype = ctx
        code = v16.astype(jnp.uint32)
        sign = code >> 15
        body = code & jnp.uint32(0x7FFF)
        p = _floor_log2(jnp.maximum(body, 1))        # marker position = 15 - n_shift
        mant = (body ^ (jnp.uint32(1) << p.astype(jnp.uint32))) \
            << (_MANT_BITS - p).astype(jnp.uint32)
        exp = (112 + p).astype(jnp.uint32)           # 127 - n_shift
        fbits = (sign << 31) | (exp << _MANT_BITS) | mant
        vals = lax.bitcast_convert_type(fbits, jnp.float32)
        vals = jnp.where(body == 0, 0.0, vals)
        # fp32 overflow lane overwrites its coordinates exactly.
        out = vals.at[idx].set(jnp.where(v32 != 0, v32, vals[idx]))
        return out.reshape(shape).astype(dtype)
