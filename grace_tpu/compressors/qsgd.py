"""QSGD stochastic quantization (Alistarh et al. 2017).

Reference: grace_dl/dist/compressor/qsgd.py:6-38 — quantize |x| to
``quantum_num`` levels scaled by the L2 norm, stochastic rounding, sign
folded into the signed level. Payload dtype: int8 when quantum_num < 128;
for larger level counts the reference casts to torch.half (qsgd.py:27),
which silently loses integer precision above 2048 — here we use int16
instead (exact, same wire width). The torch copy's leftover debug prints
(torch/compressor/qsgd.py:14-15,33-34) are, of course, not replicated.

Sub-byte wire format (grace-tpu extension, no reference analog): for
``quantum_num <= 7`` the signed levels fit a two's-complement sub-byte
field, so the payload ships packed — the field width follows the level
range (:attr:`QSGDCompressor.pack_width`): 2-bit at ``quantum_num <= 1``
(4 codes/byte), 3-bit at ``<= 3`` (an LSB-first bitstream, 8 codes per
3 bytes), 4-bit at ``<= 7`` (2 codes/byte) — via the
:mod:`grace_tpu.ops.packing` reference packers (staged path) or the
fused Pallas quantize-and-pack kernel
(:func:`grace_tpu.ops.pallas_quant.quantize_pack_stochastic`), which
emits the packed bytes directly from VMEM with no full-width intermediate
in HBM. Both paths produce the identical byte layout (the pack_widths
contract, bit-identity pinned in tests/test_pallas_quant.py). The decode
side of the wire path is fused too: :meth:`decode_accumulate` runs the
ring-hop / boundary decode→accumulate as ONE Pallas kernel
(:mod:`grace_tpu.ops.pallas_wire`) when the shared selection rule
(:func:`grace_tpu.ops.pallas_mode`, family ``"wire"``) enables it,
bit-identical to the staged sequential decompress-and-add.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.packing import (pack_2bit, pack_3bit, pack_4bit,
                                   unpack_2bit, unpack_3bit, unpack_4bit)

# Staged reference packers per two's-complement field width.
_PACKERS = {2: (pack_2bit, unpack_2bit), 3: (pack_3bit, unpack_3bit),
            4: (pack_4bit, unpack_4bit)}


@dataclasses.dataclass(frozen=True)
class QSGDCompressor(Compressor):
    # Ring hop requant (comm.RingAllreduce): re-quantizing a partial sum is
    # exactly QSGD applied to a fresh tensor — unbiased, with per-element
    # error <= ||partial||/quantum_num per hop (the EQuARX-style quantized
    # multi-hop accumulation regime). Errors add over the W-2 intermediate
    # hops; raise quantum_num on large rings if the tail matters.
    supports_hop_requant = True
    # Quantized levels decode against each rank's own norm — no payload
    # algebra (the shared-scale variant is HomoQSGDCompressor, whose one
    # negotiated scale is exactly what makes the levels summable).
    payload_algebra = None

    quantum_num: int = 64
    # Fused Pallas TPU kernel for the quantize step (in-core PRNG, one HBM
    # pass — see grace_tpu/ops/pallas_quant.py). 'auto' (the default, also
    # what grace_from_params passes): kernel on real TPU, staged XLA path
    # elsewhere — the round-5 on-chip A/B measured the kernel 42% faster
    # end-to-end (0.824 vs 0.580 of dense; BENCH_ALL_TPU_LAST.json
    # 2026-08-01). Note the OPPOSITE resolution from Top-K, whose A/B
    # measured staged faster. True forces the kernel even off-TPU
    # (interpret mode: slow, test-only); False forces staged.
    use_pallas: bool | str = "auto"

    def __post_init__(self):
        # Identity membership, not ==: 1 == True would pass equality
        # validation yet be treated differently by the `is True` checks
        # below — accept exactly the three documented spellings.
        if not (self.use_pallas == "auto" or self.use_pallas is True
                or self.use_pallas is False):
            raise ValueError(f"use_pallas must be True, False or 'auto'; "
                             f"got {self.use_pallas!r}")

    def _pallas_mode(self):
        # The ONE shared selection rule (grace_tpu.ops.pallas_mode): under
        # 'auto' the kernel runs on real TPU and the staged path elsewhere
        # — the round-5 on-chip A/B (BENCH_ALL_TPU_LAST.json 2026-08-01)
        # measured the fused quant kernel at 2111 img/s vs 1483 staged
        # (0.824 vs 0.580 of dense): unlike Top-K, where the staged path
        # wins, QSGD's per-element stochastic rounding gains 42% from the
        # single-pass kernel with in-core PRNG.
        from grace_tpu.ops import pallas_mode
        return pallas_mode(self.use_pallas, kernel="quant")

    def _wire_mode(self):
        # Decode-side kernels are their own family ("wire"): a Mosaic
        # failure in one side must not force the other onto its staged
        # path (the PR-10 lesson that split _QUANT from _TOPK).
        from grace_tpu.ops import pallas_mode
        return pallas_mode(self.use_pallas, kernel="wire")

    @property
    def packed_wire(self) -> bool:
        """True iff the payload ships sub-byte packed codes: the packed
        wire format engages when the level range (±quantum_num after the
        overshoot clamp) fits a two's-complement nibble or narrower."""
        return self.quantum_num <= 7

    @property
    def pack_width(self) -> int:
        """Two's-complement field width of the packed wire format: the
        narrowest of {2, 3, 4} whose magnitude ceiling ``2^(w-1) - 1``
        holds ``quantum_num`` (1 → 2-bit, 3 → 3-bit, 7 → 4-bit). Only
        meaningful when :attr:`packed_wire`; declared in
        ``ops.packing.pack_widths()`` so flow pass 6's sub-byte audit
        covers every width this property can select."""
        if self.quantum_num <= 1:
            return 2
        if self.quantum_num <= 3:
            return 3
        return 4

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape = x.shape
        flat = x.reshape(-1)
        norm = jnp.linalg.norm(flat)
        dtype = jnp.int8 if self.quantum_num < 128 else jnp.int16
        enabled, interpret = self._pallas_mode()
        if enabled:
            seed = jax.random.randint(rng, (), 0, 2**31 - 1, jnp.int32)
            if self.packed_wire:
                from grace_tpu.ops.pallas_quant import \
                    quantize_pack_stochastic
                packed = quantize_pack_stochastic(
                    flat, norm, seed, self.quantum_num,
                    width=self.pack_width, interpret=interpret)
                return (packed, norm), (shape, x.dtype), state
            from grace_tpu.ops.pallas_quant import quantize_stochastic
            signed = quantize_stochastic(flat, norm, seed, self.quantum_num,
                                         out_dtype=dtype,
                                         interpret=interpret)
            return (signed, norm), (shape, x.dtype), state
        abs_g = jnp.abs(flat)
        level_float = jnp.where(norm > 0, self.quantum_num / norm * abs_g, 0.0)
        previous_level = jnp.floor(level_float)
        prob = jax.random.uniform(rng, flat.shape)
        is_next = (prob < (level_float - previous_level)).astype(flat.dtype)
        new_level = previous_level + is_next
        signed = new_level * jnp.sign(flat)
        if self.packed_wire:
            # Same clamp + two's-complement fold as the fused kernel, then
            # the reference packer — staged and kernel paths share ONE
            # byte layout (they differ only in the PRNG stream).
            w = self.pack_width
            q = float(self.quantum_num)
            clamped = jnp.clip(signed.astype(jnp.float32), -q, q)
            codes = jnp.where(clamped < 0, clamped + float(1 << w),
                              clamped).astype(jnp.uint8)
            return (_PACKERS[w][0](codes), norm), (shape, x.dtype), state
        return (signed.astype(dtype), norm), (shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        levels, norm = payload
        shape, dtype = ctx
        if self.packed_wire:
            import numpy as np
            w = self.pack_width
            numel = int(np.prod(shape, dtype=np.int64)) if shape else 1
            codes = _PACKERS[w][1](levels, numel).astype(jnp.int8)
            levels = jnp.where(codes >= (1 << (w - 1)), codes - (1 << w),
                               codes)
        out = norm / self.quantum_num * levels.astype(dtype)
        return out.reshape(shape)

    def wire_fused(self) -> bool:
        """Live wire-kernel gate (core.Compressor.wire_fused): True only
        when the shared selection rule enables the "wire" family AND the
        payload ships packed — exactly the condition under which
        :meth:`decode_accumulate` takes its fused branch."""
        return self._wire_mode()[0] and self.packed_wire

    def decode_accumulate(self, payloads, ctxs):
        """The fused hop decode: K packed payloads -> one f32 partial in
        ONE Pallas kernel (grace_tpu.ops.pallas_wire.decode_accumulate),
        bit-identical to the staged sequential ``decompress +
        decompress`` the base hook runs (same unpack layout, same
        sign-extension, same per-payload ``norm/quantum_num`` scalar
        division, same accumulation order) — so 'auto' gating can only
        ever change WHERE the hop runs. Falls back to the staged spelling
        whenever the wire-kernel family is disabled, the payload is not
        packed, or the decode dtype is not f32."""
        enabled, interpret = self._wire_mode()
        shape, dtype = ctxs[0]
        if (not enabled or not self.packed_wire
                or jnp.dtype(dtype) != jnp.float32
                or any(c[:2] != (shape, dtype) for c in ctxs)):
            return super().decode_accumulate(payloads, ctxs)
        import numpy as np

        from grace_tpu.ops.pallas_wire import decode_accumulate as _fused
        numel = int(np.prod(shape, dtype=np.int64)) if shape else 1
        stacked = jnp.stack([p[0] for p in payloads])
        # The staged decompress computes ``norm / quantum_num * level``:
        # the identical scalar division here feeds the kernel, so even
        # the scale bits match the staged path.
        scales = jnp.stack([p[1] / self.quantum_num for p in payloads])
        out = _fused(stacked, scales, numel, self.pack_width,
                     interpret=interpret)
        return out.reshape(shape)
