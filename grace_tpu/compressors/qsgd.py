"""QSGD stochastic quantization (Alistarh et al. 2017).

Reference: grace_dl/dist/compressor/qsgd.py:6-38 — quantize |x| to
``quantum_num`` levels scaled by the L2 norm, stochastic rounding, sign
folded into the signed level. Payload dtype: int8 when quantum_num < 128;
for larger level counts the reference casts to torch.half (qsgd.py:27),
which silently loses integer precision above 2048 — here we use int16
instead (exact, same wire width). The torch copy's leftover debug prints
(torch/compressor/qsgd.py:14-15,33-34) are, of course, not replicated.

Sub-byte wire format (grace-tpu extension, no reference analog): for
``quantum_num <= 7`` the signed levels fit a 4-bit two's-complement
nibble, so the payload ships packed 2 codes/byte — 2× less wire than int8
— via :func:`grace_tpu.ops.packing.pack_4bit` (staged path) or the fused
Pallas quantize-and-pack kernel
(:func:`grace_tpu.ops.pallas_quant.quantize_pack_stochastic`), which
emits the packed bytes directly from VMEM with no full-width intermediate
in HBM. Both paths produce the identical byte layout (the pack_widths
contract, bit-identity pinned in tests/test_pallas_quant.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.packing import pack_4bit, unpack_4bit


@dataclasses.dataclass(frozen=True)
class QSGDCompressor(Compressor):
    # Ring hop requant (comm.RingAllreduce): re-quantizing a partial sum is
    # exactly QSGD applied to a fresh tensor — unbiased, with per-element
    # error <= ||partial||/quantum_num per hop (the EQuARX-style quantized
    # multi-hop accumulation regime). Errors add over the W-2 intermediate
    # hops; raise quantum_num on large rings if the tail matters.
    supports_hop_requant = True
    # Quantized levels decode against each rank's own norm — no payload
    # algebra (the shared-scale variant is HomoQSGDCompressor, whose one
    # negotiated scale is exactly what makes the levels summable).
    payload_algebra = None

    quantum_num: int = 64
    # Fused Pallas TPU kernel for the quantize step (in-core PRNG, one HBM
    # pass — see grace_tpu/ops/pallas_quant.py). 'auto' (the default, also
    # what grace_from_params passes): kernel on real TPU, staged XLA path
    # elsewhere — the round-5 on-chip A/B measured the kernel 42% faster
    # end-to-end (0.824 vs 0.580 of dense; BENCH_ALL_TPU_LAST.json
    # 2026-08-01). Note the OPPOSITE resolution from Top-K, whose A/B
    # measured staged faster. True forces the kernel even off-TPU
    # (interpret mode: slow, test-only); False forces staged.
    use_pallas: bool | str = "auto"

    def __post_init__(self):
        # Identity membership, not ==: 1 == True would pass equality
        # validation yet be treated differently by the `is True` checks
        # below — accept exactly the three documented spellings.
        if not (self.use_pallas == "auto" or self.use_pallas is True
                or self.use_pallas is False):
            raise ValueError(f"use_pallas must be True, False or 'auto'; "
                             f"got {self.use_pallas!r}")

    def _pallas_mode(self):
        from grace_tpu.ops import pallas_disabled
        if pallas_disabled(explicit=self.use_pallas is True, kernel="quant"):
            return False, False
        if self.use_pallas == "auto":
            # Kernel on real TPU, staged elsewhere: the round-5 on-chip A/B
            # (BENCH_ALL_TPU_LAST.json 2026-08-01, same session) measured
            # the fused quant kernel at 2111 img/s vs 1483 staged (0.824 vs
            # 0.580 of dense) — unlike Top-K, where the staged path wins,
            # QSGD's per-element stochastic rounding gains 42% from the
            # single-pass kernel with in-core PRNG.
            return jax.default_backend() == "tpu", False
        if self.use_pallas is True:
            on_tpu = jax.default_backend() == "tpu"
            return True, not on_tpu
        return False, False

    @property
    def packed_wire(self) -> bool:
        """True iff the payload ships 4-bit packed nibbles (2 codes/byte):
        the sub-byte wire format engages when the level range (±quantum_num
        after the overshoot clamp) fits a two's-complement nibble."""
        return self.quantum_num <= 7

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape = x.shape
        flat = x.reshape(-1)
        norm = jnp.linalg.norm(flat)
        dtype = jnp.int8 if self.quantum_num < 128 else jnp.int16
        enabled, interpret = self._pallas_mode()
        if enabled:
            seed = jax.random.randint(rng, (), 0, 2**31 - 1, jnp.int32)
            if self.packed_wire:
                from grace_tpu.ops.pallas_quant import \
                    quantize_pack_stochastic
                packed = quantize_pack_stochastic(
                    flat, norm, seed, self.quantum_num, interpret=interpret)
                return (packed, norm), (shape, x.dtype), state
            from grace_tpu.ops.pallas_quant import quantize_stochastic
            signed = quantize_stochastic(flat, norm, seed, self.quantum_num,
                                         out_dtype=dtype,
                                         interpret=interpret)
            return (signed, norm), (shape, x.dtype), state
        abs_g = jnp.abs(flat)
        level_float = jnp.where(norm > 0, self.quantum_num / norm * abs_g, 0.0)
        previous_level = jnp.floor(level_float)
        prob = jax.random.uniform(rng, flat.shape)
        is_next = (prob < (level_float - previous_level)).astype(flat.dtype)
        new_level = previous_level + is_next
        signed = new_level * jnp.sign(flat)
        if self.packed_wire:
            # Same clamp + nibble fold as the fused kernel, then the
            # reference packer — staged and kernel paths share ONE byte
            # layout (they differ only in the PRNG stream).
            q = float(self.quantum_num)
            clamped = jnp.clip(signed.astype(jnp.float32), -q, q)
            codes = jnp.where(clamped < 0, clamped + 16.0,
                              clamped).astype(jnp.uint8)
            return (pack_4bit(codes), norm), (shape, x.dtype), state
        return (signed.astype(dtype), norm), (shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        levels, norm = payload
        shape, dtype = ctx
        if self.packed_wire:
            import numpy as np
            numel = int(np.prod(shape, dtype=np.int64)) if shape else 1
            codes = unpack_4bit(levels, numel).astype(jnp.int8)
            levels = jnp.where(codes >= 8, codes - 16, codes)
        out = norm / self.quantum_num * levels.astype(dtype)
        return out.reshape(shape)
