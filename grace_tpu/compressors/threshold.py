"""Hard-threshold sparsification.

Reference: grace_dl/dist/compressor/threshold.py:6-27 — transmit every entry
with |x| > τ as (values, indices); payload size is data-dependent
(``tensors_size_are_same=False``). XLA requires static shapes, so this build
uses a **fixed-capacity payload** (SURVEY.md §7 hard part 1): capacity
``⌈capacity_ratio·n⌉`` lanes hold the largest-magnitude entries; lanes whose
value does not exceed τ carry value 0, making scatter decompression
value-exact without a count field. If more than `capacity` entries exceed τ
the smallest ones are dropped (a documented deviation that only ever drops
the least significant entries).

Wire-cost note: the capacity IS the wire cost — ``capacity_ratio·n`` values
+ as many int32 indices ship every step regardless of how few entries
actually exceed τ. The 0.25 default is a conservative *correctness* budget
(drops nothing until >25% of entries exceed τ) and still halves dense bytes;
for the sparsity regimes thresholding targets (≪1% selected) it is far too
generous — use :meth:`calibrated` to tune the budget to the gradients
actually observed, at setup time, keeping shapes static.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.sparse import scatter_dense


@dataclasses.dataclass(frozen=True)
class ThresholdCompressor(Compressor):
    tensors_size_are_same = False
    # (values, per-rank indices) under a capacity mask: sums mix
    # coordinates (no algebra), and the τ-mask of a partial sum is not a
    # re-encode of the members' masks.
    payload_algebra = None
    supports_hop_requant = False

    threshold: float = 0.01
    capacity_ratio: float = 0.25

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        cap = max(1, int(numel * self.capacity_ratio))
        mags, indices = lax.top_k(jnp.abs(flat), cap)
        indices = indices.astype(jnp.int32)
        values = jnp.where(mags > self.threshold, flat[indices], 0)
        return (values, indices), (numel, shape), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        values, indices = payload
        numel, shape = ctx
        return scatter_dense(values, indices, numel, shape)

    def calibrated(self, sample: jax.Array, safety: float = 1.5,
                   floor_ratio: float = 0.001) -> "ThresholdCompressor":
        """Tune ``capacity_ratio`` to the selection density observed on
        ``sample`` (a representative gradient), with ``safety`` headroom.

        XLA forbids data-dependent payload sizes, so the capacity cannot
        track density step-by-step — but it can be measured once at setup
        (e.g. on the first gradient, outside jit) and frozen. Density drift
        beyond ``safety``× only ever drops the smallest selected entries,
        and error feedback (ResidualMemory) re-injects them next step.
        """
        density = float(jnp.mean(jnp.abs(sample) > self.threshold))
        ratio = min(1.0, max(density * safety, floor_ratio,
                             1.0 / max(1, sample.size)))
        return dataclasses.replace(self, capacity_ratio=ratio)
