"""8-bit nonuniform codebook quantization (Dettmers 2015, arXiv:1511.04561).

Reference: grace_dl/tensorflow/compressor/u8bit.py:6-110 — scale by max |x|,
look the normalized magnitude up in a hard-coded 128-entry nonuniform
codebook, ship a signed int8 code plus the scale. The reference inlines the
table as 128 literal floats (twice!); here the codebook is *generated* from
the paper's dynamic-tree scheme — sign ⊕ unary base-10 exponent ⊕ linear
fraction — which produces the same kind of log-spaced grid (127 levels from
~7.5e-7 to ~0.99). Encoding is nearest-neighbor via midpoint searchsorted
(the reference's `find_bins` floors to the left edge; nearest is strictly
more accurate at identical wire cost).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from grace_tpu.core import Compressor, Ctx, Payload, State


@functools.lru_cache(maxsize=None)
def _dynamic_tree_codebook() -> np.ndarray:
    """127 strictly increasing positive levels in (0, 1).

    Dynamic-tree layout: decade e ∈ [0, 6] covers [10^-e·0.1, 10^-e·1.0)
    with b = 6 - e linear-fraction bits (mantissa normalized to [0.1, 1) so
    decades are disjoint), giving sum_{e=0}^{6} 2^(6-e) = 127 levels —
    log-spaced coarse structure, linear fine structure, like the reference's
    hard-coded table.
    """
    vals = []
    for e in range(7):
        b = 6 - e
        for m in range(2 ** b):
            frac = 0.1 + 0.9 * (m + 0.5) / 2 ** b
            vals.append(10.0 ** (-e) * frac)
    return np.sort(np.asarray(vals, np.float32))


@dataclasses.dataclass(frozen=True)
class U8bitCompressor(Compressor):
    # Codebook-indexed bytes scaled by a per-rank max: index sums are
    # garbage (no algebra) and the codebook re-encode of a partial sum is
    # unvalidated.
    payload_algebra = None
    supports_hop_requant = False

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape = x.shape
        flat = x.reshape(-1)
        book = jnp.asarray(_dynamic_tree_codebook())
        scale = jnp.max(jnp.abs(flat))
        normed = jnp.abs(flat) / jnp.maximum(scale, 1e-30)
        mids = (book[1:] + book[:-1]) / 2
        idx = jnp.searchsorted(mids, normed).astype(jnp.int8)  # [0, 126]
        code = jnp.where(flat < 0, -idx, idx).astype(jnp.int8)
        return (code, scale), (shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        code, scale = payload
        shape, dtype = ctx
        book = jnp.asarray(_dynamic_tree_codebook())
        idx = jnp.abs(code.astype(jnp.int32))
        sign = jnp.sign(code.astype(jnp.int32)).astype(dtype)
        out = book[idx].astype(dtype) * scale * sign
        return out.reshape(shape)
