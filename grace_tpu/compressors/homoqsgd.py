"""Homomorphic (shared-scale) QSGD: aggregation adds payloads directly.

THC-style aggregation-friendly quantization (PAPERS.md; also the regime
EQuARX's in-XLA quantized allreduce lives in): classic QSGD scales each
rank's levels by its OWN norm, so payloads decode differently per rank and
every multi-hop schedule must decompress → accumulate → requantize — the
per-hop loss that grows ~linearly in hop count and forced the tuner's
``MAX_REQUANT_CHAIN`` degradation gate (grace_tpu/tuning/prune.py). The
fix is to negotiate ONE scale before encoding:

1. **negotiate** — one ``lax.pmax`` of the local max magnitude over the
   mesh axis (a scalar collective, priced via
   :meth:`negotiation_nbytes`); every rank now holds the identical shared
   scale, hoisted by the communicators BEFORE the stage-1 encode so error
   feedback covers the single encode exactly;
2. **encode** — stochastic-round ``quantum_num * x / scale`` to signed
   integer LEVELS in ``[-quantum_num, quantum_num]``, shipped in an
   integer accumulator dtype wide enough that ``world`` ranks sum without
   overflow (``payload_sum_max_world`` = ``iinfo(accum_dtype).max //
   quantum_num`` — ONE constant, enforced at runtime by the communicators'
   homomorphic paths and statically by flow pass 6 and the tuner's
   numeric gate, mirroring ``comm.vote_exact_max_world``);
3. **aggregate** — every ring hop / slice boundary / psum adds the integer
   levels **in payload space**: zero re-encode loss, zero decode compute
   on the critical path, ONE decode at the very end
   (``scale / quantum_num * summed_levels``).

Wire cost: ``itemsize(accum_dtype)`` bytes per element — int16 (the
default) matches fp16's wire width while carrying exact sums for worlds up
to ``32767 // quantum_num`` (4681 at the 4-bit ``quantum_num=7``). The
win over fp16 is not bytes, it is the *quality* story: hop-count-
independent compression error at ring/hier's O(k) wire cost, where plain
qsgd pays W−2 intermediate requants and topk re-selects every hop.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import Compressor, Ctx, Payload, State


@dataclasses.dataclass(frozen=True)
class HomoQSGDCompressor(Compressor):
    # Integer levels under ONE negotiated scale: payloads add exactly in
    # integer space (the whole point of this codec) — the communicators'
    # zero-requant homomorphic path dispatches on this.
    payload_algebra = "shared_scale"
    # Hop requant would reintroduce exactly the per-hop loss the shared
    # scale exists to kill; the homomorphic path makes it unreachable.
    supports_hop_requant = False

    quantum_num: int = 7          # 4-bit levels, the qsgd4 wire family
    accum_dtype: str = "int16"    # payload/accumulator width (int8/16/32)

    def __post_init__(self):
        dt = jnp.dtype(self.accum_dtype)
        if not jnp.issubdtype(dt, jnp.signedinteger):
            raise ValueError(f"accum_dtype must be a signed integer dtype "
                             f"(the payload IS the accumulator); got "
                             f"{self.accum_dtype!r}")
        if self.quantum_num < 1:
            raise ValueError(f"quantum_num must be >= 1; got "
                             f"{self.quantum_num}")
        if self.quantum_num > int(jnp.iinfo(dt).max):
            raise ValueError(
                f"quantum_num={self.quantum_num} does not even fit ONE "
                f"rank's level in {dt.name} (max {int(jnp.iinfo(dt).max)})")

    # -- the ONE overflow constant ------------------------------------------
    def payload_sum_max_world(self) -> int:
        """Largest world whose payload-space sum stays exact: each rank
        contributes a level in ``[-quantum_num, quantum_num]``, so a W-rank
        sum lives in ``[-W·q, W·q]`` and is exact iff ``W·q <=
        iinfo(accum_dtype).max``. int16 @ q=7 → 4681; int8 @ q=7 → 18 (a
        W=32 mesh fires the static numeric-safety finding AND the runtime
        gate from this same function)."""
        return int(jnp.iinfo(jnp.dtype(self.accum_dtype)).max) \
            // self.quantum_num

    # -- negotiation ---------------------------------------------------------
    def negotiate(self, x: jax.Array, axis_name: str,
                  rng=None) -> jax.Array:
        """The shared-scale collective: pmax of the local max magnitude
        over the axis. Replicated by construction — every rank computes
        the identical scale, which is what makes the level payloads (and
        the decode ctx) rank-identical without shipping ctx."""
        local = jnp.max(jnp.abs(x.reshape(-1))).astype(jnp.float32)
        return lax.pmax(local, axis_name)

    def negotiation_nbytes(self, world: int) -> int:
        # One f32 scalar through a ring-style reduction: 2·4·(W−1)/W bytes
        # received per rank — the same schedule model recv_wire_bytes uses
        # for psums, applied to the 4-byte pmax operand.
        return 2 * 4 * max(0, world - 1) // max(1, world)

    # -- codec ---------------------------------------------------------------
    def compress(self, x: jax.Array, state: State, rng: jax.Array,
                 shared: jax.Array | None = None
                 ) -> tuple[Payload, Ctx, State]:
        """Encode against ``shared`` (the negotiated scale) when the
        communicator hoisted a negotiation; fall back to the local max
        magnitude otherwise (single-rank/Identity use and shape-only
        traces — a local scale decodes this rank's own payload exactly,
        it just isn't homomorphic)."""
        shape = x.shape
        flat = x.reshape(-1)
        scale = (jnp.asarray(shared, jnp.float32) if shared is not None
                 else jnp.max(jnp.abs(flat)).astype(jnp.float32))
        q = float(self.quantum_num)
        level_float = jnp.where(
            scale > 0, q / scale * jnp.abs(flat).astype(jnp.float32), 0.0)
        previous = jnp.floor(level_float)
        prob = jax.random.uniform(rng, flat.shape)
        level = previous + (prob < (level_float - previous))
        # |x| <= scale under a pmax'd shared scale, so levels stay within
        # ±q by construction; the clip only guards the local-scale
        # fallback's float edge cases.
        signed = jnp.clip(level * jnp.sign(flat.astype(jnp.float32)), -q, q)
        levels = signed.astype(jnp.dtype(self.accum_dtype))
        return (levels,), (shape, x.dtype, scale), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        """Linear in the (possibly hop-summed) levels: ``scale/q · levels``
        — decode-of-the-sum IS the sum-of-decodes, exactly."""
        (levels,) = payload
        shape, dtype, scale = ctx
        out = scale / self.quantum_num * levels.astype(jnp.float32)
        return out.reshape(shape).astype(dtype)
