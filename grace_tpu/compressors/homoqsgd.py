"""Homomorphic (shared-scale) QSGD: aggregation adds payloads directly.

THC-style aggregation-friendly quantization (PAPERS.md; also the regime
EQuARX's in-XLA quantized allreduce lives in): classic QSGD scales each
rank's levels by its OWN norm, so payloads decode differently per rank and
every multi-hop schedule must decompress → accumulate → requantize — the
per-hop loss that grows ~linearly in hop count and forced the tuner's
``MAX_REQUANT_CHAIN`` degradation gate (grace_tpu/tuning/prune.py). The
fix is to negotiate ONE scale before encoding:

1. **negotiate** — one ``lax.pmax`` of the local max magnitude over the
   mesh axis (a scalar collective, priced via
   :meth:`negotiation_nbytes`); every rank now holds the identical shared
   scale, hoisted by the communicators BEFORE the stage-1 encode so error
   feedback covers the single encode exactly;
2. **encode** — stochastic-round ``quantum_num * x / scale`` to signed
   integer LEVELS in ``[-quantum_num, quantum_num]``, shipped in an
   integer accumulator dtype wide enough that ``world`` ranks sum without
   overflow (``payload_sum_max_world`` = ``iinfo(accum_dtype).max //
   quantum_num`` — ONE constant, enforced at runtime by the communicators'
   homomorphic paths and statically by flow pass 6 and the tuner's
   numeric gate, mirroring ``comm.vote_exact_max_world``);
3. **aggregate** — every ring hop / slice boundary / psum adds the integer
   levels **in payload space**: zero re-encode loss, zero decode compute
   on the critical path, ONE decode at the very end
   (``scale / quantum_num * summed_levels``).

Wire cost: ``itemsize(accum_dtype)`` bytes per element — int16 (the
default) matches fp16's wire width while carrying exact sums for worlds up
to ``32767 // quantum_num`` (4681 at the 4-bit ``quantum_num=7``). The
win over fp16 is not bytes, it is the *quality* story: hop-count-
independent compression error at ring/hier's O(k) wire cost, where plain
qsgd pays W−2 intermediate requants and topk re-selects every hop.

**Packed wire mode** (``accum_bits`` ∈ {2, 3, 4}, ROADMAP item 2): the
levels ship as sub-byte two's-complement fields through the
:mod:`grace_tpu.ops.packing` reference packers — 8/5.3/4× less wire than
int16 — and the payload-space accumulate becomes unpack → integer add →
repack (staged jnp, or ONE fused Pallas kernel,
:func:`grace_tpu.ops.pallas_wire.packed_int_accumulate`, under the shared
``"wire"`` selection rule; both integer-exact, so byte-identical). The
field IS the accumulator: ``payload_sum_max_world`` tightens to
``(2^(accum_bits-1) - 1) // quantum_num`` — at 2 bits with
``quantum_num=1`` that bound is W=1, making the accumulator bound (not
the wire width) the binding constraint, which the tuner's numeric gate
and flow pass 6 reject statically and the communicators' runtime gate
rejects from the SAME constant.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.packing import (pack_2bit, pack_3bit, pack_4bit,
                                   unpack_2bit, unpack_3bit, unpack_4bit)

_PACKERS = {2: (pack_2bit, unpack_2bit), 3: (pack_3bit, unpack_3bit),
            4: (pack_4bit, unpack_4bit)}


@dataclasses.dataclass(frozen=True)
class HomoQSGDCompressor(Compressor):
    # Integer levels under ONE negotiated scale: payloads add exactly in
    # integer space (the whole point of this codec) — the communicators'
    # zero-requant homomorphic path dispatches on this.
    payload_algebra = "shared_scale"
    # Hop requant would reintroduce exactly the per-hop loss the shared
    # scale exists to kill; the homomorphic path makes it unreachable.
    supports_hop_requant = False

    quantum_num: int = 7          # 4-bit levels, the qsgd4 wire family
    accum_dtype: str = "int16"    # payload/accumulator width (int8/16/32)
    # Packed sub-byte wire mode: None ships accum_dtype levels (the
    # original wire format, untouched); 2/3/4 packs the levels into
    # two's-complement fields of that width — the field is then BOTH the
    # wire word and the hop accumulator, so payload_sum_max_world derives
    # from it instead of accum_dtype.
    accum_bits: int | None = None
    # Fused payload-accumulate kernel selection for the packed mode
    # (grace_tpu.ops.pallas_mode, family "wire"); integer-exact either
    # way, so this knob can only move WHERE the add runs.
    use_pallas: bool | str = "auto"

    def __post_init__(self):
        if not (self.use_pallas == "auto" or self.use_pallas is True
                or self.use_pallas is False):
            raise ValueError(f"use_pallas must be True, False or 'auto'; "
                             f"got {self.use_pallas!r}")
        if self.accum_bits is not None:
            if self.accum_bits not in (2, 3, 4):
                raise ValueError(f"accum_bits must be 2, 3, 4 or None; "
                                 f"got {self.accum_bits}")
            ceil = (1 << (self.accum_bits - 1)) - 1
            if self.quantum_num > ceil:
                raise ValueError(
                    f"quantum_num={self.quantum_num} does not fit ONE "
                    f"rank's level in a {self.accum_bits}-bit two's-"
                    f"complement field (magnitude <= {ceil})")
        dt = jnp.dtype(self.accum_dtype)
        if not jnp.issubdtype(dt, jnp.signedinteger):
            raise ValueError(f"accum_dtype must be a signed integer dtype "
                             f"(the payload IS the accumulator); got "
                             f"{self.accum_dtype!r}")
        if self.quantum_num < 1:
            raise ValueError(f"quantum_num must be >= 1; got "
                             f"{self.quantum_num}")
        if self.quantum_num > int(jnp.iinfo(dt).max):
            raise ValueError(
                f"quantum_num={self.quantum_num} does not even fit ONE "
                f"rank's level in {dt.name} (max {int(jnp.iinfo(dt).max)})")

    # -- the ONE overflow constant ------------------------------------------
    def payload_sum_max_world(self) -> int:
        """Largest world whose payload-space sum stays exact: each rank
        contributes a level in ``[-quantum_num, quantum_num]``, so a W-rank
        sum lives in ``[-W·q, W·q]`` and is exact iff ``W·q`` fits the
        accumulator's positive range. In packed mode the sub-byte field IS
        the accumulator, so the ceiling is ``2^(accum_bits-1) - 1`` —
        4-bit @ q=1 → 7, and 2-bit @ q=1 → 1, the config the static pass,
        the tuner's numeric gate and the runtime gate all reject from this
        same function. Unpacked: ``iinfo(accum_dtype).max`` (int16 @ q=7 →
        4681; int8 @ q=7 → 18 — a W=32 mesh fires the static finding AND
        the runtime gate)."""
        if self.accum_bits is not None:
            ceil = (1 << (self.accum_bits - 1)) - 1
        else:
            ceil = int(jnp.iinfo(jnp.dtype(self.accum_dtype)).max)
        return ceil // self.quantum_num

    # -- negotiation ---------------------------------------------------------
    def negotiate(self, x: jax.Array, axis_name: str,
                  rng=None) -> jax.Array:
        """The shared-scale collective: pmax of the local max magnitude
        over the axis. Replicated by construction — every rank computes
        the identical scale, which is what makes the level payloads (and
        the decode ctx) rank-identical without shipping ctx."""
        local = jnp.max(jnp.abs(x.reshape(-1))).astype(jnp.float32)
        return lax.pmax(local, axis_name)

    def negotiation_nbytes(self, world: int) -> int:
        # One f32 scalar through a ring-style reduction: 2·4·(W−1)/W bytes
        # received per rank — the same schedule model recv_wire_bytes uses
        # for psums, applied to the 4-byte pmax operand.
        return 2 * 4 * max(0, world - 1) // max(1, world)

    # -- codec ---------------------------------------------------------------
    def compress(self, x: jax.Array, state: State, rng: jax.Array,
                 shared: jax.Array | None = None
                 ) -> tuple[Payload, Ctx, State]:
        """Encode against ``shared`` (the negotiated scale) when the
        communicator hoisted a negotiation; fall back to the local max
        magnitude otherwise (single-rank/Identity use and shape-only
        traces — a local scale decodes this rank's own payload exactly,
        it just isn't homomorphic)."""
        shape = x.shape
        flat = x.reshape(-1)
        scale = (jnp.asarray(shared, jnp.float32) if shared is not None
                 else jnp.max(jnp.abs(flat)).astype(jnp.float32))
        q = float(self.quantum_num)
        level_float = jnp.where(
            scale > 0, q / scale * jnp.abs(flat).astype(jnp.float32), 0.0)
        previous = jnp.floor(level_float)
        prob = jax.random.uniform(rng, flat.shape)
        level = previous + (prob < (level_float - previous))
        # |x| <= scale under a pmax'd shared scale, so levels stay within
        # ±q by construction; the clip only guards the local-scale
        # fallback's float edge cases.
        signed = jnp.clip(level * jnp.sign(flat.astype(jnp.float32)), -q, q)
        if self.accum_bits is not None:
            w = self.accum_bits
            codes = jnp.where(signed < 0, signed + float(1 << w),
                              signed).astype(jnp.uint8)
            return (_PACKERS[w][0](codes),), (shape, x.dtype, scale), state
        levels = signed.astype(jnp.dtype(self.accum_dtype))
        return (levels,), (shape, x.dtype, scale), state

    def _unpack_levels(self, packed: jax.Array, n_slots: int) -> jax.Array:
        w = self.accum_bits
        codes = _PACKERS[w][1](packed, n_slots).astype(jnp.int32)
        return jnp.where(codes >= (1 << (w - 1)), codes - (1 << w), codes)

    def _pack_levels(self, levels: jax.Array) -> jax.Array:
        w = self.accum_bits
        codes = jnp.mod(levels, 1 << w).astype(jnp.uint8)
        return _PACKERS[w][0](codes)

    @staticmethod
    def _slots(nbytes: int, width: int) -> int:
        # Every code slot the packed bytes can hold (>= numel; the tail
        # slots are zero by the packers' zero padding, so accumulating
        # over slots instead of elements is exact and length-preserving).
        return nbytes * 8 // width

    def _packed_accumulate(self, stacked: jax.Array) -> jax.Array:
        """(K, nbytes) packed payloads -> the packed integer level sum:
        unpack → add → repack, as ONE fused Pallas kernel when the shared
        "wire" selection rule enables it, staged jnp otherwise. Integer-
        exact both ways (byte-identical outputs) whenever the true sums
        fit the field — the payload_sum_max_world gate's invariant."""
        from grace_tpu.ops import pallas_mode
        enabled, interpret = pallas_mode(self.use_pallas, kernel="wire")
        n_slots = self._slots(int(stacked.shape[1]), self.accum_bits)
        if enabled:
            from grace_tpu.ops.pallas_wire import packed_int_accumulate
            return packed_int_accumulate(stacked, n_slots, self.accum_bits,
                                         interpret=interpret)
        levels = jax.vmap(lambda p: self._unpack_levels(p, n_slots))(stacked)
        return self._pack_levels(jnp.sum(levels, axis=0))

    def wire_fused(self) -> bool:
        """Live wire-kernel gate (core.Compressor.wire_fused): True when
        the packed accumulate would run as the fused Pallas kernel."""
        if self.accum_bits is None:
            return False
        from grace_tpu.ops import pallas_mode
        return pallas_mode(self.use_pallas, kernel="wire")[0]

    def payload_add(self, a: Payload, b: Payload) -> Payload:
        if self.accum_bits is None:
            return super().payload_add(a, b)
        return (self._packed_accumulate(jnp.stack([a[0], b[0]])),)

    def payload_sum(self, stacked: Payload) -> Payload:
        if self.accum_bits is None:
            return super().payload_sum(stacked)
        return (self._packed_accumulate(stacked[0]),)

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        """Linear in the (possibly hop-summed) levels: ``scale/q · levels``
        — decode-of-the-sum IS the sum-of-decodes, exactly."""
        (levels,) = payload
        shape, dtype, scale = ctx
        if self.accum_bits is not None:
            import numpy as np
            numel = int(np.prod(shape, dtype=np.int64)) if shape else 1
            levels = self._unpack_levels(levels, numel)
        out = scale / self.quantum_num * levels.astype(jnp.float32)
        return out.reshape(shape).astype(dtype)
