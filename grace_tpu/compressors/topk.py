"""Top-K magnitude sparsification — exact, hardware-approximate, and chunked.

Reference: grace_dl/dist/compressor/topk.py:6-36 — keep the k = ⌈ratio·n⌉
largest-magnitude entries, ship (values, indices), scatter into zeros to
decompress. All three variants here share that wire format (fixed k, so the
all-gather path needs no size exchange; XLA static shapes).

``algorithm`` picks the selection strategy — this is where TPU-first design
diverges from the CUDA reference, because exact global top-k lowers to a
full sort, the single most expensive op in the whole pipeline (measured
~70 ms for a 25.5M-element fused ResNet-50 gradient on one chip, ~700×
the cost of an elementwise pass):

* ``'exact'`` — `lax.top_k`. Bit-exact reference parity.
* ``'approx'`` — `lax.approx_max_k`, TPU's hardware-accelerated PartialReduce
  top-k (Chern et al. 2022, arXiv:2206.14286) with a configurable
  ``recall_target``. Misses are caught by error-feedback memory the same way
  DGC's sampled threshold misses are.
* ``'chunk'`` — split the flat tensor into k equal chunks and keep the
  single largest-|x| entry of each (a pure VPU argmax reduction — no sort
  anywhere). Selection is top-1-per-chunk rather than global top-k, the
  same relaxation DGC makes with sampled thresholds
  (grace_dl/dist/compressor/dgc.py:17-24); residual feedback compensates.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.sparse import chunkwise_dense, scatter_dense


def static_k(numel: int, ratio: float) -> int:
    return max(1, int(numel * ratio))


@dataclasses.dataclass(frozen=True)
class TopKCompressor(Compressor):
    # Ring hop requant (comm.RingAllreduce): re-selecting top-k over a
    # partial sum of sparsified shards is the standard multi-hop relaxation
    # (DynamiQ-style re-sparsification) — the survivors of earlier hops
    # compete with the new contribution, and dropped mass is bounded by the
    # per-hop selection error. Sound for any selection algorithm here.
    supports_hop_requant = True
    # Per-rank index sets: summing payloads adds values belonging to
    # different coordinates (the reference's silent topk+Allreduce bug) —
    # no payload algebra, requant is the only hop-pipelined route.
    payload_algebra = None

    compress_ratio: float = 0.3
    algorithm: str = "exact"      # 'exact' | 'approx' | 'chunk'
    recall_target: float = 0.95   # for 'approx'
    wire_dtype: str = "float32"   # 'float32' | 'bfloat16' wire values
    # Fused Pallas TPU kernel for the chunk-mode LOCAL pipeline (compensate
    # + select + value extract + residual update in one HBM pass — see
    # grace_tpu/ops/pallas_topk.py), used via the Communicator.step fast
    # path with linear-error-feedback memories. 'auto' resolves to the
    # staged XLA path everywhere: the on-chip A/B (BENCH_ALL_TPU_LAST.json
    # 2026-07-31, same session) measured staged at 1602 vs fused-kernel
    # 1441 imgs/sec on the ResNet-50 headline — XLA's own fusion beats the
    # hand-written kernel end-to-end, so the kernel is an explicit opt-in
    # (True; forces interpret mode off-TPU for tests) until a measurement
    # says otherwise.
    use_pallas: bool | str = "auto"

    def __post_init__(self):
        if self.algorithm not in ("exact", "approx", "chunk"):
            raise ValueError(f"unknown topk algorithm {self.algorithm!r}")
        if self.wire_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"unknown wire_dtype {self.wire_dtype!r}")
        # Identity membership, not ==: 1 == True would pass equality
        # validation yet fail the `is True` opt-in check in _pallas_mode,
        # silently running staged — accept exactly the three spellings.
        if not (self.use_pallas == "auto" or self.use_pallas is True
                or self.use_pallas is False):
            raise ValueError(f"use_pallas must be True, False or 'auto'; "
                             f"got {self.use_pallas!r}")

    def _pallas_mode(self):
        from grace_tpu.ops import pallas_disabled
        if pallas_disabled(explicit=self.use_pallas is True, kernel="topk"):
            return False, False
        if self.use_pallas is True:
            return True, jax.default_backend() != "tpu"
        return False, False            # 'auto' == staged (measured faster)

    def _fused_chunk_gate(self, numel: int, dtype, world):
        """Shared guard for both fused fast paths. Returns (k, interpret)
        or None when the staged path must run: non-chunk algorithm, Pallas
        disabled, non-f32 data (the kernels compute/ship f32 — the staged
        path works in x.dtype, so wire size and numerics would change),
        degenerate k, or interpret mode on a multi-device mesh
        (interpreter Pallas deadlocks inside a multi-device shard_map
        program on CPU — observed: one 8-device step hangs >7 min where
        the 1-device step takes milliseconds; the compiled TPU kernel has
        no such restriction). ``world`` is a zero-arg thunk so the check
        works outside shard_map too."""
        if self.algorithm != "chunk":
            return None
        enabled, interpret = self._pallas_mode()
        if not enabled:
            return None
        if dtype != jnp.float32:
            return None
        if interpret and world() > 1:
            return None
        k = static_k(numel, self.compress_ratio)
        if numel < 2 * k:
            return None
        return k, interpret

    def fused_feedback_compress(self, x: jax.Array, state, coeffs,
                                rng: jax.Array, world=lambda: 1):
        """Communicator.step fused fast path (one-HBM-pass local pipeline).

        ``coeffs = (beta, gamma)`` is the paired memory's declared linear
        feedback ``compensate = beta*state + gamma*x``; returns
        ``(payload, ctx, new_residual_state)`` bit-identical to
        compensate -> compress -> update, or None when this config cannot
        take the fast path (see ``_fused_chunk_gate``, plus a VMEM block
        budget check for the row count).
        """
        gate = self._fused_chunk_gate(x.size, x.dtype, world)
        if gate is None or (state is not None
                            and state.dtype != jnp.float32):
            return None
        k, interpret = gate
        shape, numel = x.shape, x.size
        from grace_tpu.ops.pallas_topk import (chunk_compress_feedback,
                                               compress_block_cols)
        if compress_block_cols(numel // k) <= 0:
            return None                     # tiny ratio => too many rows
        beta, gamma = coeffs
        resid = None if state is None else state.reshape(-1)
        values, win_row, new_resid = chunk_compress_feedback(
            x.reshape(-1), resid, k, beta=float(beta), gamma=float(gamma),
            wire_bf16=self.wire_dtype == "bfloat16", interpret=interpret)
        indices = win_row * k + jnp.arange(k, dtype=jnp.int32)
        new_state = None if state is None else new_resid.reshape(state.shape)
        return ((values, indices), (numel, shape, x.dtype), new_state)

    def _select(self, flat: jax.Array, k: int) -> jax.Array:
        if self.algorithm == "approx" and flat.size > 4 * k:
            _, indices = lax.approx_max_k(jnp.abs(flat), k,
                                          recall_target=self.recall_target)
            return indices
        _, indices = lax.top_k(jnp.abs(flat), k)
        return indices

    def _chunk_compress(self, flat: jax.Array, k: int
                        ) -> tuple[jax.Array, jax.Array]:
        """Gather-free chunk-mode selection: (values, indices).

        STRIDED chunks: viewing the 0-padded flat buffer as (rows, k)
        row-major, chunk c is column c = {c, c+k, c+2k, ...}. Padding lives
        only in the last row (pad = rows*k - n < k), so every column keeps
        >= rows-1 >= 1 real elements — contiguous chunking can strand whole
        all-padding chunks when pad >= chunk. A 0-padding lane can at worst
        tie a real |x| = 0, and argmax's first-max rule resolves the tie to
        the earlier, REAL row (row 0 is never padding), so every wire index
        stays < n — no separate -1-padded buffer needed for the argmax.

        Values come from a one-hot masked sum over the (rows, k) view, NOT
        ``flat[indices]``: a k-element gather from the fused buffer
        serializes on TPU (measured ~5-6 ms of the ~10 ms compressed-step
        overhead at n=25.5M, tools/tpu_micro.py) while the masked reduction
        is one more elementwise pass (~0.3 ms). Exactly one mask row is hot
        per column, so the sum reproduces the gathered value bit-exactly —
        argmax and the mask agree on ties (both take the first max).
        """
        n = flat.size
        rows = -(-n // k)                      # ceil(n / k) >= 2
        body = jnp.zeros((rows * k,), flat.dtype).at[:n].set(flat)
        body = body.reshape(rows, k)
        win_row = jnp.argmax(jnp.abs(body), axis=0).astype(jnp.int32)
        mask = jnp.arange(rows, dtype=jnp.int32)[:, None] == win_row[None, :]
        values = jnp.sum(jnp.where(mask, body, 0), axis=0)
        indices = win_row * k + jnp.arange(k, dtype=jnp.int32)
        return values, indices

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        k = static_k(numel, self.compress_ratio)
        if self.algorithm == "chunk" and numel >= 2 * k:
            values, indices = self._chunk_compress(flat, k)
        else:
            indices = self._select(flat, k).astype(jnp.int32)
            values = flat[indices]
        if self.wire_dtype == "bfloat16":
            # 25% fewer wire bytes (6 vs 8 per kept element, with int32
            # indices); the rounding error lands in the residual memory and
            # is re-injected next step — same argument as 'approx' recall.
            values = values.astype(jnp.bfloat16)
        return (values, indices), (numel, shape, x.dtype), state

    def fused_aggregate_decompress(self, gathered: Payload, ctx: Ctx,
                                   world: int):
        """Allgather fused exchange path: (world, k) payload stacks ->
        aggregated (and world-averaged, per ``self.average``) dense tensor
        in one n-sized HBM pass (ops/pallas_topk.py chunk_aggregate_dense),
        replacing world vmapped one-hot builds + a sum. None = staged path.
        """
        numel, shape, dtype = ctx
        gate = self._fused_chunk_gate(numel, dtype, lambda: world)
        if gate is None:
            return None
        k, interpret = gate
        values, indices = gathered
        if values.shape != (world, k):
            return None              # sub-k payloads lose chunk structure
        from grace_tpu.ops.pallas_topk import (aggregate_block_cols,
                                               chunk_aggregate_dense)
        if aggregate_block_cols(numel // k, world) <= 0:
            return None              # pod-scale W inflates the input blocks
        win = (indices // k).astype(jnp.int32)
        out = chunk_aggregate_dense(values.astype(jnp.float32), win, k,
                                    numel, average=self.average,
                                    interpret=interpret)
        return out.reshape(shape).astype(dtype)

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        values, indices = payload
        numel, shape, dtype = ctx
        k = static_k(numel, self.compress_ratio)
        # Chunk-mode payloads have exactly one kept element per column of
        # the (rows, k) view, so the dense tensor is a one-hot row select —
        # no scatter (which serializes on TPU and dominated the headline
        # bench). Shape check is static: a sub-k payload (e.g. a TwoShot
        # per-rank slice) loses the full-column structure and takes the
        # general scatter path instead.
        if (self.algorithm == "chunk" and numel >= 2 * k
                and values.shape[0] == k):
            rows = -(-numel // k)
            win_row = (indices // k).astype(jnp.int32)
            return chunkwise_dense(values.astype(dtype), win_row, rows,
                                   numel, shape)
        return scatter_dense(values.astype(dtype), indices, numel, shape)
