"""Top-K magnitude sparsification.

Reference: grace_dl/dist/compressor/topk.py:6-36 — keep the k = ⌈ratio·n⌉
largest-magnitude entries, ship (values, indices), scatter into zeros to
decompress. ``jax.lax.top_k`` maps directly onto this with a static k, so
the payload shape is fixed at trace time (XLA requirement) and identical on
every rank — the all-gather path needs no size exchange.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.sparse import scatter_dense


def static_k(numel: int, ratio: float) -> int:
    return max(1, int(numel * ratio))


@dataclasses.dataclass(frozen=True)
class TopKCompressor(Compressor):
    compress_ratio: float = 0.3

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        k = static_k(numel, self.compress_ratio)
        _, indices = lax.top_k(jnp.abs(flat), k)
        indices = indices.astype(jnp.int32)
        values = flat[indices]
        return (values, indices), (numel, shape), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        values, indices = payload
        numel, shape = ctx
        return scatter_dense(values, indices, numel, shape)
