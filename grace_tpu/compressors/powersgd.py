"""PowerSGD low-rank compression (Vogels et al. 2019).

Reference: grace_dl/dist/compressor/powersgd.py:21-65 — the one algorithm
whose communication happens *inside* compress: P = MQ → allreduce(P)/W →
orthogonalize → Q = MᵀP → allreduce(Q)/W; compress returns ``([], ctx)`` so
the communicator has nothing to send, and decompress reconstructs PQᵀ. This
is natural in JAX: compress already runs inside `shard_map`, so the
allreduces are plain ``lax.psum`` over the mesh axis.

State contract (SURVEY.md §7 hard part 2): the reference couples compressor
and memory through a shared mutable ``q_memory`` dict (helper passes
``compressor.q_memory`` into the memory, which overwrites it with fresh
Gaussian Q every step — torch/dist reference never actually warm-starts).
Here Q is explicit per-leaf compressor state: ``warm_start=True`` (default)
reuses last step's Q as the power-iteration start, which is the published
algorithm and converges better; ``warm_start=False`` redraws Gaussian Q each
step, reproducing the reference's effective behavior. No shared-dict
coupling either way.

1-D tensors bypass compression (reference powersgd.py:31-32): payload is the
raw tensor, summed/averaged densely by the communicator.

Matricization: the reference views tensors as ``(shape[0], -1)``
(powersgd.py:34) — correct for torch's OIHW conv kernels, where dim 0 is the
output-channel dim. JAX convs are HWIO (output channels LAST), so the same
rule would factor a (3,3,cin,cout) kernel as a degenerate (3, 3·cin·cout)
matrix whose Q factor is nearly dense-sized (measured 2.5x the dense bytes
over ResNet-50). Here tensors matricize as ``(-1, shape[-1])`` — the
output-channel dim is one factor side, exactly the reference's semantics in
the native JAX layout; 2-D weights are unchanged.

Orthogonalization uses ``jnp.linalg.qr`` — a fused XLA op on the MXU —
instead of the reference's column-by-column @torch.jit.script Gram-Schmidt
(powersgd.py:7-18), which would serialize r matvecs.

Rung-invariant state layout (graft-retune): an adapt ladder across
PowerSGD *ranks* must thread one comp-state structure through every
``lax.switch`` branch, but a rank-r rung natively stores a ``(m, r)`` Q —
structurally different per rung. ``state_rank`` decouples the stored
layout from the active rank: the per-leaf state is padded to
``(m, min(n, m, state_rank))`` and each rung operates on its leading
``rank`` columns, writing its refined Q back into that slice and carrying
the inactive tail columns UNCHANGED. That makes the padding a warm-start
carrier, not dead weight — when the controller moves UP a rung, the new
columns resume from whatever power-iteration state they last held (the
PowerSGD paper's warm-start result, extended across rung moves). With
``state_rank=None`` (or ``== rank``) the slice and re-pad are no-ops and
the codec is bit-identical to the unpadded layout. Wire pricing is
untouched: only the ACTIVE ``(n + m) * rank`` factors ever travel.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from grace_tpu.core import DEFAULT_AXIS, Compressor, Ctx, Payload, State


@dataclasses.dataclass(frozen=True)
class PowerSGDCompressor(Compressor):
    rank: int = 1
    warm_start: bool = True
    axis_name: str = DEFAULT_AXIS
    # Stored-Q column count for rung-invariant adapt ladders: pad the
    # per-leaf state to the ladder's max rank so every rung threads the
    # same structure through lax.switch. None = store exactly `rank`
    # columns (the classic layout). Must be >= rank when set.
    state_rank: Optional[int] = None
    # 1-D leaves ride the communicator dense; >=2-D leaves were already
    # psum-reduced inside compress, so the outer allreduce sees a replicated
    # payload that sums/averages consistently — exact composition.
    payload_algebra = "exact"
    # Communicates inside compress and carries cross-step Q state — the
    # shard-parallel communicators reject it before capability gating.
    supports_hop_requant = False

    def _factor_shapes(self, shape):
        m = shape[-1]              # output-channel dim (HWIO/(*, features))
        n = int(np.prod(shape[:-1], dtype=np.int64))
        r = min(n, m, self.rank)
        return n, m, r

    def _state_cols(self, n: int, m: int) -> int:
        """Stored-Q column count: the padded layout when ``state_rank``
        is set, else exactly the active rank."""
        if self.state_rank is not None:
            if self.state_rank < self.rank:
                raise ValueError(
                    f"PowerSGD state_rank={self.state_rank} < rank="
                    f"{self.rank}: the stored Q must hold at least the "
                    "active columns")
            return min(n, m, self.state_rank)
        return min(n, m, self.rank)

    def init_state(self, x: jax.Array) -> State:
        if x.ndim <= 1:
            return None
        n, m, _ = self._factor_shapes(x.shape)
        rs = self._state_cols(n, m)
        # Deterministic initial Q; identical on all ranks by construction.
        # The bit-exactness claim for the padded layout holds at rs == r
        # (state_rank None or == rank) — a wider draw is a different
        # random matrix, which is fine: padding exists to serve ladders,
        # whose quiet-run contract is judged per layout, not across them.
        return jax.random.normal(jax.random.key(x.size), (m, rs), x.dtype)

    def wire_nbytes(self, shape, dtype) -> int:
        """Analytic: compress's psums of P (n,r) and Q (m,r) ARE the wire
        traffic; the payload tuple is empty and compress cannot be
        shape-traced without a bound mesh axis."""
        itemsize = jnp.dtype(dtype).itemsize
        if len(shape) <= 1:
            # 1-D bypass rides dense
            return int(np.prod(shape, dtype=np.int64)) * itemsize
        n, m, r = self._factor_shapes(shape)
        return (n + m) * r * itemsize

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        if x.ndim <= 1:
            return (x,), None, state
        shape = x.shape
        n, m, r = self._factor_shapes(shape)
        matrix = x.reshape(n, m)   # n = prod(leading dims), m = shape[-1]
        q_full = state             # (m, rs) with rs >= r; rs == r unpadded
        if self.warm_start:
            q = q_full[:, :r]      # active columns only drive this rung
        else:
            # rng is replicated across ranks, so the redrawn Q agrees too.
            q = jax.random.normal(rng, (m, r), x.dtype)
        q, _ = jnp.linalg.qr(q)
        w = lax.psum(1, self.axis_name)
        p = matrix @ q
        p = lax.psum(p, self.axis_name) / w
        p, _ = jnp.linalg.qr(p)
        q = matrix.T @ p
        q = lax.psum(q, self.axis_name) / w
        # Re-pad: refined active columns in front, inactive tail carried
        # untouched — the warm-start store for any HIGHER rung this ladder
        # may move to. At rs == r the tail is empty and this is q itself.
        return (), (p, q, shape), jnp.concatenate(
            [q, q_full[:, r:]], axis=1)

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        if ctx is None:
            (x,) = payload
            return x
        p, q, shape = ctx
        return (p @ q.T).reshape(shape)
