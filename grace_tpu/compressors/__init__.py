"""Compression algorithm catalog.

One module per algorithm, mirroring the reference's
grace_dl/{dist,torch,tensorflow}/compressor/ trees — except that the three
per-backend copies (SURVEY.md §1 "parallel siblings") collapse into this one
functional implementation.
"""

from grace_tpu.compressors.none import NoneCompressor
from grace_tpu.compressors.fp16 import FP16Compressor
from grace_tpu.compressors.topk import TopKCompressor
from grace_tpu.compressors.cyclictopk import CyclicTopKCompressor
from grace_tpu.compressors.randomk import RandomKCompressor
from grace_tpu.compressors.threshold import ThresholdCompressor
from grace_tpu.compressors.qsgd import QSGDCompressor
from grace_tpu.compressors.homoqsgd import HomoQSGDCompressor
from grace_tpu.compressors.countsketch import CountSketchCompressor
from grace_tpu.compressors.terngrad import TernGradCompressor
from grace_tpu.compressors.signsgd import SignSGDCompressor, SignumCompressor
from grace_tpu.compressors.efsignsgd import EFSignSGDCompressor
from grace_tpu.compressors.onebit import OneBitCompressor
from grace_tpu.compressors.natural import NaturalCompressor
from grace_tpu.compressors.dgc import DgcCompressor
from grace_tpu.compressors.powersgd import PowerSGDCompressor
from grace_tpu.compressors.sketch import SketchCompressor
from grace_tpu.compressors.u8bit import U8bitCompressor
from grace_tpu.compressors.adaq import AdaqCompressor
from grace_tpu.compressors.inceptionn import InceptionNCompressor

__all__ = [
    "NoneCompressor", "FP16Compressor", "TopKCompressor",
    "CyclicTopKCompressor", "RandomKCompressor",
    "ThresholdCompressor", "QSGDCompressor", "HomoQSGDCompressor",
    "CountSketchCompressor", "TernGradCompressor",
    "SignSGDCompressor", "SignumCompressor", "EFSignSGDCompressor",
    "OneBitCompressor", "NaturalCompressor", "DgcCompressor",
    "PowerSGDCompressor", "SketchCompressor", "U8bitCompressor",
    "AdaqCompressor", "InceptionNCompressor",
]
