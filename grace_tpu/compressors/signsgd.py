"""SignSGD with majority-vote aggregation.

Reference: grace_dl/dist/compressor/signsgd.py:6-30 — transmit ``x >= 0`` as
one uint8 per element; aggregate = sum of ±1 then re-sign (majority vote);
``average=False``. TPU-first change: signs are bit-packed 8/byte
(grace_tpu.ops.packing), an 8× wire reduction the reference leaves on the
table. Note for the allreduce-style path: ``psum`` of ±1 followed by sign is
an exact majority vote (SURVEY.md §7 hard part 4) — exposed via
``aggregate`` on the gathered stack, which XLA lowers to the same reduction.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.packing import pack_bits, unpack_bits


def _signs_to_float(bits: jax.Array, dtype) -> jax.Array:
    return bits.astype(dtype) * 2 - 1


@dataclasses.dataclass(frozen=True)
class SignSGDCompressor(Compressor):
    average = False
    vote_aggregate = True   # aggregate IS the majority vote (SignAllreduce-safe)
    # Ring hop requant (comm.RingAllreduce): re-signing the running partial
    # at each hop is a CASCADED vote — unanimous coordinates survive
    # exactly, split coordinates weight later ranks more than a one-shot
    # majority (ties resolve +1). A deliberate 1-bit-wire relaxation; the
    # exact fixed-cost vote remains SignAllreduce. (Signum inherits the
    # flag but is stateful, so the ring's stateless gate rejects it first.)
    supports_hop_requant = True
    # Packed sign bytes: psumming them is garbage — the vote routes exist
    # precisely because the payload has no composition algebra.
    payload_algebra = None

    # Fused Pallas sign-bitpack kernel (grace_tpu/ops/pallas_quant.sign_pack):
    # the packed sign mask leaves VMEM wire-ready instead of staging a full
    # bool tensor through the jnp shift/sum pack. Sign extraction is
    # deterministic, so kernel and staged paths are BIT-IDENTICAL (pinned in
    # tests/test_pallas_quant.py) — 'auto' (kernel on real TPU, staged
    # elsewhere) can never change results, only where the bytes are packed.
    # True forces the kernel even off-TPU (interpret mode: slow, test-only);
    # False forces the staged jnp pack.
    use_pallas: bool | str = "auto"

    def __post_init__(self):
        # Identity membership, not ==: 1 == True would pass equality
        # validation yet dodge the `is True` checks below.
        if not (self.use_pallas == "auto" or self.use_pallas is True
                or self.use_pallas is False):
            raise ValueError(f"use_pallas must be True, False or 'auto'; "
                             f"got {self.use_pallas!r}")

    def _pallas_mode(self):
        # The ONE shared selection rule — see grace_tpu.ops.pallas_mode.
        from grace_tpu.ops import pallas_mode
        return pallas_mode(self.use_pallas, kernel="quant")

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        enabled, interpret = self._pallas_mode()
        if enabled:
            from grace_tpu.ops.pallas_quant import sign_pack
            packed = sign_pack(flat, interpret=interpret)
        else:
            packed = pack_bits(flat >= 0)
        return (packed,), (numel, shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        (packed,) = payload
        numel, shape, dtype = ctx
        signs = _signs_to_float(unpack_bits(packed, numel), dtype)
        return signs.reshape(shape)

    def aggregate(self, stacked: jax.Array) -> jax.Array:
        # Majority vote: reference signsgd.py:25-30.
        summed = jnp.sum(stacked, axis=0)
        return (summed >= 0).astype(stacked.dtype) * 2 - 1

    def wire_fused(self) -> bool:
        """Live wire-kernel gate (core.Compressor.wire_fused) — the
        condition under which :meth:`decode_accumulate` takes its fused
        branch, consulted by the communicators' gather boundaries."""
        from grace_tpu.ops import pallas_mode
        return pallas_mode(self.use_pallas, kernel="wire")[0]

    def decode_accumulate(self, payloads, ctxs):
        """The fused sign-hop decode: unpack K packed masks, map to ±1
        and sum in ONE Pallas kernel (pallas_wire.decode_accumulate,
        sign=True) — sign extraction is deterministic, so the kernel is
        bit-identical to the staged ``decompress + decompress`` (small
        integers, exact in f32) everywhere, not just in distribution.
        Staged fallback under the shared wire-family selection rule."""
        from grace_tpu.ops import pallas_mode
        enabled, interpret = pallas_mode(self.use_pallas, kernel="wire")
        numel, shape, dtype = ctxs[0]
        if (not enabled or jnp.dtype(dtype) != jnp.float32
                or any(c != (numel, shape, dtype) for c in ctxs)):
            return super().decode_accumulate(payloads, ctxs)
        from grace_tpu.ops.pallas_wire import decode_accumulate as _fused
        stacked = jnp.stack([p[0] for p in payloads])
        scales = jnp.ones((stacked.shape[0],), jnp.float32)
        out = _fused(stacked, scales, numel, 1, sign=True,
                     interpret=interpret)
        return out.astype(dtype).reshape(shape)


@dataclasses.dataclass(frozen=True)
class SignumCompressor(SignSGDCompressor):
    """SignSGD on a momentum-filtered gradient.

    Reference: grace_dl/dist/compressor/signum.py:6-37 — the compressor holds
    per-name momentum dicts; here momentum is explicit per-leaf state
    ``(m, initialized)`` so it jits and checkpoints. First step transmits the
    raw gradient's sign (reference: ``if name in self.momentums`` miss path).
    """

    # Restated (not just inherited) per the graft-lint capability rule:
    # stateful momentum makes the shard-parallel communicators reject
    # Signum at the stateless gate, so it must not advertise hop requant
    # it can never use; sign bytes are as algebra-free as the parent's.
    payload_algebra = None
    supports_hop_requant = False

    momentum: float = 0.9

    def init_state(self, x: jax.Array) -> State:
        return {"momentum": jnp.zeros(x.size, x.dtype),
                "initialized": jnp.zeros((), jnp.bool_)}

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        blended = (1.0 - self.momentum) * flat + self.momentum * state["momentum"]
        m = jnp.where(state["initialized"], blended, flat)
        enabled, interpret = self._pallas_mode()
        if enabled:
            from grace_tpu.ops.pallas_quant import sign_pack
            packed = sign_pack(m, interpret=interpret)
        else:
            packed = pack_bits(m >= 0)
        new_state = {"momentum": m, "initialized": jnp.ones((), jnp.bool_)}
        return (packed,), (numel, shape, x.dtype), new_state
