"""Random-K sparsification with rank-shared index selection.

Reference: grace_dl/dist/compressor/randomk.py:6-40 — every rank seeds the
global torch RNG with ``hash(name) + global_step`` so all ranks draw the same
random index set; only values travel, indices live in ctx. The JAX design
makes the shared-randomness contract explicit instead of a global-seed hack
(SURVEY.md §7 hard part 5): the pipeline hands ``compress`` an rng key that
is ``fold_in(fold_in(seed, step), leaf_index)`` — replicated across ranks by
construction — so the permutation is identical everywhere and the indices
legitimately belong in ctx.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.sparse import scatter_dense
from grace_tpu.compressors.topk import static_k


@dataclasses.dataclass(frozen=True)
class RandomKCompressor(Compressor):
    compress_ratio: float = 0.3
    # Indices come from a shared fold_in key, so every rank selects the same
    # entries and payload values sum exactly (reference randomk.py:26-29).
    payload_algebra = "exact"
    # Linear codec: the exact payload-space ring path applies; no requant.
    supports_hop_requant = False

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        k = static_k(numel, self.compress_ratio)
        # Sampling WITHOUT replacement, like the dist/torch reference
        # (randperm, randomk.py:26-29). The TF variant samples with
        # replacement and has a maxval off-by-one (SURVEY.md §2.3) — a bug,
        # not replicated.
        indices = jax.random.permutation(rng, numel)[:k].astype(jnp.int32)
        values = flat[indices]
        return (values,), (indices, numel, shape), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        (values,) = payload
        indices, numel, shape = ctx
        return scatter_dense(values, indices, numel, shape)
