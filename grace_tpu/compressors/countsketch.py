"""Mergeable count-sketch codec: sign-hash tables that add exactly.

The second aggregation-homomorphic family (THC / SketchML lineage,
PAPERS.md): project the gradient into ``rows`` independent sign-hash
tables — ``table[r, h_r(i)] += s_r(i) · x[i]`` — and estimate each
coordinate on decode as the median over rows of ``s_r(i) ·
table[r, h_r(i)]``. The load-bearing property is **linearity of the
encode**: ``sketch(x) + sketch(y) == sketch(x + y)`` bit-for-bit up to
float associativity, because the hash/sign streams derive from the SHARED
replicated rng key every rank holds (the same contract RandomK's shared
indices ride). So every ring hop and slice boundary adds tables in payload
space with zero merge loss, and the single decode at the very end pays ONE
estimation error instead of the W a decode-each-then-aggregate gather
pays. Unlike the quantile :class:`~grace_tpu.compressors.sketch
.SketchCompressor` (whose per-rank bin edges shift and compose not at
all), the hash structure lives in ctx — derived from rng alone, so it is
data-free and the shard-parallel communicators' locally-derived-ctx decode
is sound without shipping it.

Wire cost: ``rows · width`` f32 cells with ``width = ceil(ratio · n /
rows)`` — ``compress_ratio`` is the total table-cells-per-element budget,
so the payload is ``ratio · n`` floats regardless of ``rows``. The
estimate is unbiased with collision noise ~ ||x||/√width per cell; the
median over odd ``rows`` suppresses heavy-collision outliers.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from grace_tpu.core import Compressor, Ctx, Payload, State


@dataclasses.dataclass(frozen=True)
class CountSketchCompressor(Compressor):
    # Linear mergeable sketches: tables add exactly across ranks/hops; ONE
    # median-estimate decode at the end of the schedule.
    payload_algebra = "sketch"
    # Re-sketching a partial sum is pointless — merging IS exact.
    supports_hop_requant = False

    compress_ratio: float = 0.25   # total table cells per input element
    rows: int = 3                  # independent hash rows (odd: true median)

    def __post_init__(self):
        if not 0.0 < self.compress_ratio <= 1.0:
            raise ValueError(f"compress_ratio must be in (0, 1]; got "
                             f"{self.compress_ratio}")
        if self.rows < 1 or self.rows % 2 == 0:
            raise ValueError(f"rows must be a positive odd count (median "
                             f"estimation); got {self.rows}")

    def _width(self, numel: int) -> int:
        return max(1, math.ceil(self.compress_ratio * numel / self.rows))

    def _hashes(self, rng: jax.Array, numel: int):
        """(idx, signs): per-row bucket indices and ±1 signs for every
        coordinate, drawn from the SHARED rng key — rank-identical by the
        replicated-key contract, hence mergeable payloads and a data-free
        ctx (the ring/hier soundness condition)."""
        width = self._width(numel)
        kidx, ksign = jax.random.split(jax.random.fold_in(rng, 0x5ce7c))
        idx = jax.random.randint(kidx, (self.rows, numel), 0, width,
                                 dtype=jnp.int32)
        signs = jax.random.rademacher(ksign, (self.rows, numel),
                                      dtype=jnp.int8)
        return idx, signs

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape = x.shape
        flat = x.reshape(-1).astype(jnp.float32)
        numel = flat.size
        width = self._width(numel)
        idx, signs = self._hashes(rng, numel)

        def row(i, s):
            return jax.ops.segment_sum(s.astype(jnp.float32) * flat, i,
                                       num_segments=width)

        table = jax.vmap(row)(idx, signs)          # (rows, width) f32
        return (table,), (idx, signs, shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        (table,) = payload
        idx, signs, shape, dtype = ctx
        est = signs.astype(jnp.float32) * jnp.take_along_axis(
            table, idx, axis=1)                    # (rows, numel)
        out = jnp.median(est, axis=0)
        return out.reshape(shape).astype(dtype)
