"""Natural compression (Horváth et al. 2019): stochastic power-of-two rounding.

Reference: grace_dl/dist/compressor/natural.py:9-40 — the only GPU-kernel
code in the reference (CuPy via DLPack). The codec: bitcast fp32 to int,
stochastically round the exponent up with probability mantissa/2^23, clip
the biased exponent to [18, 145], and pack sign+shifted-exponent into one
uint8 (code 0 ⇒ underflow to zero). On TPU this is pure
``lax.bitcast_convert_type`` + jnp bitwise ops — XLA fuses it, no custom
kernel needed (SURVEY.md §2.4).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import Compressor, Ctx, Payload, State

_MANTISSA_BITS = 23
_MANTISSA_MASK = (1 << _MANTISSA_BITS) - 1
_EXP_MASK = 0xFF << _MANTISSA_BITS
_MIN_BIASED_EXP = 18   # reference clip: 0b00001001000... = 18 << 23
_MAX_BIASED_EXP = 145  # reference clip: 0b01001000100... = 145 << 23


@dataclasses.dataclass(frozen=True)
class NaturalCompressor(Compressor):
    # Integer exponent/sign codes: adding two ranks' code words is garbage
    # (no algebra — unlike shared-scale LEVELS, these ints are codes), and
    # there is no bounded re-encode of a partial sum.
    payload_algebra = None
    supports_hop_requant = False

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape = x.shape
        flat = x.reshape(-1).astype(jnp.float32)
        bits = lax.bitcast_convert_type(flat, jnp.uint32)
        sign = (bits >> 31).astype(jnp.uint8)
        exp = (bits & _EXP_MASK) >> _MANTISSA_BITS           # biased exponent
        mantissa = bits & _MANTISSA_MASK
        rnd = jax.random.randint(rng, flat.shape, 0, _MANTISSA_MASK,
                                 dtype=jnp.int32).astype(jnp.uint32)
        exp = jnp.where(mantissa > rnd, exp + 1, exp)
        exp = jnp.clip(exp, _MIN_BIASED_EXP, _MAX_BIASED_EXP)
        # 7-bit exponent code in [0, 127]; 0 flushes to zero on decompress.
        code = (sign << 7) | (exp - _MIN_BIASED_EXP).astype(jnp.uint8)
        return (code.astype(jnp.uint8),), (shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        (code,) = payload
        shape, dtype = ctx
        sign = code >= 128
        exp_code = (code & 0x7F).astype(jnp.uint32)
        bits = (exp_code + _MIN_BIASED_EXP) << _MANTISSA_BITS
        mag = lax.bitcast_convert_type(bits.astype(jnp.uint32), jnp.float32)
        out = jnp.where(sign, -mag, mag)
        out = jnp.where(exp_code >= 1, out, 0.0)
        return out.reshape(shape).astype(dtype)
