"""Deep Gradient Compression (Lin et al. 2018): sampled-threshold top-k.

Reference: grace_dl/dist/compressor/dgc.py:6-50 — estimate the top-k
threshold from a 1% random sample, refine it for ≤10 rounds (×1.3 / ×0.7)
until the selected count lands in [0.7k, 1.3k], then transmit the selected
(values, indices). The data-dependent Python refinement loop becomes a
``lax.while_loop`` (compiled, early-exits exactly like the reference), and
the variable-size payload becomes a fixed-capacity one (capacity 1.3k + 1,
the reference's own upper acceptance bound) with sub-threshold lanes zeroed
— see SURVEY.md §7 hard part 1. Pairs with
:class:`grace_tpu.memories.DgcMemory` for momentum-corrected residuals.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.sparse import scatter_dense


@dataclasses.dataclass(frozen=True)
class DgcCompressor(Compressor):
    tensors_size_are_same = False
    # Capacity-masked (values, per-rank indices): summing payloads mixes
    # entries at different coordinates (no algebra), and a partial sum
    # destroys the sampled-threshold capacity mask a re-encode would need.
    payload_algebra = None
    supports_hop_requant = False

    compress_ratio: float = 0.01
    sample_ratio: float = 0.01
    max_refinements: int = 10

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        abs_flat = jnp.abs(flat)

        # 1% sample -> top-k of the sample estimates the global threshold
        # (reference dgc.py:17-24). Sample indices are drawn with replacement
        # like the reference's uniform_(0, numel) cast to long.
        num_samples = max(1, int(numel * self.sample_ratio))
        sample_idx = jax.random.randint(rng, (num_samples,), 0, numel)
        sample = abs_flat[sample_idx]
        k_sample = max(1, int(numel * self.compress_ratio * self.sample_ratio))
        top_sample, _ = lax.top_k(sample, k_sample)
        thr0 = top_sample[-1]

        target = numel * self.compress_ratio

        def count(thr):
            return jnp.sum(abs_flat >= thr)

        def cond(carry):
            i, thr, selected = carry
            in_band = (selected <= 1.3 * target) & (selected >= 0.7 * target)
            return (i < self.max_refinements) & ~in_band

        def body(carry):
            i, thr, selected = carry
            thr = jnp.where(selected > 1.3 * target, 1.3 * thr,
                            jnp.where(selected < 0.7 * target, 0.7 * thr, thr))
            return i + 1, thr, count(thr)

        _, thr, _ = lax.while_loop(cond, body, (0, thr0, count(thr0)))

        cap = min(numel, max(1, int(numel * self.compress_ratio * 1.3) + 1))
        mags, indices = lax.top_k(abs_flat, cap)
        indices = indices.astype(jnp.int32)
        values = jnp.where(mags >= thr, flat[indices], 0)
        return (values, indices), (numel, shape), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        values, indices = payload
        numel, shape = ctx
        return scatter_dense(values, indices, numel, shape)
