"""TernGrad ternarization (Wen et al. 2017).

Reference: grace_dl/dist/compressor/terngrad.py:6-32 — clip at 2.5σ, scale
by max |clipped|, stochastically ternarize to {-1, 0, 1}·scalar. The
reference ships one int8 per element; we pack the ternary codes 4/byte as
2-bit values (grace_tpu.ops.packing), a 4× wire reduction.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.packing import pack_2bit, unpack_2bit


@dataclasses.dataclass(frozen=True)
class TernGradCompressor(Compressor):
    # Per-rank max-scale ternary levels: payloads decode against each rank's
    # own scaler (no algebra; the shared-scale fix is HomoQSGDCompressor),
    # and re-ternarizing a partial sum compounds the stochastic scale
    # without a validated bound — Allgather only.
    payload_algebra = None
    supports_hop_requant = False

    clip_factor: float = 2.5

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        std = jnp.std(flat)
        c = self.clip_factor * std
        clipped = jnp.clip(flat, -c, c)
        abs_g = jnp.abs(clipped)
        scalar = jnp.max(abs_g)
        rnd = jax.random.uniform(rng, flat.shape, flat.dtype,
                                 maxval=jnp.maximum(scalar, 1e-30))
        keep = rnd < abs_g
        # codes: 0 -> 0, 1 -> +1, 2 -> -1 (two bits per element).
        sign_pos = clipped >= 0
        codes = jnp.where(keep, jnp.where(sign_pos, 1, 2), 0).astype(jnp.uint8)
        return (pack_2bit(codes), scalar), (numel, shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        packed, scalar = payload
        numel, shape, dtype = ctx
        codes = unpack_2bit(packed, numel)
        tern = jnp.where(codes == 1, 1.0, jnp.where(codes == 2, -1.0, 0.0))
        return (tern.astype(dtype) * scalar).reshape(shape)
