"""Identity (no-op) compressor. Reference: grace_dl/dist/compressor/none.py:4-12."""

from __future__ import annotations

import dataclasses

import jax

from grace_tpu.core import Compressor, Ctx, Payload, State


@dataclasses.dataclass(frozen=True, kw_only=True)
class NoneCompressor(Compressor):
    """Pass-through: payload is the tensor itself.

    ``average`` is configurable like the reference ctor flag
    (grace_dl/dist/__init__.py:18), but keyword-only: the reference example
    misuse ``NoneCompressor(0.005)`` (examples/torch/pytorch_mnist.py:122)
    silently set ``average=0.005``; here it is a TypeError.
    """

    average: bool = True
    # Identity payload IS the tensor: sums compose exactly by definition.
    payload_algebra = "exact"
    # Linear codec: the exact payload-space ring path applies; a requant
    # round-trip would add nothing but work.
    supports_hop_requant = False

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        return (x,), None, state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        (x,) = payload
        return x
