"""EF-SignSGD (error-feedback sign SGD, Karimireddy et al. 2019).

Reference: grace_dl/dist/compressor/efsignsgd.py:6-33 + memory at
grace_dl/dist/memory/efsignsgd.py:4-19. Payload is the mean |x| scale plus
the sign bits (bit-packed here); aggregation sums the scaled signs and
divides by the learning rate, undoing the lr-scaling the paired memory
applied during compensate.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.packing import pack_bits, unpack_bits


@dataclasses.dataclass(frozen=True)
class EFSignSGDCompressor(Compressor):
    average = False
    # Payload is (packed signs, per-rank 1/lr·mean scale): sign bytes don't
    # sum (no algebra) and the scale pair has no meaning over a partial sum.
    payload_algebra = None
    supports_hop_requant = False

    lr: float = 0.1

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        mean = jnp.mean(jnp.abs(flat))
        packed = pack_bits(flat >= 0)
        return (mean, packed), (numel, shape, x.dtype), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        mean, packed = payload
        numel, shape, dtype = ctx
        signs = unpack_bits(packed, numel).astype(dtype) * 2 - 1
        return (mean * signs).reshape(shape)

    def aggregate(self, stacked: jax.Array) -> jax.Array:
        return jnp.sum(stacked, axis=0) / self.lr
