"""1-bit SGD (Seide et al. 2014): sign mask plus per-partition means.

Reference: grace_dl/dist/compressor/onebit.py:6-31 — partition by sign,
transmit the negative-mask plus mean of negatives and mean of positives.
Signs are bit-packed here (8× wire saving vs the reference's uint8 mask).
The reference's data-dependent ``if num0 > 0`` guards become ``jnp.where``
on the count (XLA has no data-dependent control flow).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from grace_tpu.core import Compressor, Ctx, Payload, State
from grace_tpu.ops.packing import pack_bits, unpack_bits


@dataclasses.dataclass(frozen=True)
class OneBitCompressor(Compressor):
    # Payload is (packed sign mask, mean-of-negatives, mean-of-positives):
    # the mean pair has no meaning summed across ranks or over a partial —
    # no payload algebra.
    payload_algebra = None
    supports_hop_requant = False

    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        shape, numel = x.shape, x.size
        flat = x.reshape(-1)
        mask0 = flat < 0
        num0 = jnp.sum(mask0).astype(flat.dtype)
        sum0 = jnp.sum(jnp.where(mask0, flat, 0))
        mean0 = jnp.where(num0 > 0, sum0 / jnp.maximum(num0, 1), sum0)
        num1 = numel - num0
        sum1 = jnp.sum(jnp.where(mask0, 0, flat))
        mean1 = jnp.where(num1 > 0, sum1 / jnp.maximum(num1, 1), sum1)
        packed = pack_bits(mask0)
        return (packed, mean0, mean1), (numel, shape), state

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        packed, mean0, mean1 = payload
        numel, shape = ctx
        mask0 = unpack_bits(packed, numel)
        return jnp.where(mask0, mean0, mean1).reshape(shape)
