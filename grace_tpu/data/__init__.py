"""Host data pipeline: native threaded loader with a pure-Python fallback.

The reference's input path is torch ``DataLoader`` worker processes +
``DistributedSampler`` (examples/torch/pytorch_mnist.py:63-70); grace-tpu's
is a first-party C++ library (native/dataloader.cpp): worker threads
assemble normalized float32 batches into a bounded prefetch queue while the
TPU executes the previous step, with deterministic cross-process epoch
shuffling and rank-strided sharding.

`NativeLoader` binds it via ctypes (no pybind11 dependency). If the shared
library has not been built (``make -C native``), `make_loader` transparently
falls back to `PythonLoader`, a numpy implementation of the same contract:

    loader = make_loader(MemoryDataset(x_uint8, y, mean, std), batch_size=512,
                         seed=0, rank=0, world=1)
    for epoch in range(E):
        for x, y in loader.epoch(epoch):   # x: (B,H,W,C) f32, y: (B,) i32
            ...

Epoch iteration order is a pure function of (seed, epoch), identical across
ranks; rank r consumes the strided slice r::world of each epoch permutation.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
from typing import Iterator, Optional, Tuple

import numpy as np

__all__ = ["MemoryDataset", "NativeLoader", "PythonLoader", "make_loader",
           "native_library_path", "mnist_dataset", "mnist_split_dataset",
           "cifar10_dataset", "digits_dataset", "prefetch_to_device"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_DEFAULT_LIB = os.path.join(_REPO_ROOT, "native", "libgrace_data.so")


def native_library_path() -> Optional[str]:
    """Path to the built native library, or None if absent."""
    path = os.environ.get("GRACE_TPU_NATIVE_LIB", _DEFAULT_LIB)
    return path if os.path.exists(path) else None


@dataclasses.dataclass(frozen=True)
class MemoryDataset:
    """In-memory uint8 NHWC dataset + per-channel normalization stats.

    ``mean``/``std`` are in [0,1] units (multiplied by 255 internally),
    matching the torchvision convention the reference uses.
    """

    images: np.ndarray          # (n, h, w, c) uint8
    labels: np.ndarray          # (n,) int32
    mean: Optional[Tuple[float, ...]] = None
    std: Optional[Tuple[float, ...]] = None

    def __post_init__(self):
        if self.images.dtype != np.uint8 or self.images.ndim != 4:
            raise ValueError("images must be (n,h,w,c) uint8")
        if len(self.labels) != len(self.images):
            raise ValueError("labels/images length mismatch")

    def normalize(self, raw: np.ndarray) -> np.ndarray:
        x = raw.astype(np.float32)
        if self.mean is None:
            return x / 255.0
        mean = np.asarray(self.mean, np.float32) * 255.0
        std = np.asarray(self.std, np.float32) * 255.0
        return (x - mean) / std


def _read_idx(data_dir, train):
    import gzip
    import struct
    prefix = "train" if train else "t10k"

    def _open(name):
        for cand in (os.path.join(data_dir, name),
                     os.path.join(data_dir, name + ".gz")):
            if os.path.exists(cand):
                return gzip.open(cand, "rb") if cand.endswith(".gz") \
                    else open(cand, "rb")
        raise FileNotFoundError(f"{name}[.gz] not found under {data_dir}")

    with _open(f"{prefix}-images-idx3-ubyte") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051
        x = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols, 1)
    with _open(f"{prefix}-labels-idx1-ubyte") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049
        y = np.frombuffer(f.read(), np.uint8).astype(np.int32)
    return x, y


def mnist_dataset(data_dir: str, train: bool = True) -> MemoryDataset:
    """MNIST idx(.gz) files -> MemoryDataset with the standard stats."""
    x, y = _read_idx(data_dir, train)
    return MemoryDataset(x, y, mean=(0.1307,), std=(0.3081,))


def mnist_split_dataset(data_dir: str, train: bool = True,
                        split_seed: int = 0,
                        fraction: float = 0.8) -> MemoryDataset:
    """Fixed-seed 80/20 split of the MNIST *t10k* file set.

    The reference ships the 10,000-image MNIST test set as committed example
    fixtures (examples/torch/data-{0,1}/MNIST/raw/t10k-*) so 2-rank runs
    need no downloads; this repo bundles the same public-domain files under
    examples/data/MNIST/raw. With only the t10k files available, real-data
    training evidence comes from a deterministic shuffle-then-split: 8,000
    train / 2,000 held-out test, disjoint by construction, reproducible for
    a given ``split_seed``.
    """
    x, y = _read_idx(data_dir, train=False)
    idx = np.random.default_rng(split_seed).permutation(len(x))
    cut = int(fraction * len(x))
    sel = np.sort(idx[:cut] if train else idx[cut:])
    return MemoryDataset(x[sel], y[sel], mean=(0.1307,), std=(0.3081,))


def digits_dataset(train: bool = True, upscale: bool = True,
                   split_seed: int = 0) -> MemoryDataset:
    """UCI handwritten digits (real data, bundled with scikit-learn).

    1,797 scanned 8x8 grayscale digits — the only *real* image dataset
    available without network access, used as the committed convergence
    evidence (the MNIST-idx loader above covers the full-size dataset when
    files are present). A fixed-seed 80/20 split keeps train/test disjoint
    and reproducible. ``upscale`` nearest-neighbour×3 + pad → 28x28 so the
    LeNet of the flagship example (models/lenet.py) applies unchanged.
    """
    try:
        from sklearn.datasets import load_digits
    except ImportError as e:
        raise ImportError(
            "digits_dataset needs scikit-learn (the dataset is bundled with "
            "it): pip install scikit-learn") from e

    d = load_digits()
    x = np.round(d.images / 16.0 * 255.0).astype(np.uint8)[..., None]
    y = d.target.astype(np.int32)
    if upscale:
        x = np.kron(x[..., 0], np.ones((3, 3), np.uint8))[..., None]
        x = np.pad(x, ((0, 0), (2, 2), (2, 2), (0, 0)))
    order = np.random.default_rng(split_seed).permutation(len(x))
    n_train = int(0.8 * len(x))
    sel = order[:n_train] if train else order[n_train:]
    ref = x[order[:n_train]].astype(np.float32) / 255.0
    return MemoryDataset(x[sel], y[sel],
                         mean=(float(ref.mean()),), std=(float(ref.std()),))


def cifar10_dataset(data_dir: str, train: bool = True) -> MemoryDataset:
    names = [f"data_batch_{i}.bin" for i in range(1, 6)] if train \
        else ["test_batch.bin"]
    xs, ys = [], []
    for name in names:
        raw = np.fromfile(os.path.join(data_dir, name), np.uint8)
        raw = raw.reshape(-1, 3073)
        ys.append(raw[:, 0].astype(np.int32))
        xs.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
    return MemoryDataset(np.concatenate(xs), np.concatenate(ys),
                         mean=(0.4914, 0.4822, 0.4465),
                         std=(0.2471, 0.2435, 0.2616))


class _LoaderBase:
    """Loader contract shared by the native and Python implementations.

    With ``drop_last=False`` the short final batch is filled by wrapping
    (duplicating) samples from the front of the batch so every batch has a
    static shape (an XLA requirement). This double-counts those samples, so
    it is unsuitable for *exact* evaluation metrics — for eval, truncate the
    dataset to a batch multiple (examples/mnist_lenet.py does this) or weight
    the final batch by its true ``count/batch_size``.
    """

    batch_size: int
    shape: Tuple[int, int, int]

    def epoch(self, epoch: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        raise NotImplementedError


class NativeLoader(_LoaderBase):
    """ctypes binding over native/dataloader.cpp."""

    def __init__(self, dataset: MemoryDataset, batch_size: int, *,
                 shuffle: bool = True, drop_last: bool = True, seed: int = 0,
                 rank: int = 0, world: int = 1, n_threads: int = 4,
                 queue_depth: int = 4, lib_path: Optional[str] = None):
        path = lib_path or native_library_path()
        if path is None:
            raise FileNotFoundError(
                "native library not built — run `make -C native` (or set "
                "GRACE_TPU_NATIVE_LIB)")
        lib = ctypes.CDLL(path)
        lib.gl_open_memory.restype = ctypes.c_void_p
        lib.gl_open_memory.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int64, ctypes.c_int64]
        lib.gl_start_epoch.restype = ctypes.c_int64
        lib.gl_start_epoch.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                       ctypes.c_int64, ctypes.c_int64]
        lib.gl_next.restype = ctypes.c_int
        lib.gl_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_void_p]
        lib.gl_close.argtypes = [ctypes.c_void_p]
        lib.gl_last_error.restype = ctypes.c_char_p
        self._lib = lib

        imgs = np.ascontiguousarray(dataset.images)
        labs = np.ascontiguousarray(dataset.labels.astype(np.int32))
        n, h, w, c = imgs.shape
        mean = std = None
        if dataset.mean is not None:
            mean = np.zeros(3, np.float32)
            std = np.ones(3, np.float32)
            mean[:c] = np.asarray(dataset.mean, np.float32)
            std[:c] = np.asarray(dataset.std, np.float32)
        self._handle = lib.gl_open_memory(
            imgs.ctypes.data_as(ctypes.c_void_p),
            labs.ctypes.data_as(ctypes.c_void_p),
            n, h, w, c,
            mean.ctypes.data_as(ctypes.c_void_p) if mean is not None else None,
            std.ctypes.data_as(ctypes.c_void_p) if std is not None else None,
            batch_size, int(shuffle), int(drop_last), seed, rank, world)
        if not self._handle:
            raise RuntimeError(lib.gl_last_error().decode())
        self.batch_size = batch_size
        self.shape = (h, w, c)
        self._n_threads = n_threads
        self._queue_depth = queue_depth

    def epoch(self, epoch: int):
        h, w, c = self.shape
        n_batches = self._lib.gl_start_epoch(self._handle, epoch,
                                             self._n_threads,
                                             self._queue_depth)
        for _ in range(n_batches):
            x = np.empty((self.batch_size, h, w, c), np.float32)
            y = np.empty((self.batch_size,), np.int32)
            rc = self._lib.gl_next(self._handle,
                                   x.ctypes.data_as(ctypes.c_void_p),
                                   y.ctypes.data_as(ctypes.c_void_p))
            if rc != 1:
                raise RuntimeError("native loader stopped mid-epoch")
            yield x, y

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.gl_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PythonLoader(_LoaderBase):
    """Numpy implementation of the identical contract (fallback/reference)."""

    def __init__(self, dataset: MemoryDataset, batch_size: int, *,
                 shuffle: bool = True, drop_last: bool = True, seed: int = 0,
                 rank: int = 0, world: int = 1, **_ignored):
        self.ds = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.rank = rank
        self.world = world
        n, h, w, c = dataset.images.shape
        self.shape = (h, w, c)

    def epoch(self, epoch: int):
        n = len(self.ds.images)
        perm = np.arange(n)
        if self.shuffle:
            # Same Fisher-Yates + seeding contract as the native library —
            # NOT bit-identical to it (different RNG), but deterministic and
            # rank-disjoint in the same way.
            np.random.default_rng(
                (self.seed * 0x9E3779B97F4A7C15 + epoch) % 2**63
            ).shuffle(perm)
        order = perm[self.rank::self.world]
        b = self.batch_size
        stop = len(order) - (len(order) % b) if self.drop_last else len(order)
        for i in range(0, stop, b):
            count = min(b, len(order) - i)
            # Short final batch wraps deterministically (native contract).
            sel = order[i + (np.arange(b) % count)]
            yield (self.ds.normalize(self.ds.images[sel]),
                   self.ds.labels[sel].astype(np.int32))


def make_loader(dataset: MemoryDataset, batch_size: int,
                **kwargs) -> _LoaderBase:
    """NativeLoader if the shared library is built, else PythonLoader."""
    if native_library_path() is not None:
        try:
            return NativeLoader(dataset, batch_size, **kwargs)
        except (OSError, RuntimeError):
            pass
    return PythonLoader(dataset, batch_size, **kwargs)


def prefetch_to_device(iterator, mesh=None, size: int = 2, sharding=None):
    """Device-side double buffering over a host batch iterator.

    The loaders above overlap *assembly* (disk/normalize/shuffle) with the
    step; this overlaps the host→HBM *transfer* too: each batch is
    ``jax.device_put`` with the batch-sharded layout ``size`` steps ahead,
    so while step t computes, batches t+1..t+size are already in flight
    (jax transfers are asynchronous — holding references to the
    already-put batches is all the machinery needed; the flax
    ``prefetch_to_device`` pattern, made mesh-aware). The reference's
    analog is torch DataLoader ``pin_memory`` + async ``.cuda()``
    (examples/torch/pytorch_mnist.py:63-70).

    ``iterator`` yields batch pytrees (e.g. ``(x, y)`` numpy arrays with
    a leading batch dim divisible by the mesh's data axis). Pass either a
    ``mesh`` (layout = ``batch_sharded(mesh)``) or an explicit
    ``sharding``. ``size=2`` is the classic setting: one batch computing,
    one in flight. Argument validation is eager (this is a plain function
    returning a generator), so a forgotten mesh fails at the call site,
    not at the first pull inside the training loop.
    """
    from grace_tpu.parallel import batch_sharded

    if sharding is None:
        if mesh is None:
            raise ValueError("prefetch_to_device needs a mesh or a sharding")
        sharding = batch_sharded(mesh)
    if size < 1:
        raise ValueError(f"size must be >= 1, got {size}")
    return _prefetch_gen(iterator, sharding, size)


def _prefetch_gen(iterator, sharding, size: int):
    import collections

    import jax

    queue = collections.deque()
    it = iter(iterator)

    def _put_next() -> bool:
        try:
            batch = next(it)
        except StopIteration:
            return False
        queue.append(jax.device_put(batch, sharding))
        return True

    for _ in range(size):
        if not _put_next():
            break
    while queue:
        out = queue.popleft()
        _put_next()
        yield out
