"""LeNet-style MNIST CNN — the reference's flagship example model.

Reference: examples/torch/pytorch_mnist.py:73-89 (conv 10@5x5 → pool → conv
20@5x5 → pool → fc 50 → fc 10) and the TF twins
(examples/tensorflow/tensorflow2_mnist.py:30-41). Stateless (no BN), so
``state`` is an empty dict.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from grace_tpu.models import layers as L


def init(key: jax.Array) -> Tuple[L.Params, L.ModelState]:
    k = L.split_keys(key, 4)
    params = {
        "conv1": L.conv_init(k[0], 5, 5, 1, 10, use_bias=True),
        "conv2": L.conv_init(k[1], 5, 5, 10, 20, use_bias=True),
        "fc1": L.dense_init(k[2], 320, 50),
        "fc2": L.dense_init(k[3], 50, 10),
    }
    return params, {}


def apply(params: L.Params, state: L.ModelState, x: jax.Array, *,
          train: bool = True) -> Tuple[jax.Array, L.ModelState]:
    """x: (N, 28, 28, 1) → logits (N, 10)."""
    x = L.conv_apply(params["conv1"], x, padding="VALID")
    x = L.max_pool(x, 2)
    x = jax.nn.relu(x)
    x = L.conv_apply(params["conv2"], x, padding="VALID")
    x = L.max_pool(x, 2)
    x = jax.nn.relu(x)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(L.dense_apply(params["fc1"], x))
    return L.dense_apply(params["fc2"], x), state
