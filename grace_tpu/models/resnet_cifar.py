"""cifar10-fast ResNet — the reference's DAWNBench model.

Reference: examples/dist/CIFAR10-dawndist/dawn.py:60-97 builds (via a nested
dict graph) the davidcpage/cifar10-fast "basic net + 3 residual layers"
architecture: prep conv 64 → layer1 conv 128 + pool + residual(128,128) →
layer2 conv 256 + pool → layer3 conv 512 + pool + residual(512,512) → global
maxpool → linear ×0.125 logit scale. Every conv is conv→BN→ReLU
(conv_bn, dawn.py:60-66). Re-expressed here as explicit functional blocks.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from grace_tpu.models import layers as L


def _conv_bn_init(key, cin, cout):
    p_bn, s_bn = L.bn_init(cout)
    return {"conv": L.conv_init(key, 3, 3, cin, cout), "bn": p_bn}, {"bn": s_bn}


def _conv_bn_apply(p, s, x, train):
    x = L.conv_apply(p["conv"], x)
    x, s_bn = L.bn_apply(p["bn"], s["bn"], x, train)
    return jax.nn.relu(x), {"bn": s_bn}


def _residual_init(key, c):
    k1, k2 = jax.random.split(key)
    p1, s1 = _conv_bn_init(k1, c, c)
    p2, s2 = _conv_bn_init(k2, c, c)
    return {"res1": p1, "res2": p2}, {"res1": s1, "res2": s2}


def _residual_apply(p, s, x, train):
    y, s1 = _conv_bn_apply(p["res1"], s["res1"], x, train)
    y, s2 = _conv_bn_apply(p["res2"], s["res2"], y, train)
    return x + y, {"res1": s1, "res2": s2}


def init(key: jax.Array, num_classes: int = 10
         ) -> Tuple[L.Params, L.ModelState]:
    k = L.split_keys(key, 7)
    params, state = {}, {}
    params["prep"], state["prep"] = _conv_bn_init(k[0], 3, 64)
    params["l1"], state["l1"] = _conv_bn_init(k[1], 64, 128)
    params["l1res"], state["l1res"] = _residual_init(k[2], 128)
    params["l2"], state["l2"] = _conv_bn_init(k[3], 128, 256)
    params["l3"], state["l3"] = _conv_bn_init(k[4], 256, 512)
    params["l3res"], state["l3res"] = _residual_init(k[5], 512)
    params["fc"] = L.dense_init(k[6], 512, num_classes, use_bias=False)
    return params, state


def apply(params: L.Params, state: L.ModelState, x: jax.Array, *,
          train: bool = True) -> Tuple[jax.Array, L.ModelState]:
    """x: (N, 32, 32, 3) → logits (N, num_classes)."""
    ns = {}
    x, ns["prep"] = _conv_bn_apply(params["prep"], state["prep"], x, train)
    x, ns["l1"] = _conv_bn_apply(params["l1"], state["l1"], x, train)
    x = L.max_pool(x, 2)
    x, ns["l1res"] = _residual_apply(params["l1res"], state["l1res"], x, train)
    x, ns["l2"] = _conv_bn_apply(params["l2"], state["l2"], x, train)
    x = L.max_pool(x, 2)
    x, ns["l3"] = _conv_bn_apply(params["l3"], state["l3"], x, train)
    x = L.max_pool(x, 2)
    x, ns["l3res"] = _residual_apply(params["l3res"], state["l3res"], x, train)
    # global max pool (dawn.py:92 MaxPool2d(4) on the 4x4 map)
    x = jnp.max(x, axis=(1, 2))
    logits = L.dense_apply(params["fc"], x) * 0.125  # dawn.py:95 Mul(0.125)
    return logits, ns
