"""ResNet-50 (v1.5) — the reference's synthetic-benchmark workhorse.

The reference pulls `torchvision.models.resnet50` / keras ResNet50
(examples/torch/pytorch_synthetic_benchmark.py:49,
examples/tensorflow/tensorflow2_synthetic_benchmark.py:63); grace-tpu ships a
functional implementation so the benchmark stack has zero framework deps.
v1.5 variant (stride-2 in the 3x3 of the bottleneck), NHWC/bf16-friendly —
this is the BASELINE.json north-star model (Top-K 1% + residual memory).
"""

from __future__ import annotations

import re
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from grace_tpu.models import layers as L

# depth -> (block counts)
_STAGES = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}
SUPPORTED_DEPTHS = tuple(sorted(_STAGES))


def _bottleneck_init(key, cin, cmid, stride):
    k = L.split_keys(key, 4)
    cout = cmid * 4
    p, s = {}, {}
    p["conv1"] = L.conv_init(k[0], 1, 1, cin, cmid)
    p["bn1"], s["bn1"] = L.bn_init(cmid)
    p["conv2"] = L.conv_init(k[1], 3, 3, cmid, cmid)
    p["bn2"], s["bn2"] = L.bn_init(cmid)
    p["conv3"] = L.conv_init(k[2], 1, 1, cmid, cout)
    p["bn3"], s["bn3"] = L.bn_init(cout)
    if stride != 1 or cin != cout:
        p["proj"] = L.conv_init(k[3], 1, 1, cin, cout)
        p["proj_bn"], s["proj_bn"] = L.bn_init(cout)
    return p, s


def _bottleneck_apply(p, s, x, stride, train):
    ns = {}
    shortcut = x
    y = L.conv_apply(p["conv1"], x)
    y, ns["bn1"] = L.bn_apply(p["bn1"], s["bn1"], y, train)
    y = jax.nn.relu(y)
    y = L.conv_apply(p["conv2"], y, stride=stride)  # v1.5: stride on the 3x3
    y, ns["bn2"] = L.bn_apply(p["bn2"], s["bn2"], y, train)
    y = jax.nn.relu(y)
    y = L.conv_apply(p["conv3"], y)
    y, ns["bn3"] = L.bn_apply(p["bn3"], s["bn3"], y, train)
    if "proj" in p:
        shortcut = L.conv_apply(p["proj"], x, stride=stride)
        shortcut, ns["proj_bn"] = L.bn_apply(p["proj_bn"], s["proj_bn"],
                                             shortcut, train)
    return jax.nn.relu(y + shortcut), ns


def init(key: jax.Array, depth: int = 50, num_classes: int = 1000
         ) -> Tuple[L.Params, L.ModelState]:
    if depth not in _STAGES:
        raise ValueError(f"resnet depth must be one of {SUPPORTED_DEPTHS}")
    blocks = _STAGES[depth]
    keys = L.split_keys(key, 2 + sum(blocks))
    params, state = {}, {}
    params["stem"] = L.conv_init(keys[0], 7, 7, 3, 64)
    params["stem_bn"], state["stem_bn"] = L.bn_init(64)
    ki = 1
    cin = 64
    for stage, n in enumerate(blocks):
        cmid = 64 * (2 ** stage)
        for b in range(n):
            stride = 2 if (b == 0 and stage > 0) else 1
            name = f"s{stage}b{b}"
            params[name], state[name] = _bottleneck_init(keys[ki], cin, cmid,
                                                         stride)
            cin = cmid * 4
            ki += 1
    params["fc"] = L.dense_init(keys[ki], cin, num_classes, init="glorot")
    return params, state


def _stages_from_params(params: L.Params) -> Tuple[int, ...]:
    """Recover per-stage block counts from the param dict, so ``apply`` always
    matches the depth the params were initialised with."""
    counts = [0, 0, 0, 0]
    for name in params:
        m = re.fullmatch(r"s(\d+)b(\d+)", name)
        if m:
            stage, block = int(m.group(1)), int(m.group(2))
            counts[stage] = max(counts[stage], block + 1)
    return tuple(counts)


def apply(params: L.Params, state: L.ModelState, x: jax.Array, *,
          train: bool = True) -> Tuple[jax.Array, L.ModelState]:
    """x: (N, H, W, 3) NHWC → logits (N, num_classes)."""
    ns = {}
    y = L.conv_apply(params["stem"], x, stride=2)
    y, ns["stem_bn"] = L.bn_apply(params["stem_bn"], state["stem_bn"], y, train)
    y = jax.nn.relu(y)
    y = jnp.pad(y, ((0, 0), (1, 1), (1, 1), (0, 0)), constant_values=-jnp.inf
                if jnp.issubdtype(y.dtype, jnp.floating) else 0)
    y = L.max_pool(y, 3, 2)
    for stage, n in enumerate(_stages_from_params(params)):
        for b in range(n):
            stride = 2 if (b == 0 and stage > 0) else 1
            name = f"s{stage}b{b}"
            y, ns[name] = _bottleneck_apply(params[name], state[name], y,
                                            stride, train)
    y = L.global_avg_pool(y)
    return L.dense_apply(params["fc"], y.astype(jnp.float32)), ns
