"""VGG-11/13/16/19 (configurable BN) — synthetic-benchmark model family.

Reference: examples/torch/pytorch_synthetic_benchmark.py:49 instantiates any
torchvision model by name (``getattr(models, args.model)``) — vgg16 is the
canonical non-residual CNN of that list, and its ~138M parameters (vs
ResNet-50's 25.6M) make it the classic *communication-bound* benchmark:
gradient exchange dominates, which is exactly the regime gradient
compression targets. Architecture per Simonyan & Zisserman (arXiv:1409.1556):
stacked 3x3 convs between 2x2 max-pools, then a 3-layer classifier head.
TPU-first notes: NHWC layout, optional BatchNorm after every conv (the
"_bn" torchvision variants), and the torchvision head exactly — features
are pooled to the canonical 7x7 grid with true AdaptiveAvgPool2d semantics
(static-slice means, any input resolution >= 32 jits; see
`_adaptive_avg_pool`) and flattened to the 25088-wide fc1, keeping vgg16 at
its full ~138M parameters: the point of VGG in a gradient-compression
benchmark is precisely that communication-bound head. Not replicated from
torchvision: classifier Dropout(0.5) and conv biases in the _bn variants
(throughput/wire cost are parameter-shape properties; add dropout before
using this for convergence studies). Logits are computed in float32 (zoo
convention, cf. resnet.py / transformer.py) even under a bf16 compute
dtype.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from grace_tpu.models import layers as L

# Channel plans ('M' = 2x2 max-pool), arXiv:1409.1556 Table 1.
_PLANS = {
    11: (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    13: (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"),
    16: (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"),
    19: (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}
SUPPORTED_DEPTHS = tuple(sorted(_PLANS))


def init(key: jax.Array, depth: int = 16, num_classes: int = 1000,
         batch_norm: bool = True) -> Tuple[L.Params, L.ModelState]:
    if depth not in _PLANS:
        raise ValueError(f"vgg depth must be one of {sorted(_PLANS)}")
    plan = _PLANS[depth]
    n_convs = sum(1 for v in plan if v != "M")
    keys = L.split_keys(key, n_convs + 3)
    params: dict = {}
    state: dict = {}
    cin, ki = 3, 0
    for li, v in enumerate(plan):
        if v == "M":
            continue
        name = f"conv{li}"
        params[name] = L.conv_init(keys[ki], 3, 3, cin, v,
                                   use_bias=not batch_norm)
        if batch_norm:
            bn_p, bn_s = L.bn_init(v)
            params[f"bn{li}"] = bn_p
            state[f"bn{li}"] = bn_s
        cin, ki = v, ki + 1
    params["fc1"] = L.dense_init(keys[ki], 7 * 7 * 512, 4096)
    params["fc2"] = L.dense_init(keys[ki + 1], 4096, 4096)
    params["fc3"] = L.dense_init(keys[ki + 2], 4096, num_classes)
    return params, state


def _adaptive_avg_pool(x: jax.Array, out: int) -> jax.Array:
    """Exact torchvision ``AdaptiveAvgPool2d((out, out))`` semantics.

    Output cell (i, j) averages input rows [⌊i·h/out⌋, ⌈(i+1)·h/out⌉) ×
    the analogous columns — a true pool for grids larger than ``out`` and
    cell duplication for smaller ones (e.g. the 1×1 grid of a 32px input
    broadcasts, it is not bilinearly upsampled). All bounds are static
    under jit (h, w are trace-time constants), so this lowers to ``out²``
    static-slice means XLA fuses freely — no dynamic shapes.
    """
    n, h, w, c = x.shape

    def bounds(size):
        return [(i * size // out, -((-(i + 1) * size) // out))
                for i in range(out)]

    rows_out = []
    for r0, r1 in bounds(h):
        cols_out = [x[:, r0:r1, c0:c1].mean(axis=(1, 2))
                    for c0, c1 in bounds(w)]
        rows_out.append(jnp.stack(cols_out, axis=1))   # (n, out, c)
    return jnp.stack(rows_out, axis=1)                 # (n, out, out, c)


def apply(params: L.Params, state: L.ModelState, x: jax.Array, *,
          train: bool = True, depth: int | None = None
          ) -> Tuple[jax.Array, L.ModelState]:
    """x: (N, H, W, 3), H=W>=32 → logits (N, num_classes).

    ``depth`` is recovered from the params when omitted.
    """
    if depth is None:
        n_convs = sum(1 for k in params if k.startswith("conv"))
        depth = next(d for d, plan in _PLANS.items()
                     if sum(1 for v in plan if v != "M") == n_convs)
    new_state = dict(state)
    for li, v in enumerate(_PLANS[depth]):
        if v == "M":
            x = L.max_pool(x, 2)
            continue
        x = L.conv_apply(params[f"conv{li}"], x, padding="SAME")
        bn = f"bn{li}"
        if bn in params:
            x, new_state[bn] = L.bn_apply(params[bn], state[bn], x, train)
        x = jax.nn.relu(x)
    if x.shape[1] != 7 or x.shape[2] != 7:
        x = _adaptive_avg_pool(x, 7)
    x = x.reshape(x.shape[0], -1)                 # (N, 25088)
    x = jax.nn.relu(L.dense_apply(params["fc1"], x))
    x = jax.nn.relu(L.dense_apply(params["fc2"], x))
    x = x.astype(jnp.float32)                     # fp32 logits, zoo convention
    return L.dense_apply(params["fc3"], x), new_state
