"""Pure-functional NN layers for grace-tpu's model zoo.

The reference has no model library of its own — its examples lean on
torchvision / keras.applications (examples/torch/pytorch_synthetic_benchmark.py:49,
examples/tensorflow/tensorflow2_synthetic_benchmark.py:63) plus one hand-rolled
CIFAR net (examples/dist/CIFAR10-dawndist/dawn.py:60-97). grace-tpu ships a
small functional layer kit instead: params are plain pytrees (so the GRACE
memory-state pytrees mirror them one leaf per tensor), layers are pure
``apply(params, x)`` functions that jit/shard_map cleanly, and layouts are
TPU-native (NHWC activations, HWIO conv kernels — XLA's preferred MXU tiling).

Stateful normalisation (BatchNorm running stats) is explicit: ``(params,
state) -> (out, new_state)``. No module classes, no tracing magic.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = dict
ModelState = dict


# ---------------------------------------------------------------------------
# initialisers
# ---------------------------------------------------------------------------

def he_normal(key: jax.Array, shape: Sequence[int], fan_in: int,
              dtype=jnp.float32) -> jax.Array:
    std = math.sqrt(2.0 / fan_in)
    return jax.random.normal(key, tuple(shape), dtype) * std


def glorot_uniform(key: jax.Array, shape: Sequence[int], fan_in: int,
                   fan_out: int, dtype=jnp.float32) -> jax.Array:
    limit = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, tuple(shape), dtype, -limit, limit)


def trunc_normal(key: jax.Array, shape: Sequence[int], std: float = 0.02,
                 dtype=jnp.float32) -> jax.Array:
    return jax.random.truncated_normal(key, -2.0, 2.0, tuple(shape), dtype) * std


# ---------------------------------------------------------------------------
# conv / dense
# ---------------------------------------------------------------------------

def conv_init(key: jax.Array, kh: int, kw: int, cin: int, cout: int,
              use_bias: bool = False) -> Params:
    """HWIO kernel (TPU/XLA-native conv layout)."""
    p = {"w": he_normal(key, (kh, kw, cin, cout), fan_in=kh * kw * cin)}
    if use_bias:
        p["b"] = jnp.zeros((cout,))
    return p


def conv_apply(p: Params, x: jax.Array, stride: int = 1,
               padding: str = "SAME") -> jax.Array:
    """NHWC conv. Kernel is cast to the activation dtype so a bf16 forward
    pass runs the MXU in bf16 while master params stay fp32."""
    y = lax.conv_general_dilated(
        x, p["w"].astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if "b" in p:
        y = y + p["b"].astype(y.dtype)
    return y


def dense_init(key: jax.Array, din: int, dout: int, use_bias: bool = True,
               init: str = "he") -> Params:
    if init == "he":
        w = he_normal(key, (din, dout), fan_in=din)
    elif init == "glorot":
        w = glorot_uniform(key, (din, dout), din, dout)
    else:
        w = trunc_normal(key, (din, dout))
    p = {"w": w}
    if use_bias:
        p["b"] = jnp.zeros((dout,))
    return p


def dense_apply(p: Params, x: jax.Array) -> jax.Array:
    y = x @ p["w"].astype(x.dtype)
    if "b" in p:
        y = y + p["b"].astype(y.dtype)
    return y


# ---------------------------------------------------------------------------
# normalisation
# ---------------------------------------------------------------------------

def bn_init(c: int) -> Tuple[Params, ModelState]:
    params = {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}
    state = {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))}
    return params, state


def bn_apply(p: Params, s: ModelState, x: jax.Array, train: bool,
             momentum: float = 0.9, eps: float = 1e-5
             ) -> Tuple[jax.Array, ModelState]:
    """BatchNorm over all non-channel axes; stats per device (the reference's
    DDP examples likewise never sync BN stats across ranks)."""
    red = tuple(range(x.ndim - 1))
    if train:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=red)
        var = jnp.var(xf, axis=red)
        new_s = {"mean": momentum * s["mean"] + (1 - momentum) * mean,
                 "var": momentum * s["var"] + (1 - momentum) * var}
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = lax.rsqrt(var + eps) * p["scale"]
    y = (x.astype(jnp.float32) - mean) * inv + p["bias"]
    return y.astype(x.dtype), new_s


def ln_init(d: int) -> Params:
    return {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))}


def ln_apply(p: Params, x: jax.Array, eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps) * p["scale"] + p["bias"]
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# pooling / misc
# ---------------------------------------------------------------------------

def max_pool(x: jax.Array, window: int = 2, stride: int | None = None
             ) -> jax.Array:
    stride = stride or window
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else x.dtype.type(0),
        lax.max, (1, window, window, 1), (1, stride, stride, 1), "VALID")


def avg_pool(x: jax.Array, window: int, stride: int | None = None,
             padding: str = "VALID") -> jax.Array:
    stride = stride or window
    dims, strides = (1, window, window, 1), (1, stride, stride, 1)
    summed = lax.reduce_window(x, jnp.zeros((), x.dtype), lax.add,
                               dims, strides, padding)
    # Divide by the per-window count of *real* elements so SAME padding does
    # not bias edge outputs low (count_exclude_pad semantics).
    counts = lax.reduce_window(jnp.ones_like(x), jnp.zeros((), x.dtype),
                               lax.add, dims, strides, padding)
    return summed / counts


def global_avg_pool(x: jax.Array) -> jax.Array:
    return jnp.mean(x, axis=(1, 2))


def embedding_init(key: jax.Array, vocab: int, d: int) -> Params:
    return {"table": trunc_normal(key, (vocab, d))}


def embedding_apply(p: Params, ids: jax.Array, dtype=None) -> jax.Array:
    t = p["table"]
    if dtype is not None:
        t = t.astype(dtype)
    return jnp.take(t, ids, axis=0)


def split_keys(key: jax.Array, n: int):
    return list(jax.random.split(key, n))
