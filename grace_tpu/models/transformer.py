"""BERT-style transformer encoder — the reference's BERT/PowerSGD config.

BASELINE.json lists "BERT + PowerSGD rank-4" among the configs to support;
the reference itself defers BERT to the external grace-benchmarks repo
(README.md:34). grace-tpu ships a functional encoder: LayerNorm-only (so the
model is stateless — no BN running stats), bf16-friendly, MXU-shaped matmuls.
PowerSGD on its 2-D weight matrices is the intended pairing.

Masked-LM head included so examples can train on real objectives; the bench
path uses sequence classification over pooled [CLS].
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from grace_tpu.models import layers as L


@dataclasses.dataclass(frozen=True)
class Config:
    vocab_size: int = 30522
    d_model: int = 768
    num_heads: int = 12
    num_layers: int = 12
    d_ff: int = 3072
    max_len: int = 512
    num_classes: int = 2


def base(**kw) -> Config:
    return Config(**kw)


def tiny(**kw) -> Config:
    """Test-scale config."""
    d = dict(vocab_size=1000, d_model=64, num_heads=4, num_layers=2,
             d_ff=128, max_len=64, num_classes=2)
    d.update(kw)
    return Config(**d)


def _layer_init(key, cfg: Config):
    k = L.split_keys(key, 6)
    d, f = cfg.d_model, cfg.d_ff
    return {
        "ln1": L.ln_init(d),
        "qkv": L.dense_init(k[0], d, 3 * d, init="trunc"),
        "proj": L.dense_init(k[1], d, d, init="trunc"),
        "ln2": L.ln_init(d),
        "ff1": L.dense_init(k[2], d, f, init="trunc"),
        "ff2": L.dense_init(k[3], f, d, init="trunc"),
    }


def _attention(p, x, mask, num_heads):
    """Pre-LN multi-head self-attention. x: (N, T, D)."""
    n, t, d = x.shape
    h = num_heads
    dh = d // h
    qkv = L.dense_apply(p["qkv"], x).reshape(n, t, 3, h, dh)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (N, T, H, Dh)
    logits = jnp.einsum("nqhd,nkhd->nhqk", q, k) / jnp.sqrt(dh).astype(x.dtype)
    if mask is not None:
        big_neg = jnp.asarray(-1e9, logits.dtype)
        logits = jnp.where(mask[:, None, None, :], logits, big_neg)
    attn = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(x.dtype)
    out = jnp.einsum("nhqk,nkhd->nqhd", attn, v).reshape(n, t, d)
    return L.dense_apply(p["proj"], out)


def _layer_apply(p, x, mask, cfg: Config):
    y = L.ln_apply(p["ln1"], x)
    x = x + _attention(p, y, mask, cfg.num_heads)
    y = L.ln_apply(p["ln2"], x)
    y = L.dense_apply(p["ff2"], jax.nn.gelu(L.dense_apply(p["ff1"], y)))
    return x + y


def init(key: jax.Array, cfg: Config) -> Tuple[L.Params, L.ModelState]:
    k = L.split_keys(key, 4 + cfg.num_layers)
    params = {
        "tok_emb": L.embedding_init(k[0], cfg.vocab_size, cfg.d_model),
        "pos_emb": L.embedding_init(k[1], cfg.max_len, cfg.d_model),
        "ln_f": L.ln_init(cfg.d_model),
        "cls": L.dense_init(k[2], cfg.d_model, cfg.num_classes, init="trunc"),
        "layers": [_layer_init(k[4 + i], cfg) for i in range(cfg.num_layers)],
    }
    return params, {}


def encode(params: L.Params, ids: jax.Array, cfg: Config,
           mask: Optional[jax.Array] = None,
           dtype=jnp.float32) -> jax.Array:
    """ids: (N, T) int32 → hidden states (N, T, D)."""
    t = ids.shape[1]
    if t > cfg.max_len:
        raise ValueError(f"sequence length {t} exceeds max_len {cfg.max_len}")
    x = L.embedding_apply(params["tok_emb"], ids, dtype=dtype)
    x = x + L.embedding_apply(params["pos_emb"], jnp.arange(t), dtype=dtype)
    for lp in params["layers"]:
        x = _layer_apply(lp, x, mask, cfg)
    return L.ln_apply(params["ln_f"], x)


def apply(params: L.Params, state: L.ModelState, ids: jax.Array, *,
          cfg: Config, mask: Optional[jax.Array] = None, train: bool = True,
          dtype=jnp.float32) -> Tuple[jax.Array, L.ModelState]:
    """Sequence classification over the first token → logits (N, C)."""
    del train
    x = encode(params, ids, cfg, mask, dtype)
    pooled = x[:, 0].astype(jnp.float32)
    return L.dense_apply(params["cls"], pooled), state


def mlm_logits(params: L.Params, ids: jax.Array, cfg: Config,
               mask: Optional[jax.Array] = None,
               dtype=jnp.float32) -> jax.Array:
    """Masked-LM logits via weight tying with the token embedding."""
    x = encode(params, ids, cfg, mask, dtype)
    return x.astype(jnp.float32) @ params["tok_emb"]["table"].T
