"""grace-tpu model zoo: functional models for the BASELINE.json configs.

Each model module exposes ``init(key, ...) -> (params, state)`` and
``apply(params, state, x, *, train) -> (out, new_state)`` — params/state are
plain pytrees, so GRACE memory state mirrors them leaf-for-leaf and
checkpoints with orbax alongside them.

* ``lenet``         — MNIST CNN (reference examples/torch/pytorch_mnist.py:73-89)
* ``resnet_cifar``  — cifar10-fast DAWNBench net (examples/dist/CIFAR10-dawndist/dawn.py:60-97)
* ``resnet``        — ResNet-50/101/152 v1.5 (torchvision stand-in used by
                      examples/torch/pytorch_synthetic_benchmark.py:49)
* ``transformer``   — BERT-style encoder (BASELINE.json BERT/PowerSGD config)
* ``vgg``           — VGG-11/13/16/19 (the communication-bound classic of the
                      reference's synthetic-benchmark model list)
"""

from grace_tpu.models import (layers, lenet, resnet, resnet_cifar,
                              transformer, vgg)

__all__ = ["layers", "lenet", "resnet", "resnet_cifar", "transformer", "vgg"]
