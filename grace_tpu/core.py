"""Core abstractions of grace-tpu: Compressor, Memory, Communicator.

This is a TPU-native (JAX/XLA) re-design of the GRACE decomposition of
compressed data-parallel training (reference: grace_dl/dist/__init__.py:4-52).
The reference models the triad as stateful Python classes holding name-keyed
dicts of residuals/momenta and issuing eager NCCL/MPI calls per tensor. Here:

* **Compressors and memories are frozen dataclasses of static hyperparameters
  with pure methods.** All cross-step state (residual buffers, momenta,
  PowerSGD's Q factor) is an explicit per-leaf state pytree threaded through
  the step — so the whole pipeline jits into one XLA program, and compression
  state checkpoints alongside parameters (the reference never checkpoints it;
  see SURVEY.md §5).
* **Communication is expressed with `jax.lax` collectives over a named mesh
  axis** (`psum` / `all_gather`), executed inside `jax.shard_map` / `pjit`.
  XLA's async scheduling over ICI replaces Horovod's background thread and
  handle/synchronize machinery (reference patch_files/horovod/torch/mpi_ops.py).
* **Payload vs ctx contract** (replaces the reference's loose `(tensors, ctx)`
  pair): `payload` is a tuple of arrays that travel on the wire and may differ
  per rank; `ctx` is decode context that MUST be identical on every rank
  (static Python values, or arrays derived from replicated inputs such as the
  shared RNG key). This is what lets the all-gather path `vmap` decompression
  over the gathered world axis.

Wire-format note: XLA requires static shapes, so the reference's variable-size
payloads (threshold/dgc/adaq, `tensors_size_are_same=False`) become
fixed-capacity payloads whose invalid lanes carry zero values — scatter-add
decompression is then value-exact without a length field.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from grace_tpu.telemetry.scopes import (STAGE_COMPENSATE, STAGE_COMPRESS,
                                        STAGE_EXCHANGE, STAGE_MEMORY_UPDATE,
                                        trace_stage)

# A tuple of arrays that travels on the wire (may differ across ranks).
Payload = Tuple[jax.Array, ...]
# Decode context, identical across ranks (static python data or replicated arrays).
Ctx = Any
# Per-leaf cross-step compressor/memory state (arbitrary pytree, often None).
State = Any

DEFAULT_AXIS = "data"

# The payload-algebra vocabulary (Compressor.payload_algebra): HOW a codec's
# wire payloads compose under element-wise addition across ranks. This is
# the capability the communicators' accumulation paths dispatch on and the
# static analyzers verify — promoted from the old summable_payload bool
# (which survives as a derived property) so the THC-style homomorphic
# codecs can say *which* kind of summable they are:
#
# * "exact"        — decompress(sum of payloads) == sum of decompresses,
#                    bit-for-bit up to float associativity (none, fp16,
#                    randomk's shared-index values, powersgd's in-compress
#                    sum). Float payloads; averaging may divide the payload.
# * "shared_scale" — integer level payloads under ONE scale negotiated
#                    across ranks before encoding (a psum-max collective;
#                    Compressor.negotiate). Payloads add exactly in integer
#                    space — zero re-encode loss per hop — but the
#                    accumulator dtype must cover world * max_level
#                    (Compressor.payload_sum_max_world, enforced at runtime
#                    by the communicators and statically by flow pass 6),
#                    and averaging must divide AFTER the final decode.
# * "sketch"       — linear mergeable sketches (count-sketch tables):
#                    sketch(x) + sketch(y) == sketch(x + y) exactly, so
#                    hop sums merge sketches with zero loss and ONE decode
#                    estimation at the very end (better than
#                    decode-each-then-sum, which pays W estimation errors).
# * None           — per-rank payloads do not compose (per-rank norms,
#                    selection masks, quantile bins); the hop-pipelined
#                    schedules need supports_hop_requant or a gather.
PAYLOAD_ALGEBRAS = ("exact", "shared_scale", "sketch")

# Tolerance contract of the Communicator.recv_wire_bytes model, enforced by
# the static auditor's wire-byte reconciliation pass (grace_tpu.analysis):
# the model must agree with the bytes counted from the actually-traced
# collective schedule within rtol (covers per-shard rounding: ceil'd
# bit-packing, per-shard top-k counts, per-chunk scalar norms) plus a small
# atol floor for scalar/bookkeeping collectives. Widening these to make a
# drifted model "pass" defeats the audit — fix the model instead.
WIRE_MODEL_RTOL = 0.10
WIRE_MODEL_ATOL = 256


def needs_negotiation(compressor) -> bool:
    """Whether the communicators must hoist ``compressor.negotiate``
    BEFORE the stage-1 encode: every ``shared_scale`` codec (the scale IS
    the negotiation), plus codecs that declare ``negotiates = True`` for a
    non-scale shared object (cyclic Top-K's leader index set). One
    predicate so core.step, Ring, Hier, and ReduceScatter can never
    disagree about who negotiates."""
    return (getattr(compressor, "payload_algebra", None) == "shared_scale"
            or getattr(compressor, "negotiates", False))


def negotiation_bytes_for(compressor, n_elems: int, world: int) -> int:
    """Per-rank received bytes of one negotiation collective for an
    ``n_elems``-element compress call: the codec's leaf-aware
    ``negotiation_nbytes_for`` when it declares one (cyclic Top-K's index
    broadcast scales with k), else the world-only
    ``negotiation_nbytes`` (homoqsgd's scalar pmax). ONE accessor shared
    by the telemetry wire plan, the tuner's pricing, and the auditor's
    wire model so the three can never price the same collective
    differently."""
    fn = getattr(compressor, "negotiation_nbytes_for", None)
    if fn is not None:
        return int(fn(int(n_elems), world))
    return int(compressor.negotiation_nbytes(world))


class LinkBytes(NamedTuple):
    """Per-rank received bytes split by the link class they arrive over.

    N ordered tiers, slowest-boundary last: ``ici`` is intra-slice
    interconnect traffic (the fast on-chip torus), ``dcn`` cross-slice
    data-center network traffic (~3.6× slower per the public per-chip
    numbers — see ``bench.PROJECTION_MODEL``), ``wan`` cross-region
    traffic (~100× below DCN — the tier where compression decides
    feasibility, not just step time). ``wan`` defaults to 0 so the 2-tier
    constructor ``LinkBytes(ici, dcn)`` remains an exact alias of every
    pre-region call site and keeps committed evidence bit-identical. The
    tiers are priced separately by the bench projections; their sum is the
    scalar :meth:`Communicator.recv_wire_bytes` the telemetry ring records
    and the static auditor reconciles — the split refines the scalar, it
    never disagrees with it (``ici + dcn + wan == recv_wire_bytes`` is
    enforced by the auditor's wire-reconciliation pass and pinned
    bit-exactly in tests/test_communicators.py / tests/test_region.py for
    every communicator).
    """

    ici: int
    dcn: int
    wan: int = 0

    @property
    def total(self) -> int:
        return self.ici + self.dcn + self.wan

    @property
    def tiers(self) -> tuple:
        """The ordered (ici, dcn, wan) triple — fast link first."""
        return (self.ici, self.dcn, self.wan)


@dataclasses.dataclass(frozen=True)
class Topology:
    """Mesh link topology: which ranks share an ICI domain / a region.

    Ranks ``[k·slice_size, (k+1)·slice_size)`` form one ICI-connected slice;
    traffic between slices rides DCN. ``slice_size=None`` (the default)
    means a single slice spans any world — every byte is ICI, which is the
    regime all committed single-slice measurements ran in.

    ``region_size`` (in RANKS, not slices) adds the third ordered tier:
    ranks ``[ρ·region_size, (ρ+1)·region_size)`` share one region (a
    datacenter/cell of slices joined by DCN); traffic between regions
    rides WAN. It requires ``slice_size`` and must be a whole multiple of
    it — regions are made of whole slices the same way slices are made of
    whole ranks. ``region_size=None`` is the 2-tier layout every existing
    call site built, bit-identical in every model.

    This is deliberately the *minimal* descriptor the wire model needs:
    per-rank received bytes only depend on which boundary the collective's
    schedule crosses (see :meth:`Communicator.recv_link_bytes` for the
    critical-path argument). Richer descriptors (torus dims, per-link
    counts) belong in the bandwidth constants of the projection, not here.
    """

    slice_size: Optional[int] = None
    region_size: Optional[int] = None

    def __post_init__(self):
        if self.slice_size is not None and self.slice_size < 1:
            raise ValueError(f"slice_size must be >= 1 or None; "
                             f"got {self.slice_size}")
        if self.region_size is not None:
            if self.slice_size is None:
                raise ValueError(
                    "region_size requires slice_size — a region is a group "
                    "of whole ICI slices, so a 3-tier layout without a "
                    f"slice tier is contradictory (got region_size="
                    f"{self.region_size}, slice_size=None)")
            if (self.region_size < self.slice_size
                    or self.region_size % self.slice_size):
                raise ValueError(
                    f"region_size {self.region_size} must be a whole "
                    f"multiple of slice_size {self.slice_size} — regions "
                    "are made of whole slices (contiguous-block layout)")

    def crosses_dcn(self, world: int) -> bool:
        """True iff a flat collective over ``world`` ranks spans slices."""
        return self.slice_size is not None and world > self.slice_size

    def crosses_wan(self, world: int) -> bool:
        """True iff a flat collective over ``world`` ranks spans regions."""
        return self.region_size is not None and world > self.region_size

    def flat_tier(self, world: int) -> str:
        """The link tier a *flat* full-axis collective's bytes land on —
        ``'wan'``, ``'dcn'`` or ``'ici'``. The critical-path argument of
        :meth:`Communicator.recv_link_bytes`, shared by every place that
        folds a flat collective's bytes into a per-link split (watch
        gather, shared-scale negotiation pmax, adapt signal reduction):
        the slowest boundary the axis spans prices the whole collective.
        """
        if self.crosses_wan(world):
            return "wan"
        if self.crosses_dcn(world):
            return "dcn"
        return "ici"

    def shrink(self, world: int, lost_ranks) -> Tuple["Topology", int]:
        """The surviving ``(topology, new_world)`` after an elastic resize
        removes ``lost_ranks`` from a contiguous world of ``world`` ranks.

        Granularity decides how much structure survives, finest violated
        level wins (ROADMAP item 4, both halves):

        * **whole regions** lost (3-tier layouts): an R→R−1 WAN-level
          resize — survivors keep ``slice_size`` AND ``region_size``;
          when a single region remains the region tier is vacuous and the
          result collapses to the two-tier ``Topology(slice_size)`` (a
          one-region fleet has no WAN leg to price).
        * **whole slices** lost (but not whole regions): the survivors
          keep ``slice_size`` — losing a slice is a K→K−1 DCN-level
          resize that never touches intra-slice structure, so the
          hierarchical schedule (and its mixed wire split) survives. A
          3-tier layout drops its region tier here: regions with unequal
          surviving slice counts violate the contiguous-equal-regions
          contract, the same conservatism as :meth:`detect` refusing
          uneven slices.
        * **partial** slice losses break the contiguous-equal-slices
          contract entirely (the survivors of a half-dead slice share no
          full ICI domain with anyone), so the result collapses to the
          single-slice flat layout — degraded but honest.
        """
        lost = set(int(r) for r in lost_ranks)
        if not lost:
            return self, world
        bad = [r for r in lost if r < 0 or r >= world]
        if bad:
            raise ValueError(f"lost_ranks {sorted(bad)} outside the world "
                             f"[0, {world})")
        new_world = world - len(lost)
        if new_world < 1:
            raise ValueError(f"cannot shrink world {world} by "
                             f"{len(lost)} ranks — no survivors")
        if self.slice_size is None:
            return Topology(), new_world
        s = self.slice_size
        if world % s:
            raise ValueError(f"world {world} is not a multiple of "
                             f"slice_size {s} — this topology never "
                             "described that world")
        whole = all(
            all(k * s + i in lost for i in range(s))
            for k in sorted({r // s for r in lost}))
        if not whole:
            return Topology(), new_world
        if self.region_size is None:
            return Topology(slice_size=s), new_world
        rz = self.region_size
        if world % rz:
            raise ValueError(f"world {world} is not a multiple of "
                             f"region_size {rz} — this topology never "
                             "described that world")
        touched = sorted({r // rz for r in lost})
        whole_regions = all(
            all(rho * rz + i in lost for i in range(rz)) for rho in touched)
        if not whole_regions:
            # slice-granular loss inside a region: slices survive intact
            # but the regions are no longer equal-sized blocks.
            return Topology(slice_size=s), new_world
        if world // rz - len(touched) <= 1:
            # one region remains — the WAN tier is vacuous.
            return Topology(slice_size=s), new_world
        return Topology(slice_size=s, region_size=rz), new_world

    @classmethod
    def detect(cls, devices=None) -> "Topology":
        """Topology of the live devices: group by the TPU runtime's
        ``slice_index`` when exposed (multislice), and by ``region_index``
        when exposed (cross-region fleets), else a single slice.
        CPU/simulated meshes are always one slice.

        Hardened against the layouts a best-effort grouping used to
        mis-size silently (``len(devices) // len(slices)`` truncates) —
        and ``region_index`` gets the identical treatment ``slice_index``
        has, never a weaker one:

        * a device list where only *some* devices expose ``slice_index``
          (or only some expose ``region_index``) is contradictory — half
          the fleet claims the tier exists, half doesn't — and raises
          rather than guessing a width;
        * uneven slices (e.g. 5+3 devices) or uneven regions have no
          single ``slice_size``/``region_size``; the wire model's
          contiguous-block layout cannot describe them, so they raise
          with the per-group counts instead of flooring to
          ``world // n_groups`` and mis-pricing every projection;
        * regions that are not whole multiples of the detected slice
          width (a slice straddling a region boundary) raise naming both
          counts — the 3-tier descriptor requires regions made of whole
          slices.

        ``slice_index=None`` / ``region_index=None`` (some runtimes stub
        the attributes) count as absent. An empty device list is a single
        slice. A region tier without a slice tier raises (the descriptor
        cannot express it); a single detected region is simply no region
        tier.
        """
        import jax

        devices = list(devices) if devices is not None else jax.devices()

        def group_counts(attr):
            counts: dict = {}
            missing = 0
            for d in devices:
                idx = getattr(d, attr, None)
                if idx is None:
                    missing += 1
                else:
                    counts[idx] = counts.get(idx, 0) + 1
            if counts and missing:
                raise ValueError(
                    f"cannot detect topology: {missing} of {len(devices)} "
                    f"devices expose no {attr} while "
                    f"{len(devices) - missing} do — a heterogeneous device "
                    "list (mixed runtimes / stale handles?) has no "
                    "consistent layout. Pass an explicit Topology(...) "
                    "instead.")
            return counts

        def uniform_size(counts, attr, noun):
            sizes = sorted(set(counts.values()))
            if len(sizes) > 1:
                raise ValueError(
                    f"cannot detect topology: {noun}s are uneven — "
                    f"per-{noun} device counts "
                    f"{dict(sorted(counts.items()))} — so no single "
                    f"{noun}_size describes the layout (the wire model "
                    "assumes contiguous equal blocks). Pass an explicit "
                    "Topology(...) for the layout you mean.")
            return sizes[0]

        slice_counts = group_counts("slice_index")
        region_counts = group_counts("region_index")
        slice_size = (uniform_size(slice_counts, "slice_index", "slice")
                      if len(slice_counts) > 1 else None)
        region_size = (uniform_size(region_counts, "region_index", "region")
                       if len(region_counts) > 1 else None)
        if region_size is not None and slice_size is None:
            raise ValueError(
                "cannot detect topology: devices expose region_index "
                f"({len(region_counts)} regions) but no multi-slice "
                "slice_index layout — a region tier without a slice tier "
                "is contradictory (regions are groups of whole ICI "
                "slices). Pass an explicit Topology(...) instead.")
        if (region_size is not None
                and (region_size < slice_size or region_size % slice_size)):
            raise ValueError(
                f"cannot detect topology: per-region device count "
                f"{region_size} is not a whole multiple of the slice "
                f"width {slice_size} — a slice straddles a region "
                "boundary, which the contiguous-block layout cannot "
                "describe. Pass an explicit Topology(...) for the layout "
                "you mean.")
        if slice_size is None:
            return cls()
        return cls(slice_size=slice_size, region_size=region_size)


SINGLE_SLICE = Topology()


def axis_size(axis_name) -> int:
    """Static size of a bound mesh axis, across JAX versions.

    ``lax.axis_size`` only exists on newer JAX; on older releases (e.g.
    0.4.37) ``lax.psum(1, axis)`` of a Python int constant-folds to a static
    int at trace time, which is exactly the same value.
    """
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


@dataclasses.dataclass(frozen=True)
class Compressor:
    """Lossy gradient codec (reference ABC: grace_dl/dist/__init__.py:15-35).

    Class attributes (mirroring the reference's instance flags,
    grace_dl/dist/__init__.py:18-20):

    * ``average`` — divide the aggregate by world size (mean semantics).
      Sign-based methods set False (grace_dl/dist/compressor/signsgd.py:9).
    * ``tensors_size_are_same`` — retained for API parity/documentation. Under
      XLA every payload is statically shaped, so the all-gather communicator
      never needs the reference's size-exchange dance
      (grace_dl/dist/communicator/allgather.py:16-38).
    * ``vote_aggregate`` — True iff ``aggregate`` is exactly the majority
      vote over ±1 decompressed tensors (signsgd/signum). Gates the
      psum-based :class:`~grace_tpu.comm.SignAllreduce` communicator, which
      re-signs the sum and would silently drop any other aggregate's
      scaling (e.g. EF-SignSGD's 1/lr); the generic ``Allreduce`` also
      routes vote compressors through that psum-vote path.
    * ``payload_algebra`` — the declared composition law of the wire
      payload under cross-rank addition (:data:`PAYLOAD_ALGEBRAS`):
      ``"exact"`` (linear float payloads — none, fp16/bf16, randomk,
      powersgd), ``"shared_scale"`` (integer levels under one negotiated
      scale — homomorphic QSGD), ``"sketch"`` (mergeable linear sketches —
      count-sketch), or ``None`` (payloads do not compose). The reference
      only *documents* the summability matrix (IMPLEMENTING.md:43-45) and
      silently corrupts gradients for e.g. topk+Allreduce; here the
      communicators enforce it and dispatch their accumulation path on it.
      Default None: a new codec must opt in, explicitly, in its own class
      body (the ``compressor-capabilities`` AST rule).
    * ``summable_payload`` — derived, read-only: ``payload_algebra is not
      None``. Kept so every existing call site (communicator gates, tuner
      mirrors, escape-hatch validation) reads the same truth it always did;
      the algebra refines it, never contradicts it.
    * ``supports_hop_requant`` — True iff re-running ``compress`` on a
      *partial sum of decompressed tensors* is a sane (bounded-error)
      re-encoding, which is what the hop-pipelined
      :class:`~grace_tpu.comm.RingAllreduce` does at every reduce-scatter
      hop: decompress → accumulate → requantize (topk re-selects over the
      partial, qsgd re-quantizes against the partial's norm, signsgd
      re-signs — a cascaded vote). Codecs whose payload carries structure a
      partial sum destroys (dgc/threshold capacity masks, onebit's mean
      pair, sketch's bins) must leave this False; linear codecs don't need
      it (``summable_payload`` gives them the exact payload-space
      accumulation path instead). Like ``summable_payload``, this is an
      *enforced* compatibility gate, not documentation. Default False.
    """

    average = True
    tensors_size_are_same = True
    vote_aggregate = False
    payload_algebra = None
    supports_hop_requant = False

    @property
    def summable_payload(self) -> bool:
        """Derived from :attr:`payload_algebra` — True iff payloads compose
        under element-wise addition at all. The pre-algebra bool every
        call site already reads; a codec never declares it directly."""
        return self.payload_algebra is not None

    # True iff the codec runs a pre-encode negotiation collective even
    # though its payload algebra is not "shared_scale" (which implies one):
    # e.g. the ScaleCom-style cyclic local-selection Top-K negotiates a
    # shared INDEX SET (a leader's local selection, broadcast) rather than
    # a scale. Gated through needs_negotiation() so every communicator
    # hoists the same way.
    negotiates = False

    # -- pre-encode negotiation (shared scale / shared selection) -----------
    def negotiate(self, x: jax.Array, axis_name: str, rng=None):
        """The pre-encode negotiation collective: return the
        rank-replicated shared value (a pmax'd scale, a leader's
        broadcast index set) that ``compress(..., shared=...)`` encodes
        against, or None when this codec needs none. Must be called where
        ``axis_name`` is bound; the communicators hoist it BEFORE the
        stage-1 encode so error feedback covers the single negotiated
        encode exactly. ``rng`` is the replicated per-(step, leaf) key —
        rank-identical by the transform's rng contract — for negotiations
        that rotate a leader across steps (cyclic Top-K)."""
        return None

    def negotiation_nbytes(self, world: int) -> int:
        """Per-rank received bytes of one :meth:`negotiate` collective at
        world size ``world`` — 0 for codecs without a negotiation. Priced
        into the telemetry row (``negotiation_bytes``, folded like
        ``watch_bytes``) and the tuner's wire model; the traced collective
        itself is counted by the auditor's wire reconciliation (its scalar
        size sits inside ``WIRE_MODEL_ATOL``)."""
        return 0

    def payload_sum_max_world(self) -> Optional[int]:
        """Largest world size whose payload-space sum stays exact in the
        payload dtype, or None for no codec-specific bound (float "exact"
        payloads are covered by the generic fp16 saturation analysis,
        flow.safe_sum_terms). Shared-scale codecs derive this from ONE
        constant — accumulator iinfo.max // max_level — enforced at runtime
        by the communicators' homomorphic paths and statically by the
        numeric-safety pass and the tuner's numeric gate, mirroring
        :func:`grace_tpu.comm.vote_exact_max_world`."""
        return None

    # -- cross-step state ---------------------------------------------------
    def init_state(self, x: jax.Array) -> State:
        """Initial per-leaf state (e.g. Signum momentum, PowerSGD Q)."""
        return None

    # -- metrics ------------------------------------------------------------
    def wire_nbytes(self, shape, dtype) -> int | None:
        """Analytic bytes-on-wire for one tensor, or None to let
        :func:`grace_tpu.utils.payload_nbytes` shape-trace ``compress``.
        Override when compress cannot be traced without a bound mesh axis
        (PowerSGD's in-compress psum)."""
        return None

    # -- codec --------------------------------------------------------------
    def compress(self, x: jax.Array, state: State, rng: jax.Array
                 ) -> tuple[Payload, Ctx, State]:
        """Encode ``x``; return (wire payload, decode ctx, next state)."""
        raise NotImplementedError

    def decompress(self, payload: Payload, ctx: Ctx) -> jax.Array:
        """Decode one rank's payload back to a dense tensor."""
        raise NotImplementedError

    def aggregate(self, stacked: jax.Array) -> jax.Array:
        """Reduce decompressed tensors stacked along a leading world axis.

        Default: sum (reference grace_dl/dist/__init__.py:32-34). SignSGD
        overrides with a majority vote.
        """
        return jnp.sum(stacked, axis=0)

    # -- the kernel-resident wire path (ROADMAP item 2) ---------------------
    # The communicators' hop/boundary arithmetic is routed through these
    # three hooks so a codec can swap in fused Pallas kernels
    # (grace_tpu.ops.pallas_wire) without the schedules knowing. The
    # defaults reproduce the staged spellings the schedules ran before the
    # hooks existed — BIT-EXACTLY, which is what lets an override claim
    # "bit-identical to the staged path" against a stable reference.

    def decode_accumulate(self, payloads: Sequence[Payload],
                          ctxs: Sequence[Ctx]) -> jax.Array:
        """Decode K payloads and sum them into one dense partial, in
        sequence order — the ring hop's ``decompress(recv) +
        decompress(own)`` and the requant boundary's decode-side sum.
        Codecs with fused decode→accumulate kernels override this; the
        default is the staged left-to-right spelling."""
        out = self.decompress(payloads[0], ctxs[0])
        for payload, ctx in zip(payloads[1:], ctxs[1:]):
            out = out + self.decompress(payload, ctx)
        return out

    def payload_add(self, a: Payload, b: Payload) -> Payload:
        """Payload-space ``a + b`` for summable payloads (the exact-path
        ring hop). Default: element-wise tuple add — only meaningful when
        :attr:`summable_payload`; packed shared-scale codecs override
        with unpack→add→repack (optionally fused)."""
        return tuple(r + o for r, o in zip(a, b))

    def payload_sum(self, stacked: Payload) -> Payload:
        """Payload-space sum over a stacked leading world axis (the
        gather-boundary accumulate of the homomorphic paths). Default:
        dtype-pinned ``jnp.sum`` per leaf — the accumulator IS the
        payload dtype, so overflow is governed by
        :meth:`payload_sum_max_world`, never silently widened away."""
        return tuple(jnp.sum(t, axis=0, dtype=t.dtype) for t in stacked)

    def wire_fused(self) -> bool:
        """True when this codec's fused wire-path kernels would actually
        run under the current selection rule (``use_pallas`` knob, backend
        and the GRACE_DISABLE_PALLAS[_WIRE] escape hatches — ONE rule,
        :func:`grace_tpu.ops.pallas_mode`). The communicators consult this
        before swapping a gather boundary's staged vmap-decompress +
        aggregate spelling for the fused K-way ``decode_accumulate`` pass:
        the two associate float adds differently, so the swap must never
        happen behind a disabled kernel's back — staged runs must stay
        bit-identical to the committed schedules. Default False (no wire
        kernels)."""
        return False


@dataclasses.dataclass(frozen=True)
class Memory:
    """Error-feedback memory (reference ABC: grace_dl/dist/__init__.py:4-13).

    The reference mutates name-keyed dicts; here ``compensate``/``update``
    thread an explicit per-leaf state pytree. ``compensate`` may also update
    state (DGC's momentum/accumulation buffers mutate during compensate —
    grace_dl/dist/memory/dgc.py:16-30 — hence the two-stage contract).
    """

    def init_state(self, x: jax.Array) -> State:
        return None

    def compensate(self, x: jax.Array, state: State
                   ) -> tuple[jax.Array, State]:
        """Fold residual state into the incoming gradient."""
        return x, state

    def update(self, compensated: jax.Array, payload: Payload, ctx: Ctx,
               compressor: Compressor, state: State) -> State:
        """Store the new residual = compensated - decompress(payload)."""
        return state


@dataclasses.dataclass(frozen=True)
class Communicator:
    """Collective exchange of compressed payloads over a named mesh axis.

    Reference ABC: grace_dl/dist/__init__.py:37-52. ``exchange`` must be
    called inside a `shard_map`/`pjit` context where ``axis_name`` is bound.
    The reference's async handle machinery (grace_dl/torch/__init__.py:37-58)
    has no analog: XLA schedules and overlaps collectives itself.
    """

    axis_name: str = DEFAULT_AXIS

    # True for communicators that re-chunk the gradient into per-rank shards
    # inside ``step`` (TwoShotAllreduce, RingAllreduce). Shard-parallel
    # steps carry their own collective schedule (all_to_all / ppermute) and
    # are not a validated target for ``fusion='grouped'`` vmapping — the
    # transform gates on this flag at build time.
    shard_parallel = False

    def world_size(self) -> jax.Array:
        return lax.psum(1, self.axis_name)

    def shard_spec(self, n: int) -> tuple[int, int, int]:
        """Equal-shard split of an ``n``-element flat buffer over the bound
        mesh axis: ``(world, shard_elems, pad)`` with
        ``world * shard_elems == n + pad``. The chunk schedule shared by the
        shard-parallel communicators (``TwoShotAllreduce``,
        ``RingAllreduce``); must be called where ``axis_name`` is bound, and
        is static at trace time (XLA shapes stay static)."""
        w = axis_size(self.axis_name)
        pad = (-n) % w
        return w, (n + pad) // w, pad

    def _recv_total_bytes(self, payload_nbytes: int, n_elems: int,
                          world: int, vote: bool = False) -> int:
        """Schedule-total received bytes per rank — the per-communicator
        formula. Subclasses override THIS (not ``recv_wire_bytes`` /
        ``recv_link_bytes``), so the scalar model and the per-link split
        share one implementation and can never drift apart. Default:
        gather-style, every other rank's payload arrives
        (``Allgather``/``Broadcast``); reduce-style subclasses override.
        """
        return payload_nbytes * max(0, world - 1)

    def recv_link_bytes(self, payload_nbytes: int, n_elems: int, world: int,
                        topology: Optional[Topology] = None,
                        vote: bool = False) -> LinkBytes:
        """Per-rank received bytes split by link class — ``(ici, dcn, wan)``.

        The split is the **critical-path rank's** view of the flat schedule
        the collectives ride: in a ring/gather laid over the mesh axis, each
        rank receives every byte over its single incoming neighbor link, and
        the collective finishes when the slowest rank does. When
        ``topology`` says the axis spans more than one ICI slice, some
        rank's incoming link is a DCN boundary link — every pipelined chunk
        crosses it, so that rank (and therefore the collective) is priced
        entirely at DCN; when the axis additionally spans regions, some
        rank's incoming link is a WAN boundary link and the whole bill
        lands one tier lower still. Hence a *flat* communicator's breakdown
        is all-ICI within one slice, all-DCN beyond it, and all-WAN the
        moment the axis crosses regions (:meth:`Topology.flat_tier`): the
        honest statement of why flat schedules collapse at multislice scale
        (topk+allgather losing to dense at W=256 on DCN) collapses harder
        at fleet scale. The hierarchical communicator
        (:class:`grace_tpu.comm.HierarchicalAllreduce`) earns a genuinely
        mixed split by overriding this method — bench projections,
        telemetry's ``wire_bytes_ici``/``_dcn``/``_wan`` fields, and the
        auditor all pick it up for free.

        ``topology=None`` means :data:`SINGLE_SLICE` (all ICI), matching
        every committed single-slice measurement.
        """
        total = int(self._recv_total_bytes(payload_nbytes, n_elems, world,
                                           vote=vote))
        topo = topology if topology is not None else SINGLE_SLICE
        tier = topo.flat_tier(world)
        if tier == "wan":
            return LinkBytes(ici=0, dcn=0, wan=total)
        if tier == "dcn":
            return LinkBytes(ici=0, dcn=total)
        return LinkBytes(ici=total, dcn=0)

    def recv_wire_bytes(self, payload_nbytes: int, n_elems: int, world: int,
                        vote: bool = False) -> int:
        """Logical bytes RECEIVED per rank per step at world size ``world``.

        ``payload_nbytes`` is one rank's whole-gradient payload
        (:func:`grace_tpu.utils.metrics.payload_nbytes`), ``n_elems`` the
        dense element count (vote collectives move dense bf16 votes, not the
        packed payload), ``vote`` whether the exchange takes a majority-vote
        route. This is the communicator-aware wire model shared by the bench
        projections (``bench.recv_bytes_model``) and the in-graph telemetry
        ring's ``wire_bytes`` field — payload bytes alone are communicator-
        blind and cannot rank e.g. ring/two-shot's O(k) against allgather's
        O(W·k). Defined as the sum of the per-link split
        (:meth:`recv_link_bytes`), so the scalar and the breakdown are
        structurally one model.

        This model is *audited*: the static analyzer
        (:mod:`grace_tpu.analysis`, ``tools/graft_lint.py``) counts the
        received bytes of the actually-traced collective schedule and
        fails CI when the model drifts beyond ``WIRE_MODEL_RTOL`` /
        ``WIRE_MODEL_ATOL`` — an override that stops matching its
        ``exchange``/``step`` is a lint error, not a silent telemetry lie.
        """
        return self.recv_link_bytes(payload_nbytes, n_elems, world,
                                    vote=vote).total

    def wire_overlap_fraction(self) -> float:
        """Fraction of this communicator's wire time the schedule itself
        can hide behind hop compute — the ``wire_pipeline`` discount the
        tuner's cost model and the bench projections apply. 0.0 for every
        serial schedule (the NO-OVERLAP upper bound stands unchanged);
        the pipelined ring/hier schedules override with their
        double-buffer bound, and flow pass 5's chain count is the static
        referee that the traced graph actually exposes the claimed
        independent chains."""
        return 0.0

    def exchange(self, payload: Payload, ctx: Ctx, compressor: Compressor
                 ) -> jax.Array:
        """Exchange payloads across ranks; return the aggregated dense tensor."""
        raise NotImplementedError

    # -- the universal 6-stage pipeline ------------------------------------
    def step(self, x: jax.Array, mem_state: State, comp_state: State,
             memory: Memory, compressor: Compressor, rng: jax.Array
             ) -> tuple[jax.Array, State, State]:
        """compensate → compress → update-residual → exchange.

        Mirrors grace_dl/dist/__init__.py:47-52 but returns next states
        functionally instead of mutating dicts.

        Fused fast path: when the memory declares linear error feedback
        (``linear_feedback_coeffs``: compensate = β·state + γ·x, update =
        compensated − decompress) and the compressor offers
        ``fused_feedback_compress`` (e.g. chunk-mode Top-K's one-HBM-pass
        Pallas kernel, ops/pallas_topk.py), the three local stages collapse
        into one call with bit-identical semantics.
        """
        coeffs = getattr(memory, "linear_feedback_coeffs", None)
        fused = getattr(compressor, "fused_feedback_compress", None)
        if coeffs is not None and fused is not None and mem_state is not None:
            with trace_stage(STAGE_COMPRESS):
                fused_out = fused(x, mem_state, coeffs, rng,
                                  world=lambda: axis_size(self.axis_name))
            if fused_out is not None:
                payload, ctx, mem_state = fused_out
                with trace_stage(STAGE_EXCHANGE):
                    out = self.exchange(payload, ctx, compressor)
                return out, mem_state, comp_state
        # Named scopes make each stage attributable in a Perfetto/XProf
        # device trace (see grace_tpu.telemetry.scopes) — otherwise the
        # whole pipeline renders as anonymous XLA fusions.
        with trace_stage(STAGE_COMPENSATE):
            compensated, mem_state = memory.compensate(x, mem_state)
        # Pre-encode negotiation, hoisted BEFORE the encode: the codec's
        # collective (shared-scale pmax, cyclic Top-K's leader index
        # broadcast) makes the shared object — and thus the decode ctx —
        # rank-identical, so payloads sum homomorphically AND error
        # feedback covers the single negotiated encode exactly. Skipped
        # when the mesh axis is unbound (single-process Identity use):
        # the codec's local fallback decodes its own payload exactly
        # there.
        shared = None
        if needs_negotiation(compressor):
            try:
                with trace_stage(f"{STAGE_EXCHANGE}/negotiate_scale"):
                    shared = compressor.negotiate(compensated,
                                                  self.axis_name, rng=rng)
            except NameError:           # unbound axis: no mesh, no peers
                shared = None
        with trace_stage(STAGE_COMPRESS):
            if shared is None:
                payload, ctx, comp_state = compressor.compress(
                    compensated, comp_state, rng)
            else:
                payload, ctx, comp_state = compressor.compress(
                    compensated, comp_state, rng, shared=shared)
        with trace_stage(STAGE_MEMORY_UPDATE):
            mem_state = memory.update(compensated, payload, ctx, compressor,
                                      mem_state)
        with trace_stage(STAGE_EXCHANGE):
            out = self.exchange(payload, ctx, compressor)
        return out, mem_state, comp_state
