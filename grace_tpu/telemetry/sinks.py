"""Structured telemetry sinks: JSONL, TensorBoard, fan-out.

The reference framework logged with bare prints; grace-tpu's evidence
discipline (VERDICT rounds 1-5) is that every number must land in a
structured, provenance-stamped artifact. Sinks are the one funnel:
:class:`~grace_tpu.telemetry.reader.TelemetryReader`,
``utils.logging.GuardMonitor``, and the tools all emit flat dict records
through the same ``write(record)`` interface.

* :class:`JSONLSink` — one JSON object per line; the first line is a
  ``{"provenance": …}`` header (see ``utils.logging.run_provenance``, which
  stamps platform/devices/UTC time/git commit) so the file is attributable
  to a revision and an environment. Rank-0 only by default: on multi-host
  runs every process sees identical replicated telemetry, and N identical
  files are noise.
* :class:`TensorBoardSink` — a dependency-free TensorBoard scalar writer:
  it hand-encodes Event/Summary protobufs and the TFRecord framing
  (masked CRC32C) so the repo needs neither TensorFlow nor ``tensorboardX``
  (the image bakes in neither). Numeric record fields become scalar tags;
  non-numeric fields are skipped.
* :class:`MultiSink` — fan-out to several sinks (e.g. JSONL evidence +
  live TensorBoard).

All sinks are context managers; ``close()`` is idempotent.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import time
from typing import Any, Mapping, Optional

__all__ = ["Sink", "JSONLSink", "TensorBoardSink", "MultiSink"]


def _is_rank_zero() -> bool:
    try:
        import jax
        return jax.process_index() == 0
    except Exception:   # jax not initialized / unavailable: act as rank 0
        return True


def _jsonable(value: Any) -> Any:
    if hasattr(value, "item"):     # numpy / jax scalars
        try:
            return value.item()
        except Exception:
            pass
    return str(value)


def _retry_io(fn, what: str):
    """``checkpoint._retry_io`` (bounded-backoff retry of transient
    ``OSError``s — the same policy ``Checkpointer.save`` uses, so a
    preempted node's NFS blip can't drop the last window of records) when
    available; single attempt on a box without orbax's dependency tree
    (the bench.py fallback idiom)."""
    try:
        from grace_tpu.checkpoint import _retry_io as retry
    except Exception:
        return fn()
    return retry(fn, what)


class Sink:
    """Minimal structured-record sink contract."""

    def write(self, record: Mapping[str, Any]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class JSONLSink(Sink):
    """Append-mode JSONL writer with a provenance header line.

    The header is written lazily on the first record so constructing the
    sink never touches the filesystem (a run that records nothing leaves
    nothing behind). ``rank_zero_only=True`` (default) makes non-zero
    processes no-ops.

    Durability: every record is written whole + flushed under the
    checkpoint save path's bounded-backoff ``_retry_io``, and ``close()``
    fsyncs before releasing the fd — a chaos-killed or preempted run
    leaves at worst a missing tail record, never a truncated mid-line one
    (the timeline loader still skips a torn line defensively, but it
    should never see one from this writer).
    """

    def __init__(self, path: str | os.PathLike,
                 provenance: Optional[Mapping[str, Any]] = None,
                 rank_zero_only: bool = True):
        self.path = os.fspath(path)
        self._prov = dict(provenance) if provenance is not None else None
        self._rank_zero_only = rank_zero_only
        self._file = None
        self._closed = False

    def _ensure_open(self) -> bool:
        if self._closed:
            raise ValueError(f"JSONLSink({self.path}) is closed")
        if self._rank_zero_only and not _is_rank_zero():
            return False
        if self._file is None:
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._file = open(self.path, "a")
            if self._prov is not None and self._file.tell() == 0:
                self._emit({"provenance": self._prov})
        return True

    def _emit(self, obj: Mapping[str, Any]) -> None:
        line = json.dumps(obj, default=_jsonable) + "\n"

        def write():
            self._file.write(line)
            self._file.flush()

        _retry_io(write, f"telemetry record -> {self.path}")

    def write(self, record: Mapping[str, Any]) -> None:
        if self._ensure_open():
            self._emit(dict(record))

    def close(self) -> None:
        if self._file is not None:
            try:
                _retry_io(lambda: (self._file.flush(),
                                   os.fsync(self._file.fileno())),
                          f"fsync {self.path}")
            finally:
                self._file.close()
                self._file = None
        self._closed = True


# ---------------------------------------------------------------------------
# TensorBoard event-file encoding (no TF / tensorboardX dependency)
# ---------------------------------------------------------------------------

def _crc32c_table():
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ (0x82F63B78 if c & 1 else 0)
        table.append(c)
    return table


_CRC_TABLE = _crc32c_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc(data: bytes) -> int:
    """TFRecord's rotated+offset CRC32C mask."""
    crc = crc32c(data)
    return (((crc >> 15) | ((crc << 17) & 0xFFFFFFFF)) + 0xA282EAD8) \
        & 0xFFFFFFFF


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _pb_key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _pb_len(field: int, payload: bytes) -> bytes:
    return _pb_key(field, 2) + _varint(len(payload)) + payload


def _event(wall_time: float, step: Optional[int] = None,
           file_version: Optional[str] = None,
           summary: Optional[bytes] = None) -> bytes:
    # Event proto: wall_time=1 (double), step=2 (int64),
    # file_version=3 (string), summary=5 (message).
    buf = _pb_key(1, 1) + struct.pack("<d", wall_time)
    if step is not None:
        buf += _pb_key(2, 0) + _varint(int(step))
    if file_version is not None:
        buf += _pb_len(3, file_version.encode())
    if summary is not None:
        buf += _pb_len(5, summary)
    return buf


def _scalar_summary(tags_values) -> bytes:
    # Summary proto: repeated Value value=1; Value: tag=1 (string),
    # simple_value=2 (float).
    buf = b""
    for tag, value in tags_values:
        val = _pb_len(1, tag.encode()) \
            + _pb_key(2, 5) + struct.pack("<f", float(value))
        buf += _pb_len(1, val)
    return buf


def _framed(event: bytes) -> bytes:
    header = struct.pack("<Q", len(event))
    return (header + struct.pack("<I", masked_crc(header))
            + event + struct.pack("<I", masked_crc(event)))


class TensorBoardSink(Sink):
    """Write scalar records as a TensorBoard events file, pure Python.

    Every numeric field of a record becomes a scalar under
    ``<tag_prefix>/<field>``; the record's ``"step"`` field (required,
    else a running counter) becomes the global step. String/None fields
    are skipped — TensorBoard scalars are floats.
    """

    def __init__(self, logdir: str | os.PathLike, tag_prefix: str = "grace",
                 rank_zero_only: bool = True):
        self.logdir = os.fspath(logdir)
        self.tag_prefix = tag_prefix
        self._rank_zero_only = rank_zero_only
        self._file = None
        self._auto_step = 0

    def _ensure_open(self) -> bool:
        if self._rank_zero_only and not _is_rank_zero():
            return False
        if self._file is None:
            os.makedirs(self.logdir, exist_ok=True)
            name = (f"events.out.tfevents.{int(time.time())}."
                    f"{socket.gethostname()}.{os.getpid()}.v2")
            self._file = open(os.path.join(self.logdir, name), "wb")
            self._file.write(_framed(_event(time.time(),
                                            file_version="brain.Event:2")))
            self._file.flush()
        return True

    def write(self, record: Mapping[str, Any]) -> None:
        if not self._ensure_open():
            return
        step = record.get("step")
        if step is None:
            step, self._auto_step = self._auto_step, self._auto_step + 1
        scalars = []
        for key, value in record.items():
            if key == "step":
                continue
            if isinstance(value, bool):
                value = float(value)
            if hasattr(value, "item"):
                try:
                    value = value.item()
                except Exception:
                    continue
            if isinstance(value, (int, float)):
                scalars.append((f"{self.tag_prefix}/{key}", value))
        if not scalars:
            return
        self._file.write(_framed(_event(
            time.time(), step=int(step),
            summary=_scalar_summary(scalars))))
        self._file.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


class MultiSink(Sink):
    """Fan a record out to several sinks; close closes them all."""

    def __init__(self, *sinks: Sink):
        self.sinks = tuple(sinks)

    def write(self, record: Mapping[str, Any]) -> None:
        for sink in self.sinks:
            sink.write(record)

    def close(self) -> None:
        for sink in self.sinks:
            sink.close()
