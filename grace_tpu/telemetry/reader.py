"""Host-side telemetry drain: one device-to-host transfer per flush window.

``TelemetryReader`` is the bridge between the in-graph ring buffers
(:class:`~grace_tpu.telemetry.state.TelemetryState`, written on-device every
step, and the graft-watch summary ring
:class:`~grace_tpu.telemetry.aggregate.WatchState`, written on window
boundaries) and the host world of sinks. The contract that keeps telemetry
off the hot path: the training loop calls :meth:`TelemetryReader.update`
every step, but only every ``every``-th call flushes — and a flush is
exactly **one** ``jax.device_get`` of the bundled metric rings, watch
rings, step ids, and guard counters (pinned by
``tests/test_telemetry.py::test_flush_is_one_transfer_per_window`` and its
watch-armed twin in ``tests/test_watch.py``). Between flushes the loop
never blocks on telemetry.

Semantics worth knowing:

* Ring rows are keyed by the GraceState step counter, which only advances on
  steps the guard *accepted* — a skipped (rolled-back) step leaves no row.
  The guard's own counters (total skips, fallback window) are fetched in the
  same transfer and stamped onto the last record of each flush as
  ``guard_*`` fields, so bad steps remain observable.
* If more than ``capacity`` accepted steps elapse between flushes, the
  oldest rows are overwritten on-device. The reader detects the gap, counts
  it in :attr:`dropped`, and stamps ``dropped_steps`` on the flush — silent
  truncation would read as "covered everything".
* Works on either state layout: the global view (telemetry leaves carrying a
  leading world axis, as the train loop holds it) or the per-device view.
  Cross-rank aggregation follows each field's spec in
  :data:`~grace_tpu.telemetry.state.FIELDS`; graft-watch rows additionally
  re-assemble their per-rank skew columns into W-vectors from the ring's
  world axis (the host-side twin of the in-graph all_gather — see
  :data:`~grace_tpu.telemetry.aggregate.WATCH_FIELDS`).
* ``anomaly=...`` arms the streaming detectors
  (:class:`~grace_tpu.telemetry.anomaly.WatchMonitor`): every flush's
  records run through them and any ``watch_anomaly`` findings land in the
  same sink, so the JSONL artifact carries the whole causal chain —
  summary, anomaly, then (if things get worse) guard/consensus events.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import numpy as np

from grace_tpu.telemetry.aggregate import WATCH_FIELDS, WatchState
from grace_tpu.telemetry.anomaly import AnomalyConfig, WatchMonitor
from grace_tpu.telemetry.state import FIELDS, TelemetryState

__all__ = ["TelemetryReader"]

_GUARD_FIELDS = ("notfinite_count", "last_bad_step", "consecutive",
                 "fallback_remaining", "step")


def _collect(tree, is_node) -> list:
    found: list = []

    def walk(node):
        if is_node(node):
            found.append(node)
        return node

    jax.tree_util.tree_map(walk, tree, is_leaf=is_node)
    return found


def _aggregate(values: np.ndarray, agg: str) -> float:
    if agg == "max":
        return float(values.max())
    if agg == "first":
        return float(values[0])
    return float(values.mean())


def _normalize_anomaly(anomaly, sink) -> Optional[WatchMonitor]:
    """None/False (off), True (defaults), AnomalyConfig, dict of config
    kwargs, or a ready WatchMonitor (its own sink wins if it has one)."""
    if anomaly is None or anomaly is False:
        return None
    if isinstance(anomaly, WatchMonitor):
        if anomaly.sink is None:
            anomaly.sink = sink
        return anomaly
    if anomaly is True:
        return WatchMonitor(sink=sink)
    if isinstance(anomaly, AnomalyConfig):
        return WatchMonitor(sink=sink, config=anomaly)
    if isinstance(anomaly, dict):
        return WatchMonitor(sink=sink, config=AnomalyConfig(**anomaly))
    raise TypeError(f"anomaly must be None/bool/dict/AnomalyConfig/"
                    f"WatchMonitor; got {type(anomaly).__name__}")


class TelemetryReader:
    """Flush the on-device telemetry ring through a sink every N steps.

    Usage::

        reader = TelemetryReader(JSONLSink("run.jsonl",
                                           provenance=run_provenance("synthetic")),
                                 every=20, anomaly=True)
        for i, batch in enumerate(batches):
            state, loss = step(state, batch)
            reader.update(i, state)
        reader.flush(state)      # drain the tail
        reader.close()
    """

    def __init__(self, sink: Optional[Any] = None, every: int = 10,
                 anomaly=None):
        if every < 1:
            raise ValueError(f"flush interval must be >= 1; got {every}")
        self.sink = sink
        self.every = every
        self.dropped = 0         # total steps lost to ring wraparound
        self.flushes = 0         # completed device-to-host transfers
        self.monitor = _normalize_anomaly(anomaly, sink)
        self._last_step = -1     # newest step id already emitted
        self._last_watch_step = -1

    def update(self, step: int, state) -> List[dict]:
        """Per-loop-iteration hook: flushes on every ``every``-th call."""
        if (step + 1) % self.every == 0:
            return self.flush(state)
        return []

    def flush(self, state) -> List[dict]:
        """Drain all unseen ring rows in ONE device-to-host transfer.

        Returns the new records in sink order: metric rows (step-ordered),
        then graft-watch summary rows, then any ``watch_anomaly`` records
        the armed detectors produced from this window.
        """
        telems = _collect(state, lambda n: isinstance(n, TelemetryState))
        watches = _collect(state, lambda n: isinstance(n, WatchState))
        if not telems and not watches:
            return []
        from grace_tpu.resilience.guard import GuardState
        guards = _collect(state, lambda n: isinstance(n, GuardState))

        bundle: list = []
        for t in telems:
            bundle.append(t.rings)
            bundle.append(t.steps)
        for w in watches:
            bundle.append(w.rings)
            bundle.append(w.steps)
        guard_vals = None
        if guards:
            bundle.extend(getattr(guards[0], f) for f in _GUARD_FIELDS)
        host = jax.device_get(bundle)          # the single transfer
        self.flushes += 1
        if guards:
            guard_vals = {f"guard_{name}": int(v) for name, v in
                          zip(_GUARD_FIELDS, host[len(host) - len(_GUARD_FIELDS):])}
            host = host[:len(host) - len(_GUARD_FIELDS)]
        watch_host = host[2 * len(telems):]
        host = host[:2 * len(telems)]

        records: List[dict] = []
        newest = self._last_step
        n_fields = len(FIELDS)
        for ti in range(len(telems)):
            rings = np.asarray(host[2 * ti])
            steps = np.asarray(host[2 * ti + 1])
            if rings.shape[-1] != n_fields or rings.ndim < 2:
                raise ValueError(
                    f"telemetry ring has shape {rings.shape}; expected "
                    f"(..., capacity, {n_fields}) — state layout mismatch")
            # Normalize to (world, capacity, n_fields): the global layout
            # carries a leading world axis; per-device state does not.
            rings = rings.reshape((-1,) + rings.shape[-2:])
            steps = steps.reshape(-1, rings.shape[1])[0]   # replicated

            fresh = np.flatnonzero(steps > self._last_step)
            for slot in fresh[np.argsort(steps[fresh])]:
                rec = {"step": int(steps[slot])}
                if len(telems) > 1:
                    rec["telemetry_index"] = ti
                for fi, (name, agg) in enumerate(FIELDS):
                    rec[name] = _aggregate(rings[:, slot, fi], agg)
                records.append(rec)
                newest = max(newest, int(steps[slot]))

        watch_records = self._watch_records(watches, watch_host)
        if records:
            expected = newest - self._last_step
            seen = len({r["step"] for r in records})
            gap = max(0, expected - seen)
            if gap:
                self.dropped += gap
                records[-1]["dropped_steps"] = gap
            if guard_vals:
                records[-1].update(guard_vals)
            self._last_step = newest
            if self.sink is not None:
                for rec in records:
                    self.sink.write(rec)
        elif guard_vals and self.sink is not None and not watch_records:
            # No fresh rows (e.g. every accepted step already flushed, or
            # all steps in the window were skipped) — still surface guard
            # movement so a pathological run is not silent.
            self.sink.write({"event": "guard_only", **guard_vals})

        if self.sink is not None:
            for rec in watch_records:
                self.sink.write(rec)
        out = records + watch_records
        if self.monitor is not None and out:
            # Detector findings are written to the sink by the monitor
            # itself (same funnel as everything else).
            out = out + self.monitor.observe(out)
        return out

    def _watch_records(self, watches, watch_host) -> List[dict]:
        """graft-watch summary rows from the flushed ring bundle:
        replicated columns read once, per-rank ``gather`` columns
        re-assembled into W-vectors from the ring's world axis."""
        n_fields = len(WATCH_FIELDS)
        records: List[dict] = []
        newest = self._last_watch_step
        for wi in range(len(watches)):
            rings = np.asarray(watch_host[2 * wi])
            steps = np.asarray(watch_host[2 * wi + 1])
            if rings.shape[-1] != n_fields or rings.ndim < 2:
                raise ValueError(
                    f"watch ring has shape {rings.shape}; expected "
                    f"(..., capacity, {n_fields}) — state layout mismatch")
            rings = rings.reshape((-1,) + rings.shape[-2:])
            steps = steps.reshape(-1, rings.shape[1])[0]   # replicated
            fresh = np.flatnonzero(steps > self._last_watch_step)
            for slot in fresh[np.argsort(steps[fresh])]:
                rec: dict = {"event": "watch", "step": int(steps[slot])}
                if len(watches) > 1:
                    rec["watch_index"] = wi
                for fi, (name, agg) in enumerate(WATCH_FIELDS):
                    if agg == "gather":
                        rec[name] = [float(v) for v in rings[:, slot, fi]]
                    else:
                        rec[name] = float(rings[0, slot, fi])
                rec["skew_rank"] = int(rec["skew_rank"])
                records.append(rec)
                newest = max(newest, int(steps[slot]))
        self._last_watch_step = newest
        return records

    def close(self) -> None:
        if self.sink is not None:
            self.sink.close()
