"""Host-side telemetry drain: one device-to-host transfer per flush window.

``TelemetryReader`` is the bridge between the in-graph ring buffer
(:class:`~grace_tpu.telemetry.state.TelemetryState`, written on-device every
step) and the host world of sinks. The contract that keeps telemetry off the
hot path: the training loop calls :meth:`TelemetryReader.update` every step,
but only every ``every``-th call flushes — and a flush is exactly **one**
``jax.device_get`` of the bundled rings, step ids, and guard counters
(pinned by ``tests/test_telemetry.py::test_flush_is_one_transfer_per_window``).
Between flushes the loop never blocks on telemetry.

Semantics worth knowing:

* Ring rows are keyed by the GraceState step counter, which only advances on
  steps the guard *accepted* — a skipped (rolled-back) step leaves no row.
  The guard's own counters (total skips, fallback window) are fetched in the
  same transfer and stamped onto the last record of each flush as
  ``guard_*`` fields, so bad steps remain observable.
* If more than ``capacity`` accepted steps elapse between flushes, the
  oldest rows are overwritten on-device. The reader detects the gap, counts
  it in :attr:`dropped`, and stamps ``dropped_steps`` on the flush — silent
  truncation would read as "covered everything".
* Works on either state layout: the global view (telemetry leaves carrying a
  leading world axis, as the train loop holds it) or the per-device view.
  Cross-rank aggregation follows each field's spec in
  :data:`~grace_tpu.telemetry.state.FIELDS`.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import numpy as np

from grace_tpu.telemetry.state import FIELDS, TelemetryState

__all__ = ["TelemetryReader"]

_GUARD_FIELDS = ("notfinite_count", "last_bad_step", "consecutive",
                 "fallback_remaining", "step")


def _collect(tree, is_node) -> list:
    found: list = []

    def walk(node):
        if is_node(node):
            found.append(node)
        return node

    jax.tree_util.tree_map(walk, tree, is_leaf=is_node)
    return found


def _aggregate(values: np.ndarray, agg: str) -> float:
    if agg == "max":
        return float(values.max())
    if agg == "first":
        return float(values[0])
    return float(values.mean())


class TelemetryReader:
    """Flush the on-device telemetry ring through a sink every N steps.

    Usage::

        reader = TelemetryReader(JSONLSink("run.jsonl",
                                           provenance=run_provenance("synthetic")),
                                 every=20)
        for i, batch in enumerate(batches):
            state, loss = step(state, batch)
            reader.update(i, state)
        reader.flush(state)      # drain the tail
        reader.close()
    """

    def __init__(self, sink: Optional[Any] = None, every: int = 10):
        if every < 1:
            raise ValueError(f"flush interval must be >= 1; got {every}")
        self.sink = sink
        self.every = every
        self.dropped = 0         # total steps lost to ring wraparound
        self.flushes = 0         # completed device-to-host transfers
        self._last_step = -1     # newest step id already emitted

    def update(self, step: int, state) -> List[dict]:
        """Per-loop-iteration hook: flushes on every ``every``-th call."""
        if (step + 1) % self.every == 0:
            return self.flush(state)
        return []

    def flush(self, state) -> List[dict]:
        """Drain all unseen ring rows in ONE device-to-host transfer."""
        telems = _collect(state, lambda n: isinstance(n, TelemetryState))
        if not telems:
            return []
        from grace_tpu.resilience.guard import GuardState
        guards = _collect(state, lambda n: isinstance(n, GuardState))

        bundle: list = []
        for t in telems:
            bundle.append(t.rings)
            bundle.append(t.steps)
        guard_vals = None
        if guards:
            bundle.extend(getattr(guards[0], f) for f in _GUARD_FIELDS)
        host = jax.device_get(bundle)          # the single transfer
        self.flushes += 1
        if guards:
            guard_vals = {f"guard_{name}": int(v) for name, v in
                          zip(_GUARD_FIELDS, host[len(host) - len(_GUARD_FIELDS):])}
            host = host[:len(host) - len(_GUARD_FIELDS)]

        records: List[dict] = []
        newest = self._last_step
        n_fields = len(FIELDS)
        for ti in range(len(telems)):
            rings = np.asarray(host[2 * ti])
            steps = np.asarray(host[2 * ti + 1])
            if rings.shape[-1] != n_fields or rings.ndim < 2:
                raise ValueError(
                    f"telemetry ring has shape {rings.shape}; expected "
                    f"(..., capacity, {n_fields}) — state layout mismatch")
            # Normalize to (world, capacity, n_fields): the global layout
            # carries a leading world axis; per-device state does not.
            rings = rings.reshape((-1,) + rings.shape[-2:])
            steps = steps.reshape(-1, rings.shape[1])[0]   # replicated

            fresh = np.flatnonzero(steps > self._last_step)
            for slot in fresh[np.argsort(steps[fresh])]:
                rec = {"step": int(steps[slot])}
                if len(telems) > 1:
                    rec["telemetry_index"] = ti
                for fi, (name, agg) in enumerate(FIELDS):
                    rec[name] = _aggregate(rings[:, slot, fi], agg)
                records.append(rec)
                newest = max(newest, int(steps[slot]))

        if records:
            expected = newest - self._last_step
            seen = len({r["step"] for r in records})
            gap = max(0, expected - seen)
            if gap:
                self.dropped += gap
                records[-1]["dropped_steps"] = gap
            if guard_vals:
                records[-1].update(guard_vals)
            self._last_step = newest
            if self.sink is not None:
                for rec in records:
                    self.sink.write(rec)
        elif guard_vals and self.sink is not None:
            # No fresh rows (e.g. every accepted step already flushed, or
            # all steps in the window were skipped) — still surface guard
            # movement so a pathological run is not silent.
            self.sink.write({"event": "guard_only", **guard_vals})
        return records

    def close(self) -> None:
        if self.sink is not None:
            self.sink.close()
