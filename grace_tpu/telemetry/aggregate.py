"""graft-watch: in-graph cross-rank health aggregation.

The telemetry ring (:mod:`grace_tpu.telemetry.state`) records *per-rank*
scalars and the host aggregates them at flush time — which is exactly the
wrong shape for the question that matters at scale: **is one rank drifting
away from the fleet?** ScaleCom (PAPERS.md) shows top-k sparsification
degrading with world size, and the earliest observable symptom is a single
rank's compression error creeping above its peers — a signal the PR-1 guard
cannot see (the values are finite) and the PR-3 consensus audit cannot see
either (residuals and compression error are *legitimately* per-rank, so
they are deliberately outside the fingerprint).

This module computes the cross-rank view **in-graph**, on a window
boundary, for the cost of one tiny collective:

* every rank stacks its local health scalars — pre-exchange gradient norm,
  relative compression error, error-feedback residual norm — into one
  (3,)-float vector;
* ``lax.all_gather`` moves the vectors over the mesh axis (``(W-1)·12``
  bytes received per rank — 84 B at W=8);
* from the gathered ``(W, 3)`` matrix every rank derives the replicated
  cross-rank **mean/min/max** per metric, its own **skew** (deviation from
  the replicated mean), and the replicated ``skew_max``/``skew_rank`` pair
  (the worst relative compression-error deviation and the rank holding it
  — the input channel an in-graph adaptive controller can act on without a
  host round-trip);
* the row lands in a bounded per-rank ring (:class:`WatchState`, sharded
  exactly like the telemetry ring) keyed by the GraceState step counter,
  so the host reader reconstructs the full per-rank skew *vector* from the
  world axis of one flush transfer.

Why a collective and not a host join: the per-rank telemetry rings already
reach the host, so the mean/min/max *could* be joined there — but only
after a flush (a window too late to gate anything in-graph), only on the
host (the closed-loop controller of ROADMAP item 5 needs the skew *inside*
the jitted step), and only by trusting host-side code to reproduce the
replicated reduction every rank would have agreed on. The all_gather makes
the summary a *replicated in-graph fact* — every rank provably holds the
same mean and the same offender election, the same property the consensus
audit builds on — and its wire cost is folded into the telemetry ring's
``wire_bytes``/``wire_bytes_ici``/``wire_bytes_dcn`` the same honest way
``audit_bytes`` is (see IMPLEMENTING.md, "Why skew is a collective").

Gating mirrors the consensus audit: a ``lax.cond`` on
``count % window == 0`` whose predicate derives from the replicated step
counter, so graft-lint's collective-consistency pass blesses the
branch-divergent gather (see the ``*-watch*`` entries in
``analysis/configs.py``) and non-boundary steps pay ~nothing.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["WATCH_FIELDS", "WATCH_FIELD_INDEX", "WATCH_METRICS",
           "WatchConfig", "WatchState", "normalize_watch", "watch_init",
           "watch_gather_bytes", "watch_record"]

# The local health scalars gathered cross-rank, in gather-column order.
WATCH_METRICS = ("grad_norm", "compression_error", "residual_norm")

# Ring columns of one watch row. The host-side reducer mirrors the
# telemetry FIELDS convention: "first" marks values replicated across ranks
# (derived from the gathered matrix, identical everywhere); "gather" marks
# genuinely per-rank values the reader re-assembles into a W-vector from
# the ring's sharded world axis — the host-side twin of the in-graph
# all_gather.
WATCH_FIELDS = (
    ("grad_norm_mean", "first"),
    ("grad_norm_min", "first"),
    ("grad_norm_max", "first"),
    ("compression_error_mean", "first"),
    ("compression_error_min", "first"),
    ("compression_error_max", "first"),
    ("residual_norm_mean", "first"),
    ("residual_norm_min", "first"),
    ("residual_norm_max", "first"),
    ("grad_norm_skew", "gather"),          # own value − replicated mean
    ("compression_error_skew", "gather"),
    ("residual_norm_skew", "gather"),
    ("skew_max", "first"),    # max relative compression-error deviation
    ("skew_rank", "first"),   # mesh index holding skew_max (the offender
                              # election — replicated, controller-ready)
    ("watch_bytes", "first"),  # the gather's received bytes this row
)

WATCH_FIELD_INDEX = {name: i for i, (name, _) in enumerate(WATCH_FIELDS)}


@dataclasses.dataclass(frozen=True)
class WatchConfig:
    """Static graft-watch knobs (hashable — safe inside jit closures).

    ``window`` — steps between cross-rank summaries (the ``lax.cond`` gate
    on ``GraceState.count``, the consensus ``audit_every`` idiom).
    ``capacity`` bounds the on-device summary ring; size it to at least
    ``flush_interval / window`` rows or the reader sees wraparound (counted,
    never silent, like the telemetry ring).
    """

    window: int = 10
    capacity: int = 16

    def __post_init__(self):
        if self.window < 1:
            raise ValueError(f"watch window must be >= 1; got {self.window}")
        if self.capacity < 1:
            raise ValueError(f"watch capacity must be >= 1; "
                             f"got {self.capacity}")


def normalize_watch(watch):
    """Accept the ergonomic spellings of the watch knob, mirroring
    telemetry/consensus: None/False (off), True (defaults), int (window),
    dict (config kwargs), or a WatchConfig."""
    if watch is None or watch is False:
        return None
    if watch is True:
        return WatchConfig()
    if isinstance(watch, WatchConfig):
        return watch
    if isinstance(watch, int):
        return WatchConfig(window=watch)
    if isinstance(watch, dict):
        return WatchConfig(**watch)
    raise TypeError(f"watch must be None/bool/int/dict/WatchConfig; "
                    f"got {type(watch).__name__}")


class WatchState(NamedTuple):
    """Bounded on-device ring of cross-rank health summaries.

    Per-rank data like the telemetry ring (the skew columns genuinely
    differ per rank; the replicated columns are simply stored by everyone),
    so in the global view each leaf carries a leading world axis sharded
    over the mesh — ``partition_specs`` handles it alongside ``telem``.
    Rows are keyed by the GraceState step counter; ``-1`` = never written.
    """

    rings: jax.Array   # (capacity, len(WATCH_FIELDS)) float32 summary rows
    steps: jax.Array   # (capacity,) int32 step id per row; -1 = unwritten


def watch_init(config: WatchConfig) -> WatchState:
    return WatchState(
        rings=jnp.zeros((config.capacity, len(WATCH_FIELDS)), jnp.float32),
        steps=jnp.full((config.capacity,), -1, jnp.int32))


def _axis_size(axis_name: str) -> int:
    """Static size of the bound mesh axis. A local copy of
    ``grace_tpu.core.axis_size`` — this package must not import ``core``
    (which imports :mod:`scopes`; see the package docstring): on old JAX
    ``lax.psum(1, axis)`` of a Python int constant-folds to a static int."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def watch_gather_bytes(world: int) -> int:
    """Received bytes per rank of one watch gather: every other rank's
    (len(WATCH_METRICS),) float32 health vector. The number folded into the
    telemetry row's wire_bytes on window-boundary steps — and the number
    graft-lint's wire pass counts from the traced all_gather."""
    return max(0, world - 1) * len(WATCH_METRICS) * 4


def watch_record(watch: WatchState, count: jax.Array, values,
                 axis_name: str, due: jax.Array) -> WatchState:
    """Maybe-write one cross-rank summary row at slot ``count % capacity``.

    ``values`` maps each :data:`WATCH_METRICS` name to this rank's local
    scalar; ``due`` is the replicated window-boundary predicate (computed
    by the caller so the wire-byte fold can share it). The all_gather —
    the one collective graft-watch costs — runs only in the taken branch;
    the predicate descends from the replicated step counter, which is what
    lets every rank take the same branch (and graft-lint prove it).
    """
    missing = [m for m in WATCH_METRICS if m not in values]
    if missing:
        raise KeyError(f"watch_record missing metrics {missing}")
    local = jnp.stack([jnp.asarray(values[m], jnp.float32).reshape(())
                       for m in WATCH_METRICS])
    world = int(_axis_size(axis_name))

    def write(w: WatchState) -> WatchState:
        gathered = lax.all_gather(local, axis_name, axis=0,
                                  tiled=False)              # (W, 3)
        mean = jnp.mean(gathered, axis=0)
        mn = jnp.min(gathered, axis=0)
        mx = jnp.max(gathered, axis=0)
        skew = local - mean                                  # own deviation
        err_col = WATCH_METRICS.index("compression_error")
        rel = jnp.abs(gathered[:, err_col] - mean[err_col]) \
            / jnp.maximum(jnp.abs(mean[err_col]),
                          jnp.asarray(1e-12, jnp.float32))
        row = jnp.concatenate([
            jnp.stack([mean[0], mn[0], mx[0],
                       mean[1], mn[1], mx[1],
                       mean[2], mn[2], mx[2]]),
            skew,
            jnp.stack([jnp.max(rel),
                       jnp.argmax(rel).astype(jnp.float32),
                       jnp.asarray(float(watch_gather_bytes(world)),
                                   jnp.float32)]),
        ])
        idx = jnp.mod(count, w.steps.shape[0]).astype(jnp.int32)
        return WatchState(rings=w.rings.at[idx].set(row),
                          steps=w.steps.at[idx].set(
                              jnp.asarray(count, jnp.int32)))

    return lax.cond(jnp.asarray(due, jnp.bool_), write, lambda w: w, watch)
