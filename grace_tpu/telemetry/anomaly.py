"""graft-watch host-side streaming anomaly detection.

The in-graph half (:mod:`grace_tpu.telemetry.aggregate`) makes the
cross-rank health summary a replicated fact; this module is the read side:
lightweight streaming detectors that run in the
:class:`~grace_tpu.telemetry.reader.TelemetryReader` flush path (or
offline, over a saved JSONL artifact — ``tools/graft_watch.py
--anomalies``) and turn summaries into attributed ``watch_anomaly``
records *before* the guard or the consensus audit have anything to say:

* **per-rank skew outliers** — for each watch summary's skew vector
  (``compression_error_skew`` / ``grad_norm_skew`` /
  ``residual_norm_skew``), a robust cross-sectional test: deviation from
  the rank median, scaled by the median absolute deviation of the *other*
  ranks (MAD — one drifting rank cannot inflate its own yardstick, unlike
  a stddev). This is the ScaleCom early-warning signal: a single rank's
  compression error creeping away from the fleet, finite the whole time
  (guard-blind) and legitimately per-rank (consensus-blind).
* **EWMA z-score spikes** — temporal detectors over the replicated
  ``compression_error_mean`` (codec suddenly losing fidelity fleet-wide:
  LR spikes, loss-scale events) and over ``perf_step_times`` p50
  (step-time regression mid-run).
* **wire-model drift** — every telemetry row's exchange bytes
  (``wire_bytes − audit_bytes − watch_bytes − negotiation_bytes``) must
  equal the
  ``Communicator.recv_link_bytes`` total for its fallback phase; a row
  that drifts beyond :data:`~grace_tpu.core.WIRE_MODEL_RTOL`-style
  tolerance means the live schedule and the priced model disagree — the
  dynamic twin of graft-lint's wire-reconciliation pass.
* **retrace events** — any ``perf_retrace`` record from
  :class:`~grace_tpu.profiling.ProfileRecorder` is flagged verbatim: a
  mid-run recompile is never healthy.

Detectors have hysteresis: an anomaly fires on the rising edge of its
score and re-arms only after the score falls back below half the
threshold, so a persistently drifting rank produces one attributed record
per episode instead of one per window (the sink is evidence, not a pager).

Every record is a flat dict through the same :class:`Sink` funnel as the
telemetry rows::

    {"event": "watch_anomaly", "step": 120, "kind": "skew",
     "metric": "compression_error", "rank": 5,
     "value": 0.31, "score": 14.2, "threshold": 6.0}

``rank`` is -1 for fleet-wide anomalies (spikes, wire drift, retraces).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Mapping, Optional

__all__ = ["AnomalyConfig", "Ewma", "WatchMonitor"]


@dataclasses.dataclass(frozen=True)
class AnomalyConfig:
    """Static detector thresholds.

    ``skew_threshold`` — robust score (|dev from median| / MAD scale) a
    rank must exceed to be flagged; ``skew_floor`` — minimum deviation
    scale as a fraction of the metric's cross-rank mean, so a fleet of
    near-identical healthy ranks (tiny MAD) doesn't flag noise.
    ``z_threshold``/``ewma_alpha``/``warmup`` parameterize the temporal
    EWMA z-score detectors (warmup = observations before a detector may
    fire). ``wire_rtol`` — relative tolerance of the wire-model drift
    check, matching the static auditor's contract.
    """

    skew_threshold: float = 6.0
    skew_floor: float = 0.05
    z_threshold: float = 4.0
    ewma_alpha: float = 0.25
    warmup: int = 3
    wire_rtol: float = 0.10

    def __post_init__(self):
        if self.skew_threshold <= 0 or self.z_threshold <= 0:
            raise ValueError("anomaly thresholds must be > 0")
        if not 0 < self.ewma_alpha <= 1:
            raise ValueError(f"ewma_alpha must be in (0, 1]; "
                             f"got {self.ewma_alpha}")
        if self.warmup < 1:
            raise ValueError(f"warmup must be >= 1; got {self.warmup}")


class Ewma:
    """Streaming exponentially-weighted mean/variance with a z-score.

    ``update(x)`` returns the z-score of ``x`` against the statistics
    *before* folding it in (so a spike scores against the healthy past,
    not against itself), or ``None`` during warmup.
    """

    def __init__(self, alpha: float = 0.25, warmup: int = 3):
        self.alpha = alpha
        self.warmup = warmup
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def update(self, x: float) -> Optional[float]:
        x = float(x)
        z = None
        if self.n >= self.warmup:
            std = math.sqrt(max(self.var, 0.0))
            z = abs(x - self.mean) / max(std, 1e-12,
                                         1e-3 * abs(self.mean))
        if self.n == 0:
            self.mean = x
        else:
            delta = x - self.mean
            self.mean += self.alpha * delta
            self.var = (1 - self.alpha) * (self.var
                                           + self.alpha * delta * delta)
        self.n += 1
        return z


def _median(values: List[float]) -> float:
    vs = sorted(values)
    n = len(vs)
    mid = n // 2
    return vs[mid] if n % 2 else 0.5 * (vs[mid - 1] + vs[mid])


class WatchMonitor:
    """Streaming consumer of sink records; emits ``watch_anomaly`` records.

    ``observe(records)`` takes any iterable of flat record dicts (the
    reader's flush output, or a whole JSONL artifact replayed offline),
    dispatches each to the relevant detector, writes every anomaly to
    ``sink`` (when given) and returns them. All anomalies ever seen
    accumulate in :attr:`anomalies`.

    ``expected_wire`` (optional): the modeled exchange bytes per
    non-fallback step — e.g.
    ``grace.communicator.recv_wire_bytes(payload, n, world)`` — for the
    wire-model drift check. Without it the detector locks onto the first
    observed value per fallback phase (drift is then *change*, which still
    catches a schedule silently re-routing mid-run).
    """

    _SKEW_METRICS = ("compression_error", "grad_norm", "residual_norm")

    def __init__(self, sink=None, config: Optional[AnomalyConfig] = None,
                 expected_wire: Optional[float] = None):
        self.sink = sink
        self.config = config or AnomalyConfig()
        self.expected_wire = expected_wire
        self.anomalies: List[dict] = []
        self._ewma: Dict[str, Ewma] = {}
        self._active: set = set()          # (kind, metric, rank) hysteresis
        # Expected exchange bytes per (fallback, adapt_rung) phase: the
        # fallback flip and graft-adapt's rung transitions both change
        # the honest wire bill, so each phase carries its own baseline.
        self._wire_expected: Dict[tuple, float] = {}
        if expected_wire is not None:
            self._wire_expected[(False, -1)] = float(expected_wire)

    # -- plumbing -----------------------------------------------------------
    def _emit(self, step, kind: str, metric: str, rank: int, value: float,
              score: float, threshold: float, **extra) -> dict:
        rec = {"event": "watch_anomaly", "step": step, "kind": kind,
               "metric": metric, "rank": rank, "value": float(value),
               "score": float(score), "threshold": float(threshold),
               **extra}
        self.anomalies.append(rec)
        if self.sink is not None:
            self.sink.write(rec)
        return rec

    def _hysteresis(self, key, score: float, threshold: float) -> bool:
        """True exactly on the rising edge of ``score > threshold``. The
        key stays latched (no re-fire) until the score falls back below
        ``threshold / 2`` — one record per anomaly episode, not per
        window."""
        if score > threshold:
            if key in self._active:
                return False
            self._active.add(key)
            return True
        if score < threshold / 2:
            self._active.discard(key)
        return False

    def _zscore(self, name: str, value: float) -> Optional[float]:
        det = self._ewma.get(name)
        if det is None:
            det = self._ewma[name] = Ewma(self.config.ewma_alpha,
                                          self.config.warmup)
        return det.update(value)

    # -- the dispatcher -----------------------------------------------------
    def observe(self, records) -> List[dict]:
        out: List[dict] = []
        for rec in records:
            if not isinstance(rec, Mapping):
                continue
            event = rec.get("event")
            if event == "watch":
                out.extend(self._observe_watch(rec))
            elif event == "perf_step_times":
                out.extend(self._observe_step_times(rec))
            elif event == "perf_retrace":
                out.extend(self._observe_retrace(rec))
            elif event is None and "wire_bytes" in rec:
                out.extend(self._observe_telemetry(rec))
        return out

    # -- detectors ----------------------------------------------------------
    def _observe_watch(self, rec: Mapping[str, Any]) -> List[dict]:
        cfg = self.config
        step = rec.get("step")
        out: List[dict] = []
        for metric in self._SKEW_METRICS:
            vec = rec.get(f"{metric}_skew")
            if not isinstance(vec, (list, tuple)) or len(vec) < 3:
                continue
            vec = [float(v) for v in vec]
            mean = abs(float(rec.get(f"{metric}_mean", 0.0)))
            med = _median(vec)
            # MAD over the OTHER ranks: the candidate outlier must not
            # widen its own acceptance band.
            for rank, v in enumerate(vec):
                others = [abs(u - med) for i, u in enumerate(vec)
                          if i != rank]
                mad = _median(others)
                scale = max(1.4826 * mad, cfg.skew_floor * (mean + 1e-12))
                score = abs(v - med) / max(scale, 1e-300)
                if self._hysteresis(("skew", metric, rank), score,
                                    cfg.skew_threshold):
                    out.append(self._emit(
                        step, "skew", metric, rank, v, score,
                        cfg.skew_threshold,
                        mean=float(rec.get(f"{metric}_mean", 0.0))))
        # Fleet-wide compression-error spike (temporal).
        err_mean = rec.get("compression_error_mean")
        if err_mean is not None:
            z = self._zscore("compression_error_mean", float(err_mean))
            if z is not None and self._hysteresis(
                    ("spike", "compression_error_mean", -1), z,
                    cfg.z_threshold):
                out.append(self._emit(step, "spike",
                                      "compression_error_mean", -1,
                                      float(err_mean), z, cfg.z_threshold))
        return out

    def _observe_telemetry(self, rec: Mapping[str, Any]) -> List[dict]:
        cfg = self.config
        wire = rec.get("wire_bytes")
        if wire is None:
            return []
        exchange = (float(wire) - float(rec.get("audit_bytes", 0.0))
                    - float(rec.get("watch_bytes", 0.0))
                    - float(rec.get("negotiation_bytes", 0.0))
                    - float(rec.get("adapt_bytes", 0.0)))
        fallback = bool(rec.get("fallback"))
        # graft-adapt makes the exchange bytes legitimately
        # state-dependent: the expectation is keyed per (fallback, rung)
        # phase — a rung transition opens a new phase instead of reading
        # as drift (the per-rung twin of the fallback-phase split).
        rung = int(rec.get("adapt_rung", -1))
        phase = (fallback, rung)
        expected = self._wire_expected.get(phase)
        if expected is None:
            self._wire_expected[phase] = exchange
            return []
        drift = abs(exchange - expected)
        score = drift / max(cfg.wire_rtol * max(expected, 1.0), 1e-12)
        if self._hysteresis(("wire_drift", "wire_bytes", -1), score, 1.0):
            return [self._emit(
                rec.get("step"), "wire_drift", "wire_bytes", -1, exchange,
                score, 1.0, expected=expected, fallback=fallback)]
        return []

    def _observe_step_times(self, rec: Mapping[str, Any]) -> List[dict]:
        cfg = self.config
        p50 = rec.get("p50_ms")
        if p50 is None:
            return []
        z = self._zscore("step_p50_ms", float(p50))
        if z is not None and self._hysteresis(("step_time", "p50_ms", -1),
                                              z, cfg.z_threshold):
            return [self._emit(rec.get("step"), "step_time", "p50_ms", -1,
                               float(p50), z, cfg.z_threshold)]
        return []

    def _observe_retrace(self, rec: Mapping[str, Any]) -> List[dict]:
        # A retrace is categorical, not statistical: flag each one.
        return [self._emit(rec.get("step"), "retrace", "compile_cache", -1,
                           float(rec.get("cache_size", 0)), 1.0, 0.0,
                           retraces=rec.get("retraces"))]
