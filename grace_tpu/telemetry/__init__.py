"""In-graph telemetry: on-device metric rings, named trace stages, sinks.

Three layers (see each module's docstring for the design rationale):

* :mod:`~grace_tpu.telemetry.state` — the on-device
  :class:`TelemetryState` ring buffer that ``grace_transform(telemetry=…)``
  threads through the optimizer state, accumulating per-step scalars
  (gradient/update norms, residual health, compression error, *effective*
  wire bytes across the dense-fallback flip) with zero host syncs.
* :mod:`~grace_tpu.telemetry.reader` — :class:`TelemetryReader`, the host
  drain: one ``jax.device_get`` per N-step window, guard counters bundled
  into the same transfer.
* :mod:`~grace_tpu.telemetry.sinks` — structured outputs
  (:class:`JSONLSink` with provenance headers, dependency-free
  :class:`TensorBoardSink`, :class:`MultiSink`).

Plus :func:`trace_stage` (:mod:`~grace_tpu.telemetry.scopes`), which names
the compress / exchange / decompress / memory-update stages in XLA op
metadata so ``utils.profiling.trace`` captures attributable Perfetto spans.

IMPORT CONSTRAINT: modules in this package must not import
``grace_tpu.core`` / ``transform`` / ``resilience`` at module level —
``core.py`` imports :mod:`scopes`, so anything heavier would cycle. The
reader's ``GuardState`` lookup is deliberately lazy.
"""

from grace_tpu.telemetry.reader import TelemetryReader
from grace_tpu.telemetry.scopes import trace_stage
from grace_tpu.telemetry.sinks import (JSONLSink, MultiSink, Sink,
                                       TensorBoardSink)
from grace_tpu.telemetry.state import (FIELDS, TelemetryConfig,
                                       TelemetryState, telemetry_init,
                                       telemetry_record)

__all__ = [
    "FIELDS", "TelemetryConfig", "TelemetryState", "telemetry_init",
    "telemetry_record",
    "TelemetryReader",
    "Sink", "JSONLSink", "TensorBoardSink", "MultiSink",
    "trace_stage",
]
