"""In-graph telemetry: on-device metric rings, named trace stages, sinks,
cross-rank health aggregation, anomaly detection, and the run timeline.

Six layers (see each module's docstring for the design rationale):

* :mod:`~grace_tpu.telemetry.state` — the on-device
  :class:`TelemetryState` ring buffer that ``grace_transform(telemetry=…)``
  threads through the optimizer state, accumulating per-step scalars
  (gradient/update norms, residual health, compression error, *effective*
  wire bytes across the dense-fallback flip) with zero host syncs.
* :mod:`~grace_tpu.telemetry.aggregate` — graft-watch:
  ``grace_transform(watch=…)`` adds an in-graph *cross-rank* health
  summary every window (one tiny gated ``all_gather``; replicated
  mean/min/max + per-rank skew into :class:`WatchState`), wire cost
  folded into the ring's ``wire_bytes`` as ``watch_bytes``.
* :mod:`~grace_tpu.telemetry.reader` — :class:`TelemetryReader`, the host
  drain: one ``jax.device_get`` per N-step window, watch rings and guard
  counters bundled into the same transfer.
* :mod:`~grace_tpu.telemetry.anomaly` — streaming detectors
  (:class:`WatchMonitor`, armed via ``TelemetryReader(anomaly=…)``):
  robust per-rank skew outliers, EWMA spikes, wire-model drift, step-time
  and retrace anomalies → ``watch_anomaly`` sink records.
* :mod:`~grace_tpu.telemetry.timeline` — :class:`Timeline`, the unified
  step-keyed merge of every sink record kind (``tools/graft_watch.py``).
* :mod:`~grace_tpu.telemetry.sinks` — structured outputs
  (:class:`JSONLSink` with provenance headers and fsync-on-close
  durability, dependency-free :class:`TensorBoardSink`,
  :class:`MultiSink`).

Plus :func:`trace_stage` (:mod:`~grace_tpu.telemetry.scopes`), which names
the compress / exchange / decompress / memory-update stages in XLA op
metadata so ``utils.profiling.trace`` captures attributable Perfetto spans.

IMPORT CONSTRAINT: modules in this package must not import
``grace_tpu.core`` / ``transform`` / ``resilience`` at module level —
``core.py`` imports :mod:`scopes`, so anything heavier would cycle. The
reader's ``GuardState`` lookup is deliberately lazy.
"""

from grace_tpu.telemetry.aggregate import (WATCH_FIELDS, WatchConfig,
                                           WatchState, watch_init,
                                           watch_record)
from grace_tpu.telemetry.anomaly import AnomalyConfig, WatchMonitor
from grace_tpu.telemetry.reader import TelemetryReader
from grace_tpu.telemetry.scopes import trace_stage
from grace_tpu.telemetry.sinks import (JSONLSink, MultiSink, Sink,
                                       TensorBoardSink)
from grace_tpu.telemetry.state import (FIELDS, TelemetryConfig,
                                       TelemetryState, telemetry_init,
                                       telemetry_record)
from grace_tpu.telemetry.timeline import Timeline

__all__ = [
    "FIELDS", "TelemetryConfig", "TelemetryState", "telemetry_init",
    "telemetry_record",
    "WATCH_FIELDS", "WatchConfig", "WatchState", "watch_init",
    "watch_record",
    "AnomalyConfig", "WatchMonitor",
    "Timeline",
    "TelemetryReader",
    "Sink", "JSONLSink", "TensorBoardSink", "MultiSink",
    "trace_stage",
]
