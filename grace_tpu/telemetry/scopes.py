"""Named trace stages: attributable timings instead of anonymous XLA ops.

``utils.profiling.trace`` captures a Perfetto/TensorBoard device trace, but
without scope names the GRACE pipeline shows up as a soup of fusions and
``all-gather.N`` ops. :func:`trace_stage` wraps a pipeline stage in both:

* ``jax.named_scope`` — prepends the stage name to the XLA op name metadata,
  so *device-side* ops (the compress kernels, the collectives, the residual
  update) group under readable ``grace/…`` spans in the profiler; and
* ``jax.profiler.TraceAnnotation`` — emits a host-side TraceMe for the same
  span, so trace-time (and any eager host work) is attributable too.

Both are free at execution time: named_scope only rewrites op metadata
during tracing, and TraceAnnotation is a no-op unless a profiler session is
active. IMPORTANT for library code: the wrapped region must not capture
tracers across the context boundary in surprising ways — this is a plain
``contextmanager`` around pure tracing, not a transformation.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import jax

__all__ = ["trace_stage", "match_stage", "ALL_STAGES",
           "STAGE_COMPENSATE", "STAGE_COMPRESS",
           "STAGE_EXCHANGE", "STAGE_DECOMPRESS", "STAGE_MEMORY_UPDATE",
           "STAGE_FWD_BWD", "STAGE_OPTIMIZER", "STAGE_APPLY",
           "STAGE_TELEMETRY", "STAGE_DENSE_ESCAPE", "STAGE_CONSENSUS",
           "STAGE_RING_HOP", "STAGE_WATCH", "STAGE_BUCKET", "STAGE_ADAPT",
           "STAGE_PIPELINE"]

# Canonical stage names — one vocabulary for the profiler, the report tool,
# and the docs. Keep in sync with README "Observability".
STAGE_COMPENSATE = "grace/compensate"
STAGE_COMPRESS = "grace/compress"
STAGE_EXCHANGE = "grace/exchange"
STAGE_DECOMPRESS = "grace/decompress"
STAGE_MEMORY_UPDATE = "grace/memory_update"
STAGE_FWD_BWD = "grace/forward_backward"
STAGE_OPTIMIZER = "grace/optimizer"
STAGE_APPLY = "grace/apply_updates"
STAGE_TELEMETRY = "grace/telemetry"
STAGE_DENSE_ESCAPE = "grace/dense_escape"
STAGE_CONSENSUS = "grace/consensus"
# RingAllreduce reduce-scatter hops: each of the W-1 neighbor exchanges
# (ppermute + decompress + accumulate + requantize) renders as its own
# "grace/ring_hop/<s>" span, so per-hop cost is attributable in a trace.
STAGE_RING_HOP = "grace/ring_hop"
# graft-watch cross-rank health aggregation (telemetry/aggregate.py): the
# window-boundary all_gather of per-rank health vectors plus the summary
# math — one attributable span so its (tiny) cost never hides inside the
# telemetry scope it runs next to.
STAGE_WATCH = "grace/watch"
# Bucketed overlap executor (transform.py, fusion=<int bytes>): each
# bucket's full compensate→compress→exchange→decompress→memory-update
# chain renders as its own "grace/bucket/<b>" span, so a device trace
# shows bucket i's exchange overlapping bucket i+1's compression — the
# per-chain attribution the measured-vs-static overlap sandwich reads.
# The inner pipeline scopes nest inside it; match_stage's rightmost rule
# still attributes their ops to compress/exchange/… as before.
STAGE_BUCKET = "grace/bucket"
# graft-adapt in-graph controller (resilience/adapt.py): the per-step
# scalar signal reductions (pmean/pmax of the local compression error)
# plus the window-boundary rung decision — one attributable span, so the
# controller's (tiny) cost never hides inside the telemetry scope, and
# static findings against the ladder dispatch name this stage.
STAGE_ADAPT = "grace/adapt"
# Double-buffered wire pipeline (RingAllreduce/HierarchicalAllreduce with
# pipeline=P > 1): each of the P contiguous buffer segments runs the whole
# hop schedule under its own "grace/pipeline/<p>" span, so a device trace
# shows segment p's ppermute hops overlapping segment p+1's stage-1 encode
# — the per-segment attribution the static overlap pass (analysis/flow.py
# pass 5) reads to count independent collective chains. Inner hop scopes
# nest inside it; match_stage's rightmost rule still attributes their ops
# to ring_hop/exchange as before.
STAGE_PIPELINE = "grace/pipeline"

# The canonical stage vocabulary, longest-prefix-matchable: the profiler,
# tools/telemetry_report.py, and the static auditor's finding attribution
# (grace_tpu.analysis — findings name the stage whose scope the offending
# jaxpr equation was traced under) all share it. Keep sorted by length so
# "grace/exchange/psum_vote" attributes to STAGE_EXCHANGE, not a shorter
# accidental prefix.
ALL_STAGES = tuple(sorted(
    (STAGE_COMPENSATE, STAGE_COMPRESS, STAGE_EXCHANGE, STAGE_DECOMPRESS,
     STAGE_MEMORY_UPDATE, STAGE_FWD_BWD, STAGE_OPTIMIZER, STAGE_APPLY,
     STAGE_TELEMETRY, STAGE_DENSE_ESCAPE, STAGE_CONSENSUS, STAGE_RING_HOP,
     STAGE_WATCH, STAGE_BUCKET, STAGE_ADAPT, STAGE_PIPELINE),
    key=len, reverse=True))


def match_stage(path: str) -> str:
    """The canonical stage a scope path / op name belongs to.

    Scope paths nest (``grace/optimizer/grace/exchange/grace/decompress``
    is a real jax name stack: the optimizer scope wraps the transform,
    which wraps the exchange, which wraps the decode), so the *rightmost*
    matching stage from :data:`ALL_STAGES` wins — the innermost scope is
    the one doing the work. Ties at the same position take the longest
    stage (``grace/exchange/psum_vote`` attributes to ``grace/exchange``,
    never a shorter accidental prefix). Falls back to the raw two-segment
    ``grace/<x>`` prefix for ad-hoc sub-scopes, and ``""`` for paths
    outside the grace vocabulary. ONE implementation shared by the static
    auditor's finding attribution (:mod:`grace_tpu.analysis`) and the
    profiler trace analyzer (:mod:`grace_tpu.profiling`) — both read the
    scope names :func:`trace_stage` wrote, so they must parse them
    identically.
    """
    best, best_pos = "", -1
    for stage in ALL_STAGES:            # longest-first: ties keep the longer
        pos = path.rfind(stage)
        if pos > best_pos:
            best, best_pos = stage, pos
    if best:
        return best
    segs = [seg for seg in path.split("/") if seg]
    if "grace" not in segs:
        return ""
    i = segs.index("grace")
    return "/".join(segs[i:i + 2])


@contextlib.contextmanager
def trace_stage(name: str) -> Iterator[None]:
    """Name a pipeline stage in both the XLA op metadata and host TraceMe."""
    anno = getattr(jax.profiler, "TraceAnnotation", None)
    with contextlib.ExitStack() as stack:
        stack.enter_context(jax.named_scope(name))
        if anno is not None:   # absent on exotic/old jax builds — degrade
            stack.enter_context(anno(name))
        yield
