"""On-device telemetry state: a bounded ring buffer of per-step scalars.

The survey paper GRACE implements is a *measurement* paper, yet the
reproduction's observability so far is static (`wire_report` via
``eval_shape``) or print-based (``GuardMonitor``). Nothing sees what happens
*inside* the jitted step — where the dense-fallback escape hatch silently
changes the real bytes-on-wire and error-feedback residuals drift unobserved.
EQuARX-style quantized-collective work (PAPERS.md) lives or dies by readable
traces of the collective schedule; THC argues the compression-error signal is
itself a first-class training metric. Both point the same way: telemetry must
live in the graph, not around it.

:class:`TelemetryState` is that in-graph accumulator. ``grace_transform``
threads it through the optimizer state alongside the rest of ``GraceState``:
every update writes one row of :data:`FIELDS` scalars into a fixed-capacity
ring buffer, entirely on-device — zero host syncs on the hot path. A
host-side :class:`~grace_tpu.telemetry.reader.TelemetryReader` drains the
ring every N steps in a **single** device-to-host transfer.

Layout notes:

* The state is **per-rank data** (like GraceState ``mem``/``comp``): in the
  global view each leaf carries a leading world axis sharded over the mesh
  axis, so recording needs no collectives of its own — each rank accumulates
  its local scalars and the host aggregates at flush time per the field's
  ``agg`` spec (post-exchange metrics such as ``update_norm`` are
  rank-identical anyway; pre-exchange ones such as ``grad_norm`` genuinely
  differ and the host reports their cross-rank mean).
* Rows are keyed by the GraceState step counter; a slot holding step ``-1``
  has never been written. Under :func:`~grace_tpu.resilience.guard_transform`
  a skipped step rolls the whole ring back with the rest of the inner state,
  so poisoned rows never survive into a flush — the guard's own counters
  (which do record skips) ride along in the reader's flush bundle.
* Everything is float32. Byte counts above 2**24 lose integer exactness
  (~1e-7 relative) — fine for a telemetry stream; the analytic exact numbers
  remain available from :func:`grace_tpu.utils.metrics.wire_report`.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["FIELDS", "FIELD_INDEX", "TelemetryConfig", "TelemetryState",
           "telemetry_init", "telemetry_record"]

# (name, host-side cross-rank aggregation) in ring-column order. "first"
# marks values identical on every rank (static per branch, or derived from
# replicated inputs); "mean"/"max" aggregate genuinely per-rank scalars.
FIELDS = (
    ("grad_norm", "mean"),          # ‖local grad‖ over all leaves, pre-exchange
    ("update_norm", "mean"),        # ‖aggregated update‖ (rank-identical)
    ("residual_norm", "mean"),      # ‖error-feedback memory state‖ per rank
    ("residual_max", "max"),        # max |residual| — EF health / drift alarm
    ("compression_error", "mean"),  # ‖g − decompress(compress(g))‖ / ‖g‖
    ("wire_bytes", "first"),        # EFFECTIVE bytes received per rank this
                                    # step — communicator-aware
                                    # (Communicator.recv_wire_bytes), so
                                    # ring/two-shot's O(k) and allgather's
                                    # O(W·k) are comparable on one scale
    ("dense_bytes", "first"),       # raw dense bytes of the same gradients
                                    # (codec/communicator-blind reference)
    ("fallback", "max"),            # 1.0 while the dense escape hatch is live
    ("audit_bytes", "first"),       # consensus-audit wire cost this step:
                                    # fingerprint exchange + any repair
                                    # broadcast (also folded into wire_bytes
                                    # so effective bytes stay honest)
    ("wire_bytes_ici", "first"),    # wire_bytes split by link class under
    ("wire_bytes_dcn", "first"),    # the transform's Topology
                                    # (Communicator.recv_link_bytes): flat
                                    # communicators are all-ICI within one
                                    # slice, all-DCN beyond it, and all-WAN
                                    # beyond one region; the hierarchical
                                    # comm reports a mixed split.
                                    # ici + dcn + wan == the exchange's
                                    # wire_bytes (on audit steps the scalar
                                    # additionally carries audit_bytes,
                                    # which are not split by link)
    ("wire_bytes_wan", "first"),    # the third ordered tier of the same
                                    # split: cross-region traffic under a
                                    # Topology(region_size=...) — zero on
                                    # every 2-tier layout, so pre-region
                                    # readers see identical ici/dcn values
    ("watch_bytes", "first"),       # graft-watch health-gather wire cost
                                    # this step (telemetry/aggregate.py):
                                    # non-zero on window-boundary steps
                                    # only, and — unlike audit_bytes —
                                    # folded into wire_bytes AND the
                                    # per-link split (the gather is a flat
                                    # full-axis collective, priced by the
                                    # same Topology as the exchange)
    ("negotiation_bytes", "first"), # shared-scale negotiation collective
                                    # cost this step (the pmax of
                                    # payload_algebra='shared_scale'
                                    # codecs, Compressor.negotiation_
                                    # nbytes × compress calls): folded
                                    # into wire_bytes AND the per-link
                                    # split exactly like watch_bytes (a
                                    # flat full-axis collective); zero
                                    # for every other codec and during
                                    # dense-fallback windows
    ("adapt_rung", "first"),        # graft-adapt: the EFFECTIVE ladder
                                    # rung this step's exchange ran at
                                    # (0 = dense escape; the guard's
                                    # fallback flag forces 0) — the rung
                                    # the row's wire_bytes/ici/dcn were
                                    # priced at, via the per-rung wire
                                    # plan (the dense-fallback flip
                                    # generalized). -1 when the adaptive
                                    # controller is not armed
    ("adapt_bytes", "first"),       # graft-adapt signal-reduction wire
                                    # cost this step (one scalar pmean +
                                    # one scalar pmax per step —
                                    # resilience/adapt.adapt_signal_
                                    # bytes): folded into wire_bytes AND
                                    # the per-link split exactly like
                                    # watch_bytes; zero when adapt is off
)

FIELD_INDEX = {name: i for i, (name, _) in enumerate(FIELDS)}


@dataclasses.dataclass(frozen=True)
class TelemetryConfig:
    """Static telemetry knobs (hashable — safe inside jit closures).

    ``capacity`` bounds the on-device ring: it must be at least the reader's
    flush interval or the oldest rows of a window are overwritten before the
    flush reads them (the reader detects and counts such drops rather than
    failing). ``compression_error`` gates the one genuinely non-free metric:
    it re-runs compress→decompress on the step's gradients, which XLA CSEs
    away only when the pipeline input is identical (no error-feedback
    memory); with a residual memory it costs roughly one extra compress per
    step. Disable it to make telemetry near-free.
    """

    capacity: int = 128
    compression_error: bool = True

    def __post_init__(self):
        if self.capacity < 1:
            raise ValueError(f"telemetry capacity must be >= 1; "
                             f"got {self.capacity}")


class TelemetryState(NamedTuple):
    rings: jax.Array   # (capacity, len(FIELDS)) float32 metric rows
    steps: jax.Array   # (capacity,) int32 step id per row; -1 = never written


def telemetry_init(config: TelemetryConfig) -> TelemetryState:
    return TelemetryState(
        rings=jnp.zeros((config.capacity, len(FIELDS)), jnp.float32),
        steps=jnp.full((config.capacity,), -1, jnp.int32))


def telemetry_record(telem: TelemetryState, count: jax.Array,
                     values: Mapping[str, jax.Array]) -> TelemetryState:
    """Write one row of scalars at slot ``count % capacity`` (in-graph).

    ``values`` must provide every :data:`FIELDS` name; all are cast to
    float32. Pure function of (state, count, values) — safe under jit,
    shard_map, and the guard's where-select rollback.
    """
    missing = [name for name, _ in FIELDS if name not in values]
    if missing:
        raise KeyError(f"telemetry_record missing fields {missing}")
    row = jnp.stack([jnp.asarray(values[name], jnp.float32).reshape(())
                     for name, _ in FIELDS])
    idx = jnp.mod(count, telem.steps.shape[0]).astype(jnp.int32)
    return TelemetryState(rings=telem.rings.at[idx].set(row),
                          steps=telem.steps.at[idx].set(
                              jnp.asarray(count, jnp.int32)))
