"""Unified run timeline: every sink record kind, one step-keyed sequence.

Five PRs of write-side observability all funnel flat dicts into the same
JSONL sinks — telemetry metric rows (``TelemetryReader``), graft-watch
summaries and anomalies (``aggregate``/``anomaly``), guard transitions
(``GuardMonitor``), consensus repairs (``ConsensusMonitor``), graft-prof
``perf_*`` records (``ProfileRecorder``), and graft-lint ``lint_finding``
events — but nothing reads them *together*: answering "what happened
around step 140?" means hand-joining five record shapes by eye.

:class:`Timeline` is that join. It classifies every record into a **kind**
(``telemetry`` / ``watch`` / ``anomaly`` / ``guard`` / ``consensus`` /
``perf`` / ``lint`` / ``elastic`` / ``adapt`` / ``retune`` / ``other``),
orders the whole run by ``(step, file
position)`` — file position breaks ties so causality within a step is
preserved exactly as the run emitted it — and exposes a small query API
(:meth:`between`, :meth:`kinds`, :meth:`at_step`, :meth:`anomalies`) plus
a :meth:`summary` suitable for regression gating
(``tools/graft_watch.py --baseline``). Pure stdlib: usable on any box
that holds the artifact, no jax required.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Iterable, List, Mapping, Optional

__all__ = ["KINDS", "classify", "TimelineEvent", "Timeline"]

KINDS = ("telemetry", "watch", "anomaly", "guard", "consensus", "perf",
         "lint", "elastic", "adapt", "retune", "other")


def classify(record: Mapping[str, Any]) -> str:
    """The timeline kind of one flat sink record.

    Records without an ``event`` field are per-step telemetry metric rows
    (the :class:`~grace_tpu.telemetry.reader.TelemetryReader` convention);
    event names map by family prefix. Unknown events are ``other`` — kept,
    never dropped, so a new record kind degrades to visible-but-unsorted
    instead of silently missing from the story.
    """
    event = record.get("event")
    if event is None:
        return "telemetry"
    event = str(event)
    if event == "watch_anomaly":
        return "anomaly"
    if event == "watch":
        return "watch"
    if event.startswith("guard"):
        return "guard"
    if event.startswith("consensus"):
        return "consensus"
    if event.startswith("perf_"):
        return "perf"
    if event == "lint_finding":
        return "lint"
    if event.startswith("elastic"):
        return "elastic"
    if event.startswith("adapt"):
        return "adapt"
    if event.startswith("retune"):
        return "retune"
    return "other"


@dataclasses.dataclass(frozen=True)
class TimelineEvent:
    """One record in run order. ``step`` is None for step-less records
    (provenance-adjacent events, ``guard_only`` flushes); they sort by
    file position among their neighbors."""

    step: Optional[int]
    kind: str
    seq: int                 # original emission order (file position)
    record: Dict[str, Any]

    def brief(self) -> str:
        rec = self.record
        if self.kind == "telemetry":
            bits = [f"{k}={rec[k]:.4g}" for k in
                    ("grad_norm", "compression_error", "wire_bytes")
                    if isinstance(rec.get(k), (int, float))]
            return "metrics " + " ".join(bits)
        if self.kind == "watch":
            return (f"watch summary err_mean="
                    f"{rec.get('compression_error_mean', 0):.4g} "
                    f"skew_max={rec.get('skew_max', 0):.3g} "
                    f"skew_rank={rec.get('skew_rank', -1)}")
        if self.kind == "anomaly":
            return (f"ANOMALY {rec.get('kind', '?')}/"
                    f"{rec.get('metric', '?')} rank={rec.get('rank', -1)} "
                    f"score={rec.get('score', 0):.3g}")
        name = str(rec.get("event", "?"))
        extras = ", ".join(
            f"{k}={v}" for k, v in sorted(rec.items())
            if k not in ("event", "step")
            and isinstance(v, (int, float, bool)))
        return name + (f" ({extras})" if extras else "")


class Timeline:
    """Time-ordered, step-keyed view over one run's sink records."""

    def __init__(self, events: List[TimelineEvent],
                 provenance: Optional[Mapping[str, Any]] = None):
        self.events = events
        self.provenance = dict(provenance) if provenance else None

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_records(cls, records: Iterable[Mapping[str, Any]],
                     provenance: Optional[Mapping[str, Any]] = None
                     ) -> "Timeline":
        events: List[TimelineEvent] = []
        prov = dict(provenance) if provenance else None
        for seq, rec in enumerate(records):
            if not isinstance(rec, Mapping):
                continue
            if "provenance" in rec and prov is None:
                prov = dict(rec["provenance"])
                continue
            step = rec.get("step")
            step = int(step) if isinstance(step, (int, float)) else None
            events.append(TimelineEvent(step=step, kind=classify(rec),
                                        seq=seq, record=dict(rec)))
        # Stable key: records without a step inherit the last seen step so
        # they stay with their neighborhood; file position breaks ties —
        # within one step the run's own emission order IS the causal order
        # (metric row -> watch summary -> anomaly -> guard event).
        keyed, last = [], -1
        for ev in events:
            if ev.step is not None:
                last = ev.step
            keyed.append((last if ev.step is None else ev.step, ev.seq, ev))
        keyed.sort(key=lambda t: (t[0], t[1]))
        return cls([ev for _, _, ev in keyed], provenance=prov)

    @classmethod
    def from_jsonl(cls, path: str) -> "Timeline":
        records = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue            # torn tail line of a killed run
        return cls.from_records(records)

    # -- queries ------------------------------------------------------------
    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self):
        return iter(self.events)

    def kinds(self, *names: str) -> List[TimelineEvent]:
        unknown = set(names) - set(KINDS)
        if unknown:
            raise ValueError(f"unknown timeline kind(s) {sorted(unknown)}; "
                             f"known: {KINDS}")
        return [e for e in self.events if e.kind in names]

    def between(self, start: int, end: int) -> List[TimelineEvent]:
        """Events with ``start <= step <= end`` (step-less events excluded
        — they have no well-defined position in a step range)."""
        return [e for e in self.events
                if e.step is not None and start <= e.step <= end]

    def at_step(self, step: int) -> List[TimelineEvent]:
        return [e for e in self.events if e.step == step]

    def anomalies(self) -> List[TimelineEvent]:
        return self.kinds("anomaly")

    def first(self, kind: str) -> Optional[TimelineEvent]:
        for e in self.events:
            if e.kind == kind:
                return e
        return None

    def steps(self) -> List[int]:
        return sorted({e.step for e in self.events if e.step is not None})

    # -- summary / rendering ------------------------------------------------
    def summary(self) -> dict:
        """The comparable facts of a run — the document
        ``tools/graft_watch.py`` gates against a baseline. Anomaly counts
        are broken down by detector kind, and each family's first
        occurrence step is recorded so a gate can assert not just "no new
        anomalies" but "nothing fired earlier than it used to"."""
        counts = {k: 0 for k in KINDS}
        for e in self.events:
            counts[e.kind] += 1
        anomalies = [e.record for e in self.anomalies()]
        by_kind: Dict[str, int] = {}
        max_score: Dict[str, float] = {}
        for a in anomalies:
            k = str(a.get("kind", "?"))
            by_kind[k] = by_kind.get(k, 0) + 1
            score = a.get("score")
            if isinstance(score, (int, float)):
                max_score[k] = max(max_score.get(k, 0.0), float(score))
        firsts = {}
        for kind in ("anomaly", "guard", "consensus", "lint", "adapt",
                     "retune"):
            ev = self.first(kind)
            if ev is not None:
                firsts[f"first_{kind}_step"] = ev.step
        steps = self.steps()
        return {
            "events": len(self.events),
            "kind_counts": {k: v for k, v in counts.items() if v},
            "step_span": [steps[0], steps[-1]] if steps else None,
            "anomalies": len(anomalies),
            "anomalies_by_kind": by_kind,
            "anomaly_max_score": max_score,
            "anomalous_ranks": sorted({int(a["rank"]) for a in anomalies
                                       if isinstance(a.get("rank"), int)
                                       and a["rank"] >= 0}),
            **firsts,
        }

    def render(self, kinds: Optional[Iterable[str]] = None,
               limit: Optional[int] = None) -> str:
        """Human-readable timeline, one line per event."""
        events = (self.events if kinds is None
                  else self.kinds(*tuple(kinds)))
        if limit is not None and len(events) > limit:
            head = events[:limit]
            trailer = [f"  ... {len(events) - limit} more events "
                       f"(use --limit 0 for all)"]
        else:
            head, trailer = events, []
        out = []
        if self.provenance:
            out.append("== provenance ==")
            for k, v in self.provenance.items():
                out.append(f"  {k}: {v}")
            out.append("")
        out.append(f"== timeline ({len(events)} events) ==")
        for e in head:
            step = "     ?" if e.step is None else f"{e.step:>6d}"
            out.append(f"  step {step}  [{e.kind:<9s}] {e.brief()}")
        out.extend(trailer)
        return "\n".join(out)
